"""vtpctl — the framework CLI.

Reference parity: cmd/cli/vcctl.go:36-41 (job run/list/view/suspend/
resume/delete; queue create/list/get/delete; pod list) plus the
slurm-style shortcuts (vsub/vjobs/vqueues/vcancel analogues exposed as
subcommands).  Standalone mode drives a pickled FakeCluster state file
(--state), so the full control plane is scriptable without a cluster:

    python -m volcano_tpu.cli.vtpctl --state c.pkl init --slices sa=v5e-16
    python -m volcano_tpu.cli.vtpctl --state c.pkl job run -N train \
        --replicas 4 --tpu 4 --plugins jax,svc
    python -m volcano_tpu.cli.vtpctl --state c.pkl tick
    python -m volcano_tpu.cli.vtpctl --state c.pkl job list
"""

from __future__ import annotations

import argparse
import json
import pickle
import sys
from typing import List, Optional

from volcano_tpu.api.pod import Container, Pod
from volcano_tpu.api.queue import Queue
from volcano_tpu.api.resource import TPU
from volcano_tpu.api.types import GROUP_NAME_ANNOTATION
from volcano_tpu.api.vcjob import TaskSpec, VCJob
from volcano_tpu.framework.job_updater import SCHEDULING_REASON_ANNOTATION


def _load(path: str):
    try:
        # either format: legacy pickle or the snapshot JSON the
        # server's graceful save writes now
        from volcano_tpu.server.durability import load_cluster_file
        cluster = load_cluster_file(path)
    except FileNotFoundError:
        from volcano_tpu.cache.fake_cluster import FakeCluster
        cluster = FakeCluster()
    if cluster.admission is None:
        from volcano_tpu.webhooks import default_admission
        cluster.admission = default_admission()
    return cluster


def _save(cluster, path: str):
    with open(path, "wb") as f:
        pickle.dump(cluster, f)


def _table(rows: List[List[str]], headers: List[str]) -> str:
    rows = [headers] + [[str(c) for c in r] for r in rows]
    widths = [max(len(r[i]) for r in rows) for i in range(len(headers))]
    return "\n".join(
        "  ".join(c.ljust(w) for c, w in zip(r, widths)) for r in rows)


# -- subcommand handlers ----------------------------------------------

def cmd_init(cluster, args):
    from volcano_tpu.simulator import slice_nodes
    from volcano_tpu.api.devices.tpu.topology import slice_for
    for spec in args.slices or []:
        name, kind = spec.split("=", 1)
        for node in slice_nodes(slice_for(name, kind), dcn_pod=args.dcn_pod):
            cluster.add_node(node)
    from volcano_tpu.controllers.hypernode import HyperNodeController
    ctrl = HyperNodeController()
    ctrl.initialize(cluster)
    ctrl.sync()
    print(f"cluster: {len(cluster.nodes)} nodes, "
          f"{len(cluster.hypernodes)} hypernodes")


def cmd_job_run(cluster, args):
    requests = {"cpu": args.cpu}
    if args.tpu:
        requests[TPU] = args.tpu
    job = VCJob(
        name=args.name,
        namespace=args.namespace,
        min_available=args.min_available or args.replicas,
        queue=args.queue,
        tasks=[TaskSpec(name=args.task_name, replicas=args.replicas,
                        template=Pod(name="t", containers=[
                            Container(image=args.image,
                                      requests=requests)]))],
        plugins={p: [] for p in (args.plugins.split(",")
                                 if args.plugins else [])},
    )
    job = cluster.add_vcjob(job)
    print(f"job {job.key} submitted (queue={job.queue}, "
          f"minAvailable={job.min_available})")


def cmd_job_create(cluster, args):
    from volcano_tpu.cli.manifest import ManifestError, load_jobs
    try:
        jobs = load_jobs(args.filename)
    except (ManifestError, OSError) as e:
        sys.exit(f"error: {e}")
    for job in jobs:
        job = cluster.add_vcjob(job)
        print(f"job {job.key} created (queue={job.queue}, "
              f"minAvailable={job.min_available}, "
              f"tasks={[t.name for t in job.tasks]})")


def cmd_job_list(cluster, args):
    rows = []
    for job in cluster.vcjobs.values():
        if args.namespace and job.namespace != args.namespace:
            continue
        rows.append([job.namespace, job.name, job.phase.value,
                     f"{job.running}/{job.total_replicas()}",
                     job.queue, f"{job.retry_count}"])
    print(_table(rows, ["NAMESPACE", "NAME", "PHASE", "RUNNING",
                        "QUEUE", "RETRIES"]))


def cmd_job_view(cluster, args):
    job = cluster.vcjobs.get(f"{args.namespace}/{args.name}")
    if job is None:
        sys.exit(f"job {args.namespace}/{args.name} not found")
    out = {
        "name": job.name, "namespace": job.namespace,
        "phase": job.phase.value, "queue": job.queue,
        "minAvailable": job.min_available,
        "status": {"pending": job.pending, "running": job.running,
                   "succeeded": job.succeeded, "failed": job.failed},
        "tasks": [{"name": t.name, "replicas": t.replicas}
                  for t in job.tasks],
        "message": job.state_message,
        "pods": [{"name": p.name, "phase": p.phase.value,
                  "node": p.node_name,
                  # per-pod scheduling reason (scheduling-reason.md):
                  # which task blocks the gang, and why
                  **({"schedulingReason":
                          p.annotations[SCHEDULING_REASON_ANNOTATION],
                      "message": p.status_message}
                     if SCHEDULING_REASON_ANNOTATION in p.annotations
                     and not p.node_name else {})}
                 for p in cluster.pods.values() if p.owner == job.uid],
    }
    print(json.dumps(out, indent=2))


def cmd_job_delete(cluster, args):
    key = f"{args.namespace}/{args.name}"
    if key not in cluster.vcjobs:
        sys.exit(f"job {key} not found")
    cluster.delete_vcjob(key)
    print(f"job {key} deleted")


def cmd_job_command(cluster, args, action):
    key = f"{args.namespace}/{args.name}"
    if key not in cluster.vcjobs:
        sys.exit(f"job {key} not found")
    cluster.add_command(key, action)
    print(f"job {key}: {action} requested")


def cmd_jobtemplate_create(cluster, args):
    from volcano_tpu.api.jobflow import JobTemplate
    from volcano_tpu.cli.manifest import ManifestError, load_jobs
    try:
        jobs = load_jobs(args.filename)
    except (ManifestError, OSError) as e:
        sys.exit(f"error: {e}")
    for job in jobs:
        tmpl = JobTemplate(name=job.name, namespace=job.namespace,
                           job=job)
        cluster.put_object("jobtemplate", tmpl)
        print(f"jobtemplate {tmpl.key} created")


def cmd_jobtemplate_list(cluster, args):
    rows = [[t.namespace, t.name,
             ",".join(ts.name for ts in (t.job.tasks if t.job else []))]
            for t in getattr(cluster, "jobtemplates", {}).values()]
    print(_table(rows, ["NAMESPACE", "NAME", "TASKS"]))


def cmd_jobflow_create(cluster, args):
    from volcano_tpu.api.jobflow import Flow, FlowDependsOn, JobFlow
    flows = []
    for spec in args.flows:
        # "name" or "name:dep1+dep2"
        if ":" in spec:
            name, deps = spec.split(":", 1)
            flows.append(Flow(name=name, depends_on=FlowDependsOn(
                targets=deps.split("+"))))
        else:
            flows.append(Flow(name=spec))
    flow = JobFlow(name=args.name, namespace=args.namespace, flows=flows,
                   job_retain_policy=args.retain_policy)
    cluster.put_object("jobflow", flow)
    print(f"jobflow {flow.key} created ({len(flows)} steps)")


def cmd_jobflow_list(cluster, args):
    rows = []
    for flow in getattr(cluster, "jobflows", {}).values():
        rows.append([flow.namespace, flow.name, flow.phase.value,
                     f"{len(flow.deployed_jobs)}/{len(flow.flows)}"])
    print(_table(rows, ["NAMESPACE", "NAME", "PHASE", "DEPLOYED"]))


def _find_flow(cluster, args):
    flow = getattr(cluster, "jobflows", {}).get(
        f"{args.namespace}/{args.name}")
    if flow is None:
        sys.exit(f"jobflow {args.namespace}/{args.name} not found")
    return flow


def cmd_jobflow_get(cluster, args):
    flow = _find_flow(cluster, args)
    print(_table([[flow.namespace, flow.name, flow.phase.value,
                   f"{len(flow.deployed_jobs)}/{len(flow.flows)}"]],
                 ["NAMESPACE", "NAME", "PHASE", "DEPLOYED"]))


def cmd_jobflow_describe(cluster, args):
    """Full flow detail (reference cli/jobflow/describe.go YAML dump)."""
    flow = _find_flow(cluster, args)
    print(f"name: {flow.name}")
    print(f"namespace: {flow.namespace}")
    print(f"phase: {flow.phase.value}")
    print("flows:")
    for f in flow.flows:
        deps = "+".join(f.depends_on.targets) if f.depends_on and \
            f.depends_on.targets else "-"
        # deployed_jobs holds job keys "<ns>/<flow>-<step>"
        job_key = f"{flow.namespace}/{flow.job_name(f.name)}"
        state = "deployed" if job_key in flow.deployed_jobs \
            else "pending"
        print(f"  - name: {f.name}\n    dependsOn: {deps}\n"
              f"    state: {state}")


def cmd_jobflow_delete(cluster, args):
    from volcano_tpu.cache.fake_cluster import FakeCluster
    from volcano_tpu.controllers.jobflow import reap_deleted_flow
    flow = _find_flow(cluster, args)
    cluster.delete_object("jobflow", flow.key)
    if isinstance(cluster, FakeCluster):
        # no controller process is watching a pickled cluster; apply
        # the retain policy inline, including the job controller's
        # full delete path (wire mode leaves both to the watching
        # controller processes)
        reap_deleted_flow(cluster, flow, run_job_cleanup=True)
    print(f"jobflow {flow.key} deleted")


def _find_tmpl(cluster, args):
    tmpl = getattr(cluster, "jobtemplates", {}).get(
        f"{args.namespace}/{args.name}")
    if tmpl is None:
        sys.exit(f"jobtemplate {args.namespace}/{args.name} not found")
    return tmpl


def cmd_jobtemplate_get(cluster, args):
    tmpl = _find_tmpl(cluster, args)
    tasks = tmpl.job.tasks if tmpl.job else []
    print(_table([[tmpl.namespace, tmpl.name,
                   ",".join(t.name for t in tasks)]],
                 ["NAMESPACE", "NAME", "TASKS"]))


def cmd_jobtemplate_describe(cluster, args):
    tmpl = _find_tmpl(cluster, args)
    print(f"name: {tmpl.name}\nnamespace: {tmpl.namespace}")
    if tmpl.job:
        print(f"minAvailable: {tmpl.job.min_available}")
        print("tasks:")
        for t in tmpl.job.tasks:
            print(f"  - name: {t.name}\n    replicas: {t.replicas}")


def cmd_jobtemplate_delete(cluster, args):
    tmpl = _find_tmpl(cluster, args)
    cluster.delete_object("jobtemplate", tmpl.key)
    print(f"jobtemplate {tmpl.key} deleted")


def cmd_queue_create(cluster, args):
    from volcano_tpu.api.resource import Resource
    queue = Queue(name=args.name, weight=args.weight, parent=args.parent)
    if args.capability:
        queue.capability = Resource.from_resource_list(
            json.loads(args.capability))
    admission = getattr(cluster, "admission", None)
    if admission is not None:
        queue = admission.admit_queue(queue, cluster)
        cluster.add_queue(queue)
    else:
        # wire mode: the server runs the admission chain on create
        cluster.put_object("queue", queue)
    print(f"queue {queue.name} created (weight={queue.weight})")


def cmd_queue_operate(cluster, args):
    from volcano_tpu.controllers.queue import QueueController
    if args.name not in cluster.queues:
        sys.exit(f"queue {args.name} not found")
    ctrl = QueueController()
    ctrl.initialize(cluster)
    if args.action == "close":
        ctrl.close_queue(args.name)   # drained queue flips Closed now
        print(f"queue {args.name}: "
              f"{cluster.queues[args.name].state.value}")
    elif args.action == "open":
        ctrl.open_queue(args.name)
        print(f"queue {args.name} opened")


def cmd_queue_list(cluster, args):
    rows = [[q.name, q.weight, q.state.value, q.parent or "-"]
            for q in cluster.queues.values()]
    print(_table(rows, ["NAME", "WEIGHT", "STATE", "PARENT"]))


def cmd_queue_get(cluster, args):
    """Detailed queue view (reference cli/queue/get.go)."""
    q = cluster.queues.get(args.name)
    if q is None:
        sys.exit(f"queue {args.name} not found")
    pgs = [pg for pg in cluster.podgroups.values()
           if pg.queue == q.name]
    by_phase = {}
    for pg in pgs:
        by_phase[pg.phase.value] = by_phase.get(pg.phase.value, 0) + 1
    print(f"name: {q.name}")
    print(f"weight: {q.weight}")
    print(f"state: {q.state.value}")
    print(f"parent: {q.parent or '-'}")
    print(f"reclaimable: {q.reclaimable}")
    if q.capability is not None:
        print(f"capability: {q.capability}")
    if q.guarantee is not None and not q.guarantee.is_empty():
        print(f"guarantee: {q.guarantee}")
    if pgs:
        detail = ", ".join(f"{k}={v}"
                           for k, v in sorted(by_phase.items()))
        print(f"podGroups: {len(pgs)} ({detail})")
    else:
        print("podGroups: 0")


def cmd_queue_delete(cluster, args):
    """Delete a queue; refuses while podgroups still reference it
    (reference cli/queue/delete.go requires the queue drained)."""
    if args.name not in cluster.queues:
        sys.exit(f"queue {args.name} not found")
    holders = {pg.key for pg in cluster.podgroups.values()
               if pg.queue == args.name}
    holders |= {j.key for j in getattr(cluster, "vcjobs", {}).values()
                if j.queue == args.name}
    if holders and not args.force:
        sample = sorted(holders)[0]
        sys.exit(f"queue {args.name} still has {len(holders)} "
                 f"podgroup(s)/job(s) (e.g. {sample}); drain it "
                 f"or pass --force")
    cluster.delete_object("queue", args.name)
    print(f"queue {args.name} deleted")


def cmd_pod_list(cluster, args):
    rows = []
    for pod in cluster.pods.values():
        if args.namespace and pod.namespace != args.namespace:
            continue
        reason = "-"
        if not pod.node_name:
            reason = pod.annotations.get(SCHEDULING_REASON_ANNOTATION,
                                         "-")
        rows.append([pod.namespace, pod.name, pod.phase.value,
                     pod.node_name or "-", reason])
    print(_table(rows, ["NAMESPACE", "NAME", "PHASE", "NODE",
                        "REASON"]))


def cmd_pod_describe(cluster, args):
    """kubectl-describe analogue: pod state + scheduling reason +
    the server-side audit history of this pod (bind/evict/phase
    transitions as the apiserver saw them)."""
    key = f"{args.namespace}/{args.name}"
    pod = cluster.pods.get(key)
    if pod is None:
        sys.exit(f"pod {key} not found")
    out = {
        "name": pod.name, "namespace": pod.namespace,
        "uid": pod.uid, "phase": pod.phase.value,
        "node": pod.node_name or None,
        "owner": pod.owner or None, "task": pod.task_spec or None,
        "requests": dict(pod.resource_requests().res),
        "annotations": dict(pod.annotations),
    }
    if pod.status_message:
        out["message"] = pod.status_message
    reason = pod.annotations.get(SCHEDULING_REASON_ANNOTATION)
    if reason:
        out["schedulingReason"] = reason
    history = _pod_audit_history(cluster, key)
    if history is not None:
        out["events"] = history
    print(json.dumps(out, indent=2))


def _pod_audit_history(cluster, key):
    """This pod's slice of the server audit trail (wire mode only:
    the standalone state file keeps no trail).  Goes through the
    cluster client's _request so TLS context / bearer auth apply;
    records are filtered SERVER-side via the key param."""
    request = getattr(cluster, "_request", None)
    if request is None:
        return None
    from urllib.parse import quote
    try:
        records, since, truncated = [], 0, False
        while True:
            payload = request(
                "GET", f"/audit?since={since}&key={quote(key)}")
            truncated = truncated or bool(payload.get("lost"))
            batch = payload.get("records", [])
            records.extend(batch)
            if payload["idx"] <= since:
                break
            since = payload["idx"]
        import datetime
        out = [{"ts": datetime.datetime.fromtimestamp(
                    rec["ts"]).isoformat(timespec="seconds"),
                "kind": rec["kind"],
                **({"node": rec["node"]} if rec.get("node") else {}),
                **({"phase": rec["phase"]} if rec.get("phase") else {})}
               for rec in records]
        if truncated:
            # ring eviction dropped early records: never present the
            # surviving tail as the pod's complete history
            return {"historyTruncated": True, "records": out}
        return out
    except Exception:  # noqa: BLE001 — audit is best-effort extra
        return None


def cmd_node_list(cluster, args):
    from volcano_tpu.agent.agent import (
        CPU_USAGE_ANNOTATION,
        TPU_HEALTHY_LABEL,
    )
    from volcano_tpu.api.resource import Resource
    from volcano_tpu.api.types import occupied
    rows = []
    for node in cluster.nodes.values():
        alloc = Resource.from_resource_list(node.allocatable)
        used = Resource()
        npods = 0
        for pod in cluster.pods.values():
            # occupied() includes RELEASING: evicted-but-not-yet-gone
            # pods still hold capacity from the scheduler's view
            if pod.node_name == node.name and occupied(pod.phase):
                used.add(pod.resource_requests())
                npods += 1
        rows.append([
            node.name,
            "cordoned" if node.unschedulable else "ready",
            f"{used.milli_cpu / 1000:g}/{alloc.milli_cpu / 1000:g}",
            f"{used.get(TPU):g}/{alloc.get(TPU):g}",
            npods,
            node.annotations.get(CPU_USAGE_ANNOTATION, "-"),
            node.labels.get(TPU_HEALTHY_LABEL, "-"),
        ])
    print(_table(rows, ["NAME", "STATUS", "CPU", "CHIPS", "PODS",
                        "USAGE", "TPU-OK"]))


def cmd_node_view(cluster, args):
    node = cluster.nodes.get(args.name)
    if node is None:
        sys.exit(f"node {args.name} not found")
    print(f"Name:          {node.name}")
    print(f"Unschedulable: {node.unschedulable}")
    print(f"Allocatable:   {dict(node.allocatable)}")
    if node.labels:
        print("Labels:")
        for k in sorted(node.labels):
            print(f"  {k}={node.labels[k]}")
    if node.annotations:
        print("Annotations:")
        for k in sorted(node.annotations):
            print(f"  {k}={node.annotations[k]}")
    topo = getattr(cluster, "numatopologies", {}).get(node.name)
    if topo is not None:
        print("NUMA topology:")
        for res, per_cell in sorted(topo.numa_res.items()):
            cells = ", ".join(f"cell{c}={per_cell[c]:g}"
                              for c in sorted(per_cell))
            print(f"  {res}: {cells} (free)")
        for k, v in sorted(topo.policies.items()):
            print(f"  {k}={v}")
    pods = [p for p in cluster.pods.values()
            if p.node_name == node.name]
    if pods:
        print("Pods:")
        for p in sorted(pods, key=lambda p: p.key):
            print(f"  {p.key} ({p.phase.value})")


def cmd_slices(cluster, args):
    """Per-slice rollup: hosts, cordons, worst health verdict (from
    the folded SliceHealthReport annotations / the report store) and
    the failover controller's quarantine TTL."""
    import datetime

    from volcano_tpu.api.slicehealth import (
        NODE_HEALTH_ANNOTATION, NODE_QUARANTINED_UNTIL_ANNOTATION,
        VERDICT_FAILED, VERDICT_HEALTHY, VERDICT_SUSPECT)
    from volcano_tpu.api.types import TPU_SLICE_LABEL, TPU_TOPOLOGY_LABEL
    rank = {VERDICT_HEALTHY: 0, VERDICT_SUSPECT: 1, VERDICT_FAILED: 2}
    reports = getattr(cluster, "slicehealthreports", {})
    slices = {}
    for node in cluster.nodes.values():
        name = node.labels.get(TPU_SLICE_LABEL)
        if name:
            slices.setdefault(name, []).append(node)
    rows = []
    for name in sorted(slices):
        nodes = slices[name]
        health = VERDICT_HEALTHY
        until = 0.0
        for n in nodes:
            rep = reports.get(n.name)
            verdict = rep.verdict if rep is not None else \
                n.annotations.get(NODE_HEALTH_ANNOTATION,
                                  VERDICT_HEALTHY)
            if rank.get(verdict, 0) > rank.get(health, 0):
                health = verdict
            try:
                until = max(until, float(n.annotations.get(
                    NODE_QUARANTINED_UNTIL_ANNOTATION, 0) or 0))
            except (TypeError, ValueError):
                pass
        rows.append([
            name,
            nodes[0].labels.get(TPU_TOPOLOGY_LABEL, "-"),
            len(nodes),
            sum(1 for n in nodes if n.unschedulable),
            health,
            datetime.datetime.fromtimestamp(until).isoformat(
                timespec="seconds") if until else "-",
        ])
    print(_table(rows, ["NAME", "TOPOLOGY", "HOSTS", "CORDONED",
                        "HEALTH", "QUARANTINED-UNTIL"]))


def cmd_failover(cluster, args):
    """Failover view: unhealthy/quarantined slices, drained gangs
    awaiting re-placement, and the resume metadata stamped on
    podgroups (generation, resume step, checkpoint dir)."""
    from volcano_tpu.api.slicehealth import (
        CHECKPOINT_DIR_ANNOTATION, FAILOVER_GENERATION_ANNOTATION,
        REQUEUED_ANNOTATION, RESUME_STEP_ANNOTATION, VERDICT_HEALTHY)
    reports = getattr(cluster, "slicehealthreports", {})
    sick = [[r.node, r.slice or "-", r.verdict,
             f"{r.chips_healthy}/{r.chips_detected}",
             r.consecutive_bad]
            for r in sorted(reports.values(), key=lambda r: r.node)
            if r.verdict != VERDICT_HEALTHY]
    print(_table(sick, ["NODE", "SLICE", "VERDICT", "CHIPS",
                        "BAD-SYNCS"]))
    rows = []
    for pg in cluster.podgroups.values():
        ann = pg.annotations
        if FAILOVER_GENERATION_ANNOTATION not in ann and \
                REQUEUED_ANNOTATION not in ann:
            continue
        rows.append([
            pg.key,
            ann.get(FAILOVER_GENERATION_ANNOTATION, "0"),
            "yes" if ann.get(REQUEUED_ANNOTATION) == "true" else "-",
            ann.get(RESUME_STEP_ANNOTATION, "-"),
            ann.get(CHECKPOINT_DIR_ANNOTATION, "-"),
            pg.phase.value,
        ])
    if rows:
        print()
        print(_table(rows, ["PODGROUP", "GENERATION", "REQUEUED",
                            "RESUME-STEP", "CHECKPOINT-DIR", "PHASE"]))
    events = [e for e in getattr(cluster, "events", [])
              if e[1] in ("SliceFailed", "SliceRecovered",
                          "FailoverDrain", "FailoverComplete",
                          "TPUUnhealthy", "TPURecovered")]
    if events:
        print()
        print(_table([[k, r, m] for k, r, m in events[-20:]],
                     ["OBJECT", "REASON", "MESSAGE"]))


def cmd_elastic(cluster, args):
    """Elastic-gang view: per job current/min/max slices, generation,
    any in-flight decision, and the resize history the controller
    appends; --migrate stamps a policy-initiated live migration (the
    Singularity move: drain -> re-place on OTHER slices -> resume)."""
    import datetime

    from volcano_tpu.api import elastic as eapi
    from volcano_tpu.api.types import TPU_SLICE_LABEL

    if args.migrate:
        ns, _, name = args.migrate.rpartition("/")
        ns = ns or "default"
        key = f"{ns}/{name}"
        pg = cluster.podgroups.get(key)
        if pg is None or not eapi.is_elastic(pg):
            sys.exit(f"{key} is not an elastic podgroup")
        current = sorted({
            cluster.nodes[p.node_name].labels.get(TPU_SLICE_LABEL, "")
            for p in _job_pods(cluster, ns, name)
            if p.node_name and p.node_name in cluster.nodes})
        current = [s for s in current if s]
        pg.annotations[eapi.ELASTIC_DESIRED_SLICES_ANNOTATION] = \
            str(eapi.current_slices(pg))
        pg.annotations[eapi.ELASTIC_RESIZE_REASON_ANNOTATION] = \
            eapi.RESIZE_MIGRATE
        if current:
            pg.annotations[eapi.ELASTIC_AVOID_SLICES_ANNOTATION] = \
                ",".join(current)
        cluster.update_podgroup_status(pg)
        print(f"migration requested: {key} off "
              f"{','.join(current) or '(unplaced)'}")
        return

    rows, history_rows = [], []
    for pg in sorted(cluster.podgroups.values(), key=lambda g: g.key):
        if not eapi.is_elastic(pg):
            continue
        rng = eapi.elastic_range(pg) or ("?", "?")
        desired = eapi.desired_slices(pg)
        reason = pg.annotations.get(
            eapi.ELASTIC_RESIZE_REASON_ANNOTATION, "")
        resizing = f"->{desired} ({reason})" if desired is not None \
            else "-"
        try:
            last = float(pg.annotations.get(
                eapi.ELASTIC_LAST_RESIZE_TS_ANNOTATION, 0) or 0)
        except (TypeError, ValueError):
            last = 0.0
        rows.append([
            pg.key, eapi.current_slices(pg), rng[0], rng[1],
            pg.annotations.get(eapi.ELASTIC_GENERATION_ANNOTATION,
                               "0"),
            resizing,
            datetime.datetime.fromtimestamp(last).isoformat(
                timespec="seconds") if last else "-",
            pg.phase.value,
        ])
        for rec in eapi.resize_history(pg):
            history_rows.append([
                pg.key, rec.get("gen", "?"), rec.get("kind", "?"),
                f"{rec.get('from', '?')} -> {rec.get('to', '?')}",
                datetime.datetime.fromtimestamp(
                    rec.get("ts", 0)).isoformat(timespec="seconds")
                if rec.get("ts") else "-",
            ])
    print(_table(rows, ["PODGROUP", "SLICES", "MIN", "MAX", "GEN",
                        "RESIZING", "LAST-RESIZE", "PHASE"]))
    if history_rows:
        print()
        print(_table(history_rows,
                     ["PODGROUP", "GEN", "KIND", "SLICES", "AT"]))


def cmd_goodput(cluster, args):
    """One job's measured throughput: the store-folded podgroup
    summary (step, steps/s, goodput = productive/allocated
    pod-seconds) plus the per-pod progress the node agents last
    reported (GoodputReport store) and the elastic resize history —
    the operator's answer to "is this gang actually training, and how
    fast"."""
    import datetime

    from volcano_tpu.api import elastic as eapi
    from volcano_tpu.api import goodput as gapi
    key = f"{args.namespace}/{args.name}"
    pg = cluster.podgroups.get(key)
    if pg is None:
        sys.exit(f"podgroup {key} not found")
    ann = pg.annotations
    print(f"job: {key}")
    print(f"phase: {pg.phase.value}  (queue={pg.queue})")
    if gapi.PG_STEP_RATE_ANNOTATION not in ann:
        print("no goodput data published (no worker progress "
              "reported yet — does the job declare "
              f"{gapi.PROGRESS_DIR_ANNOTATION}?)")
        return
    alloc = gapi.ann_float(ann, gapi.PG_ALLOCATED_S_ANNOTATION)
    prod = gapi.ann_float(ann, gapi.PG_PRODUCTIVE_S_ANNOTATION)
    updated = gapi.ann_float(ann, gapi.PG_UPDATED_TS_ANNOTATION)
    print(f"step: {int(gapi.ann_float(ann, gapi.PG_STEP_ANNOTATION))}"
          f"  steps/s: "
          f"{gapi.ann_float(ann, gapi.PG_STEP_RATE_ANNOTATION):g}"
          f"  examples/s: "
          f"{gapi.ann_float(ann, gapi.PG_EXAMPLES_RATE_ANNOTATION):g}")
    print(f"goodput: "
          f"{ann.get(gapi.PG_GOODPUT_ANNOTATION, '-')}"
          f"  (productive {prod:.1f}s / allocated {alloc:.1f}s "
          f"pod-seconds)")
    print(f"generation: "
          f"{ann.get(gapi.PG_GENERATION_ANNOTATION, '-')}"
          f"  epoch: {int(gapi.ann_float(ann, gapi.PG_EPOCH_ANNOTATION))}"
          f"  updated: "
          + (datetime.datetime.fromtimestamp(updated).isoformat(
              timespec='seconds') if updated else "-"))
    rows = []
    for name in sorted(getattr(cluster, "goodputreports", {})):
        rep = cluster.goodputreports[name]
        for u in rep.usages:
            if u.job != key:
                continue
            rows.append([
                rep.node, u.pod_key, u.step, f"{u.steps_per_s:g}",
                f"{u.goodput:g}",
                "STALLED" if u.stalled else "stepping", u.epoch])
    if rows:
        print()
        print(_table(rows, ["NODE", "POD", "STEP", "STEPS/S",
                            "GOODPUT", "STATE", "EPOCH"]))
    hist = eapi.resize_history(pg)
    if hist:
        print()
        print(_table(
            [[rec.get("gen", "?"), rec.get("kind", "?"),
              f"{rec.get('from', '?')} -> {rec.get('to', '?')}"]
             for rec in hist],
            ["GEN", "KIND", "SLICES"]))


def cmd_serve(cluster, args):
    """Serving-group view: replicas current/min/max, the store-folded
    traffic summary (QPS, p99 vs the declared SLO, cumulative SLO
    attainment), the autoscaler's last decision and its age, and the
    per-replica rates the node agents last reported (ServingReport
    store) — the operator's answer to "is this group inside its SLO,
    and what did the autoscaler last do about it".  With no name:
    one row per serving group."""
    import datetime
    import time as _time

    from volcano_tpu.api import elastic as eapi
    from volcano_tpu.api import serving as sapi

    def _summary(pg):
        ann = pg.annotations
        qps = sapi.ann_float(ann, sapi.PG_QPS_ANNOTATION)
        p99 = sapi.ann_float(ann, sapi.PG_P99_MS_ANNOTATION)
        reqs = sapi.ann_float(ann, sapi.PG_REQUESTS_ANNOTATION)
        ok = sapi.ann_float(ann, sapi.PG_SLO_OK_ANNOTATION)
        att = ok / reqs if reqs > 0 else None
        return qps, p99, reqs, ok, att

    if not args.name:
        rows = []
        for pg in sorted(cluster.podgroups.values(),
                         key=lambda g: g.key):
            if not sapi.is_serving(pg):
                continue
            rng = sapi.replica_range(pg) or ("?", "?")
            qps, p99, _reqs, _ok, att = _summary(pg)
            slo = sapi.slo_p99_ms(pg)
            rows.append([
                pg.key, eapi.current_slices(pg), rng[0], rng[1],
                f"{qps:g}", f"{p99:g}",
                f"{slo:g}" if slo is not None else "-",
                f"{att:.4f}" if att is not None else "-",
                pg.annotations.get(
                    sapi.PG_LAST_DECISION_ANNOTATION, "-"),
            ])
        print(_table(rows, ["PODGROUP", "REPLICAS", "MIN", "MAX",
                            "QPS", "P99-MS", "SLO-MS", "ATTAIN",
                            "LAST-DECISION"]))
        return

    key = f"{args.namespace}/{args.name}"
    pg = cluster.podgroups.get(key)
    if pg is None:
        sys.exit(f"podgroup {key} not found")
    if not sapi.is_serving(pg):
        sys.exit(f"{key} is not serving-class (no "
                 f"{sapi.SLO_P99_MS_ANNOTATION})")
    ann = pg.annotations
    rng = sapi.replica_range(pg) or ("?", "?")
    qps, p99, reqs, ok, att = _summary(pg)
    slo = sapi.slo_p99_ms(pg)
    tgt = sapi.target_qps_per_replica(pg)
    print(f"group: {key}")
    print(f"phase: {pg.phase.value}  (queue={pg.queue})")
    print(f"replicas: {eapi.current_slices(pg)}"
          f"  (min {rng[0]} / max {rng[1]})"
          + (f"  target-qps/replica: {tgt:g}" if tgt else ""))
    if sapi.PG_QPS_ANNOTATION not in ann:
        print("no serving data published (no replica stats reported "
              "yet — does the job declare "
              f"{sapi.STATS_DIR_ANNOTATION}?)")
        return
    over = ""
    if slo is not None and p99 > slo:
        over = "  OVER SLO"
    print(f"qps: {qps:g}  p99: {p99:g}ms"
          + (f"  (slo {slo:g}ms{over})" if slo is not None else ""))
    print(f"requests: {int(reqs)}  slo-ok: {int(ok)}"
          + (f"  attainment: {att:.4f}" if att is not None else ""))
    updated = sapi.ann_float(ann, sapi.PG_UPDATED_TS_ANNOTATION)
    print(f"reporting-replicas: "
          f"{int(sapi.ann_float(ann, sapi.PG_REPLICAS_ANNOTATION))}"
          f"  epoch: "
          f"{int(sapi.ann_float(ann, sapi.PG_EPOCH_ANNOTATION))}"
          f"  updated: "
          + (datetime.datetime.fromtimestamp(updated).isoformat(
              timespec="seconds") if updated else "-"))
    decision = ann.get(sapi.PG_LAST_DECISION_ANNOTATION)
    if decision:
        ts = sapi.ann_float(ann, sapi.PG_LAST_DECISION_TS_ANNOTATION)
        age = f" ({_time.time() - ts:.0f}s ago)" if ts else ""
        print(f"last-decision: {decision}{age}")
    pool = sapi.pool_slices(pg)
    if pool:
        print(f"pool-slices: {','.join(pool)}")
    desired = eapi.desired_slices(pg)
    if desired is not None:
        print(f"resizing: ->{desired} "
              f"({ann.get(eapi.ELASTIC_RESIZE_REASON_ANNOTATION, '?')})")
    rows = []
    for name in sorted(getattr(cluster, "servingreports", {})):
        rep = cluster.servingreports[name]
        for u in rep.usages:
            if u.job != key:
                continue
            rows.append([
                rep.node, u.pod_key, f"{u.qps:g}", f"{u.p50_ms:g}",
                f"{u.p99_ms:g}", u.requests, u.slo_ok, u.epoch])
    if rows:
        print()
        print(_table(rows, ["NODE", "POD", "QPS", "P50-MS", "P99-MS",
                            "REQUESTS", "SLO-OK", "EPOCH"]))
    hist = eapi.resize_history(pg)
    if hist:
        print()
        print(_table(
            [[rec.get("gen", "?"), rec.get("kind", "?"),
              f"{rec.get('from', '?')} -> {rec.get('to', '?')}"]
             for rec in hist],
            ["GEN", "KIND", "REPLICAS"]))


def cmd_fleet(cluster, args):
    """Fleet observatory rollup: per-job measured throughput (from
    the folded podgroup annotations), then the cluster gauges the
    scheduler exports — ICI fragmentation per generation (largest
    placeable idle block vs total idle chips, volcano_tpu/goodput.py)
    and pending-gang counts per queue — computed here from the same
    store objects so the view works against a state file or mirror
    with no scheduler attached."""
    from volcano_tpu import goodput as gp
    from volcano_tpu import trace
    from volcano_tpu.api import elastic as eapi
    from volcano_tpu.api import goodput as gapi
    from volcano_tpu.api.types import PodGroupPhase
    import time as _time

    rows = []
    pending_by_queue = {}
    now = _time.time()
    for pg in sorted(cluster.podgroups.values(), key=lambda g: g.key):
        if pg.phase in (PodGroupPhase.PENDING, PodGroupPhase.INQUEUE):
            born = trace.phase_ts(pg.annotations, "created")
            cur = pending_by_queue.setdefault(
                pg.queue, {"gangs": 0, "age_s": 0.0})
            cur["gangs"] += 1
            if born is not None:
                cur["age_s"] = max(cur["age_s"], now - born)
        ann = pg.annotations
        if gapi.PG_STEP_RATE_ANNOTATION not in ann:
            continue
        rows.append([
            pg.key, pg.phase.value,
            ann.get(gapi.PG_GENERATION_ANNOTATION, "-"),
            eapi.current_slices(pg) if eapi.is_elastic(pg) else "-",
            int(gapi.ann_float(ann, gapi.PG_STEP_ANNOTATION)),
            f"{gapi.ann_float(ann, gapi.PG_STEP_RATE_ANNOTATION):g}",
            ann.get(gapi.PG_GOODPUT_ANNOTATION, "-"),
        ])
    print(_table(rows, ["JOB", "PHASE", "GEN", "SLICES", "STEP",
                        "STEPS/S", "GOODPUT"]))
    frag = gp.fragmentation(gp._slice_stats_from_cluster(
        cluster.nodes.values(), cluster.pods.values()))
    if frag:
        print()
        print(_table(
            [[gen, doc["idle_chips"], doc["largest_block_chips"],
              doc["index"]] for gen, doc in sorted(frag.items())],
            ["GENERATION", "IDLE-CHIPS", "LARGEST-BLOCK", "FRAG-INDEX"]))
    if pending_by_queue:
        print()
        print(_table(
            [[q, doc["gangs"], f"{doc['age_s']:.1f}"]
             for q, doc in sorted(pending_by_queue.items())],
            ["QUEUE", "PENDING-GANGS", "OLDEST-AGE-S"]))


def cmd_bandwidth(cluster, args):
    """Per-pod DCN usage as the agents measured it (BandwidthReport
    store, api/netusage.py): node summary line + per-pod rates,
    watermarks and violation tallies.  Works against a state file or
    a live server (the mirror carries the bandwidthreport kind)."""
    reports = getattr(cluster, "bandwidthreports", {})
    rows, summary = [], []
    for name in sorted(reports):
        rep = reports[name]
        if args.node and name != args.node:
            continue
        for u in rep.usages:
            rows.append([
                rep.node, u.pod_key, u.tier,
                f"1:{u.classid}" if u.classid else "-",
                f"{u.tx_mbps:g}", f"{u.rx_mbps:g}",
                f"{u.watermark_mbps:g}" if u.watermark_mbps else "-",
                ("VIOLATING" if u.violating else
                 (str(u.violations) if u.violations else "-")),
            ])
        summary.append([
            rep.node, f"{rep.online_tx_mbps:g}",
            f"{rep.offline_tx_mbps:g}", f"{rep.total_mbps:g}",
            rep.violations, "yes" if rep.saturated else "no"])
    print(_table(rows, ["NODE", "POD", "TIER", "CLASS", "TX-MBPS",
                        "RX-MBPS", "WATERMARK", "VIOLATIONS"]))
    if summary:
        print()
        print(_table(summary, ["NODE", "ONLINE-MBPS", "OFFLINE-MBPS",
                               "BUDGET", "VIOLATING", "SATURATED"]))


def _job_pods(cluster, namespace: str, name: str):
    """Pods belonging to job <namespace>/<name>: matched by the
    group-name annotation the job controller stamps (bare or ns/name
    form — the same key SchedulerCache uses) or by vcjob-uid
    ownership.  Never by name prefix: jobs "train" and "train-2"
    must not claim each other's pods."""
    job = getattr(cluster, "vcjobs", {}).get(f"{namespace}/{name}")
    wanted = (name, f"{namespace}/{name}")
    return [p for p in cluster.pods.values()
            if p.namespace == namespace
            and (p.annotations.get(GROUP_NAME_ANNOTATION) in wanted
                 or (job is not None and p.owner == job.uid))]


def cmd_explain(cluster, args):
    """Why is this job pending?  One place that answers without log
    grepping: the aggregated unschedulable reasons the scheduler
    publishes on the podgroup (trace.py: normalized reason ->
    distinct-node count, with a free-text sample each), the per-pod
    scheduling reasons, and the podgroup's Unschedulable condition."""
    from volcano_tpu import trace
    key = f"{args.namespace}/{args.name}"
    pg = cluster.podgroups.get(key)
    if pg is None:
        sys.exit(f"podgroup {key} not found")
    print(f"job: {key}")
    print(f"phase: {pg.phase.value}  "
          f"(minMember={pg.min_member}, queue={pg.queue})")
    doc = trace.parse_annotation(
        pg.annotations.get(trace.PENDING_REASONS_ANNOTATION, ""))
    if doc and doc.get("reasons"):
        detail = doc.get("detail", {})
        rows = [[reason, count, detail.get(reason, "")[:72]]
                for reason, count in sorted(
                    doc["reasons"].items(),
                    key=lambda kv: (-kv[1], kv[0]))]
        print(f"top unschedulable reason: {doc.get('top')}")
        print(_table(rows, ["REASON", "NODES", "SAMPLE"]))
    else:
        print("no aggregated unschedulable reasons published "
              "(job not gang-blocked, or no scheduling cycle yet)")
    for c in pg.conditions:
        if c.type == "Unschedulable" and c.status == "True":
            print(f"condition: {c.reason}: {c.message}")
    pods = [p for p in _job_pods(cluster, args.namespace, args.name)
            if SCHEDULING_REASON_ANNOTATION in p.annotations]
    if pods:
        print()
        print(_table(
            [[p.name,
              p.annotations.get(SCHEDULING_REASON_ANNOTATION, "-"),
              (p.status_message or "")[:72]]
             for p in sorted(pods, key=lambda p: p.key)[:16]],
            ["POD", "VERDICT", "MESSAGE"]))


def _phase_waterfall(cluster, pg, pods) -> None:
    """Per-pod lifecycle-phase segments from the wire annotations —
    the trace fallback that needs no live scheduler (works against a
    state file too)."""
    from volcano_tpu import trace
    rows = []
    for p in sorted(pods, key=lambda p: p.key):
        segs = trace.phase_segments(
            p.annotations, pg.annotations if pg is not None else None)
        if not segs:
            continue
        rows.append([p.name] +
                    [f"{segs.get(seg, 0.0) * 1e3:.1f}"
                     for seg, _f, _t in trace.SEGMENTS] +
                    [f"{sum(segs.values()) * 1e3:.1f}"])
    if rows:
        print(_table(rows, ["POD"] + [s.upper() + "-MS" for s, _f, _t
                                      in trace.SEGMENTS] + ["E2E-MS"]))
    else:
        print("no lifecycle stamps found (pods not yet created?)")


def cmd_trace(cluster, args):
    """Render the scheduling flight recorder for one job: session
    span waterfalls from the state server's trace ring (server mode),
    falling back to the per-pod lifecycle-phase waterfall derived
    from the stamped annotations (any mode)."""
    from urllib.parse import quote

    from volcano_tpu import trace
    key = f"{args.namespace}/{args.name}"
    pg = cluster.podgroups.get(key)
    pods = _job_pods(cluster, args.namespace, args.name)
    request = getattr(cluster, "_request", None)
    traces = []
    if request is not None:
        try:
            payload = request(
                "GET", f"/traces?job={quote(key, safe='')}"
                       f"&limit={args.last}")
            traces = payload.get("traces", [])
        except Exception as e:  # noqa: BLE001 — fall back to phases
            print(f"(trace ring unavailable: {e})", file=sys.stderr)
    if traces:
        for t in traces[-args.last:]:
            print(f"-- session seq={t.get('seq')} "
                  f"kept={t.get('kept_because')} --")
            for line in trace.render_waterfall(t.get("root", {})):
                print(line)
            pending = t.get("pending", {}).get(key)
            if pending and pending.get("reasons"):
                print(f"   pending: {pending['reasons']}")
            print()
    print("lifecycle phases (from wire annotations):")
    _phase_waterfall(cluster, pg, pods)


def cmd_shards(cluster, args):
    """Shard topology of both planes in one view: which subtrees (and
    how many hosts) each scheduler shard owns under the deterministic
    partition, the latest measured cycle time per scheduler shard
    (trace ring, root label `shard`), and per-leader-group write QPS
    (/durability rv deltas sampled twice).  Works against a single
    server or a semicolon-partitioned endpoint list."""
    import time as _time

    from volcano_tpu import shardmap

    count = args.shard_count
    if count is None:
        count = len(getattr(cluster, "groups", ())) or 1
    subtrees = shardmap.subtree_map(cluster.nodes.values())
    plan = shardmap.plan_partition(subtrees, max(1, count))
    print(_table(
        [[r["shard"], len(r["subtrees"]), r["hosts"],
          ", ".join(r["subtrees"][:4])
          + (" ..." if len(r["subtrees"]) > 4 else "")]
         for r in plan],
        ["SHARD", "SUBTREES", "HOSTS", "OWNS"]))

    request = getattr(cluster, "_request", None)
    if request is None:
        return
    # per-scheduler-shard cycle time: latest kept trace per root
    # `shard` label; every sharded scheduler stamps it (scheduler.py)
    try:
        traces = request("GET", "/traces?limit=64").get("traces", [])
    except Exception as e:  # noqa: BLE001 — observability only
        print(f"(trace ring unavailable: {e})", file=sys.stderr)
        traces = []
    latest = {}
    for t in traces:
        root = t.get("root") or {}
        shard = (root.get("labels") or {}).get("shard") or "unsharded"
        latest[shard] = (root.get("dur", 0.0),
                         (root.get("labels") or {}).get("cycle"))
    if latest:
        print()
        print(_table(
            [[shard, f"{dur * 1e3:.1f}ms", cycle]
             for shard, (dur, cycle) in sorted(latest.items())],
            ["SCHED-SHARD", "CYCLE-TIME", "CYCLE"]))

    # per-leader-group write QPS: rv is the server's monotonic write
    # counter, so two /durability samples give writes/second
    groups = list(getattr(cluster, "groups", ())) or [cluster]
    samples = []
    for g in groups:
        try:
            samples.append(g._request("GET", "/durability").get("rv", 0))
        except Exception:  # noqa: BLE001
            samples.append(None)
    t0 = _time.time()
    _time.sleep(max(0.05, args.interval))
    rows = []
    for i, g in enumerate(groups):
        label = "meta+nodes" if len(groups) > 1 and i == 0 else "nodes"
        if len(groups) == 1:
            label = "all"
        try:
            rv = g._request("GET", "/durability").get("rv", 0)
        except Exception as e:  # noqa: BLE001
            rows.append([i, label, f"unreachable: {e}", "-"])
            continue
        before = samples[i]
        qps = "-" if before is None else \
            f"{(rv - before) / max(1e-9, _time.time() - t0):.1f}"
        rows.append([i, label, rv, qps])
    print()
    print(_table(rows, ["GROUP", "KEYSPACE", "RV", "WRITE-QPS"]))


def cmd_regions(cluster, args):
    """Federation region registry (the `region` dict-kind on the
    GLOBAL store): one row per regional plane with its advertised
    price/locality and the router-folded liveness + capacity.  --add /
    --remove edit the registry; the router attaches/detaches on its
    next pass."""
    import time as _time

    from volcano_tpu.api import federation as fedapi

    if args.add:
        name, _, url = args.add.partition("=")
        if not url:
            sys.exit("--add wants NAME=URL")
        rec = fedapi.region_record(
            name, url, price=args.price, locality=args.locality,
            mirror_url=args.mirror_url, metrics_url=args.metrics_url)
        cluster.put_object("region", rec, key=name)
        print(f"region {name} registered at {url}")
        return
    if args.remove:
        cluster.delete_object("region", args.remove)
        print(f"region {args.remove} removed")
        return
    rows = []
    now = _time.time()
    for name, rec in sorted(cluster.regions.items()):
        try:
            age = now - float(rec.get("heartbeat_ts", 0) or 0)
        except (TypeError, ValueError):
            age = float("inf")
        stale = rec.get("mirror_staleness_s")
        rows.append([
            name, rec.get("state", "?"), rec.get("url", ""),
            f"{float(rec.get('price', 1.0) or 1.0):g}",
            rec.get("locality", "") or "-",
            f"{float(rec.get('capacity_chips', 0) or 0):g}",
            f"{float(rec.get('idle_chips', 0) or 0):g}",
            f"{age:.0f}s" if age < 1e6 else "never",
            "-" if stale is None else f"{float(stale):.1f}s",
        ])
    print(_table(rows, ["REGION", "STATE", "URL", "PRICE", "LOCALITY",
                        "CAP-CHIPS", "IDLE-CHIPS", "HEARTBEAT",
                        "STALENESS"]))


def cmd_routers(cluster, args):
    """Router replica-set status from the GLOBAL store: who holds the
    term-fenced `federation-router` lease (and at what term), plus —
    per region — the leaseholder's circuit-breaker verdict folded
    into the registry record and, when the regional plane is
    reachable, its fence floor and refused-write count (the deposed-
    router evidence trail)."""
    from volcano_tpu.api import federation as fedapi

    lease = {}
    try:
        lease = (cluster.leases() or {}).get(
            fedapi.ROUTER_LEASE_NAME) or {}
    except (AttributeError, OSError, ValueError):
        pass                    # state-file mode: no lease surface
    expires = float(lease.get("expires_in", 0) or 0)
    if lease and expires > 0:
        print(f"leaseholder: {lease.get('holder')}  "
              f"term {lease.get('term', 0)}  "
              f"(expires in {expires:.1f}s)")
    else:
        print("leaseholder: NONE (lease vacant — regions run "
              "autonomously, global admission queues)")
    rows = []
    for name, rec in sorted(cluster.regions.items()):
        fence = refused = "-"
        url = rec.get("url", "")
        if url:
            try:
                from volcano_tpu.cache.remote_cluster import \
                    RemoteCluster
                rc = RemoteCluster(url, retry_deadline=2.0)
                try:
                    f = (rc.fences() or {}).get(
                        fedapi.ROUTER_LEASE_NAME) or {}
                    fence = str(f.get("term", 0))
                    refused = str(f.get("refused", 0))
                finally:
                    rc.close()
            except (OSError, ValueError):
                fence = refused = "unreachable"
        rows.append([
            name, rec.get("state", "?"),
            rec.get("router_breaker", "-"),
            fence, refused,
        ])
    print(_table(rows, ["REGION", "STATE", "BREAKER", "FENCE-TERM",
                        "FENCED-WRITES"]))


def cmd_timeline(cluster, args):
    """The federated causal timeline of ONE job: resolve its episode
    ID from the global job's annotations, fetch the stitched cross-
    plane span tree (`fleet_trace` dict-kind, written by the
    leaseholder router's stitcher), and render it as a waterfall plus
    the per-hop wait/active segment breakdown."""
    from volcano_tpu import trace
    from volcano_tpu.api import federation as fedapi

    episode = args.episode
    if not episode:
        key = f"{args.namespace}/{args.name}"
        job = cluster.vcjobs.get(key)
        if job is None:
            sys.exit(f"no global job {key}")
        episode = fedapi.episode_of(job)
        if not episode:
            sys.exit(f"{key} carries no episode annotation (pre-"
                     f"episode job, or not yet admitted by a router)")
    doc = None
    request = getattr(cluster, "_request", None)
    if request is not None:
        try:
            payload = request("GET", f"/fleet_trace?episode={episode}")
            doc = payload.get("trace")
        except Exception as e:  # noqa: BLE001 — fall back to mirror
            print(f"(/fleet_trace unavailable: {e})", file=sys.stderr)
    if doc is None:
        doc = getattr(cluster, "fleet_traces", {}).get(episode)
    if not doc:
        sys.exit(f"no stitched trace for episode {episode} yet "
                 f"(the leaseholder router stitches once per pass)")
    print(f"episode {episode}  wall {doc.get('wall_s', 0.0):.3f}s  "
          f"planes {', '.join(doc.get('planes', []))}  "
          f"hops {doc.get('hops', [])}")
    if doc.get("jobs"):
        print(f"jobs: {', '.join(doc['jobs'])}")
    print()
    for line in trace.render_waterfall(doc.get("root", {})):
        print(line)
    segments = doc.get("segments") or {}
    if segments:
        print()
        print(_table(
            [[seg, f"{v * 1e3:.1f}ms"]
             for seg, v in sorted(segments.items())],
            ["SEGMENT", "DURATION"]))


def cmd_slo(cluster, args):
    """Fleet SLO burn rates from the durable doc the leaseholder
    router writes each observability pass (`slo` dict-kind, key
    `global`): per SLO x window the burn rate (budget spend speed;
    sustained > 1.0 means the SLO will be missed), the good-poll
    fraction and the sample count."""
    doc = getattr(cluster, "slos", {}).get("global")
    if not doc:
        sys.exit("no SLO doc on the global store yet (the leaseholder "
                 "router writes it once regions expose /metrics)")
    import time as _time
    age = _time.time() - float(doc.get("ts", 0) or 0)
    if 0 <= age < 1e6:
        print(f"as of {age:.0f}s ago")
    rows = []
    for slo, rec in sorted((doc.get("slos") or {}).items()):
        for window, w in sorted((rec.get("windows") or {}).items()):
            good = w.get("good_frac")
            rows.append([
                slo, f"{rec.get('target', 0):g}",
                f"{rec.get('budget', 0):g}", window,
                f"{w.get('burn', 0.0):.2f}",
                "-" if good is None else f"{good:.3f}",
                w.get("polls", 0),
            ])
    print(_table(rows, ["SLO", "TARGET", "BUDGET", "WINDOW", "BURN",
                        "GOOD-FRAC", "POLLS"]))


def cmd_federate(cluster, args):
    """Federated fleet view from the GLOBAL store alone: every global
    job with its admitted region, router-folded regional phase and
    migration provenance.  --migrate stamps the cross-region evacuate
    trigger; --drain/--undrain cordon a whole region (the router
    evacuates its running gangs — follow-the-sun)."""
    from volcano_tpu.api import federation as fedapi

    if args.drain or args.undrain:
        name = args.drain or args.undrain
        rec = dict(cluster.regions.get(name) or {})
        if not rec:
            sys.exit(f"unknown region {name}")
        rec["state"] = fedapi.REGION_STATE_DRAINING if args.drain \
            else fedapi.REGION_STATE_READY
        cluster.put_object("region", rec, key=name)
        print(f"region {name} -> {rec['state']}")
        return
    if args.migrate:
        ns, _, name = args.migrate.rpartition("/")
        key = f"{ns or 'default'}/{name}"
        job = cluster.vcjobs.get(key)
        if job is None:
            sys.exit(f"unknown global job {key}")
        job.annotations[fedapi.FED_EVACUATE_ANNOTATION] = \
            args.to or "auto"
        cluster.update_vcjob(job)
        print(f"migration requested: {key} -> {args.to or 'auto'}")
        return
    rows = []
    for job in sorted(cluster.vcjobs.values(), key=lambda j: j.key):
        if fedapi.home_key(job) is not None:
            continue            # a regional copy, not a global record
        region = fedapi.admitted_region(job) or "-"
        evac = job.annotations.get(
            fedapi.FED_EVACUATING_TO_ANNOTATION) or \
            job.annotations.get(fedapi.FED_EVACUATE_ANNOTATION)
        rows.append([
            job.key, job.phase.value, region,
            job.annotations.get(
                fedapi.FED_REGIONAL_PHASE_ANNOTATION, "-"),
            fedapi.migration_count(job),
            job.annotations.get(fedapi.FED_MIGRATED_FROM_ANNOTATION,
                                "-"),
            f"->{evac}" if evac else "-",
            ",".join(fedapi.data_locality(job)) or "-",
        ])
    print(_table(rows, ["JOB", "PHASE", "REGION", "REGIONAL-PHASE",
                        "MOVES", "FROM", "EVACUATING", "LOCALITY"]))


def cmd_server(cluster, args):
    """Durability + lease status of the live state server (GET
    /durability, GET /leases): whether writes are journaled, how much
    WAL a crash would replay, when the last snapshot landed, and who
    holds the control-plane leases.  Server mode only — a state file
    has no server to ask."""
    if not getattr(args, "server", ""):
        print("server status needs --server URL", file=sys.stderr)
        return
    dur = cluster._request("GET", "/durability")
    rows = [["epoch", dur.get("epoch", "-")],
            ["rv", dur.get("rv")],
            ["visible-rv", dur.get("visible_rv")],
            ["durable", "yes" if dur.get("enabled") else
             "NO (kill -9 loses state)"]]
    if dur.get("readonly"):
        # the degraded state an operator must see first: writes are
        # 503ing until the heal loop clears the poison
        rows.insert(0, ["READ-ONLY", dur["readonly"]])
    if dur.get("enabled"):
        age = dur.get("snapshot_age_s")
        rows += [
            ["data-dir", dur.get("dir", "-")],
            ["wal-records", dur.get("wal_records")],
            ["wal-bytes", dur.get("wal_bytes")],
            ["synced-rv", dur.get("synced_rv")],
            ["snapshot-rv", dur.get("snapshot_rv")],
            ["snapshot-age", f"{age:.1f}s" if age is not None else
             "never"],
            ["last-fsync", f"{dur.get('last_fsync_s', 0) * 1e3:.2f}ms"],
            ["boot-replay", f"{dur.get('replay_records')} records in "
             f"{dur.get('replay_seconds')}s"],
        ]
    rep = dur.get("replication")
    if rep:
        # the divergence an operator must see BEFORE it pages them:
        # who leads at what term, how far each replica trails, and
        # whether the commit quorum is holding
        rows += [
            ["repl-role", f"{rep.get('role')} (term {rep.get('term')},"
             f" id {rep.get('replica_id')})"],
            ["repl-leader", rep.get("leader") or "-"],
            ["repl-applied", f"rv {rep.get('applied_rv')} / seq "
             f"{rep.get('applied_seq')}"],
            ["repl-lag", f"{rep.get('lag_s', 0):.3f}s"],
            ["repl-quorum", f"commit={rep.get('commit_quorum')} "
             + ("ok" if rep.get("quorum_ok", True) else
                "LOST (writes 503)")],
            ["repl-promotions", rep.get("promotions")],
        ]
        if rep.get("role") == "leader":
            rows.append(["last-shipped", f"rv {rep.get('last_shipped_rv')}"])
            for fid, f in sorted((rep.get("followers") or {}).items()):
                rows.append(
                    [f"follower/{fid}",
                     f"applied rv {f.get('applied_rv')} "
                     f"(acked {f.get('ack_age_s', 0):.1f}s ago)"])
    print(_table([[k, str(v)] for k, v in rows], ["FIELD", "VALUE"]))
    leases = cluster._request("GET", "/leases")
    if leases:
        print()
        print(_table(
            [[n, l["holder"], str(l.get("term", 0)),
              f"{l['expires_in']:.1f}s"]
             for n, l in sorted(leases.items())],
            ["LEASE", "HOLDER", "TERM", "EXPIRES-IN"]))


def cmd_tick(cluster, args):
    """Run controllers + one scheduling cycle + kubelet tick.

    Against a live server (--server) the running control plane owns
    scheduling and reconciliation — ticking locally with a stale,
    watch-less mirror would push wrong status back — so only the
    kubelet simulation is advanced there."""
    if getattr(args, "server", ""):
        for _ in range(args.cycles):
            cluster.tick()
        cluster.resync()
        bound = sum(1 for p in cluster.pods.values() if p.node_name)
        print(f"ticked {args.cycles} time(s): {bound} pods placed")
        return
    from volcano_tpu.controllers import ControllerManager
    from volcano_tpu.scheduler import Scheduler
    mgr = ControllerManager(cluster, enabled=[
        "job", "podgroup", "queue", "hypernode", "garbagecollector",
        "jobflow", "jobtemplate", "cronjob"])
    sched = Scheduler(cluster, schedule_period=0)
    for _ in range(args.cycles):
        mgr.sync_all()
        sched.run_once()
        cluster.tick()
    mgr.stop()
    bound = sum(1 for p in cluster.pods.values() if p.node_name)
    print(f"ran {args.cycles} cycle(s): {bound} pods placed")


def _add_job_run_args(p) -> None:
    """Shared by `job run` and its slurm-style alias `vsub`."""
    p.add_argument("-N", "--name", required=True)
    p.add_argument("-n", "--namespace", default="default")
    p.add_argument("--replicas", type=int, default=1)
    p.add_argument("--min-available", type=int, default=None)
    p.add_argument("--task-name", default="worker")
    p.add_argument("--queue", default="default")
    p.add_argument("--image", default="busybox")
    p.add_argument("--cpu", default="1")
    p.add_argument("--tpu", type=int, default=0)
    p.add_argument("--plugins", default="")


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="vtpctl",
        description="volcano-tpu batch scheduling CLI")
    parser.add_argument("--state", default="vtpctl-cluster.pkl",
                        help="cluster state file (standalone mode)")
    parser.add_argument("--server", default="",
                        help="state-server URL (kubectl mode: talk to "
                             "the live control plane instead of a "
                             "state file)")
    parser.add_argument("--token", default="",
                        help="cluster bearer token (required for ALL "
                             "state-server routes when configured)")
    parser.add_argument("--token-file", default="")
    parser.add_argument("--ca-cert", default="",
                        help="CA bundle to verify an https server")
    parser.add_argument("--insecure", action="store_true",
                        help="skip server cert verification")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("init", help="provision simulated TPU slices")
    p.add_argument("--slices", nargs="*",
                   help="name=kind, e.g. sa=v5e-16")
    p.add_argument("--dcn-pod", default="dcn-0")
    p.set_defaults(fn=cmd_init)

    job = sub.add_parser("job", help="job operations").add_subparsers(
        dest="job_cmd", required=True)
    p = job.add_parser("run")
    _add_job_run_args(p)
    p.set_defaults(fn=cmd_job_run)
    p = job.add_parser("create", help="create job(s) from a YAML manifest")
    p.add_argument("-f", "--filename", required=True)
    p.set_defaults(fn=cmd_job_create)
    p = job.add_parser("list")
    p.add_argument("-n", "--namespace", default=None)
    p.set_defaults(fn=cmd_job_list)
    p = job.add_parser("view")
    p.add_argument("-N", "--name", required=True)
    p.add_argument("-n", "--namespace", default="default")
    p.set_defaults(fn=cmd_job_view)
    p = job.add_parser("delete")
    p.add_argument("-N", "--name", required=True)
    p.add_argument("-n", "--namespace", default="default")
    p.set_defaults(fn=cmd_job_delete)
    for verb, action in (("suspend", "AbortJob"), ("resume", "ResumeJob"),
                         ("restart", "RestartJob"),
                         ("complete", "CompleteJob")):
        p = job.add_parser(verb)
        p.add_argument("-N", "--name", required=True)
        p.add_argument("-n", "--namespace", default="default")
        p.set_defaults(fn=lambda c, a, _act=action: cmd_job_command(c, a, _act))

    jobtemplate = sub.add_parser(
        "jobtemplate", help="jobtemplate operations").add_subparsers(
        dest="jobtemplate_cmd", required=True)
    p = jobtemplate.add_parser("create")
    p.add_argument("-f", "--filename", required=True,
                   help="Job manifest(s) stored as templates")
    p.set_defaults(fn=cmd_jobtemplate_create)
    p = jobtemplate.add_parser("list")
    p.set_defaults(fn=cmd_jobtemplate_list)
    for verb, fn in (("get", cmd_jobtemplate_get),
                     ("describe", cmd_jobtemplate_describe),
                     ("delete", cmd_jobtemplate_delete)):
        p = jobtemplate.add_parser(verb)
        p.add_argument("-N", "--name", required=True)
        p.add_argument("-n", "--namespace", default="default")
        p.set_defaults(fn=fn)

    jobflow = sub.add_parser("jobflow",
                             help="jobflow operations").add_subparsers(
        dest="jobflow_cmd", required=True)
    p = jobflow.add_parser("create")
    p.add_argument("-N", "--name", required=True)
    p.add_argument("-n", "--namespace", default="default")
    p.add_argument("--flows", nargs="+", required=True,
                   help='steps as "template" or "template:dep1+dep2"')
    p.add_argument("--retain-policy", choices=["retain", "delete"],
                   default="retain",
                   help="what happens to stamped jobs when the flow "
                        "succeeds or is deleted (jobRetainPolicy)")
    p.set_defaults(fn=cmd_jobflow_create)
    p = jobflow.add_parser("list")
    p.set_defaults(fn=cmd_jobflow_list)
    for verb, fn in (("get", cmd_jobflow_get),
                     ("describe", cmd_jobflow_describe),
                     ("delete", cmd_jobflow_delete)):
        p = jobflow.add_parser(verb)
        p.add_argument("-N", "--name", required=True)
        p.add_argument("-n", "--namespace", default="default")
        p.set_defaults(fn=fn)

    queue = sub.add_parser("queue", help="queue operations").add_subparsers(
        dest="queue_cmd", required=True)
    p = queue.add_parser("create")
    p.add_argument("-N", "--name", required=True)
    p.add_argument("--weight", type=int, default=1)
    p.add_argument("--parent", default="")
    p.add_argument("--capability", default="",
                   help='JSON resource list, e.g. \'{"cpu": 10}\'')
    p.set_defaults(fn=cmd_queue_create)
    p = queue.add_parser("list")
    p.set_defaults(fn=cmd_queue_list)
    p = queue.add_parser("operate", help="open/close a queue")
    p.add_argument("-N", "--name", required=True)
    p.add_argument("--action", choices=["open", "close"], required=True)
    p.set_defaults(fn=cmd_queue_operate)
    p = queue.add_parser("get", help="detailed queue view")
    p.add_argument("-N", "--name", required=True)
    p.set_defaults(fn=cmd_queue_get)
    p = queue.add_parser("delete")
    p.add_argument("-N", "--name", required=True)
    p.add_argument("--force", action="store_true",
                   help="delete even with podgroups still enqueued")
    p.set_defaults(fn=cmd_queue_delete)

    pod = sub.add_parser("pod", help="pod operations").add_subparsers(
        dest="pod_cmd", required=True)
    p = pod.add_parser("list")
    p.add_argument("-n", "--namespace", default=None)
    p.set_defaults(fn=cmd_pod_list)
    p = pod.add_parser("describe", help="pod state + scheduling "
                       "reason + server audit history")
    p.add_argument("-N", "--name", required=True)
    p.add_argument("-n", "--namespace", default="default")
    p.set_defaults(fn=cmd_pod_describe)

    node = sub.add_parser("node", help="node operations").add_subparsers(
        dest="node_cmd", required=True)
    p = node.add_parser("list")
    p.set_defaults(fn=cmd_node_list)
    p = node.add_parser("view")
    p.add_argument("-N", "--name", required=True)
    p.set_defaults(fn=cmd_node_view)

    p = sub.add_parser("bandwidth", help="per-pod DCN usage as the "
                       "agents measured it (rates, watermarks, "
                       "violations)")
    p.add_argument("--node", default="",
                   help="limit to one node's report")
    p.set_defaults(fn=cmd_bandwidth)

    p = sub.add_parser("slices", help="per-slice host/health rollup "
                       "(HEALTH + QUARANTINED-UNTIL from the folded "
                       "SliceHealthReports)")
    p.set_defaults(fn=cmd_slices)

    p = sub.add_parser("failover", help="slice-failover view: sick "
                       "hosts, drained gangs, resume metadata")
    p.set_defaults(fn=cmd_failover)

    p = sub.add_parser("elastic", help="elastic gangs: current/min/"
                       "max slices, in-flight resizes, history — or "
                       "trigger a live migration off a gang's "
                       "current slices")
    p.add_argument("--migrate", default="",
                   help="<ns>/<name> (or name): drain this elastic "
                        "gang and re-place it on DIFFERENT slices at "
                        "the same world size")
    p.set_defaults(fn=cmd_elastic)

    p = sub.add_parser("goodput", help="one job's measured "
                       "throughput: step rate, goodput = productive/"
                       "allocated, per-pod progress, resize history")
    p.add_argument("name", help="job / podgroup name")
    p.add_argument("-n", "--namespace", default="default")
    p.set_defaults(fn=cmd_goodput)

    p = sub.add_parser("serve", help="serving groups: replicas "
                       "cur/min/max, folded QPS and p99 vs SLO, "
                       "last autoscaler decision + age, per-replica "
                       "agent rates")
    p.add_argument("name", nargs="?", default="",
                   help="serving group name (omit to list all)")
    p.add_argument("-n", "--namespace", default="default")
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser("fleet", help="fleet observatory rollup: "
                       "per-job measured steps/s + goodput, ICI "
                       "fragmentation per generation, pending gangs "
                       "per queue")
    p.set_defaults(fn=cmd_fleet)

    p = sub.add_parser("explain", help="why is this job pending: "
                       "aggregated unschedulable reasons (normalized "
                       "reason -> node count) + per-pod verdicts")
    p.add_argument("name", help="job / podgroup name")
    p.add_argument("-n", "--namespace", default="default")
    p.set_defaults(fn=cmd_explain)

    p = sub.add_parser("trace", help="scheduling flight recorder: "
                       "session span waterfalls (server mode) + the "
                       "per-pod lifecycle phase breakdown")
    p.add_argument("name", help="job / podgroup name")
    p.add_argument("-n", "--namespace", default="default")
    p.add_argument("--last", type=int, default=3,
                   help="how many kept session traces to render")
    p.set_defaults(fn=cmd_trace)

    p = sub.add_parser("server", help="state-server durability, "
                       "replication + lease status (WAL/snapshot/"
                       "replay, role/term/lag; needs --server)")
    p.set_defaults(fn=cmd_server)

    p = sub.add_parser("shards", help="shard topology: subtree "
                       "ownership per scheduler shard, per-shard "
                       "cycle time, per-leader-group write QPS")
    p.add_argument("--shard-count", type=int, default=None,
                   help="scheduler shards to plan for (default: the "
                        "number of leader groups in --server)")
    p.add_argument("--interval", type=float, default=1.0,
                   help="seconds between the two write-QPS samples")
    p.set_defaults(fn=cmd_shards)

    p = sub.add_parser("timeline", help="federated causal timeline: "
                       "the stitched cross-plane span tree of one "
                       "episode (router admit -> regional placement "
                       "-> cutover -> resume), waterfall + per-hop "
                       "segments")
    p.add_argument("name", nargs="?", default="",
                   help="global job name (resolves its episode)")
    p.add_argument("-n", "--namespace", default="default")
    p.add_argument("--episode", default="",
                   help="episode ID directly (skips job lookup)")
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser("slo", help="fleet SLO burn rates: per SLO x "
                       "window budget-spend speed from the router's "
                       "durable burn doc")
    p.set_defaults(fn=cmd_slo)

    p = sub.add_parser("regions", help="federation region registry: "
                       "liveness, price, capacity, mirror staleness "
                       "per regional plane")
    p.add_argument("--add", default="",
                   help="register a region: NAME=URL")
    p.add_argument("--price", type=float, default=1.0)
    p.add_argument("--locality", default="")
    p.add_argument("--mirror-url", default="")
    p.add_argument("--metrics-url", default="",
                   help="region /metrics base URL (enables the "
                        "router's rollup + SLO scrape)")
    p.add_argument("--remove", default="",
                   help="deregister a region by name")
    p.set_defaults(fn=cmd_regions)

    p = sub.add_parser("routers", help="router replica set: lease "
                       "term + holder, per-region breaker state and "
                       "fence floors")
    p.set_defaults(fn=cmd_routers)

    p = sub.add_parser("federate", help="federated fleet view; "
                       "cross-region migration and region drain")
    p.add_argument("--migrate", default="",
                   help="global job ([ns/]name) to move cross-region")
    p.add_argument("--to", default="",
                   help="destination region for --migrate "
                        "(default: auto-pick)")
    p.add_argument("--drain", default="",
                   help="cordon a region: evacuate its running gangs")
    p.add_argument("--undrain", default="",
                   help="reopen a drained region")
    p.set_defaults(fn=cmd_federate)

    p = sub.add_parser("tick",
                       help="advance the standalone control plane")
    p.add_argument("--cycles", type=int, default=1)
    p.set_defaults(fn=cmd_tick)

    # slurm-style shortcuts (reference standalone binaries vsub/vjobs/
    # vqueues/vcancel/vsuspend/vresume, Makefile:281)
    p = sub.add_parser("vjobs", help="alias of: job list")
    p.add_argument("-n", "--namespace", default=None)
    p.set_defaults(fn=cmd_job_list)
    p = sub.add_parser("vqueues", help="alias of: queue list")
    p.set_defaults(fn=cmd_queue_list)
    p = sub.add_parser("vsub", help="alias of: job run")
    _add_job_run_args(p)
    p.set_defaults(fn=cmd_job_run)
    p = sub.add_parser("vcancel", help="alias of: job delete")
    p.add_argument("-N", "--name", required=True)
    p.add_argument("-n", "--namespace", default="default")
    p.set_defaults(fn=cmd_job_delete)
    for verb, action in (("vsuspend", "AbortJob"),
                         ("vresume", "ResumeJob")):
        p = sub.add_parser(verb, help=f"alias of: job {verb[1:]}")
        p.add_argument("-N", "--name", required=True)
        p.add_argument("-n", "--namespace", default="default")
        p.set_defaults(fn=lambda c, a, _act=action: cmd_job_command(
            c, a, _act))

    return parser


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.server:
        # kubectl mode: reads come from the watch-bootstrapped mirror,
        # writes hit the live server; no state file is touched
        from volcano_tpu.cache.remote_cluster import RemoteCluster
        from volcano_tpu.server.tlsutil import load_token
        # `vtpctl server` is the incident command: it reads only
        # /durability + /leases, and a READ-ONLY (degraded) server
        # 503s the /snapshot bootstrap — the status view must not
        # block behind the mirror it never uses
        tolerant = getattr(args, "fn", None) is cmd_server
        if ";" in args.server:
            # keyspace-partitioned plane: semicolon-separated leader
            # groups — reads merge every group's mirror
            from volcano_tpu.cache.partitioned import PartitionedCluster
            cluster = PartitionedCluster(
                args.server, start_watch=False,
                token=load_token(args.token, args.token_file),
                ca_cert=args.ca_cert, insecure=args.insecure,
                tolerate_unreachable=tolerant)
        else:
            cluster = RemoteCluster(
                args.server, start_watch=False,
                tolerate_unreachable=tolerant,
                token=load_token(args.token, args.token_file),
                ca_cert=args.ca_cert, insecure=args.insecure)
    else:
        cluster = _load(args.state)
    from volcano_tpu.webhooks import AdmissionError
    try:
        args.fn(cluster, args)
    except AdmissionError as e:
        print(f"admission denied: {e}", file=sys.stderr)
        return 1
    except BrokenPipeError:
        # output piped into head etc.; state still saved below
        pass
    if args.server:
        cluster.close()
    else:
        _save(cluster, args.state)
    return 0


# global connection flags (defined on the root parser) and their
# value arity — the alias mains hoist these in front of the verb so
# `vjobs --server URL` works the way a standalone binary should
_GLOBAL_FLAGS = {"--state": 1, "--server": 1, "--token": 1,
                 "--token-file": 1, "--ca-cert": 1, "--insecure": 0}


def _alias_main(verb: str):
    """Standalone slurm-style binary (reference builds vsub/vcancel/
    vsuspend/vresume/vjobs/vqueues as separate binaries, Makefile:281):
    each console script is the vtpctl verb with argv passed through."""
    def _main() -> int:
        args = list(sys.argv[1:])
        pre, post, i = [], [], 0
        while i < len(args):
            name = args[i].split("=", 1)[0]
            if name in _GLOBAL_FLAGS:
                pre.append(args[i])
                if _GLOBAL_FLAGS[name] and "=" not in args[i] \
                        and i + 1 < len(args):
                    i += 1
                    pre.append(args[i])
            else:
                post.append(args[i])
            i += 1
        return main([*pre, verb, *post])
    _main.__name__ = verb
    return _main


vsub_main = _alias_main("vsub")
vcancel_main = _alias_main("vcancel")
vsuspend_main = _alias_main("vsuspend")
vresume_main = _alias_main("vresume")
vjobs_main = _alias_main("vjobs")
vqueues_main = _alias_main("vqueues")


if __name__ == "__main__":
    sys.exit(main())
