"""YAML job manifests — the vcjob schema, TPU-native.

Reference parity: example/job.yaml + `vcctl job run -f`.  The schema
mirrors batch/v1alpha1 with TPU-first fields:

    apiVersion: batch.volcano-tpu.io/v1alpha1
    kind: Job
    metadata: {name: train, namespace: default}
    spec:
      minAvailable: 4
      queue: research
      schedulerName: volcano-tpu
      plugins: {jax: [], svc: [], env: []}
      policies:
        - event: PodFailed
          action: RestartJob
      networkTopology: {mode: hard, highestTierAllowed: 1}
      tasks:
        - name: worker
          replicas: 4
          minAvailable: 4
          subGroup: rep0                 # optional subgroup gang
          policies: []
          template:
            spec:
              containers:
                - name: main
                  image: my-trainer
                  command: ["python", "train.py"]
                  resources:
                    requests: {cpu: 8, memory: 16Gi, google.com/tpu: 4}
              nodeSelector: {}
              tolerations: []
"""

from __future__ import annotations

from typing import List

import yaml

from volcano_tpu.api.pod import Container, Pod, Toleration
from volcano_tpu.api.podgroup import NetworkTopologySpec
from volcano_tpu.api.types import JobAction, JobEvent, NetworkTopologyMode
from volcano_tpu.api.vcjob import DependsOn, LifecyclePolicy, TaskSpec, VCJob


class ManifestError(ValueError):
    pass


def _policies(raw: List[dict]) -> List[LifecyclePolicy]:
    out = []
    for p in raw or []:
        try:
            event = JobEvent(p["event"]) if "event" in p else None
            events = [JobEvent(e) for e in p.get("events", [])]
            action = JobAction(p["action"])
        except (KeyError, ValueError) as e:
            raise ManifestError(f"invalid policy {p!r}: {e}") from e
        out.append(LifecyclePolicy(
            action=action, event=event, events=events,
            exit_code=p.get("exitCode"),
            timeout_seconds=p.get("timeout")))
    return out


def _pod_template(raw: dict) -> Pod:
    spec = (raw or {}).get("spec", raw or {})
    containers = []
    for c in spec.get("containers", [{}]):
        resources = c.get("resources", {})
        env = {}
        for e in c.get("env", []):
            if "name" not in e:
                raise ManifestError(f"env entry missing name: {e!r}")
            if "valueFrom" in e:
                raise ManifestError(
                    f"env valueFrom is not supported in the standalone "
                    f"runtime (entry {e['name']!r}); use a literal value")
            env[e["name"]] = str(e.get("value", ""))
        containers.append(Container(
            name=c.get("name", "main"),
            image=c.get("image", ""),
            command=c.get("command"),
            requests=dict(resources.get("requests", {})),
            limits=dict(resources.get("limits", {})),
            env=env,
            ports=[p.get("containerPort", p) if isinstance(p, dict) else p
                   for p in c.get("ports", [])],
        ))
    tolerations = [Toleration(
        key=t.get("key", ""), operator=t.get("operator", "Equal"),
        value=t.get("value", ""), effect=t.get("effect", ""))
        for t in spec.get("tolerations", [])]
    return Pod(name="template", containers=containers,
               node_selector=dict(spec.get("nodeSelector", {})),
               tolerations=tolerations,
               priority_class=spec.get("priorityClassName", ""))


def _task_topology(nt, default_tier=None):
    """Parse a networkTopology block (job- or task-level).

    Job level defaults highestTierAllowed to 1 (webhook-mutate parity);
    task level defaults to None = unbounded (prefer-lowest-tier)."""
    if not nt:
        return None
    try:
        raw_tier = nt.get("highestTierAllowed", default_tier)
        return NetworkTopologySpec(
            mode=NetworkTopologyMode(nt.get("mode", "hard")),
            highest_tier_allowed=None if raw_tier is None else int(raw_tier))
    except (TypeError, ValueError) as e:
        raise ManifestError(f"invalid networkTopology {nt!r}") from e


def job_from_manifest(data: dict) -> VCJob:
    if data.get("kind") != "Job":
        raise ManifestError(f"kind must be Job, got {data.get('kind')!r}")
    meta = data.get("metadata", {})
    spec = data.get("spec", {})
    if "name" not in meta:
        raise ManifestError("metadata.name is required")

    tasks = []
    for t in spec.get("tasks", []):
        if "name" not in t:
            raise ManifestError("every task needs a name")
        depends = t.get("dependsOn")
        tasks.append(TaskSpec(
            name=t["name"],
            replicas=int(t.get("replicas", 1)),
            min_available=(int(t["minAvailable"])
                           if "minAvailable" in t else None),
            template=_pod_template(t.get("template", {})),
            policies=_policies(t.get("policies", [])),
            depends_on=DependsOn(
                name=list(depends.get("name", [])),
                iteration=depends.get("iteration", "any"))
            if depends else None,
            subgroup=t.get("subGroup", ""),
            network_topology=_task_topology(t.get("networkTopology")),
        ))

    if not tasks:
        raise ManifestError("spec.tasks must declare at least one task")
    if sum(t.replicas for t in tasks) <= 0:
        raise ManifestError("total task replicas must be > 0")

    nt = spec.get("networkTopology")
    network_topology = _task_topology(nt, default_tier=1)

    plugins = spec.get("plugins", {})
    if not isinstance(plugins, dict):
        raise ManifestError("spec.plugins must be a mapping")
    for pname, pargs in plugins.items():
        if pargs is not None and not isinstance(pargs, list):
            raise ManifestError(
                f"plugin {pname!r} arguments must be a list, got "
                f"{type(pargs).__name__}")

    # reference default: minAvailable = total replicas (full gang) —
    # never 0, which would disable gang scheduling entirely
    total_replicas = sum(t.replicas for t in tasks)
    return VCJob(
        name=meta["name"],
        namespace=meta.get("namespace", "default"),
        scheduler_name=spec.get("schedulerName", "volcano-tpu"),
        min_available=int(spec.get("minAvailable", total_replicas)),
        min_success=(int(spec["minSuccess"])
                     if "minSuccess" in spec else None),
        tasks=tasks,
        policies=_policies(spec.get("policies", [])),
        plugins={k: list(v or []) for k, v in plugins.items()},
        queue=spec.get("queue", "default"),
        max_retry=int(spec.get("maxRetry", 3)),
        ttl_seconds_after_finished=spec.get("ttlSecondsAfterFinished"),
        priority_class=spec.get("priorityClassName", ""),
        network_topology=network_topology,
    )


def load_jobs(path: str) -> List[VCJob]:
    """Load one or more Job manifests from a YAML file (--- separated)."""
    with open(path) as f:
        try:
            docs = [d for d in yaml.safe_load_all(f) if d]
        except yaml.YAMLError as e:
            raise ManifestError(f"invalid YAML in {path}: {e}") from e
    if not docs:
        raise ManifestError(f"no manifests in {path}")
    for d in docs:
        if not isinstance(d, dict):
            raise ManifestError(
                f"manifest documents must be mappings, got {type(d).__name__}")
    return [job_from_manifest(d) for d in docs]
