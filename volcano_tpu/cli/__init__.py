"""CLI (reference: pkg/cli + cmd/cli vcctl)."""
