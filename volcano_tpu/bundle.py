"""Deploy bundle generator — config, units, scrape config, dashboards.

Reference parity: installer/helm/ (chart with values + CRDs) and
benchmark/manifests/monitoring/ (Grafana + Prometheus manifests for
the exported metric families).  volcano-tpu is a standalone control
plane, so its "chart" is a rendered directory an operator can run
as-is: systemd units OR a docker-compose file (both rendered from the
same values), the scheduler conf, a generated cluster bearer token,
a Prometheus scrape config that carries that token, and Grafana
dashboard JSON over the families volcano_tpu.metrics actually
exports.

    python -m volcano_tpu.bundle --out ./bundle \
        --topology sa:v5e-256,sb:v5e-256 --port 8700

renders:
    bundle/
      values.json            the resolved values (re-render input)
      token                  cluster bearer token (0600)
      scheduler.conf.yaml    actions/tiers the scheduler loads
      topology.json          slice layout consumed by cluster-init
      cluster-init.sh        registers the nodes via vtpctl
      systemd/*.service      one unit per role
      docker-compose.yaml    same roles as containers
      prometheus.yml         scrape config (bearer token wired)
      grafana/*.json         dashboards over the exported families
      README.md              bring-up order
"""

from __future__ import annotations

import argparse
import json
import os
import secrets
from typing import Dict, List

# Every metric family the control plane exports, by type — dashboards
# are generated from (and tests validated against) THIS table, so a
# renamed family breaks the build, not the operator's dashboard.
# Histogram-typed families export <name>_count / <name>_sum.
FAMILIES: Dict[str, str] = {
    # scheduler core (metrics.py call sites)
    "e2e_scheduling_latency_seconds": "histogram",
    "pod_scheduling_latency_seconds": "histogram",
    "task_scheduling_latency_seconds": "histogram",
    "predicate_sweep_seconds": "histogram",
    # process-pool sweep backend (actions/procpool.py): mirror sync
    # traffic, pool self-healing and the staleness-refusal contract
    "sweep_snapshot_delta_bytes_total": "counter",
    "sweep_worker_restarts_total": "counter",
    "sweep_stale_refusals_total": "counter",
    "action_latency_seconds": "histogram",
    "plugin_latency_seconds": "histogram",
    "open_session_duration_seconds": "histogram",
    "schedule_attempts_total": "counter",
    "unschedule_job_count": "gauge",
    "unschedule_task_count": "gauge",
    "job_retry_counts": "counter",
    # preemption / reclaim
    "pod_preemption_total": "counter",
    "preemption_victims_total": "counter",
    "gang_preemption_total": "counter",
    "pod_reclaim_total": "counter",
    "reclaim_commits_total": "counter",
    "shuffle_victims_total": "counter",
    # fair share — proportion exports deserved/allocated/request and
    # capacity exports real_capacity/inqueue/capacity/overused, each
    # resource vector as the three per-dimension gauges
    # (metrics.resource_gauge_rows); every generated name is declared
    # here or vtplint's family-coverage check fails the build
    "job_share": "gauge",
    "queue_share": "gauge",
    "queue_weight": "gauge",
    "queue_overused": "gauge",
    "queue_allocated_milli_cpu": "gauge",
    "queue_allocated_memory_bytes": "gauge",
    "queue_allocated_scalar_resources": "gauge",
    "queue_deserved_milli_cpu": "gauge",
    "queue_deserved_memory_bytes": "gauge",
    "queue_deserved_scalar_resources": "gauge",
    "queue_request_milli_cpu": "gauge",
    "queue_request_memory_bytes": "gauge",
    "queue_request_scalar_resources": "gauge",
    "queue_real_capacity_milli_cpu": "gauge",
    "queue_real_capacity_memory_bytes": "gauge",
    "queue_real_capacity_scalar_resources": "gauge",
    "queue_inqueue_milli_cpu": "gauge",
    "queue_inqueue_memory_bytes": "gauge",
    "queue_inqueue_scalar_resources": "gauge",
    "queue_capacity_milli_cpu": "gauge",
    "queue_capacity_memory_bytes": "gauge",
    "queue_capacity_scalar_resources": "gauge",
    # agent scheduler (fast path)
    "agent_pod_e2e_latency_seconds": "histogram",
    "agent_bind_conflicts_total": "counter",
    "agent_unschedulable_total": "counter",
    # audit-derived latency exporter (server/audit_exporter.py): job
    # submit -> terminal phase, the batchjob completion analogue
    "batchjob_completion_latency_seconds": "histogram",
    # client mirror resync paths (cache/remote_cluster.py): how a
    # mirror recovered — delta catch-up, refused-stale re-route, or a
    # full re-list (bounded mode enum)
    "mirror_resync_total": "counter",
    # node-agent DCN bandwidth accounting (agent/handlers.py
    # netaccounting: measured per-pod rates + watermark violations)
    "pod_dcn_tx_mbps": "gauge",
    "pod_dcn_rx_mbps": "gauge",
    "node_dcn_measured_mbps": "gauge",
    "bandwidth_violating_pods": "gauge",
    "bandwidth_violations_total": "counter",
    # slice-failure failover (controllers/failover.py): the detect ->
    # drain -> reschedule -> resume loop, each phase timed, plus the
    # end-to-end MTTR and the checkpoint recompute window
    "failover_detect_seconds": "histogram",
    "failover_drain_seconds": "histogram",
    "failover_reschedule_seconds": "histogram",
    "failover_resume_seconds": "histogram",
    "failover_mttr_seconds": "histogram",
    "failover_resume_step_gap": "histogram",
    "slice_failovers_total": "counter",
    "quarantined_slices": "gauge",
    # state-server durability (server/durability.py): the WAL journal-
    # before-ack loop, snapshot compaction cadence, and boot replay
    "server_wal_fsync_seconds": "histogram",
    "server_wal_records": "gauge",
    "server_wal_bytes": "gauge",
    "server_snapshot_seconds": "histogram",
    "server_snapshot_total": "counter",
    "server_snapshot_rv": "gauge",
    "server_replay_seconds": "histogram",
    "server_replay_records": "gauge",
    # replicated control plane (server/replication.py): shipping
    # volume, follower lag, promotions, role — labels bounded (the
    # role enum and the operator-configured replica ids, never
    # job/pod/node keys)
    "server_replication_lag_seconds": "gauge",
    "server_replication_applied_rv": "gauge",
    "server_replication_last_shipped_rv": "gauge",
    "server_replication_follower_lag_rv": "gauge",
    "server_replication_shipped_records_total": "counter",
    "server_replication_shipped_bytes_total": "counter",
    "server_replication_promotions_total": "counter",
    "server_replication_bootstraps_total": "counter",
    "server_replication_refused_batches_total": "counter",
    "server_replication_role": "gauge",
    "server_replication_term": "gauge",
    # client wire resilience: every transient retry the unified
    # backoff policy performs, labeled by route
    "client_retries_total": "counter",
    # gray-failure chaos engine (volcano_tpu/faults.py +
    # docs/design/chaos.md): every injected fault counted by bounded
    # site/kind enums, the read-only degrade flag (1 while the WAL is
    # poisoned and writes 503), and WAL records dropped by bounded
    # reason (readonly, append-error, duplicate-seq, force-truncate)
    "fault_injected_total": "counter",
    "server_readonly": "gauge",
    "server_wal_dropped_records_total": "counter",
    # scheduling flight recorder (trace.py): per-phase lifecycle
    # segments (created->enqueued->allocated->bound->admitted->
    # running, plus the telescoped e2e), span time by action/plugin,
    # kept-trace accounting, and the normalized unschedulable-reason
    # tallies (label values are the bounded REASON_ENUM — free text
    # never labels a metric)
    "sched_phase_seconds": "histogram",
    "sched_span_seconds": "histogram",
    "sched_traces_total": "counter",
    "sched_unschedulable_reasons_total": "counter",
    # sharded planes (actions/gangcommit.py + cache/partitioned.py):
    # one observation per spec drained as a batch, and every bind the
    # server's check-and-bind refused to a losing scheduler shard,
    # counted by the bounded outcome enum (refused = per-item 409,
    # requeued = the loser re-queued the gang for its next cycle)
    "sched_gang_commit_seconds": "histogram",
    "sched_cross_shard_conflicts_total": "counter",
    # elastic gangs (actions/elastic.py decisions, controllers/
    # elastic.py execution): every label is the bounded resize-kind
    # enum (grow|shrink|migrate) — job keys and slice names never
    # label these families (the PR 5 cardinality rule)
    "elastic_decisions_total": "counter",
    "elastic_resizes_total": "counter",
    "elastic_resize_seconds": "histogram",
    "elastic_drain_seconds": "histogram",
    "elastic_shrink_seconds": "histogram",
    "elastic_migration_mttr_seconds": "histogram",
    "elastic_resume_step_gap": "histogram",
    "elastic_jobs": "gauge",
    "elastic_slices_total": "gauge",
    # goodput observatory (volcano_tpu/goodput.py + agent goodput
    # handler): measured fleet throughput, learned-vector update
    # tally, grow-gate decisions, ICI fragmentation and per-queue
    # starvation — labels are bounded (generation enum,
    # allowed|declined, operator queue config; never job/pod/node)
    "goodput_jobs": "gauge",
    "goodput_fleet_steps_per_second": "gauge",
    "goodput_fraction": "gauge",
    "goodput_vector_updates_total": "counter",
    "goodput_gated_grows_total": "counter",
    "frag_index": "gauge",
    "frag_idle_chips": "gauge",
    "frag_largest_block_chips": "gauge",
    "starvation_age_seconds": "gauge",
    "starvation_pending_gangs": "gauge",
    # serving plane (controllers/serving.py + agent serving handler +
    # actions/elastic.py burst preemption): group census, folded fleet
    # QPS, worst-group SLO attainment, scale decisions (bounded
    # up|down enum) and serving-funded victim shrinks — never
    # group/pod/node labels
    "serving_groups": "gauge",
    "serving_qps_total": "gauge",
    "serving_slo_attainment_min": "gauge",
    "serving_scale_decisions_total": "counter",
    "serving_victim_shrinks_total": "counter",
    # federation tier (federation/router.py + federation/mirror.py):
    # region census by bounded state enum, per-region capacity and
    # learned goodput (region names are operator config), global-queue
    # depth, admission/requeue/migration tallies, the cutover timing
    # and its stale-mirror refusals, and the async object mirror's
    # stream accounting — job keys never label these families
    "federation_regions": "gauge",
    "federation_pending_jobs": "gauge",
    "federation_region_capacity_chips": "gauge",
    "federation_region_idle_chips": "gauge",
    "federation_region_goodput_steps_per_chip": "gauge",
    "federation_admissions_total": "counter",
    "federation_requeues_total": "counter",
    "federation_migrations_total": "counter",
    "federation_cutover_seconds": "histogram",
    "federation_cutover_refusals_total": "counter",
    "federation_source_reaps_total": "counter",
    "federation_mirror_records_total": "counter",
    "federation_mirror_resyncs_total": "counter",
    "federation_mirror_delta_resyncs_total": "counter",
    "federation_mirror_refused_batches_total": "counter",
    # router HA (federation/ha.py + federation/retry.py + the server
    # fence): leadership + lease term, adoption passes, the shared
    # cross-region RPC policy's failure/skip tallies, per-region
    # breaker state (bounded closed|open|half-open code), serving QPS
    # headroom folded into routing, and writes refused by the
    # term fence — region names and lease names are operator config
    "federation_router_is_leader": "gauge",
    "federation_router_term": "gauge",
    "federation_router_adoptions_total": "counter",
    "federation_router_rpc_failures_total": "counter",
    "federation_router_rpc_skipped_total": "counter",
    "federation_router_breaker_opens_total": "counter",
    "federation_router_breaker_state": "gauge",
    "federation_region_serving_headroom": "gauge",
    "fenced_writes_total": "counter",
    # fleet observability (federation/stitch.py + federation/slo.py +
    # router._observability): stitched-trace tally, observed mirror
    # staleness, per-region breaker detail (learned region health a
    # promoted standby adopts), per-region rollups of the bounded
    # family set (the `family` label is closed over this very schema),
    # rollup scrape failures, and the multi-window SLO burn-rate
    # gauges — episode IDs and job keys NEVER label any of these
    "federation_stitched_traces_total": "counter",
    "federation_mirror_staleness_seconds": "gauge",
    "federation_router_breaker_failures": "gauge",
    "federation_router_breaker_half_opens": "gauge",
    "federation_router_breaker_opens": "gauge",
    "federation_router_breaker_last_trip_ts": "gauge",
    "federation_router_breaker_retry_in_seconds": "gauge",
    "federation_rollup_scrape_failures_total": "counter",
    "federation_rollup_sum": "gauge",
    "federation_rollup_max": "gauge",
    "federation_rollup_count": "gauge",
    "slo_burn_rate": "gauge",
}

# the rollup's `family` label value set IS the family schema: closed
# by construction, so fleet-wide aggregation can never mint an
# unbounded label value
ROLLUP_FAMILY_ENUM = tuple(FAMILIES)

# -- label schema (enforced by volcano_tpu/analysis + tests/test_lint) --
#
# Every family's ALLOWED label keys, and what may appear as a value:
#   a tuple                  closed enum, values must be members
#   "enum:<module>:<NAME>"   closed enum resolved lazily from code (the
#                            single source of truth stays next to the
#                            subsystem that owns it)
#   CONFIG                   operator-bounded value (queue names, node
#                            names, replica ids, wire routes, resource
#                            dimensions): cardinality is capped by the
#                            deployment's configuration, not by
#                            workload churn
#   OBJECT                   per-object key (job keys, pod keys).  Only
#                            legal on families with a declared deletion
#                            lifecycle (swap_gauge_families scope swap
#                            or metrics.delete_labeled on object
#                            removal) — anything else would mint one
#                            immortal series per job forever.
#
# A family absent from this table carries NO labels.  The static half
# (analysis/astlint.py metric-family/metric-labels rules) checks call
# sites; the runtime half (analysis/schema.check_exposition) checks a
# live exposition — together they subsume the three per-PR label-
# cardinality tests this table replaced.
CONFIG = "config"
OBJECT = "object"

FAMILY_LABELS: Dict[str, Dict[str, object]] = {
    "task_scheduling_latency_seconds": {"action": CONFIG},
    "predicate_sweep_seconds": {"mode": ("serial", "thread",
                                         "process")},
    "sweep_snapshot_delta_bytes_total": {
        "kind": ("full", "delta", "ops")},
    "sweep_worker_restarts_total": {
        "reason": ("crash", "timeout")},
    "sweep_stale_refusals_total": {},
    "action_latency_seconds": {"action": CONFIG},
    "plugin_latency_seconds": {"plugin": CONFIG,
                               "point": ("open", "close")},
    "schedule_attempts_total": {"result": ("scheduled", "error")},
    "job_retry_counts": {"job": OBJECT},
    # fair share: job_share is the per-object gauge precedent — swapped
    # wholesale each session and delete_labeled on GC (metrics/job.go)
    "job_share": {"job": OBJECT},
    "queue_share": {"queue": CONFIG},
    "queue_weight": {"queue": CONFIG},
    "queue_overused": {"queue": CONFIG},
    **{f"queue_{m}{s}": ({"queue": CONFIG, "resource": CONFIG}
                         if s == "_scalar_resources"
                         else {"queue": CONFIG})
       for m in ("allocated", "deserved", "request", "real_capacity",
                 "inqueue", "capacity")
       for s in ("_milli_cpu", "_memory_bytes", "_scalar_resources")},
    # node-agent bandwidth accounting: per-pod gauges live inside a
    # per-node scope swap (handlers.py), so pod keys have a deletion
    # lifecycle; tier is the offline/online DCN split
    "pod_dcn_tx_mbps": {"pod": OBJECT, "node": CONFIG,
                        "tier": ("offline", "online")},
    "pod_dcn_rx_mbps": {"pod": OBJECT, "node": CONFIG,
                        "tier": ("offline", "online")},
    "node_dcn_measured_mbps": {"node": CONFIG,
                               "tier": ("offline", "online")},
    "bandwidth_violating_pods": {"node": CONFIG},
    "bandwidth_violations_total": {"pod": OBJECT, "node": CONFIG},
    # failover: slice names are topology configuration
    "slice_failovers_total": {"slice": CONFIG},
    "failover_detect_seconds": {"slice": CONFIG},
    "failover_drain_seconds": {"slice": CONFIG},
    "failover_reschedule_seconds": {"slice": CONFIG},
    "failover_resume_seconds": {"slice": CONFIG},
    "failover_mttr_seconds": {"slice": CONFIG},
    "failover_resume_step_gap": {"slice": CONFIG},
    # durability / replication
    "server_wal_dropped_records_total": {
        "reason": ("readonly", "append-error", "duplicate-seq",
                   "force-truncate")},
    "server_replication_role": {
        "role": ("leader", "follower", "candidate")},
    "server_replication_follower_lag_rv": {"follower": CONFIG},
    # client wire
    "client_retries_total": {"route": CONFIG},
    "mirror_resync_total": {"mode": ("delta", "stale-refused", "full")},
    # chaos engine
    "fault_injected_total": {"site": "enum:volcano_tpu.faults:SITES",
                             "kind": "enum:volcano_tpu.faults:ALL_KINDS"},
    # flight recorder: bounded enums only, free text never labels
    "sched_phase_seconds": {
        "phase": ("queue", "schedule", "bind", "admit", "start", "e2e")},
    "sched_span_seconds": {"action": CONFIG, "plugin": CONFIG,
                           "point": CONFIG},
    "sched_traces_total": {
        "kept": ("error", "unschedulable", "slow", "sampled")},
    "sched_unschedulable_reasons_total": {
        "reason": "enum:volcano_tpu.trace:REASON_ENUM"},
    "sched_cross_shard_conflicts_total": {
        "outcome": ("refused", "requeued")},
    # elastic gangs: the bounded resize-kind enum, never job keys
    "elastic_decisions_total": {
        "kind": "enum:volcano_tpu.api.elastic:RESIZE_KINDS"},
    "elastic_resizes_total": {
        "kind": "enum:volcano_tpu.api.elastic:RESIZE_KINDS"},
    "elastic_resize_seconds": {
        "kind": "enum:volcano_tpu.api.elastic:RESIZE_KINDS"},
    "elastic_drain_seconds": {
        "kind": "enum:volcano_tpu.api.elastic:RESIZE_KINDS"},
    # goodput observatory
    "goodput_vector_updates_total": {
        "generation": "enum:volcano_tpu.api.goodput:GENERATIONS"},
    "goodput_gated_grows_total": {
        "decision": "enum:volcano_tpu.goodput:GATE_DECISIONS"},
    "frag_index": {"generation": "enum:volcano_tpu.api.goodput:"
                                 "GENERATIONS"},
    "frag_idle_chips": {"generation": "enum:volcano_tpu.api.goodput:"
                                      "GENERATIONS"},
    "frag_largest_block_chips": {
        "generation": "enum:volcano_tpu.api.goodput:GENERATIONS"},
    "starvation_age_seconds": {"queue": CONFIG},
    "starvation_pending_gangs": {"queue": CONFIG},
    # serving plane: the bounded scale-direction enum, never group keys
    "serving_scale_decisions_total": {
        "kind": "enum:volcano_tpu.api.serving:SCALE_KINDS"},
    # federation tier: region names are operator configuration (the
    # registry), states/kinds bounded enums — never job keys
    "federation_regions": {
        "state": "enum:volcano_tpu.api.federation:REGION_STATES"},
    "federation_region_capacity_chips": {"region": CONFIG},
    "federation_region_idle_chips": {"region": CONFIG},
    "federation_region_goodput_steps_per_chip": {"region": CONFIG},
    "federation_admissions_total": {"region": CONFIG},
    "federation_requeues_total": {"region": CONFIG},
    "federation_migrations_total": {
        "kind": ("pending", "running")},
    "federation_cutover_refusals_total": {"region": CONFIG},
    "federation_source_reaps_total": {"region": CONFIG},
    "federation_mirror_records_total": {"region": CONFIG},
    "federation_mirror_resyncs_total": {"region": CONFIG},
    "federation_mirror_delta_resyncs_total": {"region": CONFIG},
    "federation_mirror_refused_batches_total": {"region": CONFIG},
    # router HA: regions are registry config; `op` is the router's
    # closed set of mutating RPC verbs (code, not workload); `fence`
    # is a lease name (operator config, e.g. federation-router)
    "federation_router_rpc_failures_total": {"region": CONFIG,
                                             "op": CONFIG},
    "federation_router_rpc_skipped_total": {"region": CONFIG},
    "federation_router_breaker_opens_total": {"region": CONFIG},
    "federation_router_breaker_state": {"region": CONFIG},
    "federation_region_serving_headroom": {"region": CONFIG},
    "fenced_writes_total": {"fence": CONFIG},
    # fleet observability: staleness + breaker detail are per-region
    # (registry config); the rollups add ONLY (family, region) with
    # `family` closed over the schema itself; the SLO burn labels are
    # the closed enums owned by federation/slo.py.  Episode IDs are
    # annotation/trace-label values only — never metric labels.
    "federation_mirror_staleness_seconds": {"region": CONFIG},
    "federation_router_breaker_failures": {"region": CONFIG},
    "federation_router_breaker_half_opens": {"region": CONFIG},
    "federation_router_breaker_opens": {"region": CONFIG},
    "federation_router_breaker_last_trip_ts": {"region": CONFIG},
    "federation_router_breaker_retry_in_seconds": {"region": CONFIG},
    "federation_rollup_scrape_failures_total": {"region": CONFIG},
    "federation_rollup_sum": {
        "family": "enum:volcano_tpu.bundle:ROLLUP_FAMILY_ENUM",
        "region": CONFIG},
    "federation_rollup_max": {
        "family": "enum:volcano_tpu.bundle:ROLLUP_FAMILY_ENUM",
        "region": CONFIG},
    "federation_rollup_count": {
        "family": "enum:volcano_tpu.bundle:ROLLUP_FAMILY_ENUM",
        "region": CONFIG},
    "slo_burn_rate": {
        "slo": "enum:volcano_tpu.federation.slo:SLO_NAMES",
        "window": "enum:volcano_tpu.federation.slo:SLO_WINDOWS"},
}


def _mean_expr(family: str) -> str:
    return (f"rate({family}_sum[5m]) / "
            f"clamp_min(rate({family}_count[5m]), 1e-9)")


def _panel(panel_id: int, title: str, exprs: List[str], x: int, y: int,
           unit: str = "short") -> dict:
    return {
        "id": panel_id, "type": "timeseries", "title": title,
        "gridPos": {"h": 8, "w": 12, "x": x, "y": y},
        "fieldConfig": {"defaults": {"unit": unit}, "overrides": []},
        "targets": [{"expr": e, "refId": chr(ord("A") + i)}
                    for i, e in enumerate(exprs)],
        "datasource": {"type": "prometheus", "uid": "${datasource}"},
    }


def scheduler_dashboard() -> dict:
    """Latency + throughput + fairness over the scheduler families."""
    panels = [
        _panel(1, "End-to-end scheduling latency (mean)",
               [_mean_expr("e2e_scheduling_latency_seconds"),
                _mean_expr("pod_scheduling_latency_seconds")],
               0, 0, unit="s"),
        _panel(2, "Action latency by action (mean)",
               [f"sum by (action) (rate(action_latency_seconds_sum[5m]))"
                f" / sum by (action) "
                f"(clamp_min(rate(action_latency_seconds_count[5m]),"
                f" 1e-9))"], 12, 0, unit="s"),
        _panel(3, "Plugin latency by plugin (mean)",
               [f"sum by (plugin) (rate(plugin_latency_seconds_sum[5m]))"
                f" / sum by (plugin) "
                f"(clamp_min(rate(plugin_latency_seconds_count[5m]),"
                f" 1e-9))"], 0, 8, unit="s"),
        _panel(4, "Schedule attempts / retries",
               ["rate(schedule_attempts_total[5m])",
                "rate(job_retry_counts[5m])"], 12, 8),
        _panel(5, "Unschedulable jobs / tasks",
               ["unschedule_job_count", "unschedule_task_count"],
               0, 16),
        _panel(6, "Preemption + reclaim activity",
               ["rate(pod_preemption_total[5m])",
                "rate(preemption_victims_total[5m])",
                "rate(gang_preemption_total[5m])",
                "rate(pod_reclaim_total[5m])",
                "rate(shuffle_victims_total[5m])"], 12, 16),
        _panel(7, "Queue dominant share vs weight",
               ["queue_share", "queue_weight"], 0, 24),
        _panel(8, "Queue allocated mCPU / chips",
               ["queue_allocated_milli_cpu",
                "queue_allocated_scalar_resources"], 12, 24),
        _panel(9, "State-server durability (mean)",
               [_mean_expr("server_wal_fsync_seconds"),
                _mean_expr("server_snapshot_seconds"),
                _mean_expr("server_replay_seconds")], 0, 32,
               unit="s"),
        _panel(10, "WAL backlog + wire retries",
               ["server_wal_records",
                "rate(server_snapshot_total[5m])",
                "sum by (route) (rate(client_retries_total[5m]))"],
               12, 32),
        # latency waterfall: one series per lifecycle phase, stacked
        # in the panel they sum to the e2e series — where a pod's
        # seconds went (queue / schedule / bind / admit / start)
        _panel(11, "Lifecycle phase waterfall (mean)",
               ["sum by (phase) (rate(sched_phase_seconds_sum[5m]))"
                " / sum by (phase) "
                "(clamp_min(rate(sched_phase_seconds_count[5m]),"
                " 1e-9))"], 0, 40, unit="s"),
        _panel(12, "Span time by action / plugin (mean)",
               ["sum by (action) (rate(sched_span_seconds_sum[5m]))"
                " / sum by (action) "
                "(clamp_min(rate(sched_span_seconds_count[5m]),"
                " 1e-9))",
                "sum by (plugin, point) "
                "(rate(sched_span_seconds_sum[5m])) / "
                "sum by (plugin, point) "
                "(clamp_min(rate(sched_span_seconds_count[5m]),"
                " 1e-9))"], 12, 40, unit="s"),
        _panel(13, "Unschedulable reasons (normalized enum)",
               ["sum by (reason) "
                "(rate(sched_unschedulable_reasons_total[5m]))",
                "sum by (kept) (rate(sched_traces_total[5m]))"],
               0, 48),
        _panel(14, "Elastic resize latency by kind (mean)",
               ["sum by (kind) (rate(elastic_resize_seconds_sum[5m]))"
                " / sum by (kind) "
                "(clamp_min(rate(elastic_resize_seconds_count[5m]),"
                " 1e-9))",
                _mean_expr("elastic_shrink_seconds"),
                _mean_expr("elastic_migration_mttr_seconds")],
               12, 48, unit="s"),
        _panel(15, "Elastic gangs / slices / decisions",
               ["elastic_jobs", "elastic_slices_total",
                "sum by (kind) (rate(elastic_decisions_total[5m]))",
                "sum by (kind) (rate(elastic_resizes_total[5m]))",
                _mean_expr("elastic_resume_step_gap")], 0, 56),
        # goodput observatory: measured fleet throughput + goodput
        # fraction, learned-vector updates and the grow-gate verdicts
        _panel(16, "Goodput: fleet steps/s, fraction, gated grows",
               ["goodput_fleet_steps_per_second", "goodput_jobs",
                "goodput_fraction",
                "sum by (generation) "
                "(rate(goodput_vector_updates_total[5m]))",
                "sum by (decision) "
                "(rate(goodput_gated_grows_total[5m]))"], 12, 56),
        _panel(17, "ICI fragmentation / queue starvation",
               ["frag_index",
                "sum by (generation) (frag_idle_chips)",
                "sum by (generation) (frag_largest_block_chips)",
                "max by (queue) (starvation_age_seconds)",
                "sum by (queue) (starvation_pending_gangs)"], 0, 64),
        # replicated control plane: who leads at what term, how far
        # each replica trails (the divergence an operator must see
        # before it pages them), shipping volume, and the
        # promotion/bootstrap/refusal event counters
        _panel(18, "Control-plane replication: role / term / lag",
               ["sum by (role) (server_replication_role)",
                "server_replication_term",
                "server_replication_lag_seconds",
                "max by (follower) "
                "(server_replication_follower_lag_rv)"], 12, 64),
        _panel(19, "WAL shipping + promotions",
               ["rate(server_replication_shipped_records_total[5m])",
                "rate(server_replication_shipped_bytes_total[5m])",
                "rate(server_replication_promotions_total[5m])",
                "rate(server_replication_bootstraps_total[5m])",
                "rate(server_replication_refused_batches_total[5m])"],
               0, 72),
        # parallel scheduler cycle: sweep latency by backend, mirror
        # sync traffic by kind, and the pool's self-healing/staleness
        # counters — the waterfall an operator reads when a cycle's
        # fan-out stops paying for itself
        _panel(20, "Predicate sweep: latency by mode / mirror sync",
               ["sum by (mode) "
                "(rate(predicate_sweep_seconds_sum[5m])) / sum by "
                "(mode) (clamp_min("
                "rate(predicate_sweep_seconds_count[5m]), 1e-9))",
                "sum by (kind) "
                "(rate(sweep_snapshot_delta_bytes_total[5m]))",
                "sum by (reason) "
                "(rate(sweep_worker_restarts_total[5m]))",
                "rate(sweep_stale_refusals_total[5m])"], 12, 72),
    ]
    return {
        "title": "volcano-tpu / scheduler", "uid": "vtp-scheduler",
        "timezone": "browser", "schemaVersion": 39, "version": 1,
        "refresh": "10s", "panels": panels,
        "templating": {"list": [{
            "name": "datasource", "type": "datasource",
            "query": "prometheus"}]},
    }


def agent_dashboard() -> dict:
    """Fast-path + session health over the agent families."""
    panels = [
        _panel(1, "Agent-scheduler pod e2e latency (mean)",
               [_mean_expr("agent_pod_e2e_latency_seconds")], 0, 0,
               unit="s"),
        _panel(2, "Bind conflicts / unschedulable (fast path)",
               ["rate(agent_bind_conflicts_total[5m])",
                "rate(agent_unschedulable_total[5m])"], 12, 0),
        _panel(3, "Session open duration (mean)",
               [_mean_expr("open_session_duration_seconds")], 0, 8,
               unit="s"),
        _panel(4, "Per-job dominant share",
               ["topk(20, job_share)"], 12, 8),
        _panel(5, "DCN measured bandwidth by node/tier (mbps)",
               ["sum by (node, tier) (node_dcn_measured_mbps)",
                "topk(20, pod_dcn_tx_mbps)"], 0, 16),
        _panel(6, "Bandwidth watermark violations",
               ["sum by (node) (bandwidth_violating_pods)",
                "rate(bandwidth_violations_total[5m])"], 12, 16),
        _panel(7, "Slice failover MTTR breakdown (mean)",
               [_mean_expr("failover_mttr_seconds"),
                _mean_expr("failover_detect_seconds"),
                _mean_expr("failover_drain_seconds"),
                _mean_expr("failover_reschedule_seconds"),
                _mean_expr("failover_resume_seconds")], 0, 24,
               unit="s"),
        _panel(8, "Slice failures / quarantined slices / resume gap",
               ["rate(slice_failovers_total[5m])",
                "quarantined_slices",
                _mean_expr("failover_resume_step_gap")], 12, 24),
    ]
    return {
        "title": "volcano-tpu / agents", "uid": "vtp-agents",
        "timezone": "browser", "schemaVersion": 39, "version": 1,
        "refresh": "10s", "panels": panels,
        "templating": {"list": [{
            "name": "datasource", "type": "datasource",
            "query": "prometheus"}]},
    }


def federation_dashboard() -> dict:
    """Fleet rollups + SLO burn over the router-side families: every
    panel reads the LEASEHOLDER ROUTER's /metrics (the only process
    that sees all regions), so one Grafana datasource covers the
    federation without scraping N regional planes."""
    panels = [
        # burn > 1.0 sustained = the SLO will be missed; the two
        # windows make fast-burn pages and slow-burn tickets
        _panel(1, "SLO burn rate by SLO x window",
               ["slo_burn_rate"], 0, 0),
        _panel(2, "Mirror staleness by region",
               ["federation_mirror_staleness_seconds"], 12, 0,
               unit="s"),
        _panel(3, "Region breaker state",
               ["federation_router_breaker_failures",
                "federation_router_breaker_opens",
                "federation_router_breaker_half_opens",
                "federation_router_breaker_retry_in_seconds"], 0, 8),
        _panel(4, "Fleet scheduling latency rollup (per region)",
               ["sum by (region) (federation_rollup_sum{family="
                "\"e2e_scheduling_latency_seconds\"}) / clamp_min("
                "sum by (region) (federation_rollup_count{family="
                "\"e2e_scheduling_latency_seconds\"}), 1e-9)"],
               12, 8, unit="s"),
        _panel(5, "Fleet failover MTTR rollup (per region)",
               ["sum by (region) (federation_rollup_sum{family="
                "\"failover_mttr_seconds\"}) / clamp_min("
                "sum by (region) (federation_rollup_count{family="
                "\"failover_mttr_seconds\"}), 1e-9)"], 0, 16,
               unit="s"),
        _panel(6, "Worst serving attainment across fleet",
               ["min(federation_rollup_max{family="
                "\"serving_slo_attainment_min\"})"], 12, 16),
        _panel(7, "Stitched episode traces / scrape failures",
               ["rate(federation_stitched_traces_total[5m])",
                "sum by (region) "
                "(rate(federation_rollup_scrape_failures_total[5m]))"],
               0, 24),
        _panel(8, "Federation queue + migration activity",
               ["federation_pending_jobs",
                "rate(federation_migrations_total[5m])",
                "sum by (region) "
                "(rate(federation_router_rpc_failures_total[5m]))"],
               12, 24),
    ]
    return {
        "title": "volcano-tpu / federation", "uid": "vtp-federation",
        "timezone": "browser", "schemaVersion": 39, "version": 1,
        "refresh": "10s", "panels": panels,
        "templating": {"list": [{
            "name": "datasource", "type": "datasource",
            "query": "prometheus"}]},
    }


def dashboard_metric_names(dash: dict) -> set:
    """Metric families referenced by a dashboard's exprs (validation
    seam: tests cross-check these against FAMILIES and a live
    exposition)."""
    import re
    names = set()
    for panel in dash.get("panels", []):
        for tgt in panel.get("targets", []):
            for m in re.finditer(r"[a-z_][a-z0-9_]*", tgt["expr"]):
                tok = m.group(0)
                if tok in FAMILIES:
                    # exact family first: gauge names may themselves
                    # end in _count (unschedule_job_count)
                    names.add(tok)
                    continue
                base = re.sub(r"_(count|sum)$", "", tok)
                if base in FAMILIES:
                    names.add(base)
    return names


DEFAULT_CONF = {
    "actions": "enqueue, allocate, elastic, backfill, preempt, reclaim",
    "tiers": [
        {"plugins": [
            {"name": "priority"}, {"name": "gang"},
            # failover: quarantined-slice filter + requeued-gang
            # priority (controllers/failover.py is the other half)
            {"name": "failover"},
            # elastic: shrink-before-preempt veto + migration steering
            # (actions/elastic.py decides, controllers/elastic.py
            # executes)
            {"name": "elastic"},
            {"name": "conformance"}]},
        {"plugins": [
            {"name": "overcommit"}, {"name": "drf"},
            {"name": "predicates"}, {"name": "proportion"},
            {"name": "nodeorder"}, {"name": "binpack"},
            {"name": "network-topology-aware"}]},
    ],
}

# role -> (command template, metrics port offset from the server
# port).  The scheduler/controllers/agents processes each carry their
# own Prometheus registry (the families the dashboards query live
# THERE, not on the state server), so every role gets a --metrics-port
# and the scrape config targets all of them.
ROLES = [
    # --data-dir: the WAL + snapshot durability layer — with
    # Restart=always a kill -9/OOM replays the journal and loses no
    # acked write (docs/design/durability.md)
    ("server", "volcano-tpu-server --port {port} --data-dir "
               "{data_dir}/state --token-file {bundle_dir}/token",
     0),
    ("scheduler", "volcano-tpu --cluster-url http://127.0.0.1:{port} "
                  "--components scheduler --leader-elect --holder %H "
                  "--conf {bundle_dir}/scheduler.conf.yaml "
                  "--metrics-port {port1} "
                  "--token-file {bundle_dir}/token", 1),
    ("controllers", "volcano-tpu --cluster-url http://127.0.0.1:{port}"
                    " --components controllers "
                    "--metrics-port {port2} "
                    "--token-file {bundle_dir}/token", 2),
    # netaccounting reads the same volcano-owned cgroup subtree the
    # cgroup enforcer narrows to (its default root), closing the
    # shape->measure loop in the deployed agent; goodput reads the
    # workload progress files (api/goodput.py default root)
    ("agents", "volcano-tpu --cluster-url http://127.0.0.1:{port} "
               "--components none --agent-scheduler --node-agents all "
               "--usage-source collectors:local,tpu,netaccounting,"
               "goodput,serving "
               "--enforcer cgroup:/sys/fs/cgroup,tc:eth0 "
               "--metrics-port {port3} "
               "--token-file {bundle_dir}/token", 3),
]

UNIT_TEMPLATE = """[Unit]
Description=volcano-tpu {role}
After=network-online.target {after}
[Service]
ExecStart={cmd}
Restart=always
RestartSec=2
[Install]
WantedBy=multi-user.target
"""


# yaml is not a baked-in dependency everywhere; the conf loader
# accepts JSON (a YAML subset), so the bundle writes JSON-formatted
# .yaml files that both PyYAML and the loader parse.
def render(out_dir: str, topology: str = "sa:v5e-256",
           port: int = 8700, data_dir: str = "/var/lib/volcano-tpu",
           token: str = "") -> Dict[str, str]:
    """Render the bundle; returns {relative path: absolute path}."""
    bundle_dir = os.path.abspath(out_dir)
    os.makedirs(bundle_dir, exist_ok=True)
    values = {"topology": topology, "port": port,
              "port1": port + 1, "port2": port + 2, "port3": port + 3,
              "data_dir": data_dir, "bundle_dir": bundle_dir}
    written: Dict[str, str] = {}

    def emit(rel: str, content: str, mode: int = 0o644):
        path = os.path.join(bundle_dir, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # secret-permissioned BEFORE any secret byte lands: a default-
        # umask create would leave a world-readable window
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, mode)
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            f.write(content)
        os.chmod(path, mode)    # pre-existing files keep umask bits
        written[rel] = path

    emit("values.json", json.dumps(values, indent=2) + "\n")
    token_path = os.path.join(bundle_dir, "token")
    if not token and os.path.exists(token_path):
        # re-render of a live bundle: rotating the credential would
        # 401 every running daemon until restart — keep it (pass
        # --token to rotate deliberately)
        token = open(token_path, encoding="utf-8").read().strip()
    emit("token", (token or secrets.token_urlsafe(32)) + "\n", 0o600)
    emit("scheduler.conf.yaml",
         json.dumps(DEFAULT_CONF, indent=2) + "\n")

    slices = []
    for item in (s for s in topology.split(",") if s):
        name, _, kind = item.partition(":")
        slices.append({"name": name, "kind": kind or "v5e-256"})
    emit("topology.json", json.dumps({"slices": slices}, indent=2)
         + "\n")
    slice_args = " ".join(f"{s['name']}={s['kind']}" for s in slices)
    emit("cluster-init.sh", "\n".join(
        ["#!/bin/sh", "# registers the slice topology on the state "
         "server (run once)", "set -e",
         f"vtpctl --server http://127.0.0.1:{port} "
         f"--token-file {bundle_dir}/token init --slices "
         f"{slice_args}", ""]), 0o755)

    after = {"server": "", "scheduler": "volcano-tpu-server.service",
             "controllers": "volcano-tpu-server.service",
             "agents": "volcano-tpu-server.service"}
    for role, cmd_tmpl, _off in ROLES:
        cmd = cmd_tmpl.format(**values)
        emit(f"systemd/volcano-tpu-{role}.service",
             UNIT_TEMPLATE.format(role=role, cmd=cmd,
                                  after=after[role]))

    compose_services = {}
    for role, cmd_tmpl, _off in ROLES:
        cmd = cmd_tmpl.format(**dict(
            values, bundle_dir="/bundle", data_dir="/data"))
        # %H is a systemd specifier (hostname: unique per host, one
        # scheduler unit per host).  Compose runs with host networking
        # where every scaled replica reports the SAME hostname — and
        # identical lease holders would BOTH hold the lease — so each
        # container derives a per-boot unique holder from the kernel
        # instead.  (A restarted replica gets a fresh identity and
        # simply re-contends once the old lease expires.)
        compose_services[role] = {
            "image": "volcano-tpu:latest",
            "command": ["sh", "-c", cmd.replace(
                "%H",
                "$(cat /proc/sys/kernel/random/uuid)")],
            "network_mode": "host",
            "volumes": [f"{bundle_dir}:/bundle:ro", "data:/data"],
            **({} if role == "server"
               else {"depends_on": ["server"]}),
        }
    emit("docker-compose.yaml", json.dumps(
        {"services": compose_services, "volumes": {"data": {}}},
        indent=2) + "\n")

    emit("prometheus.yml", json.dumps({
        "global": {"scrape_interval": "10s"},
        "scrape_configs": [{
            "job_name": "volcano-tpu",
            "bearer_token_file": f"{bundle_dir}/token",
            "static_configs": [{
                # the state server's own registry AND every role
                # process's --metrics-port (scheduler latency/fair-
                # share families live in those registries)
                "targets": [f"127.0.0.1:{port + off}"
                            for _, _, off in ROLES],
                "labels": {"control_plane": "volcano-tpu"}}],
        }]}, indent=2) + "\n")

    for fname, dash in (("scheduler.json", scheduler_dashboard()),
                        ("agents.json", agent_dashboard()),
                        ("federation.json", federation_dashboard())):
        emit(f"grafana/{fname}", json.dumps(dash, indent=2) + "\n")

    emit("README.md", BUNDLE_README.format(**values))
    return written


BUNDLE_README = """# volcano-tpu deploy bundle

Rendered by `python -m volcano_tpu.bundle` — edit values.json and
re-render rather than hand-editing outputs.

Bring-up order:
1. `systemd/`: `systemctl enable --now volcano-tpu-server`, then the
   other units (they After= the server).  Or: `docker compose up`.
2. `./cluster-init.sh` once to register the topology
   ({topology}).
3. Point Prometheus at `prometheus.yml` (the scrape carries the
   bearer token — ALL state-server routes except /healthz and
   /metrics require it) and import `grafana/*.json`.

The token in `token` (mode 0600) guards every read and write on the
state server at port {port}.
"""


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="render the volcano-tpu deploy bundle")
    parser.add_argument("--out", required=True)
    parser.add_argument("--topology", default="sa:v5e-256")
    parser.add_argument("--port", type=int, default=8700)
    parser.add_argument("--data-dir", default="/var/lib/volcano-tpu")
    parser.add_argument("--token", default="",
                        help="cluster token (default: generate)")
    args = parser.parse_args(argv)
    written = render(args.out, args.topology, args.port,
                     args.data_dir, args.token)
    for rel in sorted(written):
        print(rel)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
