"""Preempt action — in-queue, priority-based preemption.

Reference parity: actions/preempt/preempt.go:101-712 (starving jobs
preempt lower-priority tasks in the same queue; k8s-style dry-run
victim selection per node; preemptor pipelines onto the releasing
resources).  Hard-topology jobs are skipped here — gangpreempt owns
them (preempt.go:130-135).
"""

from __future__ import annotations

import logging
from typing import List, Optional

from volcano_tpu.api.job_info import JobInfo, TaskInfo
from volcano_tpu.api.types import PodGroupPhase, TaskStatus
from volcano_tpu.framework.plugins import Action, register_action
from volcano_tpu.util import PriorityQueue
from volcano_tpu import metrics

log = logging.getLogger(__name__)


from volcano_tpu.actions.util import may_preempt, victim_sort_key


def select_victims_on_node(ssn, preemptor: TaskInfo, node,
                           candidates: List[TaskInfo]
                           ) -> Optional[List[TaskInfo]]:
    """Dry-run victim selection: smallest prefix of *candidates* whose
    eviction lets *preemptor* fit node.future_idle (preempt.go
    SelectVictimsOnNode)."""
    if not candidates:
        return None
    chosen: List[TaskInfo] = []
    freed = node.future_idle()
    for victim in sorted(candidates, key=victim_sort_key(ssn)):
        chosen.append(victim)
        freed.add(victim.resreq)
        if preemptor.init_resreq.less_equal(freed):
            return chosen
    return None


class PreemptAction(Action):
    name = "preempt"

    def execute(self, ssn) -> None:
        for queue_name, queue in sorted(ssn.queues.items()):
            starving = [
                job for job in ssn.jobs.values()
                if job.queue == queue_name
                and ssn.job_starving(job)
                and not job.has_topology_constraint()
                and ssn.job_valid(job) is None
                and may_preempt(ssn, job)
                and (job.podgroup is None or job.podgroup.phase in
                     (PodGroupPhase.INQUEUE, PodGroupPhase.RUNNING,
                      PodGroupPhase.UNKNOWN))
            ]
            if not starving:
                continue
            jobs = PriorityQueue(ssn.job_order_fn, starving)
            for job in jobs:
                self._preempt_for_job(ssn, queue, job)

    def _preempt_for_job(self, ssn, queue, job: JobInfo):
        stmt = ssn.statement()
        tasks = PriorityQueue(ssn.task_order_fn,
                              (t for t in job.tasks_in_status(TaskStatus.PENDING)
                               if not t.best_effort))
        for task in tasks:
            if not ssn.job_starving(job):
                break  # gang floor met — stop evicting (preempt.go)
            # no queue-share gate: in-queue preemption leaves the
            # queue's total allocation unchanged (reference preempt.go
            # never consults Preemptive)
            self._preempt_for_task(ssn, stmt, queue, job, task)
        if ssn.job_pipelined(job):
            stmt.commit()
            metrics.inc("preemption_victims_total")
        else:
            stmt.discard()

    @staticmethod
    def _preempt_for_task(ssn, stmt, queue, job: JobInfo,
                          task: TaskInfo) -> bool:
        job_priority = job.priority
        for node in ssn.nodes.values():
            if not node.ready:
                continue
            status, waved = ssn.predicate_for_preempt(task, node)
            if status is not None:
                continue
            # no eviction needed if it already fits future idle — but
            # when a curable failure was waved through, the FULL
            # predicate must agree (releasing resources doesn't cure
            # it); with nothing waved the verdicts are identical and
            # re-running the chain would be pure duplicate work
            if task.init_resreq.less_equal(node.future_idle()) and \
                    (not waved or ssn.predicate(task, node) is None):
                stmt.pipeline(task, node)
                return True
            candidates = [
                t for t in node.tasks.values()
                if t.status is TaskStatus.RUNNING
                and t.job != task.job
                and t.preemptable
                and (ssn.jobs[t.job].priority if t.job in ssn.jobs else
                     t.priority) < job_priority
                and (ssn.jobs[t.job].queue == queue.name
                     if t.job in ssn.jobs else False)
            ]
            victims = ssn.preemptable(task, candidates)
            chosen = select_victims_on_node(ssn, task, node, victims)
            if chosen is None:
                continue
            mark = len(stmt.operations)
            for victim in chosen:
                # evict through the session view of the victim task
                vjob = ssn.jobs.get(victim.job)
                vtask = vjob.tasks.get(victim.uid) if vjob else victim
                stmt.evict(vtask or victim,
                           f"preempted by {task.key}")
            # the evictions must actually cure whatever curable failure
            # was waved through (e.g. an occupied NUMA cell): otherwise
            # we'd evict fresh victims every cycle without ever binding
            if waved and ssn.predicate(task, node) is not None:
                stmt.rollback_to(mark)
                continue
            metrics.inc("pod_preemption_total", len(chosen))
            stmt.pipeline(task, node)
            return True
        return False


register_action(PreemptAction())
