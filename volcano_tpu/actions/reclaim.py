"""Reclaim action — cross-queue fair-share enforcement.

Reference parity: actions/reclaim/reclaim.go:56.  A starving queue
(under its deserved share) reclaims resources from queues running over
their deserved share; victims chosen per node from reclaimable queues
ordered by VictimQueueOrder, gated by the Reclaimable plugin
intersection (gang floors, conformance, queue reclaimable flag).
"""

from __future__ import annotations

import logging

from volcano_tpu.api.job_info import JobInfo, TaskInfo
from volcano_tpu.api.types import PodGroupPhase, TaskStatus
from volcano_tpu.framework.plugins import Action, register_action
from volcano_tpu.util import PriorityQueue
from volcano_tpu import metrics

from volcano_tpu.actions.preempt import select_victims_on_node
from volcano_tpu.actions.util import may_preempt

log = logging.getLogger(__name__)


class ReclaimAction(Action):
    name = "reclaim"

    def execute(self, ssn) -> None:
        for queue_name, queue in sorted(ssn.queues.items()):
            if ssn.overused(queue):
                continue
            starving = [
                job for job in ssn.jobs.values()
                if job.queue == queue_name
                and ssn.job_starving(job)
                and ssn.job_valid(job) is None
                # preemptionPolicy: Never bars reclaim too (reclaim.go:144)
                and may_preempt(ssn, job)
                and (job.podgroup is None or job.podgroup.phase in
                     (PodGroupPhase.INQUEUE, PodGroupPhase.RUNNING,
                      PodGroupPhase.UNKNOWN))
            ]
            if not starving:
                continue
            jobs = PriorityQueue(ssn.job_order_fn, starving)
            for job in jobs:
                if job.has_topology_constraint():
                    continue  # gangreclaim owns topology jobs
                self._reclaim_for_job(ssn, queue, job)

    def _reclaim_for_job(self, ssn, queue, job: JobInfo):
        stmt = ssn.statement()
        tasks = PriorityQueue(ssn.task_order_fn,
                              (t for t in job.tasks_in_status(TaskStatus.PENDING)
                               if not t.best_effort))
        for task in tasks:
            if not ssn.job_starving(job):
                break  # gang floor met — stop reclaiming (reclaim.go:127)
            # may this queue still absorb the task? (reclaim.go:149)
            if not ssn.preemptive(queue, task):
                continue
            self._reclaim_for_task(ssn, stmt, queue, task)
        if ssn.job_pipelined(job):
            stmt.commit()
            metrics.inc("reclaim_commits_total")
        else:
            stmt.discard()

    @staticmethod
    def _reclaim_for_task(ssn, stmt, queue, task: TaskInfo) -> bool:
        for node in ssn.nodes.values():
            if not node.ready:
                continue
            status, waved = ssn.predicate_for_preempt(task, node)
            if status is not None:
                continue
            if task.init_resreq.less_equal(node.future_idle()) and \
                    (not waved or ssn.predicate(task, node) is None):
                stmt.pipeline(task, node)
                return True
            candidates = []
            for t in node.tasks.values():
                if t.status is not TaskStatus.RUNNING or not t.preemptable:
                    continue
                vjob = ssn.jobs.get(t.job)
                if vjob is None or vjob.queue == queue.name:
                    continue
                vqueue = ssn.queues.get(vjob.queue)
                if vqueue is None or not vqueue.reclaimable:
                    continue
                candidates.append(t)
            victims = ssn.reclaimable(task, candidates)
            chosen = select_victims_on_node(ssn, task, node, victims)
            if chosen is None:
                continue
            mark = len(stmt.operations)
            for victim in chosen:
                vjob = ssn.jobs.get(victim.job)
                vtask = vjob.tasks.get(victim.uid) if vjob else victim
                stmt.evict(vtask or victim, f"reclaimed by queue {queue.name}")
            # evictions must cure the curable failure waved through by
            # predicate_for_preempt, or this evicts every cycle
            # without ever placing the reclaimer
            if waved and ssn.predicate(task, node) is not None:
                stmt.rollback_to(mark)
                continue
            metrics.inc("pod_reclaim_total", len(chosen))
            stmt.pipeline(task, node)
            return True
        return False


register_action(ReclaimAction())
