"""Hard network-topology allocation: gradient search over hypernodes.

Reference parity: actions/allocate/allocate.go:370-463 (per-gradient,
per-hypernode dry-run with Statement discard/recover, committing the
best domain) + network-topology-aware gradient production + subgroup
domains (SubJobInfo.AllocatedHyperNode, sub_job_info.go:40).

TPU semantics: gradients are tier buckets ordered by ICI closeness —
tier 1 (single ICI slice) first, then DCN tiers up to the job's
highestTierAllowed.  Within a tier, domains are ordered by the
HyperNodeOrder plugin score (slice binpack + job affinity).  A job with
subGroupPolicies places each subgroup in its own domain (e.g. one ICI
slice per data-parallel replica) using statement savepoints, so a
multi-slice training job gets per-slice gang placement in one pass.
"""

from __future__ import annotations

import logging
from typing import List, Optional

from volcano_tpu.api.job_info import JobInfo, SubJobInfo
from volcano_tpu.api.types import TaskStatus

log = logging.getLogger(__name__)


def candidate_domains(ssn, job: JobInfo,
                      max_tier: Optional[int] = None) -> List[List[str]]:
    """Tier-bucketed candidate hypernode domains (the 'gradients'),
    closest tier first, best-scored first within a tier."""
    if max_tier is None:
        nt = job.network_topology
        max_tier = nt.highest_tier_allowed if nt else None
    if max_tier is None:    # unbounded: every tier, lowest first
        max_tier = max(ssn.hypernodes.tiers, default=1)
    gradients = []
    for tier in ssn.hypernodes.tiers:
        if tier > max_tier:
            break
        names = [h.name for h in ssn.hypernodes.at_tier(tier) if h.nodes]
        if not names:
            continue
        scores = ssn.hyper_node_order(job, names)
        names.sort(key=lambda n: (-scores.get(n, 0.0), n))
        gradients.append(names)
    return gradients


def allocate_for_topology_job(ssn, queue, job: JobInfo) -> bool:
    sub_jobs = [s for s in job.sub_jobs.values()
                if s.name and s.min_member > 0]
    if sub_jobs:
        return _allocate_per_subjob(ssn, queue, job, sub_jobs)
    return _allocate_whole_job(ssn, queue, job)


def _domain_nodes(ssn, domain_name: str):
    info = ssn.hypernodes.members.get(domain_name)
    if info is None:
        return []
    return [ssn.nodes[n] for n in info.nodes if n in ssn.nodes]


def _allocate_whole_job(ssn, queue, job: JobInfo) -> bool:
    """Dry-run the whole job into candidate domains; commit the first
    (tier-closest, best-scored) domain where the gang becomes ready."""
    from volcano_tpu.actions.allocate import AllocateAction

    # Nomination fast path: gangpreempt pinned a domain last cycle.
    nominated = {sub.nominated_hypernode
                 for sub in job.sub_jobs.values() if sub.nominated_hypernode}
    gradients = candidate_domains(ssn, job)
    if nominated:
        gradients.insert(0, sorted(nominated))

    for gradient in gradients:
        for domain_name in gradient:
            nodes = _domain_nodes(ssn, domain_name)
            if not nodes:
                continue
            stmt = ssn.statement()
            AllocateAction._allocate_tasks(ssn, queue, job, stmt, nodes,
                                           record_errors=False)
            if ssn.job_ready(job):
                for sub in job.sub_jobs.values():
                    sub.allocated_hypernode = domain_name
                    sub.nominated_hypernode = ""
                job.persist_nominations()
                stmt.commit()
                log.debug("topology job %s committed into domain %s",
                          job.key, domain_name)
                return True
            if ssn.job_pipelined(job):
                # gang becomes ready once this domain's releasing
                # resources free up — keep the reservations in-session
                return True
            stmt.discard()

    return _fail(ssn, job)


def _allocate_per_subjob(ssn, queue, job: JobInfo,
                         sub_jobs: List[SubJobInfo]) -> bool:
    """Place each subgroup into its own hypernode domain (its topology
    constraint, falling back to the job's), all within one statement
    with per-subgroup savepoints."""
    from volcano_tpu.actions.allocate import AllocateAction

    stmt = ssn.statement()
    chosen = {}
    # name order for determinism, SubJobOrder plugins take precedence
    ordered = sorted(sorted(sub_jobs, key=lambda s: s.name),
                     key=_cmp_key(ssn))

    for sub in ordered:
        pending = [t for t in sub.tasks.values()
                   if t.status is TaskStatus.PENDING and not t.best_effort]
        if not pending:
            continue  # nothing to place; keep its allocated_hypernode
        nt = sub.network_topology or job.network_topology
        # nt present but tier None = explicitly unbounded; resolve here
        # so candidate_domains doesn't fall back to the job-level cap
        if nt is None:
            max_tier = None
        elif nt.highest_tier_allowed is None:
            max_tier = max(ssn.hypernodes.tiers, default=1)
        else:
            max_tier = nt.highest_tier_allowed
        placed = False
        gradients = candidate_domains(ssn, job, max_tier=max_tier)
        # sticky placement: an already-allocated subgroup scales up in
        # its own domain first; nominations next
        for pinned in (sub.nominated_hypernode, sub.allocated_hypernode):
            if pinned:
                gradients.insert(0, [pinned])
        for gradient in gradients:
            for domain_name in gradient:
                nodes = _domain_nodes(ssn, domain_name)
                if not nodes:
                    continue
                mark = len(stmt.operations)
                AllocateAction._allocate_tasks(
                    ssn, queue, job, stmt, nodes, record_errors=False,
                    task_filter=lambda t, s=sub: t.sub_job == s.name)
                # a domain counts only if it actually took new tasks
                # (a satisfied gang floor must not claim a full domain)
                if len(stmt.operations) > mark and \
                        (sub.is_ready() or sub.is_pipelined()):
                    chosen[sub.name] = domain_name
                    placed = True
                    break
                stmt.rollback_to(mark)
            if placed:
                break
        if not placed:
            if sub.is_ready():
                continue  # floor already met; extras wait for capacity
            stmt.discard()
            return _fail(ssn, job, subjob=sub.name)

    # tasks outside any policed subgroup may go anywhere in the cluster
    policed = {s.name for s in sub_jobs}
    AllocateAction._allocate_tasks(
        ssn, queue, job, stmt, list(ssn.nodes.values()),
        record_errors=False, task_filter=lambda t: t.sub_job not in policed)

    if ssn.job_ready(job):
        for sub in job.sub_jobs.values():
            if sub.name in chosen and not sub.allocated_hypernode:
                sub.allocated_hypernode = chosen[sub.name]
            if sub.name in chosen:
                sub.nominated_hypernode = ""
        job.persist_nominations()
        stmt.commit()
        log.debug("multi-slice job %s committed: %s", job.key, chosen)
        return True
    if ssn.job_pipelined(job):
        # keep in-session reservations on releasing resources, exactly
        # like the non-topology path (allocate.py _finish)
        return True
    stmt.discard()
    return _fail(ssn, job)


def _cmp_key(ssn):
    import functools

    def cmp(a, b):
        if ssn.sub_job_order_fn(a, b):
            return -1
        if ssn.sub_job_order_fn(b, a):
            return 1
        return 0
    return functools.cmp_to_key(cmp)


def _fail(ssn, job: JobInfo, subjob: str = "") -> bool:
    # clear stale nominations that failed validation (allocate.go:595-717)
    for sub in job.sub_jobs.values():
        sub.nominated_hypernode = ""
    job.persist_nominations()
    nt = job.network_topology
    if subjob:
        sub = job.sub_jobs.get(subjob)
        if sub is not None and sub.network_topology is not None:
            nt = sub.network_topology   # the binding constraint
    where = f"subgroup {subjob} of " if subjob else ""
    tier = nt.highest_tier_allowed if nt else None
    cap = "at any tier" if tier is None else f"within tier {tier}"
    ssn.set_job_pending_reason(
        job, "Unschedulable",
        f"no hypernode domain {cap} can hold {where}job "
        f"{job.key} (minAvailable={job.min_available})")
    return False
