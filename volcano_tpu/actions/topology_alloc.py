"""Hard network-topology allocation: gradient search over hypernodes.

Reference parity: actions/allocate/allocate.go:370-463 (per-gradient,
per-hypernode dry-run with Statement discard/recover, committing the
best domain) + network-topology-aware gradient production.

TPU semantics: gradients are tier buckets ordered by ICI closeness —
tier 1 (single ICI slice) first, then DCN tiers up to the job's
highestTierAllowed.  Within a tier, domains are ordered by the
HyperNodeOrder plugin score (binpack over slices by default).
"""

from __future__ import annotations

import logging
from typing import List, Optional

from volcano_tpu.api.job_info import JobInfo

log = logging.getLogger(__name__)


def candidate_domains(ssn, job: JobInfo) -> List[List[str]]:
    """Tier-bucketed candidate hypernode domains (the 'gradients'),
    closest tier first, best-scored first within a tier."""
    nt = job.network_topology
    max_tier = nt.highest_tier_allowed if nt else max(
        ssn.hypernodes.tiers, default=1)
    gradients = []
    for tier in ssn.hypernodes.tiers:
        if tier > max_tier:
            break
        names = [h.name for h in ssn.hypernodes.at_tier(tier) if h.nodes]
        if not names:
            continue
        scores = ssn.hyper_node_order(job, names)
        names.sort(key=lambda n: (-scores.get(n, 0.0), n))
        gradients.append(names)
    return gradients


def allocate_for_topology_job(ssn, queue, job: JobInfo) -> bool:
    """Dry-run the job into candidate domains, commit the first tier
    containing a domain that makes the gang ready (preferring the
    highest-scored domain inside that tier)."""
    from volcano_tpu.actions.allocate import AllocateAction

    # Nomination fast path: gangpreempt pinned a domain last cycle.
    nominated = {sub.nominated_hypernode
                 for sub in job.sub_jobs.values() if sub.nominated_hypernode}
    gradients = candidate_domains(ssn, job)
    if nominated:
        gradients.insert(0, sorted(nominated))

    for gradient in gradients:
        best_ops = None
        best_domain: Optional[str] = None
        for domain_name in gradient:
            info = ssn.hypernodes.members.get(domain_name)
            if info is None:
                continue
            nodes = [ssn.nodes[n] for n in info.nodes if n in ssn.nodes]
            if not nodes:
                continue
            stmt = ssn.statement()
            AllocateAction._allocate_tasks(ssn, queue, job, stmt, nodes,
                                           record_errors=False)
            if ssn.job_ready(job):
                ops = stmt.save_operations()
                stmt.discard()
                best_ops, best_domain = ops, domain_name
                break  # domains pre-sorted best-first inside the tier
            stmt.discard()

        if best_ops is not None:
            stmt = ssn.statement()
            stmt.recover_operations(best_ops)
            for sub in job.sub_jobs.values():
                sub.allocated_hypernode = best_domain
                sub.nominated_hypernode = ""
            stmt.commit()
            log.debug("topology job %s committed into domain %s",
                      job.key, best_domain)
            return True

    # clear stale nominations that failed validation (allocate.go:595-717)
    for sub in job.sub_jobs.values():
        sub.nominated_hypernode = ""
    ssn.set_job_pending_reason(
        job, "Unschedulable",
        f"no hypernode domain within tier {job.network_topology.highest_tier_allowed} "
        f"can hold job {job.key} (minAvailable={job.min_available})")
    return False
