"""Shuffle action — evict running tasks chosen by VictimTasks plugins.

Reference parity: actions/shuffle/shuffle.go (rescheduling / tdm feed
victims; shuffle just executes the evictions).
"""

from __future__ import annotations

import logging

from volcano_tpu.framework.plugins import Action, register_action
from volcano_tpu import metrics

log = logging.getLogger(__name__)


class ShuffleAction(Action):
    name = "shuffle"

    def execute(self, ssn) -> None:
        victims = ssn.victim_tasks()
        if not victims:
            return
        stmt = ssn.statement()
        for task in victims:
            stmt.evict(task, "shuffled for rebalancing")
            metrics.inc("shuffle_victims_total")
        stmt.commit()


register_action(ShuffleAction())
