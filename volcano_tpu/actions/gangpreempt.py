"""Gangpreempt action — gang-aware, domain-scoped preemption for
topology jobs.

Reference parity: actions/gangpreempt/gangpreempt.go:78,137,183.  For
each starving hard-topology job: walk candidate hypernode domains in
gradient order; inside a domain build victim Bundles (safe = beyond
minAvailable, whole = entire gang), gate them through UnifiedEvictable,
evict cumulatively cheapest-first and after each bundle simulate a full
nomination plan (dry-run placement of the preemptor onto the domain's
future-idle).  On success: commit the evictions, pin the domain into
the PodGroup nomination annotation and each planned pod's
nominatedNodeName — the NEXT allocate cycle takes the fast path
(gangpreempt.go:124-128 -> allocate.go:331-341,595-717).
"""

from __future__ import annotations

import logging
from typing import List

from volcano_tpu.api.job_info import JobInfo, TaskInfo
from volcano_tpu.api.types import PodGroupPhase, TaskStatus
from volcano_tpu.framework.plugins import Action, register_action
from volcano_tpu.framework.statement import ALLOCATE, PIPELINE
from volcano_tpu.util import PriorityQueue
from volcano_tpu import metrics

from volcano_tpu.actions.bundle import (
    create_job_bundles,
    sort_bundles_for_preempt,
)
from volcano_tpu.actions.util import may_preempt
from volcano_tpu.actions.topology_alloc import candidate_domains

log = logging.getLogger(__name__)

MAX_DOMAINS = 8  # cap per job per cycle (reference maxDomains)


class EvictContext:
    """What the UnifiedEvictable plugins see."""

    __slots__ = ("preemptor_job", "cross_queue")

    def __init__(self, preemptor_job: JobInfo, cross_queue: bool):
        self.preemptor_job = preemptor_job
        self.cross_queue = cross_queue


def _victim_candidates(ssn, job: JobInfo, domain_nodes,
                       cross_queue: bool) -> List[TaskInfo]:
    out = []
    for node in domain_nodes:
        for t in node.tasks.values():
            if t.status is not TaskStatus.RUNNING or not t.preemptable:
                continue
            vjob = ssn.jobs.get(t.job)
            if vjob is None or vjob.uid == job.uid:
                continue
            if cross_queue:
                if vjob.queue == job.queue:
                    continue
                vqueue = ssn.queues.get(vjob.queue)
                if vqueue is None or not vqueue.reclaimable:
                    continue
            else:
                if vjob.queue != job.queue or vjob.priority >= job.priority:
                    continue
            # session-held task object (node holds a clone)
            vtask = vjob.tasks.get(t.uid)
            if vtask is not None:
                out.append(vtask)
    return out


def preempt_job_in_domains(ssn, job: JobInfo, cross_queue: bool) -> bool:
    """Try each candidate domain; True once one yields a plan."""
    gradients = candidate_domains(ssn, job)
    tried = 0
    for gradient in gradients:
        for domain_name in gradient:
            if tried >= MAX_DOMAINS:
                return False
            tried += 1
            if _try_domain(ssn, job, domain_name, cross_queue):
                return True
    return False


def _try_domain(ssn, job: JobInfo, domain_name: str,
                cross_queue: bool) -> bool:
    from volcano_tpu.actions.allocate import AllocateAction

    info = ssn.hypernodes.members.get(domain_name)
    if info is None:
        return False
    nodes = [ssn.nodes[n] for n in info.nodes if n in ssn.nodes]
    if not nodes:
        return False

    candidates = _victim_candidates(ssn, job, nodes, cross_queue)
    ctx = EvictContext(job, cross_queue)
    evictable = ssn.unified_evictable(ctx, candidates)
    if not evictable:
        return False
    bundles = sort_bundles_for_preempt(create_job_bundles(ssn, evictable))
    if not bundles:
        return False

    queue = ssn.queues.get(job.queue)
    stmt = ssn.statement()
    evicted_uids = set()
    for bundle in bundles:
        # bundles overlap (a job's safe bundle is a subset of its whole
        # bundle); evict only tasks not already taken
        new_victims = [v for v in bundle.tasks if v.uid not in evicted_uids]
        if not new_victims:
            continue
        for victim in new_victims:
            stmt.evict(victim, f"gang-preempted for {job.key}")
            evicted_uids.add(victim.uid)

        # nomination plan: can the preemptor fully land on future idle?
        evict_mark = len(stmt.operations)
        AllocateAction._allocate_tasks(ssn, queue, job, stmt, nodes,
                                       record_errors=False)
        if ssn.job_pipelined(job):
            # record plan, then unwind the placements — allocate
            # re-places next cycle via the nomination fast path
            plan = [(op.task, op.node_name)
                    for op in stmt.operations[evict_mark:]
                    if op.kind in (PIPELINE, ALLOCATE)]
            stmt.rollback_to(evict_mark)
            n_victims = len(stmt.operations)  # only evicts remain
            for task, node_name in plan:
                ssn.cache.nominate(task, node_name)
            for sub in job.sub_jobs.values():
                sub.nominated_hypernode = domain_name
            job.persist_nominations()
            ssn.dirty_jobs.add(job.uid)
            stmt.commit()  # evictions fire
            metrics.inc("gang_preemption_total")
            log.info("gangpreempt: job %s nominated into %s (%d victims)",
                     job.key, domain_name, n_victims)
            return True
        stmt.rollback_to(evict_mark)
    stmt.discard()
    return False


class GangPreemptAction(Action):
    name = "gangpreempt"

    cross_queue = False

    def execute(self, ssn) -> None:
        if ssn.hypernodes is None or len(ssn.hypernodes.members) <= 1:
            return
        for queue_name, queue in sorted(ssn.queues.items()):
            if self.cross_queue and ssn.overused(queue):
                # gangreclaim must not push a queue further past its
                # share (gangreclaim.go:114)
                continue
            starving = [
                job for job in ssn.jobs.values()
                if job.queue == queue_name
                and job.has_topology_constraint()
                and ssn.job_starving(job)
                and ssn.job_valid(job) is None
                and may_preempt(ssn, job)
                and not any(s.nominated_hypernode
                            for s in job.sub_jobs.values())
                and (job.podgroup is None or job.podgroup.phase in
                     (PodGroupPhase.INQUEUE, PodGroupPhase.RUNNING,
                      PodGroupPhase.UNKNOWN))
            ]
            jobs = PriorityQueue(ssn.job_order_fn, starving)
            for job in jobs:
                if self.cross_queue and not all(
                        ssn.preemptive(queue, t)
                        for t in job.tasks_in_status(TaskStatus.PENDING)
                        if not t.best_effort):
                    continue  # queue can't absorb it (gangreclaim.go:145)
                preempt_job_in_domains(ssn, job,
                                       cross_queue=self.cross_queue)


class GangReclaimAction(GangPreemptAction):
    name = "gangreclaim"

    cross_queue = True


register_action(GangPreemptAction())
register_action(GangReclaimAction())
