"""Process-pool sweep backend — long-lived snapshot MIRRORS in worker
OS processes (ROADMAP item 3: the GIL-bound thread pilot's successor).

The thread backend (actions/sweep.py, PR 11) proved the batched
prepared-form sweep bit-identical to the serial dispatch, but CPython
threads share one interpreter lock: on a multi-core host the fan-out
serializes.  This module fans the same sweep across real OS processes
without paying the obvious tax — pickling a 100k-node snapshot per
sweep — by giving every worker a PERSISTENT mirror of the session
snapshot, kept current by three message kinds:

  full     the whole model (nodes/jobs/queues/priority classes/
           hypernodes/conf/cluster maps), shipped once per worker
           lifetime or whenever the generation chain breaks (worker
           restart, cache full rebuild, delta ring exhausted).
  delta    per-cycle changes keyed by the scheduler cache's existing
           event stream (cache.SnapshotDelta): only the rebuilt
           NodeInfo/JobInfo objects cross the boundary — on a steady
           fleet that is nothing at all.
  ops      the within-cycle mutation journal (Session.mirror_log):
           the owner's 5 state primitives replayed through the
           worker session's OWN primitives, so a sweep fanned out
           mid-cycle sees exactly the in-session view the owner does.

Staleness contract: every sweep request is stamped (generation,
ops-applied); a worker whose mirror does not match answers ``stale``
and the owner REFUSES the rows and re-sweeps those shards serially —
rows computed against the wrong world never merge.  A crashed worker
(SIGKILL, OOM) degrades the same way: its shards re-sweep serially,
the pool respawns it (counted in ``sweep_worker_restarts_total``) and
the newborn full-syncs on the next cycle.

Purity contract: nothing callable ever crosses the boundary.  ALL
sends funnel through :func:`post` → :func:`ship`, whose pickler
REFUSES functions/methods/lambdas/partials outright; workers resolve
the prepared PreFilter/PreScore plugin forms themselves, from shipped
data, via framework.open_mirror_session.  The vtplint rule
``process-ship-purity`` pins the funnel statically; the armed freeze
auditor compares per-worker mirror digests against the owner snapshot
every fan-out (mirror-divergence audit) at runtime.
"""

from __future__ import annotations

import hashlib
import io
import os
import pickle
import threading
import time
from typing import Dict, List, Optional

from volcano_tpu import metrics, trace

# owner -> worker cluster-map attributes plugins consult at session
# open (volumebinding, dra, numaaware, resourcequota, datalocality);
# shipped with every sync so mirror sessions resolve the same
# predicate state the owner session did
MIRROR_CLUSTER_ATTRS = (
    "pvs", "pvcs", "datasources", "numatopologies", "config_maps",
    "resource_slices", "resource_claims", "admin_namespaces",
)

REQ_TIMEOUT_S = 120.0      # per-worker sweep reply budget


class PicklePurityError(TypeError):
    """A callable tried to cross the process boundary."""


class _PurePickler(pickle.Pickler):
    """Data-only pickler for the ship seam: functions, methods,
    lambdas and partials are refused outright — worker-side behavior
    must come from worker-side resolution, never from shipped code."""

    def reducer_override(self, obj):
        import functools
        import types
        if isinstance(obj, (types.FunctionType, types.MethodType,
                            types.BuiltinFunctionType,
                            types.BuiltinMethodType,
                            functools.partial)):
            raise PicklePurityError(
                f"refusing to ship callable {obj!r} across the "
                f"process boundary (pickled-callback purity)")
        return NotImplemented


def ship(obj) -> bytes:
    """THE serialization seam: every cross-process payload is built
    here (vtplint: process-ship-purity pins all conn sends to
    :func:`post`, which calls this)."""
    buf = io.BytesIO()
    _PurePickler(buf, protocol=pickle.HIGHEST_PROTOCOL).dump(obj)
    return buf.getvalue()


def unship(data: bytes):
    return pickle.loads(data)


def post(conn, obj) -> int:
    """THE wire seam: every pipe send happens here or in post_bytes —
    nowhere else (vtplint rule process-ship-purity pins this
    statically).  Returns bytes shipped (the delta-bytes metric's
    source)."""
    return post_bytes(conn, ship(obj))


def post_bytes(conn, data: bytes) -> int:
    """Raw half of the wire seam for payloads shipped once and sent
    to several workers (the full-sync broadcast): *data* MUST come
    from :func:`ship`."""
    conn.send_bytes(data)
    return len(data)


# -- worker side -------------------------------------------------------

class MirrorCache:
    """The cache stub a mirror session sees: shipped read-only
    cluster maps, no-op mutation routes (workers only predicate and
    score — anything that would need these seams is an owner-side
    duty by contract)."""

    class _Cluster:
        def __init__(self, maps: dict):
            for attr in MIRROR_CLUSTER_ATTRS:
                setattr(self, attr, maps.get(attr, {}))

        def watch(self, fn):
            pass

        def unwatch(self, fn):
            pass

        def put_object(self, kind, obj, key=None):
            pass

    def __init__(self, maps: dict, scheduler_name: str):
        self.cluster = MirrorCache._Cluster(maps)
        self.scheduler_name = scheduler_name
        self.plugin_state: Dict[str, dict] = {}

    def record_event(self, obj_key, reason, message):
        pass


class _Mirror:
    """One worker's long-lived model + per-generation session."""

    def __init__(self):
        from volcano_tpu.cache.cache import Snapshot
        self.snap = Snapshot()
        self.gen = -1
        self.ops = 0
        self.conf = None
        self.maps: dict = {}
        self.scheduler_name = "volcano-tpu"
        self.session = None

    def retire_session(self):
        if self.session is not None:
            from volcano_tpu.framework.framework import \
                close_mirror_session
            close_mirror_session(self.session)
            self.session = None

    def apply_full(self, payload: dict) -> None:
        from volcano_tpu.cache.cache import Snapshot
        self.retire_session()
        snap = Snapshot()
        snap.nodes = payload["nodes"]
        snap.jobs = payload["jobs"]
        snap.queues = payload["queues"]
        snap.priority_classes = payload["priority_classes"]
        snap.hypernodes = payload["hypernodes"]
        snap._total = payload["total"]
        snap.gen = payload["gen"]
        self.snap = snap
        self._common(payload)
        self.gen = payload["gen"]
        # a full payload is a point-in-time copy of LIVE session
        # state: it already embodies every journaled op up to
        # ops_base — replaying those would double-apply (a respawned
        # worker mid-cycle crash-looped on node.add_task KeyError)
        self.ops = payload.get("ops_base", 0)

    def apply_delta(self, payload: dict) -> bool:
        """Returns False (mirror marked stale) when the delta's base
        generation is not the mirror's — the owner finds out through
        the next sweep's stale reply and full-syncs."""
        if payload["from_gen"] != self.gen:
            self.gen = -1
            return False
        self.retire_session()
        snap = self.snap
        for name, ni in payload["nodes"].items():
            snap.nodes[name] = ni
        for key, job in payload["jobs"].items():
            snap.jobs[key] = job
        for key in payload["removed_jobs"]:
            snap.jobs.pop(key, None)
        snap.queues = payload["queues"]
        snap.priority_classes = payload["priority_classes"]
        if payload["hypernodes"] is not None:
            snap.hypernodes = payload["hypernodes"]
        snap._total = payload["total"]
        snap.gen = payload["gen"]
        self._common(payload)
        self.gen = payload["gen"]
        self.ops = 0
        return True

    def _common(self, payload: dict) -> None:
        self.conf = payload["conf"]
        self.maps = payload["maps"]
        self.scheduler_name = payload["scheduler_name"]

    def ensure_session(self):
        if self.session is None:
            from volcano_tpu.framework.framework import \
                open_mirror_session
            self.session = open_mirror_session(
                MirrorCache(self.maps, self.scheduler_name),
                self.snap, self.conf)
        return self.session

    def replay(self, ops) -> None:
        """Apply the owner's mutation journal through this mirror
        session's OWN primitives: same code, same order, same state."""
        ssn = self.ensure_session()
        for op in ops:
            kind, job_uid, task_uid = op[0], op[1], op[2]
            job = ssn.jobs.get(job_uid)
            task = job.tasks.get(task_uid) if job is not None else None
            if task is None:
                # the owner mutated a job this mirror doesn't hold —
                # impossible while the sync protocol holds; poison the
                # mirror rather than sweep against a diverged world
                self.gen = -1
                return
            if kind == "alloc":
                ssn.allocate(task, ssn.nodes[op[3]])
            elif kind == "pipe":
                ssn.pipeline(task, ssn.nodes[op[3]])
            elif kind == "evict":
                ssn.evict(task)
            elif kind == "dealloc":
                ssn.deallocate(task)
            elif kind == "unevict":
                ssn.unevict(task, op[3])
            self.ops += 1


def snapshot_digest(nodes: dict, names=None) -> str:
    """Order-independent fingerprint of scheduling-relevant node
    state, comparable across the process boundary (the mirror-
    divergence audit): per-node idle/used/releasing resources, task
    census and readiness."""
    h = 0
    sha = hashlib.sha1
    items = ((n, nodes[n]) for n in names if n in nodes) \
        if names is not None else nodes.items()
    for name, ni in items:
        row = (name, sorted(ni.idle.res.items()),
               sorted(ni.used.res.items()),
               sorted(ni.releasing.res.items()),
               sorted(ni.tasks.keys()), ni.ready)
        h ^= int.from_bytes(sha(repr(row).encode()).digest()[:8],
                            "big")
    return format(h, "016x")


def serve_fd(fd: int, worker_id: int) -> None:
    """Worker entry: wrap the inherited socket fd and serve.  Workers
    are plain ``python -c`` subprocesses, NOT multiprocessing spawn
    children — no re-import of the parent's __main__, no fork of a
    threaded owner; volcano_tpu imports fresh, audits arm from the
    inherited environment exactly as any process in the plane does
    and flush their own per-pid reports."""
    from multiprocessing.connection import Connection
    _worker_main(Connection(fd), worker_id)


def _worker_main(conn, worker_id: int) -> None:
    """Serve sync/sweep/digest requests until the pipe closes."""
    mirror = _Mirror()
    prepared: dict = {}        # task_spec -> (pred_fns, score_fns)
    while True:
        try:
            data = conn.recv_bytes()
        except (EOFError, OSError):
            break
        # an undecodable stream dies loudly (the owner retires on
        # EOF immediately); anything that raises while HANDLING a
        # decoded message degrades instead — see the except below
        msg = unship(data)
        kind = msg[0]
        try:
            _worker_handle(conn, mirror, prepared, msg)
        except _WorkerExit:
            break
        except Exception:  # noqa: BLE001
            # a deterministic poison (a plugin raising in the mirror,
            # replay divergence, a malformed payload) must degrade
            # ONCE — not kill the worker into a respawn + full-sync
            # + same-request crash loop.  Poison the mirror, answer
            # stale so the owner re-sweeps serially and full-syncs
            # next cycle, and surface the traceback on stderr.
            import traceback
            traceback.print_exc()
            mirror.gen = -1
            try:
                mirror.retire_session()
            except Exception:  # noqa: BLE001
                mirror.session = None   # thaw failed: drop the wreck
            if kind in ("sweep", "digest", "ping") and len(msg) > 1:
                try:
                    post(conn, ("stale", msg[1], -1, -1))
                except OSError:
                    break
    try:
        conn.close()
    except OSError:
        # vtplint: disable=except-pass (worker teardown; the pipe may already be gone)
        pass


class _WorkerExit(Exception):
    """Internal: the owner asked this worker to exit."""


def _worker_handle(conn, mirror: "_Mirror", prepared: dict,
                   msg) -> None:
    from volcano_tpu.actions import sweep as sweep_mod
    kind = msg[0]
    if kind == "exit":
        raise _WorkerExit
    elif kind == "full":
        mirror.apply_full(msg[1])
        prepared.clear()
    elif kind == "delta":
        mirror.apply_delta(msg[1])
        prepared.clear()
    elif kind == "ops":
        _, gen, start, ops = msg
        if gen != mirror.gen or start != mirror.ops:
            mirror.gen = -1          # journal gap: poison
        else:
            mirror.replay(ops)
    elif kind == "sweep":
        (_, req_id, gen, op_seq, job_uid, task_uid, spec,
         shards, need_class) = msg
        if gen != mirror.gen or op_seq != mirror.ops:
            post(conn, ("stale", req_id, mirror.gen, mirror.ops))
            return
        ssn = mirror.ensure_session()
        # the task is addressed BY REFERENCE into the mirror (the
        # sync protocol already shipped its job): re-shipping the
        # owner's task object would drag the whole job graph —
        # every sibling TaskInfo — across the pipe per request
        job = ssn.jobs.get(job_uid)
        task = job.tasks.get(task_uid) if job is not None else None
        if task is None or task.task_spec != spec:
            post(conn, ("stale", req_id, mirror.gen, mirror.ops))
            return
        forms = prepared.get(spec)
        if forms is None or forms[0] != (gen, op_seq):
            forms = ((gen, op_seq),
                     sweep_mod.prepared_fns(
                         ssn, "predicate", "predicatePrepare",
                         task),
                     sweep_mod.prepared_fns(
                         ssn, "nodeOrder", "nodeOrderPrepare",
                         task))
            prepared[spec] = forms
        _, pred_fns, score_fns = forms
        rows = []          # one (fits, fails) pair PER SHARD so
        nodes = mirror.snap.nodes   # the owner can merge in
        for shard in shards:        # global shard order
            shard_nodes = [nodes[n] for n in shard if n in nodes]
            f, e = sweep_mod.sweep_shard(
                task, shard_nodes, pred_fns, score_fns,
                need_class)
            rows.append(
                ([(n.name, score, cls) for n, score, cls in f],
                 [(n.name, st) for n, st in e]))
        post(conn, ("rows", req_id, gen, op_seq, rows))
    elif kind == "digest":
        _, req_id, names = msg
        post(conn, ("digest", req_id, mirror.gen, mirror.ops,
                    snapshot_digest(mirror.snap.nodes, names)))
    elif kind == "ping":
        post(conn, ("pong", msg[1], os.getpid(), mirror.gen,
                    mirror.ops))


# -- owner side --------------------------------------------------------

class _Worker:
    __slots__ = ("id", "proc", "conn", "gen", "ops")

    def __init__(self, wid, proc, conn):
        self.id = wid
        self.proc = proc
        self.conn = conn
        self.gen = -1
        self.ops = 0


class ProcSweepPool:
    """Owner handle: spawns/heals workers, keeps their mirrors in
    sync, fans sweep requests and merges the stamped rows."""

    def __init__(self, workers: int):
        self._next_id = 0
        self.workers: List[_Worker] = []
        self.restarts = 0
        self.stale_refusals = 0
        for _ in range(workers):
            self.workers.append(self._spawn())

    def _spawn(self) -> _Worker:
        import socket
        import subprocess
        import sys
        from multiprocessing.connection import Connection
        import volcano_tpu
        wid = self._next_id
        # vtplint: disable=shared-cache-unkeyed (pool bookkeeping is confined to the session owner thread — every fan-out originates there; workers are separate processes)
        self._next_id += 1
        parent_sock, child_sock = socket.socketpair()
        child_fd = child_sock.fileno()
        env = dict(os.environ)
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(volcano_tpu.__file__))))
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(volcano_tpu.__file__)))
        extra = os.pathsep.join(p for p in (pkg_root, repo_root) if p)
        env["PYTHONPATH"] = extra + (
            os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH") else "")
        proc = subprocess.Popen(
            [sys.executable, "-c",
             "from volcano_tpu.actions.procpool import serve_fd; "
             f"serve_fd({child_fd}, {wid})"],
            pass_fds=(child_fd,), env=env, close_fds=True)
        child_sock.close()
        return _Worker(wid, proc, Connection(parent_sock.detach()))

    def size(self) -> int:
        return len(self.workers)

    def grow(self, workers: int) -> None:
        """Add workers up to *workers* total.  Existing workers keep
        their mirrors — growth never abandons in-flight state (the
        thread pool's old grow path did; see sweep.sweep_pool)."""
        while len(self.workers) < workers:
            # vtplint: disable=shared-cache-unkeyed (pool bookkeeping on the session owner thread; growth never tears down live workers)
            self.workers.append(self._spawn())

    def _retire(self, w: _Worker, reason: str) -> None:
        """A worker failed (crash, pipe loss, timeout): respawn in
        place.  The newborn full-syncs on the next ensure_sync."""
        try:
            w.conn.close()
        except OSError:
            # vtplint: disable=except-pass (the pipe is already broken; respawn is the remedy)
            pass
        if w.proc.poll() is None:
            w.proc.kill()
        try:
            w.proc.wait(timeout=5)
        except Exception:  # noqa: BLE001
            # vtplint: disable=except-pass (kill already sent; a zombie is reaped by the next wait or interpreter exit)
            pass
        fresh = self._spawn()
        # vtplint: disable=shared-cache-unkeyed (pool bookkeeping on the session owner thread — retire/respawn happens inside the fan-out that observed the failure)
        self.workers[self.workers.index(w)] = fresh
        # vtplint: disable=shared-cache-unkeyed (owner-thread counter; the metrics registry sink is lock-guarded)
        self.restarts += 1
        metrics.inc("sweep_worker_restarts_total", reason=reason)

    # -- sync ----------------------------------------------------------

    def ensure_sync(self, ssn) -> None:
        """Bring every worker's mirror to (ssn.snapshot_gen, len(log))
        before a fan-out: per-cycle delta (or full) plus the unsent
        ops suffix.  Sends are pipelined — the pipe's ordering IS the
        barrier, and the staleness stamp catches anything that slips
        (a worker that restarted underneath us)."""
        with trace.span("delta_ship", kind="action"):
            self._ensure_sync(ssn)

    def _ensure_sync(self, ssn) -> None:
        gen = ssn.snapshot_gen
        log = ssn.mirror_log
        # payloads are pickled ONCE per sync pass and the bytes sent
        # to every worker that needs them (workers at the same
        # generation need identical deltas; re-pickling a 100k-node
        # payload per worker multiplied the owner's serialization
        # cost by the pool size)
        full_bytes: Optional[bytes] = None
        delta_bytes: Dict[int, Optional[bytes]] = {}
        ops_bytes: Dict[int, bytes] = {}
        for w in list(self.workers):
            try:
                if w.gen != gen:
                    payload_bytes = None
                    # deltas describe PRISTINE between-cycle state;
                    # once this session has journaled ops, its node/
                    # job objects are already mutated, so a catching-
                    # up worker must take the point-in-time full copy
                    # (delta + whole-journal replay double-applies on
                    # the shipped changed objects)
                    if w.gen >= 0 and not log:
                        if w.gen not in delta_bytes:
                            p = self._delta_payload(ssn, w.gen)
                            delta_bytes[w.gen] = (
                                ship(("delta", p))
                                if p is not None else None)
                        payload_bytes = delta_bytes[w.gen]
                    if payload_bytes is not None:
                        n = post_bytes(w.conn, payload_bytes)
                        metrics.inc("sweep_snapshot_delta_bytes_total",
                                    n, kind="delta")
                        w.ops = 0
                    else:
                        if full_bytes is None:
                            full_bytes = ship(
                                ("full", self._full_payload(ssn)))
                        n = post_bytes(w.conn, full_bytes)
                        metrics.inc("sweep_snapshot_delta_bytes_total",
                                    n, kind="full")
                        w.ops = len(log)
                    w.gen = gen
                if w.ops < len(log):
                    ob = ops_bytes.get(w.ops)
                    if ob is None:
                        ob = ops_bytes[w.ops] = ship(
                            ("ops", gen, w.ops, log[w.ops:]))
                    n = post_bytes(w.conn, ob)
                    metrics.inc("sweep_snapshot_delta_bytes_total",
                                n, kind="ops")
                    w.ops = len(log)
            except (BrokenPipeError, OSError):
                self._retire(w, "crash")

    def _common_payload(self, ssn) -> dict:
        cluster = getattr(ssn.cache, "cluster", None)
        maps = {}
        for attr in MIRROR_CLUSTER_ATTRS:
            m = getattr(cluster, attr, None)
            if m:
                maps[attr] = dict(m)
        return {
            "gen": ssn.snapshot_gen,
            "ops_base": len(ssn.mirror_log),
            "conf": ssn.conf,
            "maps": maps,
            "scheduler_name": getattr(ssn.cache, "scheduler_name",
                                      "volcano-tpu"),
            "queues": dict(ssn.queues),
            "priority_classes": dict(ssn.priority_classes),
            "total": ssn.total_resource,
        }

    def _full_payload(self, ssn) -> dict:
        payload = self._common_payload(ssn)
        payload["nodes"] = dict(ssn.nodes)
        payload["jobs"] = dict(ssn.jobs)
        payload["hypernodes"] = ssn.hypernodes
        return payload

    def _delta_payload(self, ssn, from_gen: int) -> Optional[dict]:
        delta_since = getattr(ssn.cache, "delta_since", None)
        if delta_since is None:
            return None
        if getattr(ssn.cache, "_gen", None) != ssn.snapshot_gen:
            # the cache snapshotted again since this session opened
            # (harness pattern): the ring composes to a world this
            # session isn't looking at — full-sync from session state
            return None
        composed = delta_since(from_gen)
        if composed is None:
            return None
        changed_nodes, changed_jobs, removed_jobs, hn_changed = composed
        payload = self._common_payload(ssn)
        payload["from_gen"] = from_gen
        payload["nodes"] = {n: ssn.nodes[n] for n in changed_nodes
                            if n in ssn.nodes}
        payload["jobs"] = {k: ssn.jobs[k] for k in changed_jobs
                           if k in ssn.jobs}
        payload["removed_jobs"] = sorted(removed_jobs)
        payload["hypernodes"] = ssn.hypernodes if hn_changed else None
        return payload

    # -- fan-out -------------------------------------------------------

    def sweep(self, ssn, task, shards: List[list], need_class: bool):
        """Fan *shards* (lists of NodeInfo) across the workers.
        Returns (per_shard, leftover): per_shard maps GLOBAL shard
        index -> ([(node_name, score, cls)], [(node_name, status)]);
        leftover lists (index, shard) pairs the caller must re-sweep
        serially and merge at their index (stale refusals / crashed
        workers — degradation, never wrong rows, never a different
        merge order than the serial walk)."""
        self.ensure_sync(ssn)
        with trace.span("sweep_fanout", kind="action"):
            return self._sweep_synced(ssn, task, shards, need_class)

    def _sweep_synced(self, ssn, task, shards: List[list],
                      need_class: bool):
        gen, op_seq = ssn.snapshot_gen, len(ssn.mirror_log)
        alive = [w for w in self.workers]
        if not alive:
            return {}, list(enumerate(shards))
        assignments: Dict[int, list] = {i: [] for i in
                                        range(len(alive))}
        for i, shard in enumerate(shards):
            assignments[i % len(alive)].append((i, shard))
        pending = []
        for i, w in enumerate(alive):
            mine = assignments[i]
            if not mine:
                continue
            names = [[n.name for n in shard] for _, shard in mine]
            req_id = id(w) ^ int(time.monotonic_ns() & 0xFFFFFFF)
            try:
                post(w.conn, ("sweep", req_id, gen, op_seq,
                              task.job, task.uid, task.task_spec,
                              names, need_class))
                pending.append((w, req_id, mine))
            except (BrokenPipeError, OSError):
                self._retire(w, "crash")
                pending.append((None, req_id, mine))
        # rows keyed by GLOBAL shard index so the caller's merge —
        # including serially re-swept leftovers — lands in exactly
        # the order the serial shard walk would have produced
        per_shard: Dict[int, tuple] = {}
        leftover: list = []
        for w, req_id, mine in pending:
            if w is None:
                leftover.extend(mine)
                continue
            reply = self._recv(w, req_id)
            if reply is None:
                leftover.extend(mine)
                continue
            stale = reply[0] == "stale" or reply[2] != gen \
                or reply[3] != op_seq
            if stale:
                # rows computed against the wrong world are refused
                # wholesale; a full sync heals the worker next cycle
                # vtplint: disable=shared-cache-unkeyed (owner-thread counter; fan-outs are serialized on the session owner thread)
                self.stale_refusals += 1
                metrics.inc("sweep_stale_refusals_total")
                w.gen = -1
                leftover.extend(mine)
                continue
            rows = reply[4]
            if len(rows) != len(mine):
                # a malformed reply never half-merges
                leftover.extend(mine)
                continue
            for (idx, _shard), pair in zip(mine, rows):
                per_shard[idx] = pair
        return per_shard, leftover

    def _recv(self, w: _Worker, req_id: int):
        """One stamped reply from *w*, or None after retiring it
        (crash/timeout).  Unmatched req-ids are discarded — they are
        replies to requests an earlier failure already wrote off."""
        deadline = time.monotonic() + REQ_TIMEOUT_S
        while True:
            budget = deadline - time.monotonic()
            if budget <= 0:
                self._retire(w, "timeout")
                return None
            try:
                if not w.conn.poll(budget):
                    self._retire(w, "timeout")
                    return None
                msg = unship(w.conn.recv_bytes())
            except (EOFError, OSError):
                self._retire(w, "crash")
                return None
            if msg[1] == req_id:
                return msg

    # -- mirror divergence audit ---------------------------------------

    def audit_mirrors(self, ssn, names=None) -> bool:
        """Armed-auditor check: ask every synced worker for a digest
        of its mirror and compare against the owner snapshot.  A
        mismatch is recorded as a ``mirror-divergence`` violation on
        the freeze auditor's report surface and poisons the worker
        (full re-sync).  Returns True when all mirrors matched."""
        from volcano_tpu.analysis import freezeaudit
        self.ensure_sync(ssn)
        gen, op_seq = ssn.snapshot_gen, len(ssn.mirror_log)
        want = snapshot_digest(ssn.nodes, names)
        ok = True
        for w in list(self.workers):
            req_id = id(w) ^ 0x5A5A
            try:
                post(w.conn, ("digest", req_id, names))
            except (BrokenPipeError, OSError):
                self._retire(w, "crash")
                continue
            reply = self._recv(w, req_id)
            if reply is None:
                continue
            _, _, rgen, rops, digest = reply
            if rgen != gen or rops != op_seq:
                continue            # raced a restart: not divergence
            if digest != want:
                ok = False
                freezeaudit.record_boundary_violation(
                    "mirror-divergence",
                    ("mirror-divergence", w.id, gen, op_seq),
                    worker=w.id, gen=gen, ops=op_seq,
                    owner_digest=want, worker_digest=digest)
                w.gen = -1
        return ok

    def ping(self) -> List[tuple]:
        """(worker_id, pid, gen, ops) per worker — test/debug aid."""
        out = []
        for w in list(self.workers):
            req_id = id(w) ^ 0x9999
            try:
                post(w.conn, ("ping", req_id))
            except (BrokenPipeError, OSError):
                self._retire(w, "crash")
                continue
            reply = self._recv(w, req_id)
            if reply is not None:
                out.append((w.id,) + tuple(reply[2:]))
        return out

    def shutdown(self) -> None:
        for w in self.workers:
            try:
                post(w.conn, ("exit",))
                w.conn.close()
            except OSError:
                # vtplint: disable=except-pass (already-dead worker; join below reaps it)
                pass
        for w in self.workers:
            try:
                w.proc.wait(timeout=5)
            except Exception:  # noqa: BLE001
                # vtplint: disable=except-pass (the kill below is the remedy for a wedged worker)
                pass
            if w.proc.poll() is None:
                w.proc.kill()
        # vtplint: disable=shared-cache-unkeyed (teardown on the owner thread after every fan-out joined)
        self.workers = []


# -- process-wide pool (mirrors sweep.sweep_pool's lifetime) -----------

_POOL: Optional[ProcSweepPool] = None
_POOL_LOCK = threading.Lock()


def pool(workers: int) -> ProcSweepPool:
    """Process-wide sweep pool, grown (never shrunk) to *workers*.
    Growth adds workers; it never tears the pool down, so existing
    mirrors and any in-flight fan-out survive a mid-session resize."""
    global _POOL
    with _POOL_LOCK:
        if _POOL is None:
            _POOL = ProcSweepPool(workers)
        elif _POOL.size() < workers:
            _POOL.grow(workers)
        return _POOL


def shutdown() -> None:
    """Tear down the process-wide pool (tests / interpreter exit)."""
    global _POOL
    with _POOL_LOCK:
        if _POOL is not None:
            _POOL.shutdown()
            _POOL = None
