"""Per-spec predicate/score sweep machinery — allocate's hot cache,
refactored out of closures so it can be (a) fanned out across a
thread pool over the frozen snapshot (ROADMAP item 3's first measured
step) and (b) named by the static race pass (analysis/racecheck.py)
as the reader call tree it certifies.

Three sweep backends build the same entry:

  serial    the legacy path: ``ssn.predicate``/``ssn.node_order``
            dispatch per node, with per-plugin trace attribution.
            Always correct, always available — the fallback.
  thread    (``parallelPredicates: true`` / ``thread`` under the
            allocate action's configurations) the per-spec sweep is
            sharded by LEAF HYPERNODE GROUP and fanned out across a
            shared thread pool.  Workers run the prepared
            PreFilter/PreScore plugin forms over a read-only snapshot
            and return plain result rows; every mutation — entry
            assembly, heap builds, fit-error recording — happens on
            the calling thread after the barrier.  The freeze auditor
            (analysis/freezeaudit.py) brackets the fan-out so any
            write to snapshot state while workers are in flight is a
            recorded violation, and the batched form (no tier walk,
            no trace-timing wrapper, no Session dispatch per node) is
            what the measured sweep speedup in RACE_r15.json comes
            from.  GIL-bound: real hardware parallelism needs the
            process backend.
  process   (``parallelPredicates: process``) the same leaf shards
            fan across a pool of worker OS PROCESSES holding
            long-lived snapshot mirrors kept current by per-cycle
            deltas plus a within-cycle op journal; rows come back
            stamped with the (generation, ops) they were computed
            against, and anything stale re-sweeps serially.  See
            actions/procpool.py for the mirror/staleness protocol and
            docs/design/parallel-cycle.md for the contract.

The entry shape, the heap fast path and the single-node invalidation
contract are unchanged from allocate.py's original closures; see
AllocateAction for how picks consume them.
"""

from __future__ import annotations

import heapq
import os
import threading
import time
from typing import Dict, List, Optional

from volcano_tpu import metrics, trace
from volcano_tpu.actions.util import fit_class, predicate_nodes
from volcano_tpu.analysis import freezeaudit

# -- the shared sweep pool -------------------------------------------

_POOL = None
_POOL_WORKERS = 0
_POOL_LOCK = threading.Lock()

DEFAULT_WORKERS = min(8, (os.cpu_count() or 1) * 2)


def sweep_pool(workers: int):
    """Process-wide sweep executor, grown (never shrunk) to *workers*.
    One pool outlives every session: predicate sweeps run thousands of
    times per cycle and pool churn would dominate the win."""
    global _POOL, _POOL_WORKERS
    with _POOL_LOCK:
        if _POOL is None or _POOL_WORKERS < workers:
            from concurrent.futures import ThreadPoolExecutor
            old = _POOL
            _POOL = ThreadPoolExecutor(
                max_workers=workers, thread_name_prefix="vtp-sweep")
            _POOL_WORKERS = workers
            if old is not None:
                # DRAIN the old pool: shutdown(wait=False) abandoned
                # any fan-out another session had in flight — its
                # futures died unresolved and the barrier hung.  The
                # grower's own futures are not submitted yet (pools
                # resize at fan-out start), so waiting here can only
                # block on OTHER threads' already-running shards,
                # which complete without us.
                old.shutdown(wait=True)
        return _POOL


def parallel_conf(ssn):
    """(backend, workers) from the allocate action's configurations:

        configurations:
          allocate:
            parallelPredicates: thread      # or: process / true / off
            parallelPredicates.workers: 8

    ``true`` keeps meaning the thread backend (the PR 11 pilot's
    spelling); ``process`` selects the mirror-worker process pool
    (actions/procpool.py).  Backend is "" when disabled."""
    conf = ssn.conf.configurations.get("allocate", {})
    raw = conf.get("parallelPredicates", False)
    val = str(raw).lower()
    if not raw or val in ("false", "0", "none", "off"):
        return "", 0
    backend = "process" if val == "process" else "thread"
    try:
        workers = int(conf.get("parallelPredicates.workers",
                               DEFAULT_WORKERS))
    except (TypeError, ValueError):
        workers = DEFAULT_WORKERS
    return backend, max(1, workers)


# -- the per-shard worker (runs on pool threads: READS ONLY) ---------

def prepared_fns(ssn, point: str, prepare_point: str, task):
    """Per-node callables for *task* at *point*: the plugin's
    prepared form (PreFilter/PreScore idiom — every task-side
    constant hoisted once per sweep) when it registered one, else the
    raw callback partially applied to the task.  Built on the calling
    thread; workers only ever invoke the results."""
    import functools
    preps = dict(ssn.resolved_named_fns(prepare_point))
    out = []
    for name, fn in ssn.resolved_named_fns(point):
        prep = preps.get(name)
        if prep is not None:
            out.append(prep(task))
        else:
            out.append(functools.partial(fn, task))
    return out


def sweep_shard(task, shard, pred_fns, score_fns, need_class):
    """Predicate + score one shard of candidate nodes for *task*.

    Pool-thread body: touches nothing but its arguments and its own
    result rows.  pred_fns/score_fns are per-node callables from
    prepared_fns.  Fit errors are returned as (node, status) rows for
    the caller to record AFTER the barrier — job.record_fit_error is
    a mutation seam and seams are barred while a fan-out is active.
    """
    fits = []       # (node, score, cls)
    fails = []      # (node, status)
    for node in shard:
        verdict = None
        for fn in pred_fns:
            st = fn(node)
            if st is None or st.ok:
                continue
            verdict = st
            break
        if verdict is not None:
            fails.append((node, verdict))
            continue
        score = 0.0
        for fn in score_fns:
            score += fn(node)
        cls = fit_class(task, node) if need_class else None
        fits.append((node, score, cls))
    return fits, fails


def shard_nodes(ssn, nodes, workers) -> List[list]:
    """Shard candidates by leaf hypernode group — the unit item 3
    partitions by.  Groups are packed into at most ~2*workers batches
    (tiny leaves merge, one giant flat group splits): enough slack
    for the pool to balance, few enough that per-future overhead
    stays a rounding error at 1k+ hosts."""
    target = max(1, workers * 2)
    groups: Dict[object, list] = {}
    if ssn.hypernodes is not None:
        for n in nodes:
            groups.setdefault(ssn.node_group(n.name), []).append(n)
    else:
        groups[None] = list(nodes)
    if len(groups) == 1:
        (flat,) = groups.values()
        size = max(1, (len(flat) + target - 1) // target)
        return [flat[i:i + size] for i in range(0, len(flat), size)]
    shards: List[list] = []
    bucket: list = []
    per = max(1, len(nodes) // target)
    for _, members in sorted(groups.items(),
                             key=lambda kv: str(kv[0])):
        bucket.extend(members)
        if len(bucket) >= per:
            shards.append(bucket)
            bucket = []
    if bucket:
        shards.append(bucket)
    return shards


class SpecCache:
    """Per-spec predicate/score/fit-class cache with single-node
    invalidation: a gang's tasks are identical, and a placement only
    changes the state of the ONE node it landed on — so feasibility,
    per-node scores AND idle/future classification are recomputed
    just for that node instead of sweeping all nodes per task (the
    reference parallelizes this sweep; we make it incremental AND,
    with ``parallelPredicates``, parallel).

    Heap fast path is exact when every enabled BatchNodeOrder plugin
    also provides the leaf-grouped form (scores constant within a
    node group): the per-group heaps stay ordered by the cached
    NodeOrder score and the group offset is added at pick time.  Any
    ungrouped batch scorer (extender) forces the linear scan.
    """

    def __init__(self, ssn, candidate_nodes, record_errors: bool = True,
                 capacity_prefilter: bool = False):
        self.ssn = ssn
        self.candidate_nodes = list(candidate_nodes)
        # one shared name set for the whole cache: every entry sweeps
        # the same candidates, so invalidate's never-a-candidate skip
        # is a single O(1) lookup, not a per-entry set (which at 40k
        # hosts would cost an O(nodes) set build per spec)
        self.candidate_names = frozenset(
            n.name for n in self.candidate_nodes)
        self.record_errors = record_errors
        self.entries: Dict[str, dict] = {}
        if freezeaudit.enabled():
            # TSan-lite wiring: the static pass waives this table as
            # "confined to the allocate loop thread" — track it so a
            # cross-thread access (a leaked reference into a pool
            # worker) surfaces as an unsync-pair at runtime
            self.entries = freezeaudit.track(
                self.entries, "sweep.SpecCache.entries")
        batch_names = ssn.fn_plugin_names("batchNodeOrder")
        grouped_names = ssn.fn_plugin_names("groupedBatchNodeOrder")
        self.use_heap = not (batch_names - grouped_names)
        self.has_grouped = bool(grouped_names)
        backend, workers = parallel_conf(ssn)
        self.backend = backend
        self.workers = workers if backend else 0
        # Batched gang commit (actions/gangcommit.py) opts into a
        # cheap capacity gate ahead of the plugin chain: a node whose
        # idle AND future-idle cannot hold even one replica gets no
        # predicate/score dispatch at all — on a 60%-occupied fleet
        # that skips the majority of the sweep.  Serial backend only:
        # the parallel backends amortize differently and their entry
        # rows are byte-identity-certified against the UNfiltered
        # serial build.  Skipped nodes are remembered per entry so the
        # failure path can still surface per-node Insufficient rows.
        self.capacity_prefilter = bool(capacity_prefilter) and not backend
        if backend:
            # resolve the raw callback tables ONCE, on this thread,
            # before any fan-out: resolution populates the session's
            # dispatch memo (_raw_cache) so no worker ever writes it
            # mid-sweep (process workers resolve their OWN tables, but
            # the serial-fallback path still reads these)
            ssn.resolved_named_fns("predicate")
            ssn.resolved_named_fns("predicatePrepare")
            ssn.resolved_named_fns("nodeOrder")
            ssn.resolved_named_fns("nodeOrderPrepare")
            self._shards = shard_nodes(ssn, self.candidate_nodes,
                                       workers)

    def get(self, spec: str) -> Optional[dict]:
        return self.entries.get(spec)

    # -- build ---------------------------------------------------------

    def build_entry(self, task) -> dict:
        """Sweep every candidate node for *task* and cache the result
        under its spec.  The parallel path shards by leaf group; the
        serial path is the legacy per-node dispatch."""
        t0 = time.perf_counter()
        if self.backend == "process":
            entry = self._build_process(task)
        elif self.backend == "thread":
            entry = self._build_parallel(task)
        else:
            entry = self._build_serial(task)
        metrics.observe("predicate_sweep_seconds",
                        time.perf_counter() - t0,
                        mode=self.backend or "serial")
        # vtplint: disable=shared-cache-unkeyed (SpecCache is confined to the allocate loop thread; pool workers only ever see sweep_shard's arguments)
        self.entries[task.task_spec] = entry
        return entry

    def _new_entry(self, task) -> dict:
        return {
            "proto": task,
            "fits": {},     # name -> node (predicate-passing)
            "scores": {},   # name -> cached NodeOrder score
            # name -> (gen, cls, score): heap validity in ONE lookup —
            # heap_peek runs ~60x per task on a 10k-host gang, and
            # three separate dict.gets per peek were a measurable
            # slice of the cycle
            "meta": {},
            "group": {},    # name -> node group (leaf hypernode)
            # cls -> group -> heap of (-score, name, gen)
            "heaps": {"idle": {}, "future": {}},
            # cls -> {group: valid heap top (score, name)|None}.
            # Only a placement/invalidate can change a group's top,
            # so heap_best reads this cache instead of re-peeking
            # every group for every task; per-class dicts let it
            # iterate items() instead of hashing a (cls, group) tuple
            # per group per task
            "top": {"idle": {}, "future": {}},
            # the node names this entry was built over (shared
            # frozenset — see __init__): a placement on a node outside
            # the candidate set cannot change any cached verdict
            "candidates": self.candidate_names,
            # node names the capacity prefilter skipped (never swept):
            # the gang-commit failure path reports them as
            # Insufficient alongside the swept non-fitting nodes
            "prefiltered": (),
        }

    def _build_serial(self, task) -> dict:
        ssn = self.ssn
        entry = self._new_entry(task)
        if self.capacity_prefilter:
            kept, classes, skipped = [], {}, []
            for n in self.candidate_nodes:
                cls = fit_class(task, n)
                if cls is None:
                    skipped.append(n.name)
                else:
                    kept.append(n)
                    classes[n.name] = cls
            entry["prefiltered"] = skipped
            # the thread backend's certified batched form, on this
            # thread: prepared PreFilter/PreScore callables instead of
            # per-node Session dispatch (scores are byte-identity-
            # certified against ssn.node_order in RACE_r15.json)
            pred_fns = prepared_fns(ssn, "predicate",
                                    "predicatePrepare", task)
            score_fns = prepared_fns(ssn, "nodeOrder",
                                     "nodeOrderPrepare", task)
            fits, fails = sweep_shard(task, kept, pred_fns, score_fns,
                                      False)
            for n, score, _cls in fits:
                self._admit(entry, task, n, score,
                            classes[n.name] if self.use_heap else None)
            job = ssn.jobs.get(task.job)
            if self.record_errors and job is not None:
                from volcano_tpu.api.fit_error import FitError
                for n, st in fails:
                    # vtplint: disable=shared-cache-unkeyed (serial path on the session owner thread — no fan-out is live; record_fit_error is a designated mutation seam)
                    job.record_fit_error(task, n.name,
                                         FitError(task, n, statuses=[st]))
            self._seal(entry)
            return entry
        fit_nodes = predicate_nodes(ssn, task, self.candidate_nodes,
                                    self.record_errors)
        for n in fit_nodes:
            self._admit(entry, task, n, ssn.node_order(task, n),
                        fit_class(task, n) if self.use_heap else None)
        self._seal(entry)
        return entry

    def _build_parallel(self, task) -> dict:
        ssn = self.ssn
        entry = self._new_entry(task)
        pool = sweep_pool(self.workers)
        pred_fns = prepared_fns(ssn, "predicate", "predicatePrepare",
                                task)
        score_fns = prepared_fns(ssn, "nodeOrder", "nodeOrderPrepare",
                                 task)
        need_class = self.use_heap
        t0 = time.perf_counter()
        freezeaudit.fanout_begin()
        try:
            # the calling thread takes the first shard itself instead
            # of idling at the barrier — one fewer future, and on a
            # busy pool the submit queue drains while it works
            futures = [pool.submit(sweep_shard, task, shard, pred_fns,
                                   score_fns, need_class)
                       for shard in self._shards[1:]]
            results = [sweep_shard(task, self._shards[0], pred_fns,
                                   score_fns, need_class)] \
                if self._shards else []
            results += [f.result() for f in futures]
        finally:
            freezeaudit.fanout_end()
        # the barrier is behind us: every mutation below runs on the
        # calling thread against worker-returned rows
        trace.add_plugin_time("predicate", "_parallel_sweep",
                              time.perf_counter() - t0)
        job = ssn.jobs.get(task.job)
        for fits, fails in results:
            for node, score, cls in fits:
                self._admit(entry, task, node, score, cls)
            if self.record_errors and job is not None:
                from volcano_tpu.api.fit_error import FitError
                for node, st in fails:
                    # vtplint: disable=shared-cache-unkeyed (post-barrier merge on the session owner thread: the fan-out has joined and record_fit_error is a designated mutation seam)
                    job.record_fit_error(
                        task, node.name,
                        FitError(task, node, statuses=[st]))
        self._seal(entry)
        return entry

    def _build_process(self, task) -> dict:
        """Fan the sweep across the mirror-worker process pool
        (actions/procpool.py).  Workers hold long-lived snapshot
        mirrors and resolve the prepared plugin forms themselves —
        only the task, shard names and compact (name, score, class)
        rows cross the boundary.  Stale/crashed shards degrade to the
        serial prepared-form sweep on this thread; the merge below is
        owner-thread-only, exactly like the thread backend."""
        from volcano_tpu.actions import procpool
        ssn = self.ssn
        entry = self._new_entry(task)
        pool = procpool.pool(self.workers)
        need_class = self.use_heap
        t0 = time.perf_counter()
        freezeaudit.fanout_begin()
        try:
            per_shard, leftover = pool.sweep(
                ssn, task, self._shards, need_class)
            if freezeaudit.enabled():
                pool.audit_mirrors(ssn, self.candidate_names)
        finally:
            freezeaudit.fanout_end()
        if leftover:
            # refused/stale/crashed shards re-sweep serially with the
            # owner's own prepared forms and merge at their GLOBAL
            # shard index — a degraded cycle's entry order stays
            # byte-identical to a healthy one's
            pred_fns = prepared_fns(ssn, "predicate",
                                    "predicatePrepare", task)
            score_fns = prepared_fns(ssn, "nodeOrder",
                                     "nodeOrderPrepare", task)
            for idx, shard in leftover:
                f, e = sweep_shard(task, shard, pred_fns, score_fns,
                                   need_class)
                per_shard[idx] = (
                    [(n.name, score, cls) for n, score, cls in f],
                    [(n.name, st) for n, st in e])
        fit_rows: list = []
        fail_rows: list = []
        for idx in sorted(per_shard):
            f, e = per_shard[idx]
            fit_rows.extend(f)
            fail_rows.extend(e)
        trace.add_plugin_time("predicate", "_process_sweep",
                              time.perf_counter() - t0)
        with trace.span("sweep_merge", kind="action"):
            job = ssn.jobs.get(task.job)
            by_name = ssn.nodes
            for name, score, cls in fit_rows:
                node = by_name.get(name)
                if node is not None:
                    self._admit(entry, task, node, score, cls)
            if self.record_errors and job is not None:
                from volcano_tpu.api.fit_error import FitError
                for name, st in fail_rows:
                    node = by_name.get(name)
                    if node is None:
                        continue
                    # vtplint: disable=shared-cache-unkeyed (post-barrier merge on the session owner thread; record_fit_error is a designated mutation seam)
                    job.record_fit_error(
                        task, name,
                        FitError(task, node, statuses=[st]))
            self._seal(entry)
        return entry

    def _admit(self, entry, task, node, score, cls):
        """Fold one predicate-passing node into a being-built entry."""
        entry["fits"][node.name] = node
        entry["scores"][node.name] = score
        if self.use_heap:
            group = self.ssn.node_group(node.name) \
                if self.has_grouped else None
            entry["group"][node.name] = group
            entry["meta"][node.name] = (0, cls, score)
            if cls is not None:
                entry["heaps"][cls].setdefault(group, []).append(
                    (-score, node.name, 0))

    def _seal(self, entry):
        if not self.use_heap:
            return
        for cls, groups in entry["heaps"].items():
            tops = entry["top"][cls]
            for group, heap in groups.items():
                heapq.heapify(heap)
                tops[group] = heap_peek(entry, cls, group)

    # -- single-node invalidation --------------------------------------

    def invalidate(self, node) -> None:
        """A placement landed on *node*: recompute just that node's
        feasibility/score/class in every cached entry that swept it.
        A node outside the cache's candidate set is skipped outright —
        no cached verdict can have changed, and the per-spec
        ``ssn.predicate`` re-run used to be pure waste.  Allocate
        itself always places on a swept node, so in-tree this guard is
        the cache's API contract for restricted-candidate callers
        (item 3's partitioned schedulers fan placements from OTHER
        shards' statements at caches built over their own subtree)."""
        if node.name not in self.candidate_names:
            return
        ssn = self.ssn
        use_heap = self.use_heap
        for entry in self.entries.values():
            proto = entry["proto"]
            old = entry["meta"].get(node.name) if use_heap else None
            gen = (old[0] + 1) if old else 1
            if ssn.predicate(proto, node) is None:
                entry["fits"][node.name] = node
                score = ssn.node_order(proto, node)
                entry["scores"][node.name] = score
                if use_heap:
                    cls = fit_class(proto, node)
                    entry["meta"][node.name] = (gen, cls, score)
                    if cls is not None:
                        group = entry["group"].get(node.name)
                        heapq.heappush(
                            entry["heaps"][cls].setdefault(group, []),
                            (-score, node.name, gen))
            else:
                entry["fits"].pop(node.name, None)
                entry["scores"].pop(node.name, None)
                if use_heap:
                    entry["meta"][node.name] = (gen, None, None)
            if use_heap:
                # this node's group is the only one whose top can
                # have changed (either class: a node may have moved
                # idle <-> future) — refresh just those two cache
                # slots
                group = entry["group"].get(node.name)
                for cls in ("idle", "future"):
                    if group in entry["heaps"][cls]:
                        entry["top"][cls][group] = heap_peek(
                            entry, cls, group)


def heap_peek(entry, cls, group):
    """Valid top of one group heap (lazy-discarding stale)."""
    heap = entry["heaps"][cls].get(group)
    if not heap:
        return None
    meta = entry["meta"]
    while heap:
        neg_score, name, gen = heap[0]
        m = meta.get(name)
        if m is not None and m[0] == gen and m[1] == cls \
                and m[2] == -neg_score:
            return -neg_score, name
        heapq.heappop(heap)
    return None


def heap_best(entry, cls, group_scores):
    """Highest (cached score + group offset) node of *cls*; ties
    broken by smallest name, exactly like the linear scan.  Group
    tops come from the entry's top cache (maintained by
    build/invalidate), so scoring a task is one arithmetic pass over
    groups, not a heap walk."""
    best = None          # (total, name)
    if group_scores:
        get_offset = group_scores.get
        for group, top in entry["top"][cls].items():
            if top is None:
                continue
            total = top[0] + get_offset(group, 0.0)
            if best is None or total > best[0] or \
                    (total == best[0] and top[1] < best[1]):
                best = (total, top[1])
    else:
        for top in entry["top"][cls].values():
            if top is None:
                continue
            if best is None or top[0] > best[0] or \
                    (top[0] == best[0] and top[1] < best[1]):
                best = top
    return entry["fits"][best[1]] if best else None
