"""Backfill action — place BestEffort (no-request) tasks.

Reference parity: actions/backfill/backfill.go.  Best-effort tasks
don't consume accounted resources, so each is bound individually as
soon as any predicate-passing node exists — no gang gating.
"""

from __future__ import annotations

import logging

from volcano_tpu.api.types import PodGroupPhase, TaskStatus
from volcano_tpu.framework.plugins import Action, register_action

from volcano_tpu.actions.util import predicate_nodes, prioritize_nodes

log = logging.getLogger(__name__)


class BackfillAction(Action):
    name = "backfill"

    def execute(self, ssn) -> None:
        for job in ssn.jobs.values():
            # cheap emptiness probe FIRST: on a steady fleet the
            # per-job gang-validity walk below cost more than every
            # other action combined, for jobs with nothing to backfill
            pending = job.task_status_index.get(TaskStatus.PENDING)
            if not pending:
                continue
            if job.podgroup is not None and \
                    job.podgroup.phase is PodGroupPhase.PENDING and \
                    "enqueue" in ssn.conf.actions:
                continue
            if ssn.job_valid(job) is not None:
                continue
            for task in list(pending.values()):
                if not task.best_effort:
                    continue
                nodes = predicate_nodes(ssn, task,
                                        list(ssn.nodes.values()))
                node = prioritize_nodes(ssn, task, nodes)
                if node is None:
                    continue
                stmt = ssn.statement()
                stmt.allocate(task, node)
                stmt.commit()


register_action(BackfillAction())
