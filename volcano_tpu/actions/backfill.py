"""Backfill action — place BestEffort (no-request) tasks.

Reference parity: actions/backfill/backfill.go.  Best-effort tasks
don't consume accounted resources, so each is bound individually as
soon as any predicate-passing node exists — no gang gating.
"""

from __future__ import annotations

import logging

from volcano_tpu.api.types import PodGroupPhase, TaskStatus
from volcano_tpu.framework.plugins import Action, register_action

from volcano_tpu.actions.util import predicate_nodes, prioritize_nodes

log = logging.getLogger(__name__)


class BackfillAction(Action):
    name = "backfill"

    def execute(self, ssn) -> None:
        for job in ssn.jobs.values():
            if job.podgroup is not None and \
                    job.podgroup.phase is PodGroupPhase.PENDING and \
                    "enqueue" in ssn.conf.actions:
                continue
            if ssn.job_valid(job) is not None:
                continue
            for task in job.tasks_in_status(TaskStatus.PENDING):
                if not task.best_effort:
                    continue
                nodes = predicate_nodes(ssn, task,
                                        list(ssn.nodes.values()))
                node = prioritize_nodes(ssn, task, nodes)
                if node is None:
                    continue
                stmt = ssn.statement()
                stmt.allocate(task, node)
                stmt.commit()


register_action(BackfillAction())
