"""Elastic action — world size as a scheduler decision.

Runs AFTER allocate (fixed-size placement first; elasticity spends
what is left) and BEFORE gangpreempt/backfill (a shrink that frees a
slice must pre-empt the pre-emptor: evicting a gang loses its pods,
shrinking one loses nothing — it checkpoints and resumes smaller).

Per cycle, over the post-allocate state:

  grow     whole idle slices beyond what pending gangs need are handed
           to running elastic jobs below their max-slices, in job
           order.  A slice only counts when every host is ready,
           untainted by quarantine, and chip-idle — elastic growth
           must absorb stranded capacity, not race real placements.

  shrink   when pending gangs cannot fit idle capacity, running
           elastic jobs above min-slices shed slices to cover the
           deficit — victims picked TOPOLOGY-AWARE: prefer shedding
           slices in the domain (DCN pod) that already holds the most
           idle chips, so the freed block is contiguous with existing
           idle and a multi-slice pending gang lands in ONE domain.

  fit      a PENDING elastic job above its floor that cannot place at
           its current size is resized DOWN to what idle capacity can
           hold (spec-only — nothing to drain, it never started);
           pending at the floor with no capacity records the bounded
           `elastic-waiting-for-capacity` reason so `vtpctl explain`
           names the wait instead of `other`.

Decisions are annotation stamps on the podgroup (desired-slices +
resize-reason); controllers/elastic.py executes them via the
checkpoint-drain-resume path.  Flap damping: a job resized less than
`elastic.cooldownSeconds` ago (action configuration, default 30) is
not re-decided.

Reference analogues: Singularity transparent resize (arxiv
2202.07848); Pollux-style elastic goodput scheduling (arxiv
2008.12260).
"""

from __future__ import annotations

import logging
import math
import time
from typing import Dict, List, Optional

from volcano_tpu import metrics
from volcano_tpu.api import elastic as eapi
from volcano_tpu.api.fit_error import FitErrors
from volcano_tpu.api.job_info import JobInfo
from volcano_tpu.api.resource import TPU
from volcano_tpu.api.types import PodGroupPhase, TaskStatus, TPU_SLICE_LABEL
from volcano_tpu.controllers.hypernode import DCN_POD_LABEL
from volcano_tpu.framework.plugins import Action, register_action
from volcano_tpu.util import PriorityQueue

log = logging.getLogger(__name__)

DEFAULT_COOLDOWN_S = 30.0
# goodput grow gate (the minimal Pollux step): a further grow is
# declined when the LAST grow's measured speedup fell below
# 1 + frac * (linear - 1) — with 0.5, growing 1 -> 2 slices must have
# bought at least 1.5x measured steps/s before a third slice is
# granted.  A job the observatory has no data on is never blocked.
GROW_MARGINAL_FRACTION = 0.5


class SliceView:
    """One slice as the elastic action sees it this session."""

    __slots__ = ("name", "domain", "nodes", "chips", "idle_chips",
                 "busy", "quarantined")

    def __init__(self, name: str, domain: str):
        self.name = name
        self.domain = domain
        self.nodes: List = []
        self.chips = 0.0
        self.idle_chips = 0.0
        self.busy = False
        self.quarantined = False

    @property
    def idle(self) -> bool:
        return not self.busy and not self.quarantined and self.chips > 0


def _quarantined(node) -> bool:
    from volcano_tpu.api.slicehealth import (
        NODE_QUARANTINED_UNTIL_ANNOTATION)
    if node.node is None:
        return False
    try:
        until = float(node.node.annotations.get(
            NODE_QUARANTINED_UNTIL_ANNOTATION, 0) or 0)
    except (TypeError, ValueError):
        return False
    return until > time.time()


def slice_views(ssn) -> Dict[str, SliceView]:
    """slice name -> SliceView over the session's node snapshot."""
    out: Dict[str, SliceView] = {}
    for node in ssn.nodes.values():
        if node.node is None:
            continue
        sl = node.node.labels.get(TPU_SLICE_LABEL)
        if not sl:
            continue
        view = out.get(sl)
        if view is None:
            view = out[sl] = SliceView(
                sl, node.node.labels.get(DCN_POD_LABEL, ""))
        view.nodes.append(node)
        chips = float(node.allocatable.get(TPU))
        used = float(node.used.get(TPU))
        view.chips += chips
        view.idle_chips += max(0.0, chips - used)
        if node.tasks or used > 0 or not node.ready:
            view.busy = True
        if _quarantined(node):
            view.quarantined = True
    return out


def job_slices(ssn, job: JobInfo) -> List[str]:
    """Slices the job's placed tasks currently occupy."""
    names = set()
    for task in job.tasks.values():
        if task.status in (TaskStatus.ALLOCATED, TaskStatus.BINDING,
                           TaskStatus.BOUND, TaskStatus.RUNNING) \
                and task.node_name:
            node = ssn.nodes.get(task.node_name)
            if node is not None and node.node is not None:
                sl = node.node.labels.get(TPU_SLICE_LABEL)
                if sl:
                    names.add(sl)
    return sorted(names)


def _chips_per_slice(job: JobInfo, pg) -> float:
    """Chips one slice of this job's world costs: pods-per-slice x
    per-pod TPU request (pods-per-slice = replicas / current slices,
    invariant across resizes — admission validates divisibility)."""
    tasks = list(job.tasks.values())
    if not tasks:
        return 0.0
    per_pod = max(float(t.resreq.get(TPU)) for t in tasks)
    cur = eapi.current_slices(pg)
    per_slice_pods = max(1, len(tasks) // max(1, cur))
    return per_pod * per_slice_pods


class ElasticAction(Action):
    name = "elastic"

    def execute(self, ssn) -> None:
        elastic_jobs = [
            j for j in ssn.jobs.values()
            if j.podgroup is not None and eapi.is_elastic(j.podgroup)]
        if not elastic_jobs:
            return
        conf = ssn.conf.configurations.get("elastic", {})
        try:
            cooldown = float(conf.get("elastic.cooldownSeconds",
                                      DEFAULT_COOLDOWN_S))
        except (TypeError, ValueError):
            cooldown = DEFAULT_COOLDOWN_S
        self._gate_on = str(conf.get("elastic.goodputGateGrow",
                                     "true")).lower() not in (
            "false", "0", "no", "off")
        try:
            self._gate_frac = float(conf.get(
                "elastic.growMarginalFraction", GROW_MARGINAL_FRACTION))
        except (TypeError, ValueError):
            self._gate_frac = GROW_MARGINAL_FRACTION
        now = time.time()
        slices = slice_views(ssn)
        idle = [s for s in slices.values() if s.idle]

        # pending demand in chips: gang-blocked jobs whose pending
        # tasks allocate could not place this cycle (the capacity a
        # shrink must produce / a grow must NOT consume)
        pending_jobs = []
        pending_chips = 0.0
        for job in ssn.jobs.values():
            pg = job.podgroup
            if pg is None or pg.phase not in (PodGroupPhase.PENDING,
                                              PodGroupPhase.INQUEUE):
                continue
            if eapi.evacuating(pg):
                # a gang drained (or draining) for a cross-region
                # cutover is LEAVING: its held pods are not demand
                # this region should shrink donors to fund
                continue
            pending = [t for t in
                       job.tasks_in_status(TaskStatus.PENDING)
                       if not t.best_effort]
            if not pending or ssn.job_ready(job):
                continue
            pending_jobs.append(job)
            pending_chips += sum(float(t.resreq.get(TPU))
                                 for t in pending)

        decided = self._shrink_pending_to_fit(ssn, pending_jobs, idle,
                                              cooldown, now)
        # ONE resize in flight at a time: while any elastic gang is
        # mid-drain, its vacated slices read as idle — deciding a new
        # grow/shrink against them double-spends the same capacity
        # and the fleet oscillates (gang A grows into gang B's drain,
        # B re-places into A's, forever).  Pending-to-fit above is
        # exempt: it only ever shrinks a gang toward what exists.
        # DEMAND-side gangs are exempt too: a gang requeued by its
        # own grow (the serving scale-up path) is pending precisely
        # FOR the capacity this cycle must produce — counting it
        # would wedge the funding shrink below until the cooldown
        # expires and pending-to-fit reverts the grow instead.  Its
        # vacated slices are already subtracted from the deficit, so
        # nothing is double-spent.
        demand = {id(j) for j in pending_jobs}
        if any(self._in_flight(j.podgroup) for j in elastic_jobs
               if id(j) not in demand):
            return
        # slices reserved for pending fixed demand are not growable
        reserve = pending_chips
        grow_pool = []
        for s in sorted(idle, key=lambda s: (s.domain, s.name)):
            if s.name in decided:
                continue
            if reserve > 0:
                reserve -= s.chips
                continue
            grow_pool.append(s)
        self._grow(ssn, elastic_jobs, grow_pool, cooldown, now)
        # the deficit is recomputed against IN-FLIGHT DRAINS at
        # decision time: a demand-side gang requeued by its own grow
        # (the serving scale-up path) still OCCUPIES its old slices
        # while the drain executes — they read busy, not idle, yet
        # the restart is guaranteed to vacate them before the gang
        # re-places.  Counting those chips as neither idle nor freed
        # inflated the deficit by the gang's whole old footprint and
        # over-evicted training victims (a 2->3 serving grow funded 3
        # slices instead of 1, self-correcting only a cooldown later
        # via regrow).  Credit the draining chips up front instead.
        draining = self._draining_chips(ssn, pending_jobs, now)
        deficit = pending_chips - sum(s.chips for s in idle) - draining
        if deficit > 0:
            self._shrink(ssn, elastic_jobs, slices, idle, deficit,
                         cooldown, now)

    # -- decision plumbing ---------------------------------------------

    def _draining_chips(self, ssn, pending_jobs, now: float) -> float:
        """Chips that in-flight drains of DEMAND-SIDE gangs are about
        to free: every node-holding task (allocated/bound/running, or
        already releasing) of a pending elastic gang whose resize or
        requeue is executing.  These are exactly the gangs the
        in-flight barrier exempts — their teardown is the other half
        of the capacity this cycle's deficit must produce."""
        from volcano_tpu.api.types import ALLOCATED_TASK_STATUSES
        holding = ALLOCATED_TASK_STATUSES | {TaskStatus.RELEASING}
        freed = 0.0
        for job in pending_jobs:
            pg = job.podgroup
            if pg is None or not eapi.is_elastic(pg) or \
                    not self._in_flight(pg, now):
                continue
            freed += sum(float(t.resreq.get(TPU))
                         for t in job.tasks.values()
                         if t.status in holding and t.node_name)
        return freed

    @staticmethod
    def _in_flight(pg, now: Optional[float] = None) -> bool:
        from volcano_tpu.api.types import PodGroupPhase
        from volcano_tpu.api.slicehealth import REQUEUED_ANNOTATION
        # A desired decision counts only while FRESH: with no elastic
        # controller alive to execute it, the decision must expire
        # rather than freeze the loop (and the preempt veto) forever.
        # REQUEUED counts only while the gang is NOT running: a
        # failover/resize in progress keeps capacity in flux, but a
        # stale marker on a running gang (controller restarted before
        # clearing it) must not freeze the decision loop.
        now = time.time() if now is None else now
        return ((eapi.desired_slices(pg) is not None
                 and not eapi.decision_stale(pg, now))
                or eapi.ELASTIC_RESIZING_ANNOTATION in pg.annotations
                or bool(eapi.avoid_slices(pg))
                or (pg.annotations.get(REQUEUED_ANNOTATION) == "true"
                    and pg.phase is not PodGroupPhase.RUNNING))

    @staticmethod
    def _cooling(pg, cooldown: float, now: float) -> bool:
        try:
            last = float(pg.annotations.get(
                eapi.ELASTIC_LAST_RESIZE_TS_ANNOTATION, 0) or 0)
        except (TypeError, ValueError):
            return False
        return bool(cooldown) and now - last < cooldown

    def _stamp(self, ssn, job: JobInfo, desired: int, kind: str,
               detail: str) -> None:
        pg = job.podgroup
        prev = eapi.desired_slices(pg)
        pg.annotations[eapi.ELASTIC_DESIRED_SLICES_ANNOTATION] = \
            str(desired)
        pg.annotations[eapi.ELASTIC_RESIZE_REASON_ANNOTATION] = kind
        if prev != desired or eapi.ELASTIC_DECIDED_TS_ANNOTATION \
                not in pg.annotations:
            # first-stamp time of THIS desired value: re-deciding the
            # same value must not refresh it, or an unexecuted
            # decision could never go stale
            pg.annotations[eapi.ELASTIC_DECIDED_TS_ANNOTATION] = \
                f"{time.time():.3f}"
        ssn.cache.update_podgroup_status(pg)
        ssn.cache.record_event(
            job.key, "ElasticDecision",
            f"{kind} to {desired} slice(s): {detail}")
        metrics.inc("elastic_decisions_total", kind=kind)
        log.info("elastic: %s %s -> %d slices (%s)", kind, job.key,
                 desired, detail)

    # -- grow -----------------------------------------------------------

    def _grow(self, ssn, elastic_jobs, pool: List[SliceView],
              cooldown: float, now: float) -> None:
        from volcano_tpu.api import serving as sapi
        growable = PriorityQueue(ssn.job_order_fn)
        for job in elastic_jobs:
            pg = job.podgroup
            rng = eapi.elastic_range(pg)
            if rng is None or pg.phase is not PodGroupPhase.RUNNING:
                continue
            # serving groups size from TRAFFIC, not from idle chips:
            # the SLO autoscaler (controllers/serving.py) owns their
            # replica count — greedy absorption would hand a quiet
            # group chips it must immediately shed
            if sapi.is_serving(pg):
                continue
            if self._in_flight(pg) or self._cooling(pg, cooldown, now):
                continue
            if eapi.current_slices(pg) < rng[1]:
                growable.push(job)
        for job in growable:
            if not pool:
                break
            pg = job.podgroup
            cur = eapi.current_slices(pg)
            if not self._grow_pays(ssn, job, pg, cur):
                continue
            per_slice = _chips_per_slice(job, pg)
            usable = [s for s in pool if s.chips >= per_slice > 0]
            take = min(eapi.elastic_range(pg)[1] - cur, len(usable))
            if take <= 0:
                continue
            taken = usable[:take]
            for s in taken:
                pool.remove(s)
            self._stamp(ssn, job, cur + take, eapi.RESIZE_GROW,
                        f"absorbing {take} idle slice(s) "
                        f"({', '.join(s.name for s in taken)})")

    def _grow_pays(self, ssn, job: JobInfo, pg, cur: int) -> bool:
        """Goodput grow gate (closed loop over the observatory):
        consult the session's ThroughputBook for the measured marginal
        throughput the job's LAST grow bought.  Declining is a
        per-cycle decision, not a latch — once the measured rate at
        the current size improves (or the data ages into a better
        EWMA), the gate reopens.  No data -> no opinion -> allow:
        greedy absorption stays the cold-start behavior."""
        book = getattr(ssn, "goodput", None)
        if not self._gate_on or book is None:
            return True
        verdict = book.grow_verdict(pg.key, cur, self._gate_frac)
        if verdict is None:
            return True
        if verdict:
            metrics.inc("goodput_gated_grows_total",
                        decision="allowed")
            return True
        metrics.inc("goodput_gated_grows_total", decision="declined")
        ssn.cache.record_event(
            job.key, "ElasticGrowDeclined",
            f"measured marginal throughput below threshold at {cur} "
            f"slice(s); idle capacity left for better scalers")
        log.info("elastic: grow of %s declined by goodput gate at %d "
                 "slice(s)", job.key, cur)
        return False

    # -- shrink (running victims, topology-aware) ------------------------

    @staticmethod
    def _slice_leaf(ssn, view: SliceView) -> Optional[str]:
        """Leaf hypernode hosting a slice (tier-1: hypernode == ICI
        slice, so any member node resolves it)."""
        hn = getattr(ssn, "hypernodes", None)
        if hn is None:
            return None
        for node in view.nodes:
            leaf = hn.leaf_of_node(node.name)
            if leaf:
                return leaf
        return None

    def _serving_tier(self, ssn, slices, anchor_leaves,
                      job: JobInfo, slice_name: str) -> float:
        """ICI/DCN distance (hypernode LCA tier; lower = closer) from
        one of the victim's slices to the nearest serving-pool slice."""
        view = slices.get(slice_name)
        if view is None:
            return math.inf
        leaf = self._slice_leaf(ssn, view)
        if leaf is None:
            return math.inf
        hn = ssn.hypernodes
        return min((hn.lca_tier_of_leaves(leaf, al)
                    for al in anchor_leaves), default=math.inf)

    def _shrink(self, ssn, elastic_jobs, slices, idle, deficit: float,
                cooldown: float, now: float) -> None:
        from volcano_tpu.api import serving as sapi
        victims = []
        for job in elastic_jobs:
            pg = job.podgroup
            rng = eapi.elastic_range(pg)
            if rng is None or pg.phase is not PodGroupPhase.RUNNING:
                continue
            # serving groups are never donors: shedding a replica to
            # fund generic pending demand trades a latency SLO for
            # queue progress — only their own autoscaler shrinks them
            if sapi.is_serving(pg):
                continue
            if self._in_flight(pg) or self._cooling(pg, cooldown, now):
                continue
            cur = eapi.current_slices(pg)
            if cur > rng[0]:
                victims.append(job)
        if not victims:
            return
        # topology-aware ordering: idle chips already concentrate in
        # some domain — shed slices THERE first, so freed + idle form
        # one contiguous block a multi-slice gang can take whole
        idle_by_domain: Dict[str, float] = {}
        for s in idle:
            idle_by_domain[s.domain] = \
                idle_by_domain.get(s.domain, 0.0) + s.chips

        def domain_affinity(job: JobInfo) -> float:
            return max((idle_by_domain.get(slices[sl].domain, 0.0)
                        for sl in job_slices(ssn, job)
                        if sl in slices), default=0.0)

        # lowest-allocation-priority victims shed first (reverse job
        # order), then stable-sorted so the topology key dominates
        by_priority = list(PriorityQueue(ssn.job_order_fn, victims))
        by_priority.reverse()

        # serving burst preemption (plugins/serving.py exported the
        # anchor): rank victims by hypernode-LCA proximity of their
        # occupied slices to the SERVING POOL, so the eviction frees
        # an ICI-contiguous block next to the replicas — not merely
        # an equally-sized hole anywhere.  Without an anchor, fall
        # back to idle-domain affinity (freed + idle form one block).
        anchors = {s for s in getattr(ssn, "serving_anchor_slices",
                                      ()) or () if s in slices}
        anchor_leaves = []
        if anchors and getattr(ssn, "hypernodes", None) is not None:
            anchor_leaves = [
                leaf for leaf in (self._slice_leaf(ssn, slices[a])
                                  for a in anchors)
                if leaf is not None]
        serving_mode = bool(anchor_leaves)

        def pool_tier(job: JobInfo) -> float:
            return min((self._serving_tier(ssn, slices, anchor_leaves,
                                           job, sl)
                        for sl in job_slices(ssn, job)),
                       default=math.inf)

        if serving_mode:
            ranked = sorted(by_priority, key=pool_tier)
        else:
            ranked = sorted(by_priority,
                            key=lambda j: -domain_affinity(j))
        for job in ranked:
            if deficit <= 0:
                break
            pg = job.podgroup
            rng = eapi.elastic_range(pg)
            cur = eapi.current_slices(pg)
            per_slice = _chips_per_slice(job, pg)
            if per_slice <= 0:
                continue
            want = math.ceil(deficit / per_slice)
            take = min(cur - rng[0], want)
            if take <= 0:
                continue
            deficit -= take * per_slice
            detail = f"freeing {take} slice(s) for pending demand"
            if serving_mode:
                # steer the victim's re-placement OFF its slices
                # nearest the serving pool: the avoid preference
                # (elastic plugin predicate, yield-guarded by the
                # controller) makes the freed block the ADJACENT one,
                # not whichever slices the re-place happens to leave
                near = sorted(
                    job_slices(ssn, job),
                    key=lambda sl: self._serving_tier(
                        ssn, slices, anchor_leaves, job, sl))[:take]
                if near:
                    from volcano_tpu.api import serving as sapi
                    pg.annotations[
                        eapi.ELASTIC_AVOID_SLICES_ANNOTATION] = \
                        ",".join(near)
                    pg.annotations[sapi.VICTIM_ANNOTATION] = "true"
                    detail = (f"freeing {take} ICI-adjacent slice(s) "
                              f"({', '.join(near)}) for a serving "
                              f"scale-up")
                metrics.inc("serving_victim_shrinks_total")
            self._stamp(ssn, job, cur - take, eapi.RESIZE_SHRINK,
                        detail)

    # -- pending elastic jobs: fit down / name the wait ------------------

    def _shrink_pending_to_fit(self, ssn, pending_jobs, idle,
                               cooldown: float, now: float) -> set:
        """Resize a PENDING elastic gang down to what idle capacity
        holds (spec-only; it never started, nothing drains).  Returns
        slice names notionally consumed by these decisions so grow
        does not double-spend them."""
        consumed: set = set()
        for job in pending_jobs:
            pg = job.podgroup
            rng = eapi.elastic_range(pg) if eapi.is_elastic(pg) else None
            if rng is None:
                continue
            # NARROWER in-flight check than grow/shrink: a pending
            # gang is re-fit even while REQUEUED (a drained gang that
            # can no longer place at its decided size would otherwise
            # wedge forever — shrink-to-fit is the unwedge); a STALE
            # decision (no controller consuming it) is replaceable
            if (eapi.desired_slices(pg) is not None
                    and not eapi.decision_stale(pg, now)) or \
                    self._cooling(pg, cooldown, now):
                continue
            cur = eapi.current_slices(pg)
            per_slice = _chips_per_slice(job, pg)
            free = [s for s in idle
                    if s.name not in consumed and s.chips >= per_slice]
            fit = min(len(free), cur)
            if per_slice <= 0:
                continue
            if cur > rng[0] and rng[0] <= fit < cur:
                for s in free[:fit]:
                    consumed.add(s.name)
                self._stamp(ssn, job, fit, eapi.RESIZE_SHRINK,
                            f"pending gang resized to fit {fit} idle "
                            f"slice(s)")
            elif fit < max(cur, rng[0]):
                # blocked at (or below) the floor: name the wait with
                # the bounded enum instead of the generic fit errors
                # normalizing to `other`/`insufficient-resources` only
                pending = job.tasks_in_status(TaskStatus.PENDING)
                if pending:
                    errs = job.fit_errors.setdefault(
                        pending[0].uid, FitErrors())
                    if not errs.err:
                        errs.set_error(
                            f"elastic: waiting for capacity — "
                            f"{fit} idle slice(s) for a "
                            f"min {rng[0]}-slice gang")
        return consumed


register_action(ElasticAction())
