"""Scheduling actions (reference: pkg/scheduler/actions/factory.go).

Importing this package registers every action.
"""

import volcano_tpu.actions.enqueue      # noqa: F401
import volcano_tpu.actions.allocate     # noqa: F401
import volcano_tpu.actions.backfill     # noqa: F401
import volcano_tpu.actions.preempt      # noqa: F401
import volcano_tpu.actions.reclaim      # noqa: F401
import volcano_tpu.actions.gangpreempt  # noqa: F401
import volcano_tpu.actions.shuffle      # noqa: F401
import volcano_tpu.actions.elastic      # noqa: F401
