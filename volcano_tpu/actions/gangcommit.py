"""Batched gang commit — allocate a whole gang as one statement drain.

The per-pod walk (allocate.py `_allocate_tasks`) pays, per task, one
`grouped_batch_node_order` dispatch, one `heap_best` scan over every
leaf group, one single-node `SpecCache.invalidate` re-predication and
one metrics/trace observation.  For a gang of identical controller-
stamped tasks all of that is recomputing the same answers 8192 times:
at 100k hosts the walk dominates the cycle (SCALE100K_r16.json,
allocate 6.4s of a 6.8s cycle).

This module drains the already-built SpecCache entry in ONE pass per
task spec:

  * group offsets are computed once per spec (identical tasks get
    identical `groupedBatchNodeOrder` verdicts);
  * all (score + offset) rows go into one global heap — picking a
    node is O(log n), not O(groups);
  * each popped node is filled to its capacity for the spec
    (`fit_count` over idle / future-idle) instead of being re-swept
    after every single placement.  Stacked placements beyond the
    first re-run the predicate chain once per extra pod so pod-count
    and port predicates keep their say;
  * per-task metrics/trace observations collapse into one
    `sched_gang_commit_seconds` observation per spec.

The drain is opt-in (`allocate.gangCommit: batch` under the action's
configurations) because its placement CONTRACT differs from the walk:
the walk re-scores a node after every placement, so a spread-style
scorer can alternate nodes mid-gang; the drain fills each node to
capacity in score order (the binpack/topology-compact behavior gang
workloads want).  Statement semantics are unchanged — everything still
rides `stmt.allocate`/`stmt.pipeline` and commits (or discards) with
the gang in `_finish`, and the commit still leaves the scheduler as
one idempotency-keyed `/bind_batch` per cycle (cache.flush_binds).
"""

from __future__ import annotations

import heapq
import logging
import time
from collections import deque
from typing import Dict, List, Optional

from volcano_tpu import metrics
from volcano_tpu.api.fit_error import FitError, FitErrors
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.util import PriorityQueue

log = logging.getLogger(__name__)

_UNBOUNDED = 1 << 30


def enabled(ssn) -> bool:
    conf = ssn.conf.configurations.get("allocate", {})
    return str(conf.get("gangCommit", "walk")).lower() == "batch"


def fit_count(resreq, avail) -> int:
    """How many replicas of *resreq* fit into *avail* at once.  A
    request with no positive dimension fits without bound (the caller
    clamps to the number of waiting tasks)."""
    count = _UNBOUNDED
    for name, want in resreq.res.items():
        if want <= 0:
            continue
        have = avail.get(name)
        c = int(have / want + 1e-9)
        if c <= 0:
            return 0
        if c < count:
            count = c
    return count


def allocate_tasks_batched(ssn, queue, job, stmt, candidate_nodes,
                           record_errors: bool = True) -> Optional[int]:
    """Batched replacement for the per-pod walk.  Returns the placed
    count, or None when the batch contract cannot hold (ungrouped
    batch scorer / task-identity-dependent predicates) and the caller
    must fall back to the walk.  Non-cacheable tasks (bare pods,
    best-effort) are delegated back to the walk via task_filter."""
    from volcano_tpu.actions.allocate import AllocateAction
    from volcano_tpu.actions.sweep import SpecCache

    if ssn.task_dependent_predicates:
        return None
    cache = SpecCache(ssn, candidate_nodes, record_errors,
                      capacity_prefilter=True)
    if not cache.use_heap:
        return None

    # spec -> tasks.  Replicas of one spec are interchangeable under
    # the batch contract, so they keep creation (job.tasks insertion)
    # order instead of paying a comparator-heap pass over the whole
    # gang — at 8k tasks the task_order_fn dispatch per heap compare
    # was a measurable slice of the cycle.  SPECS still drain in task
    # order, decided by comparing one representative per spec.
    by_spec: Dict[str, List] = {}
    has_bare = False
    for task in job.tasks_in_status(TaskStatus.PENDING):
        if task.best_effort:
            continue
        if task.task_spec:
            by_spec.setdefault(task.task_spec, []).append(task)
        else:
            has_bare = True
    spec_order = list(by_spec)
    if len(spec_order) > 1:
        reps = PriorityQueue(ssn.task_order_fn,
                             (by_spec[s][0] for s in spec_order))
        spec_order = [t.task_spec for t in reps]

    placed = 0
    for spec in spec_order:
        tasks = by_spec[spec]
        more_specs = len(by_spec) > 1
        placed += _drain_spec(ssn, queue, job, stmt, cache, spec, tasks,
                              record_errors, more_specs)
    if has_bare:
        placed += AllocateAction._allocate_tasks(
            ssn, queue, job, stmt, candidate_nodes, record_errors,
            task_filter=lambda t: not t.task_spec)
    return placed


def _drain_spec(ssn, queue, job, stmt, cache, spec, tasks,
                record_errors: bool, more_specs: bool) -> int:
    t0 = time.perf_counter()
    proto = tasks[0]
    status = ssn.pre_predicate(proto)
    if status is not None:
        if record_errors:
            job.record_fit_error(proto, "",
                                 FitError(proto, None, statuses=[status]))
        return 0

    entry = cache.get(spec) or cache.build_entry(proto)
    group_scores = None
    if cache.has_grouped:
        # restrict scoring to the leaves this entry can actually rank
        # — a subtree shard's candidate set covers a fraction of the
        # fleet's leaves, and the binpack scorer walks domains per leaf
        group_scores = ssn.grouped_batch_node_order(
            proto, groups=set(entry["group"].values()))
    remaining = deque(tasks)
    placed = 0
    touched: List = []

    for cls, place in (("idle", stmt.allocate), ("future", stmt.pipeline)):
        if not remaining:
            break
        rows = _score_rows(entry, cls, group_scores)
        heapq.heapify(rows)
        while rows and remaining:
            _, name = heapq.heappop(rows)
            node = entry["fits"].get(name)
            if node is None:
                continue
            avail = node.idle if cls == "idle" else node.future_idle()
            cap = fit_count(proto.init_resreq, avail)
            stacked = 0
            while cap > 0 and remaining:
                task = remaining[0]
                if not ssn.allocatable(queue, task):
                    # same per-task skip as the walk: a later sibling
                    # may still clear the share once others commit
                    if record_errors:
                        errs = job.fit_errors.setdefault(task.uid,
                                                         FitErrors())
                        errs.set_error(
                            f"task would exceed queue {queue.name}'s "
                            f"deserved share")
                    remaining.popleft()
                    continue
                if stacked and ssn.predicate(proto, node) is not None:
                    # stacking re-check: resources allowed another
                    # replica but a count-style predicate (pod limit,
                    # host port) vetoed it
                    break
                remaining.popleft()
                place(task, node)
                placed += 1
                stacked += 1
                cap -= 1
            if stacked:
                touched.append(node)

    if remaining and record_errors:
        _record_leftovers(job, proto, remaining, entry, ssn)
    if more_specs:
        # other specs' cached entries must see these nodes' new state;
        # the drained spec's own entry is spent — drop it instead of
        # re-predicating every touched node against it
        cache.entries.pop(spec, None)
        for node in touched:
            cache.invalidate(node)
    metrics.observe("sched_gang_commit_seconds",
                    time.perf_counter() - t0)
    return placed


def _score_rows(entry, cls, group_scores) -> list:
    """(-(score+offset), name) rows for every node of *cls* — one
    global heap replaces the per-task per-group heap_best scan.  Tie
    order (same total) is smallest name first, exactly like
    heap_best."""
    meta = entry["meta"]
    groups = entry["group"]
    rows = []
    if group_scores:
        get_off = group_scores.get
        for name, (_gen, c, score) in meta.items():
            if c == cls:
                rows.append((-(score + get_off(groups.get(name), 0.0)),
                             name))
    else:
        for name, (_gen, c, score) in meta.items():
            if c == cls:
                rows.append((-score, name))
    return rows


def _record_leftovers(job, proto, remaining, entry, ssn) -> None:
    """Per-node Insufficient rows for tasks the drain could not seat:
    the swept-but-unseated nodes get the walk's fit_delta message, and
    the capacity-prefiltered (never-swept) nodes get the same from
    their live state — error fidelity is only paid on the failure
    path."""
    entries = []
    for node in entry["fits"].values():
        missing = node.future_idle().fit_delta(proto.resreq)
        dims = ", ".join(sorted(missing.res)) or "resources"
        entries.append((node.name, f"Insufficient {dims}"))
    by_name = ssn.nodes
    for name in entry["prefiltered"]:
        node = by_name.get(name)
        if node is None:
            continue
        missing = node.future_idle().fit_delta(proto.resreq)
        dims = ", ".join(sorted(missing.res)) or "resources"
        entries.append((name, f"Insufficient {dims}"))
    from volcano_tpu.api.fit_error import unschedulable
    for task in remaining:
        for node_name, reason in entries:
            job.record_fit_error(task, node_name, FitError(
                proto, node_name, statuses=[unschedulable(reason)]))
