"""Enqueue action — gang admission gate.

Reference parity: actions/enqueue/enqueue.go:44.  Pending PodGroups are
promoted to Inqueue only when every JobEnqueueable voter (overcommit /
proportion / capacity / sla / resourcequota) permits, so the allocate
action never wastes cycles on jobs the cluster can't hold.
"""

from __future__ import annotations

import logging

from volcano_tpu import trace
from volcano_tpu.api.types import PodGroupPhase
from volcano_tpu.framework.plugins import Action, register_action
from volcano_tpu.util import PriorityQueue

log = logging.getLogger(__name__)


class EnqueueAction(Action):
    name = "enqueue"

    def execute(self, ssn) -> None:
        from volcano_tpu import metrics
        from volcano_tpu.api import elastic as eapi
        jobs_per_queue = {}
        for job in ssn.jobs.values():
            if job.podgroup is None or \
                    job.podgroup.phase is not PodGroupPhase.PENDING:
                continue
            if eapi.evacuating(job.podgroup):
                # cross-region evacuation hold (api/elastic.py): the
                # drained gang belongs to the federation cutover now —
                # admitting it would race the destination region's
                # re-place against a local one
                metrics.inc("sched_unschedulable_reasons_total",
                            reason="evacuating-region")
                continue
            queue = ssn.queues.get(job.queue)
            if queue is None or not queue.is_open():
                continue
            jobs_per_queue.setdefault(
                queue.name, PriorityQueue(ssn.job_order_fn)).push(job)

        queues = PriorityQueue(ssn.queue_order_fn,
                               (ssn.queues[qn] for qn in jobs_per_queue))
        while not queues.empty():
            queue = queues.pop()
            jobs = jobs_per_queue[queue.name]
            if jobs.empty():
                continue
            job = jobs.pop()
            if ssn.job_enqueueable(job):
                job.podgroup.phase = PodGroupPhase.INQUEUE
                # lifecycle stamp: ONE gang admission timestamp on the
                # podgroup (not N pod writes); pods inherit it in the
                # e2e phase decomposition (trace.phase_segments)
                trace.stamp_phase(job.podgroup.annotations, "enqueued")
                ssn.job_enqueued(job)
                ssn.dirty_jobs.add(job.uid)
                log.debug("enqueued job %s", job.key)
            queues.push(queue)


register_action(EnqueueAction())
