"""Allocate action — the scheduler's hot loop.

Reference parity: actions/allocate/allocate.go:122-981.  Nested
priority queues (queue -> job -> task); per-task predicate + score;
statement-buffered placement committed only when the gang is ready
(or left pipelined when it can become ready on releasing resources);
hard-topology jobs dry-run across hypernode candidate domains and the
best-scoring domain is recovered (allocate.go:370-463).
"""

from __future__ import annotations

import logging
import time
from typing import Dict

from volcano_tpu import metrics, trace
from volcano_tpu.api.fit_error import (FitError, FitErrors,
                                       unschedulable)
from volcano_tpu.api.job_info import JobInfo
from volcano_tpu.api.types import PodGroupPhase, TaskStatus
from volcano_tpu.framework.plugins import Action, register_action
from volcano_tpu.util import PriorityQueue

from volcano_tpu.actions.sweep import SpecCache, heap_best
from volcano_tpu.actions.util import (
    predicate_nodes,
    prioritize_nodes,
    split_by_fit,
)

log = logging.getLogger(__name__)


def _record_insufficient(job, task, fit_nodes, spec_memo) -> None:
    """Per-node insufficient-resource fit errors for a task whose
    predicates passed but that fit NO node's idle or future-idle
    (the `1 node(s) Insufficient cpu`-style histogram).  fit_nodes
    may be the cached dict form ({name: node}) or a list.  The
    histogram is identical across a gang's identical siblings, so it
    is computed once per task_spec and replayed (the future_idle
    clone per node is the expensive part)."""
    entries = spec_memo.get(task.task_spec) if task.task_spec else None
    if entries is None:
        nodes = (fit_nodes.values() if isinstance(fit_nodes, dict)
                 else fit_nodes)
        entries = []
        for node in nodes:
            missing = node.future_idle().fit_delta(task.resreq)
            dims = ", ".join(sorted(missing.res)) or "resources"
            entries.append((node.name, f"Insufficient {dims}"))
        if task.task_spec:
            spec_memo[task.task_spec] = entries
    for node_name, reason in entries:
        job.record_fit_error(task, node_name, FitError(
            task, node_name, statuses=[unschedulable(reason)]))


class AllocateAction(Action):
    name = "allocate"

    def execute(self, ssn) -> None:
        enqueue_configured = "enqueue" in ssn.conf.actions

        jobs_per_queue: Dict[str, PriorityQueue] = {}
        shard_plan = self._subtree_plan(ssn)
        for job in ssn.jobs.values():
            if shard_plan is not None and not job.tasks_in_status(
                    TaskStatus.BINDING):
                # partitioned schedulers: every pending job has ONE
                # home shard driving its placement (stable hash, so
                # all shards agree without coordination).  The home
                # shard spills cross-subtree when its own subtrees
                # can't seat the gang; only those optimistic spills
                # ever race another shard, and the server's
                # check-and-bind arbitrates them.  A job with BINDING
                # tasks stays with whoever started it this cycle.
                from volcano_tpu import shardmap
                idx, count = shard_plan
                if shardmap.home_shard(job.key, count) != idx:
                    continue
            if not self._job_eligible(ssn, job, enqueue_configured):
                continue
            queue = ssn.queues.get(job.queue)
            if queue is None or not queue.is_open():
                continue
            jobs_per_queue.setdefault(
                queue.name, PriorityQueue(ssn.job_order_fn)).push(job)

        queues = PriorityQueue(ssn.queue_order_fn,
                               (ssn.queues[qn] for qn in jobs_per_queue))
        while not queues.empty():
            queue = queues.pop()
            if ssn.overused(queue):
                log.debug("queue %s overused, skipping", queue.name)
                continue
            jobs = jobs_per_queue[queue.name]
            if jobs.empty():
                continue
            job = jobs.pop()
            # per-job child span: the trace's unit of latency
            # attribution (predicate/score aggregates land under it)
            with trace.span(job.key, kind="job", job=job.key,
                            queue=queue.name):
                self._allocate_job(ssn, queue, job)
            from volcano_tpu.api.queue import DEQUEUE_FIFO
            if queue.queue.dequeue_strategy == DEQUEUE_FIFO and \
                    not ssn.job_ready(job):
                # strict FIFO: the head job blocks the queue until it
                # schedules (Queue.dequeueStrategy, types.go:459-519);
                # "traverse" (default behavior here) moves on
                log.debug("queue %s fifo head %s not ready; queue blocked",
                          queue.name, job.key)
                continue
            queues.push(queue)

    @staticmethod
    def _job_eligible(ssn, job: JobInfo, enqueue_configured: bool) -> bool:
        if not job.tasks_in_status(TaskStatus.PENDING):
            return False
        result = ssn.job_valid(job)
        if result is not None:
            ssn.set_job_pending_reason(job, result[0], result[1])
            return False
        if job.podgroup is not None and enqueue_configured and \
                job.podgroup.phase is PodGroupPhase.PENDING:
            # not admitted by enqueue yet (allocate.go:153-164)
            return False
        return True

    def _allocate_job(self, ssn, queue, job: JobInfo) -> None:
        if job.has_topology_constraint() and ssn.hypernodes is not None and \
                len(ssn.hypernodes.members) > 1:
            from volcano_tpu.actions.topology_alloc import allocate_for_topology_job
            allocate_for_topology_job(ssn, queue, job)
            return

        stmt = ssn.statement()
        own_shard, mode = self._shard_view(ssn)
        if own_shard is not None:
            shard_nodes = [n for n in ssn.nodes.values()
                           if n.name in own_shard]
            self._allocate_tasks(ssn, queue, job, stmt, shard_nodes)
            if mode == "soft" and job.tasks_in_status(TaskStatus.PENDING):
                # spill what didn't fit the shard onto the full cluster
                self._allocate_tasks(ssn, queue, job, stmt,
                                     list(ssn.nodes.values()),
                                     record_errors=False)
        else:
            self._allocate_tasks(ssn, queue, job, stmt,
                                 list(ssn.nodes.values()))
        self._finish(ssn, job, stmt)

    @staticmethod
    def _subtree_plan(ssn):
        """(shard_index, shard_count) when the subtree partition is
        on (``shard-mode: subtree`` + a shard-count > 1), else None."""
        conf = ssn.conf.configurations.get("allocate", {})
        if str(conf.get("shard-mode", "none")) != "subtree":
            return None
        try:
            idx = int(conf.get("shard-index", 0))
            count = int(conf.get("shard-count", 1))
        except (TypeError, ValueError):
            return None
        if count <= 1 or not 0 <= idx < count:
            return None
        return idx, count

    @staticmethod
    def _shard_view(ssn):
        """(own shard node set, mode) — None when sharding is off.

        Candidate-node gradient by shard (allocate.go:886-919): hard
        restricts to the scheduler's NodeShard; soft prefers it.
        ``subtree`` mode instead derives ownership from the
        deterministic topology-subtree partition (volcano_tpu/
        shardmap.py) shared with the keyspace-partitioned write plane;
        its spill gradient (``shard-spill``, default soft) is what
        makes cross-subtree gangs optimistic rather than stuck.
        """
        conf = ssn.conf.configurations.get("allocate", {})
        mode = str(conf.get("shard-mode", "none"))
        if mode == "subtree":
            plan = AllocateAction._subtree_plan(ssn)
            if plan is None:
                return None, "none"
            from volcano_tpu import shardmap
            idx, count = plan
            own = shardmap.owned_nodes(
                shardmap.subtree_map(ssn.nodes.values()), count, idx)
            ssn.cache.shard_plan = f"{idx}/{count}"
            spill = str(conf.get("shard-spill", "soft"))
            return (own or None), \
                (spill if spill in ("soft", "hard") else "soft")
        if mode not in ("soft", "hard"):
            return None, "none"
        from volcano_tpu.controllers.sharding import shard_nodes_for
        own = shard_nodes_for(ssn.cache.cluster,
                              ssn.cache.scheduler_name)
        if not own:
            return None, mode
        return set(own), mode

    def _finish(self, ssn, job: JobInfo, stmt) -> None:
        if ssn.job_ready(job):
            stmt.commit()
        elif ssn.job_pipelined(job):
            # keep reservations in-session; pods wait on releasing nodes
            pass
        else:
            stmt.discard()
            if job.fit_errors:
                errs = FitErrors()
                errs.set_error(job.fit_error())
                job.set_job_fit_errors(errs)
            ssn.set_job_pending_reason(
                job, "Unschedulable",
                job.fit_error() or
                f"job {job.key} not ready: {job.ready_task_num()}/"
                f"{job.min_available} tasks allocatable")

    @staticmethod
    def _allocate_tasks(ssn, queue, job: JobInfo, stmt,
                        candidate_nodes, record_errors: bool = True,
                        task_filter=None) -> int:
        """Try to place every pending non-best-effort task of *job* onto
        *candidate_nodes* (optionally restricted by *task_filter*).
        Returns number placed."""
        if task_filter is None:
            # gangCommit: batch — drain whole specs over the cached
            # sweep instead of walking pod-at-a-time; None means the
            # batch contract cannot hold and the walk below runs.
            # (task_filter is how the batch path delegates its own
            # non-cacheable leftovers here — never re-enter on it.)
            from volcano_tpu.actions import gangcommit
            if gangcommit.enabled(ssn):
                placed = gangcommit.allocate_tasks_batched(
                    ssn, queue, job, stmt, candidate_nodes,
                    record_errors)
                if placed is not None:
                    return placed
        tasks = PriorityQueue(ssn.task_order_fn)
        for task in job.tasks_in_status(TaskStatus.PENDING):
            if task.best_effort:
                continue
            if task_filter is not None and not task_filter(task):
                continue
            tasks.push(task)

        placed = 0
        failed_specs = set()
        # A plugin with task-identity-dependent predicates (extender)
        # makes cached verdicts unsound: fall back to per-task sweeps.
        cache_enabled = not ssn.task_dependent_predicates

        def task_cacheable(task) -> bool:
            # bare pods default to spec "": they may be heterogeneous,
            # so only named (controller-stamped, identical) specs cache
            return cache_enabled and bool(task.task_spec)
        # Per-spec predicate/score/fit-class cache with single-node
        # invalidation + optional parallel leaf-shard sweep — the
        # machinery lives in actions/sweep.py (SpecCache) so the
        # static race pass can name the reader call tree and the
        # thread-pool pilot can fan it out over the frozen snapshot.
        cache = SpecCache(ssn, candidate_nodes, record_errors)
        use_heap = cache.use_heap
        has_grouped = cache.has_grouped
        insufficient_memo: Dict[str, list] = {}
        spec_error_rep: Dict[str, str] = {}   # failed spec -> task uid

        for task in tasks:
            t_task = time.perf_counter()
            if task.task_spec in failed_specs:
                # identical spec already failed everywhere this round
                # (fit-error memoization, allocate.go TaskHasFitErrors).
                # Share the representative's recorded errors so the
                # sibling is REPORTED as a blocker too, not mislabeled
                # Schedulable by the reason publisher
                if record_errors:
                    rep = spec_error_rep.get(task.task_spec)
                    if rep is not None and rep in job.fit_errors:
                        job.fit_errors.setdefault(
                            task.uid, job.fit_errors[rep])
                continue
            if not ssn.allocatable(queue, task):
                # skip just this task: a smaller sibling may still fit the
                # queue's share (allocate.go:744-747 uses continue).
                # RECORD the reason: without it the pod shows nothing
                # at all (scheduling-reason.md)
                if record_errors:
                    errs = job.fit_errors.setdefault(task.uid,
                                                     FitErrors())
                    errs.set_error(f"task would exceed queue "
                                   f"{queue.name}'s deserved share")
                log.debug("queue %s quota exhausted for task %s",
                          queue.name, task.key)
                continue

            status = ssn.pre_predicate(task)
            if status is not None:
                if record_errors:
                    job.record_fit_error(task, "",
                                         FitError(task, None,
                                                  statuses=[status]))
                    spec_error_rep.setdefault(task.task_spec, task.uid)
                failed_specs.add(task.task_spec)
                continue

            if task_cacheable(task):
                entry = cache.get(task.task_spec) or \
                    cache.build_entry(task)
                if use_heap:
                    # O(groups log n) pick straight off the cached heaps
                    group_scores = (ssn.grouped_batch_node_order(task)
                                    if has_grouped else None)
                    node = heap_best(entry, "idle", group_scores)
                    pipelined = False
                    if node is None:
                        node = heap_best(entry, "future", group_scores)
                        pipelined = node is not None
                    fit_nodes = entry["fits"]   # truthiness check below
                else:
                    fit_nodes = list(entry["fits"].values())
                    idle_fit, future_fit = split_by_fit(task, fit_nodes)
                    node = prioritize_nodes(ssn, task, idle_fit,
                                            base_scores=entry["scores"])
                    pipelined = False
                    if node is None:
                        node = prioritize_nodes(
                            ssn, task, future_fit,
                            base_scores=entry["scores"])
                        pipelined = node is not None
            else:
                fit_nodes = predicate_nodes(ssn, task, candidate_nodes,
                                            record_errors)
                idle_fit, future_fit = split_by_fit(task, fit_nodes)
                node = prioritize_nodes(ssn, task, idle_fit)
                pipelined = False
                if node is None:
                    node = prioritize_nodes(ssn, task, future_fit)
                    pipelined = node is not None
            if node is not None:
                if pipelined:
                    stmt.pipeline(task, node)
                else:
                    stmt.allocate(task, node)
                placed += 1
                metrics.observe("task_scheduling_latency_seconds",
                                time.perf_counter() - t_task,
                                action="allocate")
                cache.invalidate(node)
                continue

            if record_errors:
                if not fit_nodes:
                    failed_specs.add(task.task_spec)
                    spec_error_rep.setdefault(task.task_spec, task.uid)
                else:
                    # predicates passed somewhere but nothing had the
                    # resources (now or releasing): without an explicit
                    # record the task shows NO reason at all — the
                    # reference surfaces per-node "Insufficient cpu"
                    # entries here (node_info.go FutureIdle checks)
                    _record_insufficient(job, task, fit_nodes,
                                         insufficient_memo)
        return placed


register_action(AllocateAction())
