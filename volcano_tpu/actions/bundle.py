"""Victim bundles for gang-aware eviction.

Reference parity: actions/utils/bundle.go:53,232,248 (CreateJobBundles:
SAFE bundles hold only tasks beyond the victim job's gang floor so the
victim survives; WHOLE bundles take the entire job down.  Sorted for
preemption by type then ROI so the cheapest sufficient eviction wins).
"""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass, field
from typing import Dict, List

from volcano_tpu.api.job_info import TaskInfo
from volcano_tpu.api.resource import Resource
from volcano_tpu.actions.util import victim_sort_key

SAFE = "safe"
WHOLE = "whole"


@dataclass
class Bundle:
    job_uid: str
    kind: str
    tasks: List[TaskInfo] = field(default_factory=list)
    freed: Resource = field(default_factory=Resource)
    job_priority: int = 0

    def add(self, task: TaskInfo):
        self.tasks.append(task)
        self.freed.add(task.resreq)


def create_job_bundles(ssn, candidates: List[TaskInfo]) -> List[Bundle]:
    """Group candidate victims by job into SAFE and WHOLE bundles.

    SAFE: up to (occupying - minAvailable) cheapest tasks — eviction
    keeps the victim's gang intact.  WHOLE: every occupying task of the
    job, valid only when ALL of them are in the candidate set (you
    can't take a gang half down).
    """
    by_job: Dict[str, List[TaskInfo]] = defaultdict(list)
    for t in candidates:
        by_job[t.job].append(t)

    bundles: List[Bundle] = []
    for job_uid, tasks in by_job.items():
        job = ssn.jobs.get(job_uid)
        if job is None:
            b = Bundle(job_uid, SAFE)
            for t in tasks:
                b.add(t)
            bundles.append(b)
            continue
        occupying = [t for t in job.tasks.values()
                     if t.occupies_resources()]
        surplus = len(occupying) - job.min_available
        ordered = sorted(tasks, key=victim_sort_key(ssn))
        if surplus > 0:
            safe = Bundle(job_uid, SAFE, job_priority=job.priority)
            for t in ordered[:surplus]:
                safe.add(t)
            if safe.tasks:
                bundles.append(safe)
        if len(tasks) >= len(occupying) and occupying:
            whole = Bundle(job_uid, WHOLE, job_priority=job.priority)
            for t in ordered:
                whole.add(t)
            bundles.append(whole)
    return bundles


def sort_bundles_for_preempt(bundles: List[Bundle]) -> List[Bundle]:
    """SAFE before WHOLE; lower-priority victims first; smaller freed
    first (cumulative eviction stops as soon as the plan fits, so
    cheap-first minimizes collateral damage)."""
    return sorted(bundles, key=lambda b: (
        0 if b.kind == SAFE else 1,
        b.job_priority,
        sum(b.freed.res.values()),
    ))
