"""Shared action helpers: predicate sweep + node selection.

Reference parity: pkg/scheduler/util/predicate_helper.go (parallel
predicate over nodes with fit-error collection) and
actions/allocate/allocate.go:879-949 (idle vs future-idle gradients,
prioritizeNodes).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from volcano_tpu.api.fit_error import FitError
from volcano_tpu.api.job_info import TaskInfo
from volcano_tpu.api.node_info import NodeInfo


def may_preempt(ssn, job) -> bool:
    """PriorityClass preemptionPolicy: Never bars a job from being a
    preemptor in preempt, reclaim, gangpreempt and gangreclaim alike
    (it still schedules normally)."""
    pc = ssn.priority_classes.get(job.priority_class)
    return pc is None or pc.preemption_policy != "Never"


def victim_sort_key(ssn):
    """Cheapest eviction first: lowest job priority, then lowest task
    priority, then smallest request — shared by per-node victim
    selection and bundle ordering so the two policies cannot drift."""
    def key(t: TaskInfo):
        job = ssn.jobs.get(t.job)
        jp = job.priority if job else 0
        return (jp, t.priority, sum(t.resreq.res.values()))
    return key


def predicate_nodes(ssn, task: TaskInfo, nodes: List[NodeInfo],
                    record_errors: bool = True) -> List[NodeInfo]:
    """Return nodes passing all predicate plugins for *task*."""
    job = ssn.jobs.get(task.job)
    fits = []
    for node in nodes:
        status = ssn.predicate(task, node)
        if status is None:
            fits.append(node)
        elif record_errors and job is not None:
            # vtplint: disable=snapshot-write (serial sweep only, single-threaded on the session owner thread; the parallel path defers fit-error rows to sweep._build_parallel's post-barrier merge)
            job.record_fit_error(task, node.name,
                                 FitError(task, node, statuses=[status]))
    return fits


from volcano_tpu.api.types import QOS_BEST_EFFORT, QOS_LEVEL_ANNOTATION


def fit_class(task: TaskInfo, node: NodeInfo) -> Optional[str]:
    """Classify ONE node for *task*: "idle" (fits now), "future" (fits
    only once releasing resources free up — drives pipelining), or None.
    Best-effort-QoS tasks may additionally consume the node agent's
    REMAINING measured oversubscription slack."""
    is_be = task.pod.annotations.get(QOS_LEVEL_ANNOTATION) == \
        QOS_BEST_EFFORT
    idle = node.idle
    if is_be and not node.oversubscription.is_empty():
        slack = node.oversub_remaining()
        idle = idle.clone().add(slack)
        future = node.future_idle().add(slack)
        if task.init_resreq.less_equal(idle):
            return "idle"
        if task.init_resreq.less_equal(future):
            return "future"
        return None
    if task.init_resreq.less_equal(idle):
        return "idle"
    # nothing releasing and nothing pipelined => future_idle == idle:
    # skip the clone+add+sub (the sweep calls this once per fit node,
    # and on a mostly-settled cluster the slow path was pure waste)
    if node.releasing.is_empty() and node.pipelined.is_empty():
        return None
    if task.init_resreq.less_equal(node.future_idle()):
        return "future"
    return None


def split_by_fit(task: TaskInfo, nodes: List[NodeInfo]
                 ) -> Tuple[List[NodeInfo], List[NodeInfo]]:
    """Split candidates into (fits idle now, fits only future idle)
    (allocate.go idle/future-idle gradients)."""
    idle_fit, future_fit = [], []
    for node in nodes:
        cls = fit_class(task, node)
        if cls == "idle":
            idle_fit.append(node)
        elif cls == "future":
            future_fit.append(node)
    return idle_fit, future_fit


def prioritize_nodes(ssn, task: TaskInfo, nodes: List[NodeInfo],
                     base_scores: Optional[Dict[str, float]] = None
                     ) -> Optional[NodeInfo]:
    """Score candidates (BatchNodeOrder + NodeOrder) and return the best.

    base_scores: precomputed per-node NodeOrder scores (the allocate
    hot loop's per-spec cache); task-dependent BatchNodeOrder is always
    evaluated fresh.
    """
    if not nodes:
        return None
    if len(nodes) == 1:
        return nodes[0]
    scores: Dict[str, float] = ssn.batch_node_order(task, nodes)
    best, best_score = None, None
    for node in nodes:
        per_node = (base_scores.get(node.name, 0.0) if base_scores is not None
                    else ssn.node_order(task, node))
        s = scores.get(node.name, 0.0) + per_node
        if best_score is None or s > best_score or \
                (s == best_score and node.name < best.name):
            best, best_score = node, s
    return best
