"""Cluster backend interface.

The scheduler talks to the cluster only through this interface
(reference: the k8s clientset + informers behind pkg/scheduler/cache).
Implementations: FakeCluster (tests/benchmarks — the KWOK analogue);
a real deployment would back this with an apiserver client.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from volcano_tpu.api.hypernode import HyperNode
from volcano_tpu.api.node_info import Node
from volcano_tpu.api.pod import Pod
from volcano_tpu.api.podgroup import PodGroup
from volcano_tpu.api.queue import Queue


@dataclass
class PriorityClass:
    name: str
    value: int = 0
    preemption_policy: str = "PreemptLowerPriority"  # or "Never"


@dataclass
class ClusterSnapshot:
    """Raw cluster objects as of one point in time."""

    pods: List[Pod] = field(default_factory=list)
    nodes: List[Node] = field(default_factory=list)
    podgroups: List[PodGroup] = field(default_factory=list)
    queues: List[Queue] = field(default_factory=list)
    hypernodes: List[HyperNode] = field(default_factory=list)
    priority_classes: List[PriorityClass] = field(default_factory=list)
    vcjobs: List[object] = field(default_factory=list)  # VCJob


class Cluster(abc.ABC):
    """The apiserver surface the scheduler AND controllers need.

    Implementations must also expose live mapping views used by
    controllers and plugins:
      pods / podgroups / queues / hypernodes / vcjobs  (key -> object)
      services / config_maps / secrets                 (plugin artifacts)
    """

    @abc.abstractmethod
    def list_all(self) -> ClusterSnapshot:
        """Return the current cluster objects (read-only view)."""

    @abc.abstractmethod
    def bind_pod(self, namespace: str, name: str, node_name: str,
                 ts_alloc: Optional[float] = None) -> None:
        """POST pods/binding analogue.  Raises on conflict/missing.
        ts_alloc optionally carries the scheduler's placement-decision
        wall time for the `allocated` lifecycle stamp (trace.py)."""

    def bind_pods(self, binds) -> List[Optional[str]]:
        """Batch bind: `binds` is [(namespace, name, node_name), ...]
        — items may carry a 4th element, the ts_alloc decision stamp;
        returns a per-item list of None (bound) or an error string,
        NEVER raising — per-item failure semantics match the per-pod
        path (a conflict on one pod must not veto its gang-mates, the
        discipline flush_binds already had).  The default loops
        bind_pod; wire backends override with ONE request so a 256-pod
        gang's binds don't cost 256 HTTP round-trips."""
        errors: List[Optional[str]] = []
        for item in binds:
            namespace, name, node_name = item[0], item[1], item[2]
            ts_alloc = item[3] if len(item) > 3 else None
            try:
                self.bind_pod(namespace, name, node_name,
                              ts_alloc=ts_alloc)
                errors.append(None)
            except Exception as e:  # noqa: BLE001 — per-item verdicts
                errors.append(str(e) or type(e).__name__)
        return errors

    @abc.abstractmethod
    def evict_pod(self, namespace: str, name: str, reason: str = "") -> None:
        """Graceful eviction: mark pod terminating; the 'kubelet' side
        completes deletion asynchronously (FakeCluster does it on tick)."""

    @abc.abstractmethod
    def nominate_pod(self, namespace: str, name: str, node_name: str) -> None:
        """Persist status.nominatedNodeName for a pipelined pod."""

    @abc.abstractmethod
    def update_podgroup_status(self, pg: PodGroup) -> None:
        """Flush PodGroup phase/conditions."""

    @abc.abstractmethod
    def record_event(self, obj_key: str, reason: str, message: str) -> None:
        """Event recorder analogue."""

    # -- controller surface -------------------------------------------

    @abc.abstractmethod
    def watch(self, fn) -> None:
        """Register fn(kind, obj) for object change notifications."""

    @abc.abstractmethod
    def unwatch(self, fn) -> None:
        """Detach a watcher registered with watch()."""

    @abc.abstractmethod
    def add_hypernode(self, hn: HyperNode) -> None:
        """Create/update a HyperNode CR (discovery controller)."""

    @abc.abstractmethod
    def delete_hypernode(self, name: str) -> None:
        """Delete a HyperNode CR."""

    @abc.abstractmethod
    def add_pod(self, pod: Pod) -> None:
        """Create a pod (job controller materialization)."""

    @abc.abstractmethod
    def delete_pod(self, key: str) -> None:
        """Force-delete a pod by ns/name key."""

    @abc.abstractmethod
    def add_podgroup(self, pg: PodGroup) -> None:
        """Create a PodGroup CR."""

    @abc.abstractmethod
    def delete_podgroup(self, key: str) -> None:
        """Delete a PodGroup CR."""

    @abc.abstractmethod
    def add_vcjob(self, job):
        """Create a vcjob, applying the admission chain; returns the
        (possibly mutated) stored object or raises AdmissionError."""

    @abc.abstractmethod
    def update_vcjob(self, job) -> None:
        """Persist vcjob spec/status changes."""

    @abc.abstractmethod
    def delete_vcjob(self, key: str) -> None:
        """Delete a vcjob by ns/name key."""

    # -- generic object store ------------------------------------------
    # One create/update + delete pair covering every registered kind
    # (cache/kinds.py) so controllers and plugins persist through the
    # SAME seam regardless of backend (in-memory or wire).  Mirrors the
    # reference's dynamic clientset over the CRD scheme.

    @abc.abstractmethod
    def put_object(self, kind: str, obj, key: Optional[str] = None):
        """Create or update an object of `kind`; returns the stored
        object (admission may mutate for admission-gated kinds)."""

    @abc.abstractmethod
    def delete_object(self, kind: str, key: str) -> None:
        """Delete by key; no-op when absent."""

    def get_objects(self, kind: str) -> Dict[str, object]:
        """Read view of a kind's store (key -> object)."""
        from volcano_tpu.cache.kinds import KINDS
        return getattr(self, KINDS[kind].attr)

    # -- command bus (bus/v1alpha1 Command analogue) -------------------
    # Default in-memory implementation; backends may override to
    # persist Commands as CRs.

    def add_command(self, target_key: str, action: str) -> None:
        if not hasattr(self, "commands"):
            self.commands = []
        self.commands.append({"target": target_key, "action": action})

    def drain_commands(self, target_key: str):
        cmds = getattr(self, "commands", [])
        mine = [c for c in cmds if c["target"] == target_key]
        self.commands = [c for c in cmds if c["target"] != target_key]
        return mine
