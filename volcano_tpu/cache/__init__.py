"""Scheduler cache layer (reference: pkg/scheduler/cache)."""

from volcano_tpu.cache.cluster import Cluster, ClusterSnapshot
from volcano_tpu.cache.fake_cluster import FakeCluster
from volcano_tpu.cache.cache import SchedulerCache, Snapshot

__all__ = ["Cluster", "ClusterSnapshot", "FakeCluster", "SchedulerCache",
           "Snapshot"]
