"""SchedulerCache: builds the per-session Snapshot and executes binds.

Reference parity: pkg/scheduler/cache/cache.go (Snapshot:1479, Bind:984,
Evict:938, AddBindTask:1342).  Rebuilt without informer machinery: the
cache reads the Cluster interface and constructs a fresh consistent
model per session (equivalent cost to the reference's deep-copy
Snapshot), and pushes binds/evictions back through a batched queue with
rollback-on-failure.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from volcano_tpu import trace
from volcano_tpu.api.hypernode import HyperNodesInfo
from volcano_tpu.api.job_info import JobInfo, TaskInfo
from volcano_tpu.api.node_info import NodeInfo
from volcano_tpu.api.podgroup import PodGroup
from volcano_tpu.api.queue_info import QueueInfo
from volcano_tpu.api.types import (
    DEFAULT_QUEUE,
    GROUP_NAME_ANNOTATION,
    QUEUE_NAME_ANNOTATION,
    TaskStatus,
)
from volcano_tpu.cache.cluster import Cluster, PriorityClass

log = logging.getLogger(__name__)

# Device-layer enrichment hooks, keyed by device name.  The TPU device
# layer registers here (reference: api.RegisteredDevices +
# shared_device_pool).  Each hook: fn(node_info) -> device object stored
# in node_info.others[name].
REGISTERED_DEVICES: Dict[str, Callable[[NodeInfo], object]] = {}


def register_device(name: str, factory: Callable[[NodeInfo], object]):
    REGISTERED_DEVICES[name] = factory


# TPU is first-class: registered at module load so the very first
# snapshot of a fresh process already carries device state (a lazy
# plugin-import side effect would run AFTER the first snapshot).
from volcano_tpu.api.devices.tpu.device_info import TPUDevices  # noqa: E402

register_device("tpu", TPUDevices)


class Snapshot:
    """One session's consistent view of the cluster."""

    def __init__(self):
        self.jobs: Dict[str, JobInfo] = {}
        self.nodes: Dict[str, NodeInfo] = {}
        self.queues: Dict[str, QueueInfo] = {}
        self.hypernodes: Optional[HyperNodesInfo] = None
        self.priority_classes: Dict[str, PriorityClass] = {}
        # the cache's ThroughputBook (volcano_tpu/goodput.py): learned
        # per-(job, generation) step-rate vectors, exposed to plugins
        # and actions as session.goodput
        self.goodput = None
        # monotonic snapshot generation stamped by the cache: the
        # staleness token of the process-mirror protocol (every sweep
        # row a pool worker returns carries the generation it was
        # computed against; actions/procpool.py)
        self.gen = 0
        # fleet capacity carried incrementally by the cache (a reused
        # node's contribution never changes); None on bare snapshots
        self._total = None

    def total_resource(self):
        if self._total is not None:
            # callers own the result (plugins fold shares into it):
            # hand out a clone, never the cached instance that now
            # survives across sessions
            return self._total.clone()
        from volcano_tpu.api.resource import Resource
        total = Resource()
        for n in self.nodes.values():
            if n.ready:
                total.add(n.allocatable)
                # measured oversubscription slack is real capacity for
                # queue-share math; node-level fit still restricts it to
                # best-effort-QoS tasks (actions/util.split_by_fit)
                total.add(n.oversubscription)
        return total


class SnapshotDelta:
    """What changed between two consecutive snapshots — the unit the
    process-pool mirror protocol ships (actions/procpool.py) and the
    goodput fragmentation memo consumes.  ``full=True`` marks a
    rebuild-everything snapshot (mirrors must full-resync; memos must
    recompute)."""

    __slots__ = ("gen", "full", "changed_nodes", "removed_nodes",
                 "changed_jobs", "removed_jobs", "hypernodes_changed")

    def __init__(self, gen: int, full: bool = False,
                 changed_nodes=frozenset(), removed_nodes=frozenset(),
                 changed_jobs=frozenset(), removed_jobs=frozenset(),
                 hypernodes_changed: bool = False):
        self.gen = gen
        self.full = full
        self.changed_nodes = changed_nodes
        self.removed_nodes = removed_nodes
        self.changed_jobs = changed_jobs
        self.removed_jobs = removed_jobs
        self.hypernodes_changed = hypernodes_changed


class BindContext:
    __slots__ = ("task", "node_name", "t_alloc")

    def __init__(self, task: TaskInfo, node_name: str):
        self.task = task
        self.node_name = node_name
        # placement-decision wall time, shipped with the bind so the
        # store's `allocated` lifecycle stamp reflects the decision,
        # not the end-of-cycle batch commit (trace.py phases)
        self.t_alloc = time.time()


# statuses that mean a job still has in-flight scheduling state: its
# JobInfo must be rebuilt from cluster truth every cycle (fit errors,
# nominations, partial gangs).  Jobs whose every task is outside this
# set are steady and reusable between cycles.
_NONSTEADY_STATUSES = (
    TaskStatus.PENDING, TaskStatus.ALLOCATED, TaskStatus.PIPELINED,
    TaskStatus.BINDING, TaskStatus.BOUND, TaskStatus.RELEASING,
)


class SchedulerCache:
    def __init__(self, cluster: Cluster, scheduler_name: str = "volcano-tpu"):
        self.cluster = cluster
        self.scheduler_name = scheduler_name
        self._lock = threading.Lock()
        self._bind_queue: List[BindContext] = []
        self.bind_failures: List[Tuple[str, str]] = []   # (task key, error)
        # "idx/count" when this scheduler owns a topology-subtree shard
        # (allocate shard-mode: subtree); stamped per session by
        # AllocateAction._shard_view.  flush_binds uses it to label
        # per-item bind refusals as cross-shard conflicts: under the
        # partitioned plane an overcommit 409 means another shard's
        # optimistic spill won the server's atomic check-and-bind, and
        # the loser's job retries through its next cycle.
        self.shard_plan: Optional[str] = None
        # cross-session scratch for plugins (rate limiters etc.), keyed
        # by plugin name.  Plugin INSTANCES are rebuilt every session
        # (framework.open_session), so state that must survive cycles
        # lives here — scoped to this scheduler, never module-global
        # (two schedulers in one process must not share a limiter).
        self.plugin_state: Dict[str, dict] = {}
        # incremental snapshot state (VERDICT r2 item 7): the previous
        # snapshot is the reuse base; cluster watch events and session
        # touch reports accumulate the dirty sets consumed per cycle.
        self._base: Optional[Snapshot] = None
        self._dirty_lock = threading.Lock()
        self._dirty_nodes: set = set()
        self._dirty_jobs: set = set()
        self._needs_full = True
        self._hn_dirty = False
        # snapshot generation + delta ring: last_delta describes how
        # the NEWEST snapshot differs from its predecessor; the ring
        # lets a process-mirror several generations behind catch up
        # with one composed delta instead of a full re-sync
        self._gen = 0
        self.last_delta: Optional[SnapshotDelta] = None
        self._deltas: deque = deque(maxlen=16)
        # job keys with in-flight scheduling state in the CURRENT base
        # (anything non-steady rebuilds every cycle, so the steady
        # fast path below only fires when this is empty)
        self._unsteady_jobs: set = set()
        self._base_counts: tuple = ()
        # pods whose lifecycle-phase segments were already fed to
        # sched_phase_seconds (once per pod, bounded window)
        self._phase_seen: set = set()
        self._phase_seen_order: deque = deque()
        # learned per-(job, generation) throughput vectors, fed from
        # folded podgroup goodput annotations on ordinary watch
        # events — works identically in-process and over the wire
        from volcano_tpu.goodput import ThroughputBook
        self.goodput_book = ThroughputBook()
        watch = getattr(cluster, "watch", None)
        if watch is not None:
            watch(self._on_cluster_event)

    # -- dirty tracking ------------------------------------------------

    def _on_cluster_event(self, kind: str, obj) -> None:
        """Cluster mutations invalidate exactly the model objects they
        feed (the informer-handler analogue, event_handlers.go)."""
        with self._dirty_lock:
            if kind in ("pod", "pod_deleted"):
                node = getattr(obj, "node_name", "")
                if node:
                    self._dirty_nodes.add(node)
                self._dirty_jobs.add(self._job_key_for_pod(obj)
                                     or obj.key)
            elif kind == "node":
                name = getattr(obj, "name", None)
                if self._base is not None and \
                        name not in self._base.nodes:
                    self._needs_full = True     # membership grew
                else:
                    self._dirty_nodes.add(name)
            elif kind in ("podgroup", "podgroup_deleted"):
                self._dirty_jobs.add(obj.key)
            elif kind in ("node_deleted", "priority_class",
                          "priority_class_deleted", "queue",
                          "queue_deleted"):
                # membership shrank / priorities shifted / queue specs
                # changed or vanished: queue+priority feed job
                # construction, so rebuild everything (all are rare
                # control events).  Deliberately NOT a *_deleted
                # catch-all: vcjob_deleted/jobflow_deleted fire on
                # routine job churn and their cascaded pod/podgroup
                # deletions already dirty the right objects.
                self._needs_full = True
            elif kind in ("hypernode", "hypernode_deleted"):
                # topology CRs changed: the (otherwise reused)
                # HyperNodesInfo must rebuild next snapshot
                self._hn_dirty = True
            # numatopology/vcjob/command/...: controller-side state,
            # not part of the reused model
        if kind == "pod":
            # outside the dirty lock: phase-metric derivation reads
            # the podgroup store and feeds the metrics registry
            self._maybe_observe_phases(obj)
        elif kind == "podgroup":
            self._maybe_observe_goodput(obj)
        elif kind == "podgroup_deleted":
            self.goodput_book.forget(getattr(obj, "key", ""))

    _PHASE_SEEN_MAX = 8192

    def _maybe_observe_phases(self, pod) -> None:
        """Feed a pod's lifecycle-phase segments (trace.py stamps) to
        sched_phase_seconds once it reaches Running — the scheduler-
        process half of the e2e derivation, driven by ordinary watch
        events so it works identically in-process and over the wire."""
        from volcano_tpu import trace
        if getattr(pod, "phase", None) is not TaskStatus.RUNNING:
            return
        ann = getattr(pod, "annotations", None)
        if not ann or trace.TS_PREFIX + "running" not in ann:
            return
        uid = getattr(pod, "uid", None)
        if uid is None or uid in self._phase_seen:
            return
        self._phase_seen.add(uid)
        self._phase_seen_order.append(uid)
        while len(self._phase_seen_order) > self._PHASE_SEEN_MAX:
            self._phase_seen.discard(self._phase_seen_order.popleft())
        pg_ann = None
        jkey = self._job_key_for_pod(pod)
        if jkey:
            pg = getattr(self.cluster, "podgroups", {}).get(jkey)
            if pg is not None:
                pg_ann = pg.annotations
        trace.observe_phase_metrics(ann, pg_ann)

    def _maybe_observe_goodput(self, pg) -> None:
        """Feed a podgroup's folded goodput annotations (store-side
        GoodputReport fold) into the throughput-vector book — the
        learn half of the Gavel loop, driven by ordinary watch events
        so it works identically in-process and over the wire.  The
        fold timestamp dedupes watch re-deliveries."""
        from volcano_tpu.api import elastic as eapi
        from volcano_tpu.api import goodput as gapi
        ann = getattr(pg, "annotations", None)
        if not ann or gapi.PG_STEP_RATE_ANNOTATION not in ann:
            return
        rate = gapi.ann_float(ann, gapi.PG_STEP_RATE_ANNOTATION)
        if rate <= 0:
            return
        self.goodput_book.note(
            pg.key,
            ann.get(gapi.PG_GENERATION_ANNOTATION, "other"),
            rate,
            eapi.current_slices(pg),
            gapi.ann_float(ann, gapi.PG_UPDATED_TS_ANNOTATION))

    def note_touched(self, nodes, jobs) -> None:
        """Session mutations (committed OR discarded) — close_session
        reports them; the touched objects rebuild next cycle."""
        with self._dirty_lock:
            self._dirty_nodes.update(nodes)
            self._dirty_jobs.update(jobs)

    def _consume_dirty(self):
        with self._dirty_lock:
            dirty = (self._needs_full, self._dirty_nodes,
                     self._dirty_jobs, self._hn_dirty)
            self._needs_full = False
            self._dirty_nodes = set()
            self._dirty_jobs = set()
            self._hn_dirty = False
            return dirty

    # -- snapshot ------------------------------------------------------

    # exact incremental totals drift at most an ulp per non-integral
    # capacity change; a periodic full recompute bounds even that
    _TOTAL_REFRESH_EVERY = 512

    def snapshot(self) -> Snapshot:
        from volcano_tpu import features
        needs_full, dirty_nodes, dirty_jobs, hn_dirty = \
            self._consume_dirty()
        with trace.span("snapshot_build", kind="action") as sp:
            raw = self.cluster.list_all()
            counts = (len(raw.pods), len(raw.nodes),
                      len(raw.podgroups), len(raw.queues),
                      len(raw.priority_classes))
            self._gen += 1
            gen = self._gen
            incremental_ok = (self._base is not None and not needs_full
                              and features.enabled("IncrementalSnapshot"))
            if incremental_ok and not dirty_nodes and not dirty_jobs \
                    and not hn_dirty and not self._unsteady_jobs \
                    and counts == self._base_counts \
                    and gen % self._TOTAL_REFRESH_EVERY:
                # steady fast path: no event touched the reused model
                # and no job carries in-flight scheduling state — the
                # whole object graph carries over (fresh top-level
                # dicts so in-session additions never alias the base)
                snap = self._reuse_steady()
                delta = SnapshotDelta(gen)
                mode = "steady"
            elif incremental_ok:
                snap, delta = self._build_incremental(
                    raw, dirty_nodes, dirty_jobs, hn_dirty, gen)
                mode = "incremental"
            else:
                snap = self._build_full(raw)
                snap._total = snap.total_resource()
                delta = SnapshotDelta(gen, full=True,
                                      hypernodes_changed=True)
                mode = "full"
            if sp is not None:
                sp.labels["mode"] = mode
        snap.gen = gen
        snap.goodput = self.goodput_book
        self._base = snap
        self._base_counts = counts
        self.last_delta = delta
        self._deltas.append(delta)
        return snap

    def _reuse_steady(self) -> Snapshot:
        base = self._base
        snap = Snapshot()
        snap.jobs = dict(base.jobs)
        snap.nodes = dict(base.nodes)
        snap.queues = dict(base.queues)
        snap.priority_classes = dict(base.priority_classes)
        snap.hypernodes = base.hypernodes
        snap._total = base._total
        return snap

    def delta_since(self, gen: int):
        """Changes between snapshot *gen* and the current one,
        composed from the delta ring: (changed_nodes, changed_jobs,
        removed_jobs, hypernodes_changed), or None when *gen* has
        fallen off the ring or a full rebuild intervened (the caller
        must full-resync).  ``gen == current`` composes to empty."""
        if gen == self._gen:
            return set(), set(), set(), False
        changed_nodes: set = set()
        changed_jobs: set = set()
        removed_jobs: set = set()
        hn_changed = False
        covered = gen
        for d in self._deltas:
            if d.gen <= gen:
                continue
            if d.gen != covered + 1 or d.full:
                return None
            covered = d.gen
            changed_nodes |= set(d.changed_nodes)
            # composition is ORDER-SENSITIVE per key: the last
            # generation's verdict wins — changed-then-removed ships
            # as a removal only, removed-then-recreated ships as a
            # change (a plain set-difference at the end shipped a
            # same-key resubmit as a removal and silently desynced
            # every mirror that composed across the gap)
            changed_jobs |= set(d.changed_jobs)
            changed_jobs -= set(d.removed_jobs)
            removed_jobs |= set(d.removed_jobs)
            removed_jobs -= set(d.changed_jobs)
            hn_changed = hn_changed or d.hypernodes_changed
        if covered != self._gen:
            return None
        return changed_nodes, changed_jobs, removed_jobs, hn_changed

    @staticmethod
    def _node_capacity(ni: NodeInfo):
        """One node's contribution to Snapshot.total_resource —
        stable under task churn (allocatable/oversubscription/ready
        only move with node-object rebuilds, which dirty the node)."""
        from volcano_tpu.api.resource import Resource
        cap = Resource()
        if ni.ready:
            cap.add(ni.allocatable)
            cap.add(ni.oversubscription)
        return cap

    def _build_full(self, raw) -> Snapshot:
        snap = Snapshot()
        snap.priority_classes = {pc.name: pc for pc in raw.priority_classes}
        self._build_queues(snap, raw)

        for node in raw.nodes:
            ni = NodeInfo(node)
            snap.nodes[node.name] = ni

        # jobs from podgroups
        for pg in raw.podgroups:
            job = JobInfo(uid=pg.key, podgroup=pg)
            job.priority = self._priority_of(snap, pg.priority_class)
            snap.jobs[job.uid] = job

        # tasks from pods
        for pod in raw.pods:
            if pod.scheduler_name != self.scheduler_name:
                continue
            task = self._make_task(snap, pod)
            if task.node_name and (task.occupies_resources()
                                   or task.status is TaskStatus.RELEASING):
                ni = snap.nodes.get(task.node_name)
                if ni is not None:
                    ni.add_task(task)

        self._build_hypernodes(snap, raw)
        for ni in snap.nodes.values():
            self._enrich_devices(ni)
        self._unsteady_jobs = {
            k for k, j in snap.jobs.items() if not self._job_steady(j)}
        return snap

    def _build_incremental(self, raw, dirty_nodes: set,
                           dirty_jobs: set, hn_dirty: bool,
                           gen: int):
        """Reuse the previous snapshot's steady nodes/jobs; rebuild
        only what cluster events or session mutations invalidated.
        Non-steady jobs (anything with in-flight tasks) always rebuild
        — their fit errors and partial state must come from truth.
        Correctness contract: a pod mutation dirties BOTH its node and
        its job, so a clean node can only hold tasks whose pods are
        byte-identical to the base build's.  Returns (snap, delta)."""
        base = self._base
        snap = Snapshot()
        snap.priority_classes = {pc.name: pc
                                 for pc in raw.priority_classes}
        self._build_queues(snap, raw)

        # jobs: raw podgroups are the ground truth for existence;
        # decide reuse-vs-rebuild first so the single pods pass below
        # groups only what a rebuild will actually consume (at 100k
        # hosts, appending every pod to per-job/per-node lists was a
        # fifth of the idle cycle)
        pg_keys = set()
        rebuild_pgs = []
        for pg in raw.podgroups:
            pg_keys.add(pg.key)
            prev = base.jobs.get(pg.key)
            if prev is not None and pg.key not in dirty_jobs and \
                    prev.podgroup is pg and self._job_steady(prev):
                snap.jobs[pg.key] = prev
            else:
                rebuild_pgs.append(pg)
        # reusable shadow jobs (bare pods / orphaned groups): carried
        # over unless an event dirtied them — a dirtied shadow job
        # rebuilds purely from its grouped pods below
        for jkey, prev in base.jobs.items():
            if jkey in pg_keys or jkey in snap.jobs:
                continue
            if jkey not in dirty_jobs and self._job_steady(prev):
                snap.jobs[jkey] = prev

        # nodes: membership is fixed inside the incremental path (any
        # add/delete set _needs_full), so only dirty/replaced node
        # objects rebuild
        rebuild_raw_nodes = []
        for node in raw.nodes:
            prev = base.nodes.get(node.name)
            if prev is not None and node.name not in dirty_nodes and \
                    prev.node is node:
                snap.nodes[node.name] = prev
            else:
                rebuild_raw_nodes.append(node)
        rebuild_node_names = {n.name for n in rebuild_raw_nodes}

        # ONE lean pass over pods (reused jobs keep their tasks, so a
        # pod whose job is already in snap.jobs needs no grouping)
        pods_by_job: Dict[str, list] = {}
        pods_by_node: Dict[str, list] = {}
        for pod in raw.pods:
            if pod.scheduler_name != self.scheduler_name:
                continue
            jkey = self._job_key_for_pod(pod) or pod.key
            if jkey not in snap.jobs:
                pods_by_job.setdefault(jkey, []).append(pod)
            node = pod.node_name
            if node and node in rebuild_node_names:
                pods_by_node.setdefault(node, []).append(pod)

        for pg in rebuild_pgs:
            job = JobInfo(uid=pg.key, podgroup=pg)
            job.priority = self._priority_of(snap, pg.priority_class)
            snap.jobs[pg.key] = job
            for pod in pods_by_job.get(pg.key, ()):
                self._make_task(snap, pod)
        changed_jobs = {pg.key for pg in rebuild_pgs}
        for jkey, pods in pods_by_job.items():
            if jkey in snap.jobs:
                continue            # a rebuilt podgroup consumed them
            changed_jobs.add(jkey)
            for pod in pods:
                self._make_task(snap, pod)
        # _make_task may mint shadow jobs under keys the grouping
        # didn't predict (pod.owner fallbacks): count every job the
        # base didn't have, or whose object was replaced, as changed
        for jkey, job in snap.jobs.items():
            if base.jobs.get(jkey) is not job:
                changed_jobs.add(jkey)

        total = base._total.clone() if base._total is not None else None
        if gen % self._TOTAL_REFRESH_EVERY == 0:
            total = None                    # periodic exact recompute
        for node in rebuild_raw_nodes:
            ni = NodeInfo(node)
            snap.nodes[node.name] = ni
            for pod in pods_by_node.get(node.name, ()):
                task = self._task_for_pod(snap, pod)
                if task is not None and \
                        (task.occupies_resources()
                         or task.status is TaskStatus.RELEASING):
                    ni.add_task(task)
            self._enrich_devices(ni)
            if total is not None:
                prev = base.nodes.get(node.name)
                res = total.res
                if prev is not None:
                    for name, v in self._node_capacity(prev).res.items():
                        left = res.get(name, 0.0) - v
                        if left:
                            res[name] = left
                        else:
                            res.pop(name, None)
                total.add(self._node_capacity(ni))
        snap._total = total if total is not None \
            else snap.total_resource()

        # hypernodes: reuse unless a topology CR event fired or a
        # rebuilt node's labels moved (membership can't change here)
        labels_moved = any(
            n.name in base.nodes
            and base.nodes[n.name].node is not None
            and base.nodes[n.name].node.labels is not n.labels
            and base.nodes[n.name].node.labels != n.labels
            for n in rebuild_raw_nodes)
        hn_changed = hn_dirty or labels_moved or base.hypernodes is None
        if hn_changed:
            self._build_hypernodes(snap, raw)
        else:
            snap.hypernodes = base.hypernodes

        removed_jobs = base.jobs.keys() - snap.jobs.keys()
        changed_jobs -= removed_jobs
        self._unsteady_jobs = {
            k for k, j in snap.jobs.items() if not self._job_steady(j)}
        delta = SnapshotDelta(
            gen, changed_nodes=rebuild_node_names,
            changed_jobs=changed_jobs,
            removed_jobs=set(removed_jobs),
            hypernodes_changed=hn_changed)
        return snap, delta

    @staticmethod
    def _job_steady(job: JobInfo) -> bool:
        idx = job.task_status_index
        return not any(idx[s] for s in _NONSTEADY_STATUSES)

    def _make_task(self, snap: Snapshot, pod) -> TaskInfo:
        """Build a TaskInfo and attach it to its (possibly shadow)
        job; shared by the full and incremental paths."""
        job_uid = self._job_key_for_pod(pod)
        task = TaskInfo(pod, job_uid=job_uid or "")
        task.status = self._task_status(pod)
        if job_uid is not None:
            job = snap.jobs.get(job_uid)
            if job is None:
                # pod references a podgroup we haven't seen: shadow job
                job = JobInfo(uid=job_uid)
                job.queue = pod.annotations.get(
                    QUEUE_NAME_ANNOTATION, DEFAULT_QUEUE)
                snap.jobs[job_uid] = job
        else:
            # bare pod: per-pod shadow job with min_available=1
            job = snap.jobs.get(pod.key)
            if job is None:
                job = JobInfo(uid=pod.key)
                job.name = pod.name
                job.namespace = pod.namespace
                job.queue = pod.annotations.get(
                    QUEUE_NAME_ANNOTATION, DEFAULT_QUEUE)
                snap.jobs[pod.key] = job
        job.add_task(task)
        if task.priority == 0 and pod.priority_class:
            task.priority = self._priority_of(snap, pod.priority_class)
        return task

    def _task_for_pod(self, snap: Snapshot, pod) -> Optional[TaskInfo]:
        """The task object a rebuilt node should hold: the owning
        job's instance (identity with job.tasks preserved whether the
        job was reused or rebuilt)."""
        jkey = self._job_key_for_pod(pod) or pod.key
        job = snap.jobs.get(jkey)
        if job is not None:
            task = job.tasks.get(pod.uid)
            if task is not None:
                return task
        return None

    @staticmethod
    def _build_queues(snap: Snapshot, raw) -> None:
        for q in raw.queues:
            snap.queues[q.name] = QueueInfo(q)
        if DEFAULT_QUEUE not in snap.queues:
            from volcano_tpu.api.queue import Queue
            snap.queues[DEFAULT_QUEUE] = QueueInfo(
                Queue(name=DEFAULT_QUEUE))

    @staticmethod
    def _build_hypernodes(snap: Snapshot, raw) -> None:
        node_labels = {n.name: n.labels for n in raw.nodes}
        snap.hypernodes = HyperNodesInfo(
            raw.hypernodes, [n.name for n in raw.nodes], node_labels)

    @staticmethod
    def _enrich_devices(ni: NodeInfo) -> None:
        for name, factory in REGISTERED_DEVICES.items():
            ni.others[name] = factory(ni)

    def _priority_of(self, snap: Snapshot, pc_name: str) -> int:
        pc = snap.priority_classes.get(pc_name)
        return pc.value if pc else 0

    @staticmethod
    def _job_key_for_pod(pod) -> Optional[str]:
        group = pod.annotations.get(GROUP_NAME_ANNOTATION) or pod.owner
        if not group:
            return None
        if "/" in group:
            return group
        return f"{pod.namespace}/{group}"

    @staticmethod
    def _task_status(pod) -> TaskStatus:
        if pod.phase is TaskStatus.PENDING and pod.node_name:
            return TaskStatus.BOUND
        return pod.phase

    # -- bind / evict --------------------------------------------------

    def add_bind_task(self, task: TaskInfo):
        """Queue an allocated task for asynchronous binding."""
        with self._lock:
            self._bind_queue.append(BindContext(task, task.node_name))

    def flush_binds(self) -> int:
        """Execute queued binds against the cluster; returns bound count.
        Failures are recorded and the pod left Pending for resync
        (reference: resyncTask queue).  The whole queue goes through
        ONE bind_pods call: in-process that is the same loop as before,
        over the wire it is one /bind_batch request per cycle instead
        of one POST per pod — the per-item error contract keeps the
        failure bookkeeping identical either way."""
        with self._lock:
            queue, self._bind_queue = self._bind_queue, []
        if not queue:
            return 0
        from volcano_tpu import metrics
        errors = self.cluster.bind_pods(
            [(ctx.task.namespace, ctx.task.name, ctx.node_name,
              ctx.t_alloc)
             for ctx in queue])
        bound = 0
        requeued: set = set()   # jobs already counted as conflict losers
        for ctx, err in zip(queue, errors):
            if err is None:
                bound += 1
                metrics.inc("schedule_attempts_total", result="scheduled")
            else:
                if self.shard_plan is not None and "overcommit" in err:
                    # another shard's optimistic spill won the server's
                    # atomic check-and-bind for these chips; mark the
                    # refusal so trace reason aggregation buckets it
                    # under the bounded cross-shard-conflict slug and
                    # the loser's next cycle retries with fresh state
                    err = (f"cross-shard conflict (shard "
                           f"{self.shard_plan}): {err}")
                    metrics.inc("sched_cross_shard_conflicts_total",
                                outcome="refused")
                    if ctx.task.job not in requeued:
                        requeued.add(ctx.task.job)
                        metrics.inc("sched_cross_shard_conflicts_total",
                                    outcome="requeued")
                log.warning("bind failed for %s on %s: %s",
                            ctx.task.key, ctx.node_name, err)
                self.bind_failures.append((ctx.task.key, err))
                self.cluster.record_event(
                    ctx.task.key, "FailedBinding", err)
                metrics.inc("schedule_attempts_total", result="error")
        return bound

    def nominate(self, task: TaskInfo, node_name: str):
        self.cluster.nominate_pod(task.namespace, task.name, node_name)

    def evict(self, task: TaskInfo, reason: str = ""):
        self.cluster.evict_pod(task.namespace, task.name, reason)
        self.cluster.record_event(task.key, "Evict", reason)

    def update_podgroup_status(self, pg: PodGroup):
        self.cluster.update_podgroup_status(pg)

    def record_event(self, obj_key: str, reason: str, message: str):
        self.cluster.record_event(obj_key, reason, message)
