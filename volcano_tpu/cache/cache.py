"""SchedulerCache: builds the per-session Snapshot and executes binds.

Reference parity: pkg/scheduler/cache/cache.go (Snapshot:1479, Bind:984,
Evict:938, AddBindTask:1342).  Rebuilt without informer machinery: the
cache reads the Cluster interface and constructs a fresh consistent
model per session (equivalent cost to the reference's deep-copy
Snapshot), and pushes binds/evictions back through a batched queue with
rollback-on-failure.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from volcano_tpu.api.hypernode import HyperNodesInfo
from volcano_tpu.api.job_info import JobInfo, TaskInfo
from volcano_tpu.api.node_info import NodeInfo
from volcano_tpu.api.podgroup import PodGroup
from volcano_tpu.api.queue_info import QueueInfo
from volcano_tpu.api.types import (
    DEFAULT_QUEUE,
    GROUP_NAME_ANNOTATION,
    QUEUE_NAME_ANNOTATION,
    TaskStatus,
)
from volcano_tpu.cache.cluster import Cluster, PriorityClass

log = logging.getLogger(__name__)

# Device-layer enrichment hooks, keyed by device name.  The TPU device
# layer registers here (reference: api.RegisteredDevices +
# shared_device_pool).  Each hook: fn(node_info) -> device object stored
# in node_info.others[name].
REGISTERED_DEVICES: Dict[str, Callable[[NodeInfo], object]] = {}


def register_device(name: str, factory: Callable[[NodeInfo], object]):
    REGISTERED_DEVICES[name] = factory


# TPU is first-class: registered at module load so the very first
# snapshot of a fresh process already carries device state (a lazy
# plugin-import side effect would run AFTER the first snapshot).
from volcano_tpu.api.devices.tpu.device_info import TPUDevices  # noqa: E402

register_device("tpu", TPUDevices)


class Snapshot:
    """One session's consistent view of the cluster."""

    def __init__(self):
        self.jobs: Dict[str, JobInfo] = {}
        self.nodes: Dict[str, NodeInfo] = {}
        self.queues: Dict[str, QueueInfo] = {}
        self.hypernodes: Optional[HyperNodesInfo] = None
        self.priority_classes: Dict[str, PriorityClass] = {}

    def total_resource(self):
        from volcano_tpu.api.resource import Resource
        total = Resource()
        for n in self.nodes.values():
            if n.ready:
                total.add(n.allocatable)
                # measured oversubscription slack is real capacity for
                # queue-share math; node-level fit still restricts it to
                # best-effort-QoS tasks (actions/util.split_by_fit)
                total.add(n.oversubscription)
        return total


class BindContext:
    __slots__ = ("task", "node_name")

    def __init__(self, task: TaskInfo, node_name: str):
        self.task = task
        self.node_name = node_name


class SchedulerCache:
    def __init__(self, cluster: Cluster, scheduler_name: str = "volcano-tpu"):
        self.cluster = cluster
        self.scheduler_name = scheduler_name
        self._lock = threading.Lock()
        self._bind_queue: List[BindContext] = []
        self.bind_failures: List[Tuple[str, str]] = []   # (task key, error)
        # cross-session scratch for plugins (rate limiters etc.), keyed
        # by plugin name.  Plugin INSTANCES are rebuilt every session
        # (framework.open_session), so state that must survive cycles
        # lives here — scoped to this scheduler, never module-global
        # (two schedulers in one process must not share a limiter).
        self.plugin_state: Dict[str, dict] = {}

    # -- snapshot ------------------------------------------------------

    def snapshot(self) -> Snapshot:
        raw = self.cluster.list_all()
        snap = Snapshot()

        snap.priority_classes = {pc.name: pc for pc in raw.priority_classes}

        for q in raw.queues:
            snap.queues[q.name] = QueueInfo(q)
        if DEFAULT_QUEUE not in snap.queues:
            from volcano_tpu.api.queue import Queue
            snap.queues[DEFAULT_QUEUE] = QueueInfo(Queue(name=DEFAULT_QUEUE))

        for node in raw.nodes:
            ni = NodeInfo(node)
            snap.nodes[node.name] = ni

        # jobs from podgroups
        pg_by_key: Dict[str, PodGroup] = {}
        for pg in raw.podgroups:
            pg_by_key[pg.key] = pg
            job = JobInfo(uid=pg.key, podgroup=pg)
            job.priority = self._priority_of(snap, pg.priority_class)
            snap.jobs[job.uid] = job

        # tasks from pods
        for pod in raw.pods:
            if pod.scheduler_name != self.scheduler_name:
                continue
            job_uid = self._job_key_for_pod(pod)
            task = TaskInfo(pod, job_uid=job_uid or "")
            task.status = self._task_status(pod)
            if job_uid is not None:
                job = snap.jobs.get(job_uid)
                if job is None:
                    # pod references a podgroup we haven't seen: shadow job
                    job = JobInfo(uid=job_uid)
                    job.queue = pod.annotations.get(
                        QUEUE_NAME_ANNOTATION, DEFAULT_QUEUE)
                    snap.jobs[job_uid] = job
            else:
                # bare pod: per-pod shadow job with min_available=1
                job = snap.jobs.get(pod.key)
                if job is None:
                    job = JobInfo(uid=pod.key)
                    job.name = pod.name
                    job.namespace = pod.namespace
                    job.queue = pod.annotations.get(
                        QUEUE_NAME_ANNOTATION, DEFAULT_QUEUE)
                    snap.jobs[pod.key] = job
            job.add_task(task)
            if task.priority == 0 and pod.priority_class:
                task.priority = self._priority_of(snap, pod.priority_class)

            if task.node_name and (task.occupies_resources()
                                   or task.status is TaskStatus.RELEASING):
                ni = snap.nodes.get(task.node_name)
                if ni is not None:
                    ni.add_task(task)

        # topology
        node_labels = {n.name: n.labels for n in raw.nodes}
        snap.hypernodes = HyperNodesInfo(
            raw.hypernodes, [n.name for n in raw.nodes], node_labels)

        # device enrichment (tpu slice inventory etc.)
        for ni in snap.nodes.values():
            for name, factory in REGISTERED_DEVICES.items():
                ni.others[name] = factory(ni)

        return snap

    def _priority_of(self, snap: Snapshot, pc_name: str) -> int:
        pc = snap.priority_classes.get(pc_name)
        return pc.value if pc else 0

    @staticmethod
    def _job_key_for_pod(pod) -> Optional[str]:
        group = pod.annotations.get(GROUP_NAME_ANNOTATION) or pod.owner
        if not group:
            return None
        if "/" in group:
            return group
        return f"{pod.namespace}/{group}"

    @staticmethod
    def _task_status(pod) -> TaskStatus:
        if pod.phase is TaskStatus.PENDING and pod.node_name:
            return TaskStatus.BOUND
        return pod.phase

    # -- bind / evict --------------------------------------------------

    def add_bind_task(self, task: TaskInfo):
        """Queue an allocated task for asynchronous binding."""
        with self._lock:
            self._bind_queue.append(BindContext(task, task.node_name))

    def flush_binds(self) -> int:
        """Execute queued binds against the cluster; returns bound count.
        Failures are recorded and the pod left Pending for resync
        (reference: resyncTask queue)."""
        with self._lock:
            queue, self._bind_queue = self._bind_queue, []
        from volcano_tpu import metrics
        bound = 0
        for ctx in queue:
            try:
                self.cluster.bind_pod(ctx.task.namespace, ctx.task.name,
                                      ctx.node_name)
                bound += 1
                metrics.inc("schedule_attempts_total", result="scheduled")
            except Exception as e:  # noqa: BLE001 - record any bind failure
                log.warning("bind failed for %s on %s: %s",
                            ctx.task.key, ctx.node_name, e)
                self.bind_failures.append((ctx.task.key, str(e)))
                self.cluster.record_event(
                    ctx.task.key, "FailedBinding", str(e))
                metrics.inc("schedule_attempts_total", result="error")
        return bound

    def nominate(self, task: TaskInfo, node_name: str):
        self.cluster.nominate_pod(task.namespace, task.name, node_name)

    def evict(self, task: TaskInfo, reason: str = ""):
        self.cluster.evict_pod(task.namespace, task.name, reason)
        self.cluster.record_event(task.key, "Evict", reason)

    def update_podgroup_status(self, pg: PodGroup):
        self.cluster.update_podgroup_status(pg)

    def record_event(self, obj_key: str, reason: str, message: str):
        self.cluster.record_event(obj_key, reason, message)
