"""SchedulerCache: builds the per-session Snapshot and executes binds.

Reference parity: pkg/scheduler/cache/cache.go (Snapshot:1479, Bind:984,
Evict:938, AddBindTask:1342).  Rebuilt without informer machinery: the
cache reads the Cluster interface and constructs a fresh consistent
model per session (equivalent cost to the reference's deep-copy
Snapshot), and pushes binds/evictions back through a batched queue with
rollback-on-failure.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from volcano_tpu.api.hypernode import HyperNodesInfo
from volcano_tpu.api.job_info import JobInfo, TaskInfo
from volcano_tpu.api.node_info import NodeInfo
from volcano_tpu.api.podgroup import PodGroup
from volcano_tpu.api.queue_info import QueueInfo
from volcano_tpu.api.types import (
    DEFAULT_QUEUE,
    GROUP_NAME_ANNOTATION,
    QUEUE_NAME_ANNOTATION,
    TaskStatus,
)
from volcano_tpu.cache.cluster import Cluster, PriorityClass

log = logging.getLogger(__name__)

# Device-layer enrichment hooks, keyed by device name.  The TPU device
# layer registers here (reference: api.RegisteredDevices +
# shared_device_pool).  Each hook: fn(node_info) -> device object stored
# in node_info.others[name].
REGISTERED_DEVICES: Dict[str, Callable[[NodeInfo], object]] = {}


def register_device(name: str, factory: Callable[[NodeInfo], object]):
    REGISTERED_DEVICES[name] = factory


# TPU is first-class: registered at module load so the very first
# snapshot of a fresh process already carries device state (a lazy
# plugin-import side effect would run AFTER the first snapshot).
from volcano_tpu.api.devices.tpu.device_info import TPUDevices  # noqa: E402

register_device("tpu", TPUDevices)


class Snapshot:
    """One session's consistent view of the cluster."""

    def __init__(self):
        self.jobs: Dict[str, JobInfo] = {}
        self.nodes: Dict[str, NodeInfo] = {}
        self.queues: Dict[str, QueueInfo] = {}
        self.hypernodes: Optional[HyperNodesInfo] = None
        self.priority_classes: Dict[str, PriorityClass] = {}
        # the cache's ThroughputBook (volcano_tpu/goodput.py): learned
        # per-(job, generation) step-rate vectors, exposed to plugins
        # and actions as session.goodput
        self.goodput = None

    def total_resource(self):
        from volcano_tpu.api.resource import Resource
        total = Resource()
        for n in self.nodes.values():
            if n.ready:
                total.add(n.allocatable)
                # measured oversubscription slack is real capacity for
                # queue-share math; node-level fit still restricts it to
                # best-effort-QoS tasks (actions/util.split_by_fit)
                total.add(n.oversubscription)
        return total


class BindContext:
    __slots__ = ("task", "node_name", "t_alloc")

    def __init__(self, task: TaskInfo, node_name: str):
        self.task = task
        self.node_name = node_name
        # placement-decision wall time, shipped with the bind so the
        # store's `allocated` lifecycle stamp reflects the decision,
        # not the end-of-cycle batch commit (trace.py phases)
        self.t_alloc = time.time()


# statuses that mean a job still has in-flight scheduling state: its
# JobInfo must be rebuilt from cluster truth every cycle (fit errors,
# nominations, partial gangs).  Jobs whose every task is outside this
# set are steady and reusable between cycles.
_NONSTEADY_STATUSES = (
    TaskStatus.PENDING, TaskStatus.ALLOCATED, TaskStatus.PIPELINED,
    TaskStatus.BINDING, TaskStatus.BOUND, TaskStatus.RELEASING,
)


class SchedulerCache:
    def __init__(self, cluster: Cluster, scheduler_name: str = "volcano-tpu"):
        self.cluster = cluster
        self.scheduler_name = scheduler_name
        self._lock = threading.Lock()
        self._bind_queue: List[BindContext] = []
        self.bind_failures: List[Tuple[str, str]] = []   # (task key, error)
        # cross-session scratch for plugins (rate limiters etc.), keyed
        # by plugin name.  Plugin INSTANCES are rebuilt every session
        # (framework.open_session), so state that must survive cycles
        # lives here — scoped to this scheduler, never module-global
        # (two schedulers in one process must not share a limiter).
        self.plugin_state: Dict[str, dict] = {}
        # incremental snapshot state (VERDICT r2 item 7): the previous
        # snapshot is the reuse base; cluster watch events and session
        # touch reports accumulate the dirty sets consumed per cycle.
        self._base: Optional[Snapshot] = None
        self._dirty_lock = threading.Lock()
        self._dirty_nodes: set = set()
        self._dirty_jobs: set = set()
        self._needs_full = True
        # pods whose lifecycle-phase segments were already fed to
        # sched_phase_seconds (once per pod, bounded window)
        self._phase_seen: set = set()
        self._phase_seen_order: deque = deque()
        # learned per-(job, generation) throughput vectors, fed from
        # folded podgroup goodput annotations on ordinary watch
        # events — works identically in-process and over the wire
        from volcano_tpu.goodput import ThroughputBook
        self.goodput_book = ThroughputBook()
        watch = getattr(cluster, "watch", None)
        if watch is not None:
            watch(self._on_cluster_event)

    # -- dirty tracking ------------------------------------------------

    def _on_cluster_event(self, kind: str, obj) -> None:
        """Cluster mutations invalidate exactly the model objects they
        feed (the informer-handler analogue, event_handlers.go)."""
        with self._dirty_lock:
            if kind in ("pod", "pod_deleted"):
                node = getattr(obj, "node_name", "")
                if node:
                    self._dirty_nodes.add(node)
                self._dirty_jobs.add(self._job_key_for_pod(obj)
                                     or obj.key)
            elif kind == "node":
                name = getattr(obj, "name", None)
                if self._base is not None and \
                        name not in self._base.nodes:
                    self._needs_full = True     # membership grew
                else:
                    self._dirty_nodes.add(name)
            elif kind in ("podgroup", "podgroup_deleted"):
                self._dirty_jobs.add(obj.key)
            elif kind in ("node_deleted", "priority_class",
                          "priority_class_deleted", "queue",
                          "queue_deleted"):
                # membership shrank / priorities shifted / queue specs
                # changed or vanished: queue+priority feed job
                # construction, so rebuild everything (all are rare
                # control events).  Deliberately NOT a *_deleted
                # catch-all: vcjob_deleted/jobflow_deleted fire on
                # routine job churn and their cascaded pod/podgroup
                # deletions already dirty the right objects.
                self._needs_full = True
            # hypernode/numatopology/vcjob/command/...: not part of
            # the reused model (hypernodes rebuild every snapshot;
            # the rest is controller-side state)
        if kind == "pod":
            # outside the dirty lock: phase-metric derivation reads
            # the podgroup store and feeds the metrics registry
            self._maybe_observe_phases(obj)
        elif kind == "podgroup":
            self._maybe_observe_goodput(obj)
        elif kind == "podgroup_deleted":
            self.goodput_book.forget(getattr(obj, "key", ""))

    _PHASE_SEEN_MAX = 8192

    def _maybe_observe_phases(self, pod) -> None:
        """Feed a pod's lifecycle-phase segments (trace.py stamps) to
        sched_phase_seconds once it reaches Running — the scheduler-
        process half of the e2e derivation, driven by ordinary watch
        events so it works identically in-process and over the wire."""
        from volcano_tpu import trace
        if getattr(pod, "phase", None) is not TaskStatus.RUNNING:
            return
        ann = getattr(pod, "annotations", None)
        if not ann or trace.TS_PREFIX + "running" not in ann:
            return
        uid = getattr(pod, "uid", None)
        if uid is None or uid in self._phase_seen:
            return
        self._phase_seen.add(uid)
        self._phase_seen_order.append(uid)
        while len(self._phase_seen_order) > self._PHASE_SEEN_MAX:
            self._phase_seen.discard(self._phase_seen_order.popleft())
        pg_ann = None
        jkey = self._job_key_for_pod(pod)
        if jkey:
            pg = getattr(self.cluster, "podgroups", {}).get(jkey)
            if pg is not None:
                pg_ann = pg.annotations
        trace.observe_phase_metrics(ann, pg_ann)

    def _maybe_observe_goodput(self, pg) -> None:
        """Feed a podgroup's folded goodput annotations (store-side
        GoodputReport fold) into the throughput-vector book — the
        learn half of the Gavel loop, driven by ordinary watch events
        so it works identically in-process and over the wire.  The
        fold timestamp dedupes watch re-deliveries."""
        from volcano_tpu.api import elastic as eapi
        from volcano_tpu.api import goodput as gapi
        ann = getattr(pg, "annotations", None)
        if not ann or gapi.PG_STEP_RATE_ANNOTATION not in ann:
            return
        rate = gapi.ann_float(ann, gapi.PG_STEP_RATE_ANNOTATION)
        if rate <= 0:
            return
        self.goodput_book.note(
            pg.key,
            ann.get(gapi.PG_GENERATION_ANNOTATION, "other"),
            rate,
            eapi.current_slices(pg),
            gapi.ann_float(ann, gapi.PG_UPDATED_TS_ANNOTATION))

    def note_touched(self, nodes, jobs) -> None:
        """Session mutations (committed OR discarded) — close_session
        reports them; the touched objects rebuild next cycle."""
        with self._dirty_lock:
            self._dirty_nodes.update(nodes)
            self._dirty_jobs.update(jobs)

    def _consume_dirty(self):
        with self._dirty_lock:
            dirty = (self._needs_full, self._dirty_nodes,
                     self._dirty_jobs)
            self._needs_full = False
            self._dirty_nodes = set()
            self._dirty_jobs = set()
            return dirty

    # -- snapshot ------------------------------------------------------

    def snapshot(self) -> Snapshot:
        from volcano_tpu import features
        needs_full, dirty_nodes, dirty_jobs = self._consume_dirty()
        raw = self.cluster.list_all()
        if self._base is None or needs_full or \
                not features.enabled("IncrementalSnapshot"):
            snap = self._build_full(raw)
        else:
            snap = self._build_incremental(raw, dirty_nodes, dirty_jobs)
        snap.goodput = self.goodput_book
        self._base = snap
        return snap

    def _build_full(self, raw) -> Snapshot:
        snap = Snapshot()
        snap.priority_classes = {pc.name: pc for pc in raw.priority_classes}
        self._build_queues(snap, raw)

        for node in raw.nodes:
            ni = NodeInfo(node)
            snap.nodes[node.name] = ni

        # jobs from podgroups
        for pg in raw.podgroups:
            job = JobInfo(uid=pg.key, podgroup=pg)
            job.priority = self._priority_of(snap, pg.priority_class)
            snap.jobs[job.uid] = job

        # tasks from pods
        for pod in raw.pods:
            if pod.scheduler_name != self.scheduler_name:
                continue
            task = self._make_task(snap, pod)
            if task.node_name and (task.occupies_resources()
                                   or task.status is TaskStatus.RELEASING):
                ni = snap.nodes.get(task.node_name)
                if ni is not None:
                    ni.add_task(task)

        self._build_hypernodes(snap, raw)
        for ni in snap.nodes.values():
            self._enrich_devices(ni)
        return snap

    def _build_incremental(self, raw, dirty_nodes: set,
                           dirty_jobs: set) -> Snapshot:
        """Reuse the previous snapshot's steady nodes/jobs; rebuild
        only what cluster events or session mutations invalidated.
        Non-steady jobs (anything with in-flight tasks) always rebuild
        — their fit errors and partial state must come from truth.
        Correctness contract: a pod mutation dirties BOTH its node and
        its job, so a clean node can only hold tasks whose pods are
        byte-identical to the base build's."""
        base = self._base
        snap = Snapshot()
        snap.priority_classes = {pc.name: pc
                                 for pc in raw.priority_classes}
        self._build_queues(snap, raw)

        # group pods once (cheap dict ops; the expensive TaskInfo math
        # runs only for rebuilt jobs/nodes)
        pods_by_job: Dict[str, list] = {}
        pods_by_node: Dict[str, list] = {}
        for pod in raw.pods:
            if pod.scheduler_name != self.scheduler_name:
                continue
            jkey = self._job_key_for_pod(pod) or pod.key
            pods_by_job.setdefault(jkey, []).append(pod)
            if pod.node_name:
                pods_by_node.setdefault(pod.node_name, []).append(pod)

        # jobs: raw podgroups are the ground truth for existence
        pg_keys = set()
        for pg in raw.podgroups:
            pg_keys.add(pg.key)
            prev = base.jobs.get(pg.key)
            if prev is not None and pg.key not in dirty_jobs and \
                    prev.podgroup is pg and self._job_steady(prev):
                snap.jobs[pg.key] = prev
                continue
            job = JobInfo(uid=pg.key, podgroup=pg)
            job.priority = self._priority_of(snap, pg.priority_class)
            snap.jobs[pg.key] = job
            for pod in pods_by_job.get(pg.key, ()):
                self._make_task(snap, pod)
        # shadow jobs (bare pods / orphaned groups)
        for jkey, pods in pods_by_job.items():
            if jkey in snap.jobs:
                continue
            prev = base.jobs.get(jkey)
            if prev is not None and jkey not in dirty_jobs and \
                    self._job_steady(prev):
                snap.jobs[jkey] = prev
                continue
            for pod in pods:
                self._make_task(snap, pod)

        # nodes
        for node in raw.nodes:
            prev = base.nodes.get(node.name)
            if prev is not None and node.name not in dirty_nodes and \
                    prev.node is node:
                snap.nodes[node.name] = prev
                continue
            ni = NodeInfo(node)
            snap.nodes[node.name] = ni
            for pod in pods_by_node.get(node.name, ()):
                task = self._task_for_pod(snap, pod)
                if task is not None and \
                        (task.occupies_resources()
                         or task.status is TaskStatus.RELEASING):
                    ni.add_task(task)
            self._enrich_devices(ni)

        self._build_hypernodes(snap, raw)
        return snap

    @staticmethod
    def _job_steady(job: JobInfo) -> bool:
        idx = job.task_status_index
        return not any(idx[s] for s in _NONSTEADY_STATUSES)

    def _make_task(self, snap: Snapshot, pod) -> TaskInfo:
        """Build a TaskInfo and attach it to its (possibly shadow)
        job; shared by the full and incremental paths."""
        job_uid = self._job_key_for_pod(pod)
        task = TaskInfo(pod, job_uid=job_uid or "")
        task.status = self._task_status(pod)
        if job_uid is not None:
            job = snap.jobs.get(job_uid)
            if job is None:
                # pod references a podgroup we haven't seen: shadow job
                job = JobInfo(uid=job_uid)
                job.queue = pod.annotations.get(
                    QUEUE_NAME_ANNOTATION, DEFAULT_QUEUE)
                snap.jobs[job_uid] = job
        else:
            # bare pod: per-pod shadow job with min_available=1
            job = snap.jobs.get(pod.key)
            if job is None:
                job = JobInfo(uid=pod.key)
                job.name = pod.name
                job.namespace = pod.namespace
                job.queue = pod.annotations.get(
                    QUEUE_NAME_ANNOTATION, DEFAULT_QUEUE)
                snap.jobs[pod.key] = job
        job.add_task(task)
        if task.priority == 0 and pod.priority_class:
            task.priority = self._priority_of(snap, pod.priority_class)
        return task

    def _task_for_pod(self, snap: Snapshot, pod) -> Optional[TaskInfo]:
        """The task object a rebuilt node should hold: the owning
        job's instance (identity with job.tasks preserved whether the
        job was reused or rebuilt)."""
        jkey = self._job_key_for_pod(pod) or pod.key
        job = snap.jobs.get(jkey)
        if job is not None:
            task = job.tasks.get(pod.uid)
            if task is not None:
                return task
        return None

    @staticmethod
    def _build_queues(snap: Snapshot, raw) -> None:
        for q in raw.queues:
            snap.queues[q.name] = QueueInfo(q)
        if DEFAULT_QUEUE not in snap.queues:
            from volcano_tpu.api.queue import Queue
            snap.queues[DEFAULT_QUEUE] = QueueInfo(
                Queue(name=DEFAULT_QUEUE))

    @staticmethod
    def _build_hypernodes(snap: Snapshot, raw) -> None:
        node_labels = {n.name: n.labels for n in raw.nodes}
        snap.hypernodes = HyperNodesInfo(
            raw.hypernodes, [n.name for n in raw.nodes], node_labels)

    @staticmethod
    def _enrich_devices(ni: NodeInfo) -> None:
        for name, factory in REGISTERED_DEVICES.items():
            ni.others[name] = factory(ni)

    def _priority_of(self, snap: Snapshot, pc_name: str) -> int:
        pc = snap.priority_classes.get(pc_name)
        return pc.value if pc else 0

    @staticmethod
    def _job_key_for_pod(pod) -> Optional[str]:
        group = pod.annotations.get(GROUP_NAME_ANNOTATION) or pod.owner
        if not group:
            return None
        if "/" in group:
            return group
        return f"{pod.namespace}/{group}"

    @staticmethod
    def _task_status(pod) -> TaskStatus:
        if pod.phase is TaskStatus.PENDING and pod.node_name:
            return TaskStatus.BOUND
        return pod.phase

    # -- bind / evict --------------------------------------------------

    def add_bind_task(self, task: TaskInfo):
        """Queue an allocated task for asynchronous binding."""
        with self._lock:
            self._bind_queue.append(BindContext(task, task.node_name))

    def flush_binds(self) -> int:
        """Execute queued binds against the cluster; returns bound count.
        Failures are recorded and the pod left Pending for resync
        (reference: resyncTask queue).  The whole queue goes through
        ONE bind_pods call: in-process that is the same loop as before,
        over the wire it is one /bind_batch request per cycle instead
        of one POST per pod — the per-item error contract keeps the
        failure bookkeeping identical either way."""
        with self._lock:
            queue, self._bind_queue = self._bind_queue, []
        if not queue:
            return 0
        from volcano_tpu import metrics
        errors = self.cluster.bind_pods(
            [(ctx.task.namespace, ctx.task.name, ctx.node_name,
              ctx.t_alloc)
             for ctx in queue])
        bound = 0
        for ctx, err in zip(queue, errors):
            if err is None:
                bound += 1
                metrics.inc("schedule_attempts_total", result="scheduled")
            else:
                log.warning("bind failed for %s on %s: %s",
                            ctx.task.key, ctx.node_name, err)
                self.bind_failures.append((ctx.task.key, err))
                self.cluster.record_event(
                    ctx.task.key, "FailedBinding", err)
                metrics.inc("schedule_attempts_total", result="error")
        return bound

    def nominate(self, task: TaskInfo, node_name: str):
        self.cluster.nominate_pod(task.namespace, task.name, node_name)

    def evict(self, task: TaskInfo, reason: str = ""):
        self.cluster.evict_pod(task.namespace, task.name, reason)
        self.cluster.record_event(task.key, "Evict", reason)

    def update_podgroup_status(self, pg: PodGroup):
        self.cluster.update_podgroup_status(pg)

    def record_event(self, obj_key: str, reason: str, message: str):
        self.cluster.record_event(obj_key, reason, message)
