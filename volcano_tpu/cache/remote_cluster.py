"""RemoteCluster: the wire-backed Cluster implementation.

The client half of the process split (server half:
volcano_tpu/server/state_server.py).  Mirrors the reference scheduler's
informer architecture (pkg/scheduler/cache/cache.go:109,
event_handlers.go): a local object mirror is bootstrapped by one full
LIST (/snapshot) and then kept current by a background WATCH long-poll
thread; reads (list_all, store attributes) are served from the mirror
with zero RPCs, and every write goes to the server AND is echoed into
the mirror immediately so a process observes its own writes without
waiting for the watch round-trip (the reference's assume-cache
discipline, cache.go:1342 AddBindTask).

Stdlib-only: urllib over HTTP/JSON with the api/codec.py codec.
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.error
import urllib.request
from http.client import HTTPException
from typing import Callable, Dict, List, Optional

from volcano_tpu.api import codec
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.cache.cluster import Cluster, ClusterSnapshot
from volcano_tpu.cache.kinds import KINDS, key_for

log = logging.getLogger(__name__)


class RemoteError(RuntimeError):
    def __init__(self, code: int, message: str):
        super().__init__(message)
        self.code = code


class RemoteCluster(Cluster):
    def __init__(self, base_url: str, start_watch: bool = True,
                 timeout: float = 10.0, token: str = "",
                 ca_cert: str = "", insecure: bool = False,
                 tolerate_unreachable: bool = False):
        """tolerate_unreachable: a dead server at construction time
        leaves the mirror empty instead of raising — the watch loop's
        resync-on-reconnect self-heals once the server returns (the
        hub's member-cluster clients must survive a member outage)."""
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.token = token
        from volcano_tpu.server.tlsutil import client_ssl_context
        self._ssl_ctx = client_ssl_context(ca_cert, insecure)
        self._mlock = threading.RLock()        # mirror + watchers
        self._watchers: List[Callable[[str, object], None]] = []
        self._rv = 0
        self._epoch = ""
        self._stop = threading.Event()
        # mirror stores, same attribute names as FakeCluster
        for spec in KINDS.values():
            setattr(self, spec.attr, {})
        self.commands: List[dict] = []
        self.events: List[tuple] = []          # local record only
        try:
            self.resync()
        except Exception as e:  # noqa: BLE001 — classified below
            # Tolerable: anything the watch loop could heal once the
            # server is back — connection failures (URLError IS an
            # OSError), truncated/garbled responses (HTTPException),
            # and server-side 5xx (a restarting proxy).  NOT
            # tolerable: 4xx auth/config errors — every retry would
            # 401 forever, so fail fast even in tolerant mode.
            transient = isinstance(e, (OSError, HTTPException)) or \
                (isinstance(e, RemoteError) and e.code >= 500)
            if not tolerate_unreachable or not transient:
                raise
            log.warning("state server %s unreachable at startup (%s); "
                        "mirror starts empty and the watch loop will "
                        "resync when it returns", self.base_url, e)
        self._watch_thread = None
        if start_watch:
            self._watch_thread = threading.Thread(
                target=self._watch_loop, name="cluster-watch", daemon=True)
            self._watch_thread.start()

    # -- HTTP ----------------------------------------------------------

    def _request(self, method: str, path: str, payload=None,
                 timeout: Optional[float] = None):
        data = None
        if payload is not None:
            data = json.dumps(payload, separators=(",", ":")).encode()
        headers = {"Content-Type": "application/json",
                   # big GET bodies (snapshot/watch/delta) come back
                   # gzip'd; the server leaves small ones plain
                   "Accept-Encoding": "gzip"}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        req = urllib.request.Request(
            self.base_url + path, data=data, method=method,
            headers=headers)
        try:
            with urllib.request.urlopen(
                    req, timeout=timeout or self.timeout,
                    context=self._ssl_ctx) as resp:
                from volcano_tpu.server.httputil import read_json_body
                return read_json_body(resp)
        except urllib.error.HTTPError as e:
            try:
                msg = json.loads(e.read()).get("error", str(e))
            except Exception:  # noqa: BLE001
                msg = str(e)
            if e.code == 422:
                from volcano_tpu.webhooks.admission import AdmissionError
                raise AdmissionError(msg) from None
            if e.code == 409:
                raise ValueError(msg) from None
            if e.code == 404:
                raise KeyError(msg) from None
            raise RemoteError(e.code, msg) from None

    # -- mirror maintenance --------------------------------------------

    def resync(self) -> None:
        """Reconcile the mirror with the server, delta-first.

        A mirror that already holds a revision asks the watch endpoint
        (timeout=0: no long-poll, same payload shape, works against
        any server vintage) for the events since it: O(churn) work
        and bytes, not O(cluster) — at a few thousand hosts the full
        snapshot is megabytes while a churn window is a handful of
        events.  Falls back to the full LIST when the mirror is empty
        (bootstrap), the revision fell off the server's compaction
        horizon (resync verdict), the server is a new incarnation
        (epoch change: its counters restarted), or the delta request
        itself fails."""
        # _epoch marks "bootstrapped at least once" — rv 0 is a valid
        # revision (a mirror synced before the first event), so gate on
        # the epoch, not the revision
        if self._epoch:
            try:
                payload = self._request(
                    "GET", f"/watch?since={self._rv}&timeout=0")
            except Exception as e:  # noqa: BLE001 — fall back to LIST
                log.debug("delta resync failed (%s); full re-list", e)
                payload = None
            if payload is not None and not payload.get("resync") \
                    and payload.get("epoch", "") == self._epoch \
                    and payload["rv"] >= self._rv:
                from volcano_tpu import metrics
                metrics.inc("mirror_resync_total", mode="delta")
                # fold like a watch batch (copy-on-write swap) and
                # NOTIFY: these are real missed events, and watchers
                # (controllers) level-trigger off them exactly as if
                # the watch stream had delivered them
                for kind, obj in self._apply_batch(payload["events"]):
                    self._notify(kind, obj)
                with self._mlock:
                    self._rv = max(self._rv, payload["rv"])
                return
        self._full_resync()

    def _full_resync(self) -> None:
        """Full LIST: replace the mirror (bootstrap + ring fall-off +
        server restart)."""
        from volcano_tpu import metrics
        payload = self._request("GET", "/snapshot")
        metrics.inc("mirror_resync_total", mode="full")
        with self._mlock:
            self._rv = payload["rv"]
            self._epoch = payload.get("epoch", "")
            stores = payload["stores"]
            for kind, spec in KINDS.items():
                # whole-store swap, never in-place clear: readers on
                # other threads keep iterating their consistent copy
                setattr(self, spec.attr, {
                    k: codec.decode(enc)
                    for k, enc in stores.get(kind, {}).items()})
            self.commands = codec.decode(stores.get("_commands", [])) or []

    def _apply_batch(self, events) -> list:
        """Fold a watch batch into the mirror copy-on-write: each
        affected store is rebuilt as a fresh dict and swapped in, so a
        controller iterating `cluster.pods` on another thread never
        sees a dict mutate under it.  Returns (kind, obj) pairs for
        watcher notification."""
        decoded = []
        for ev in events:
            try:
                decoded.append((ev["kind"], codec.decode(ev["obj"])))
            except Exception:  # noqa: BLE001
                log.exception("watch event %s undecodable", ev["kind"])
        updated: dict = {}          # attr -> new dict
        new_commands = None
        notifications = []
        with self._mlock:           # copies + swap atomic vs local echo
            for kind, obj in decoded:
                deleted = kind.endswith("_deleted")
                base = kind[:-len("_deleted")] if deleted else kind
                spec = KINDS.get(base)
                if spec is not None:
                    key = obj["key"] if spec.key_of is None \
                        else spec.key_of(obj)
                    store = updated.get(spec.attr)
                    if store is None:
                        store = dict(getattr(self, spec.attr))
                        updated[spec.attr] = store
                    if deleted:
                        store.pop(key, None)
                    else:
                        store[key] = obj if spec.key_of else obj["obj"]
                elif base == "command":
                    if new_commands is None:
                        new_commands = list(self.commands)
                    new_commands.append(obj)
                notifications.append((kind, obj))
            for attr, store in updated.items():
                setattr(self, attr, store)
            if new_commands is not None:
                self.commands = new_commands
        return notifications

    def _watch_loop(self) -> None:
        while not self._stop.is_set():
            try:
                payload = self._request(
                    "GET", f"/watch?since={self._rv}&timeout=25",
                    timeout=60.0)
            except Exception:  # noqa: BLE001 — server restart etc.
                if self._stop.wait(1.0):
                    return
                continue
            epoch = payload.get("epoch", "")
            if payload.get("resync") or payload["rv"] < self._rv or \
                    (self._epoch and epoch and epoch != self._epoch):
                # ring fall-off, rv regression, or a NEW server
                # incarnation (epoch change — catches a restarted
                # server whose counter already passed ours): only a
                # full re-list recovers the stream
                try:
                    self.resync()
                except Exception:  # noqa: BLE001
                    log.exception("resync failed")
                continue
            for kind, obj in self._apply_batch(payload["events"]):
                self._notify(kind, obj)
            self._rv = max(self._rv, payload["rv"])

    def close(self) -> None:
        self._stop.set()

    def _notify(self, kind: str, obj) -> None:
        for w in list(self._watchers):
            try:
                w(kind, obj)
            except Exception:  # noqa: BLE001
                log.exception("watcher failed on %s", kind)

    # -- Cluster interface: reads --------------------------------------

    def list_all(self) -> ClusterSnapshot:
        with self._mlock:
            return ClusterSnapshot(
                pods=list(self.pods.values()),
                nodes=list(self.nodes.values()),
                podgroups=list(self.podgroups.values()),
                queues=list(self.queues.values()),
                hypernodes=list(self.hypernodes.values()),
                priority_classes=list(self.priority_classes.values()),
                vcjobs=list(self.vcjobs.values()),
            )

    def watch(self, fn) -> None:
        self._watchers.append(fn)

    def unwatch(self, fn) -> None:
        try:
            self._watchers.remove(fn)
        except ValueError:
            pass

    # -- Cluster interface: writes (server + local echo) ---------------

    def put_object(self, kind: str, obj, key: Optional[str] = None):
        resp = self._request("POST", f"/objects/{kind}",
                             {"obj": codec.encode(obj), "key": key})
        stored = codec.decode(resp["obj"])
        spec = KINDS[kind]
        k = key_for(kind, stored if spec.key_of else obj, key)
        with self._mlock:
            getattr(self, spec.attr)[k] = stored
        self._notify(kind, stored if spec.key_of
                     else {"key": k, "obj": stored})
        return stored

    def delete_object(self, kind: str, key: str) -> None:
        from urllib.parse import quote
        self._request("DELETE",
                      f"/objects/{kind}?key={quote(key, safe='')}")
        spec = KINDS[kind]
        with self._mlock:
            obj = getattr(self, spec.attr).pop(key, None)
        if obj is not None:
            self._notify(f"{kind}_deleted",
                         obj if spec.key_of else {"key": key, "obj": obj})

    # typed conveniences matching the FakeCluster surface ---------------

    def add_node(self, node):
        return self.put_object("node", node)

    def remove_node(self, name: str):
        self.delete_object("node", name)

    def add_pod(self, pod) -> None:
        self.put_object("pod", pod)

    def delete_pod(self, key: str) -> None:
        self.delete_object("pod", key)

    def add_podgroup(self, pg) -> None:
        self.put_object("podgroup", pg)

    def delete_podgroup(self, key: str) -> None:
        self.delete_object("podgroup", key)

    def add_queue(self, queue):
        return self.put_object("queue", queue)

    def add_hypernode(self, hn) -> None:
        self.put_object("hypernode", hn)

    def delete_hypernode(self, name: str) -> None:
        self.delete_object("hypernode", name)

    def add_numatopology(self, topo) -> None:
        self.put_object("numatopology", topo)

    def add_priority_class(self, pc) -> None:
        self.put_object("priority_class", pc)

    def add_vcjob(self, job):
        return self.put_object("vcjob", job)

    def update_vcjob(self, job) -> None:
        # explicit key marks this as an UPDATE: the server must not
        # re-run create admission on a status flush (e.g. a job whose
        # queue has closed since creation would 422 forever)
        self.put_object("vcjob", job, key=job.key)

    def delete_vcjob(self, key: str) -> None:
        self.delete_object("vcjob", key)

    # -- scheduler write path ------------------------------------------

    def bind_pod(self, namespace: str, name: str, node_name: str) -> None:
        self._request("POST", "/bind", {
            "namespace": namespace, "name": name, "node_name": node_name})
        with self._mlock:
            pod = self.pods.get(f"{namespace}/{name}")
            if pod is not None:
                pod.node_name = node_name
                pod.phase = TaskStatus.BOUND

    def bind_pods(self, binds) -> List[Optional[str]]:
        """A gang's binds as ONE /bind_batch request instead of N bind
        POSTs — the client half of the wire fast lane.  Per-item error
        strings mirror the per-pod path (Cluster.bind_pods contract);
        successes are echoed into the mirror under one lock.  A server
        that predates the route (rolling upgrade: 404s the path) or a
        transport failure falls back to the per-pod loop — bind_pod
        re-sent for an already-applied bind is idempotent (same-node
        rebind is accepted), so the fallback never double-faults."""
        binds = list(binds)
        if not binds:
            return []
        try:
            resp = self._request("POST", "/bind_batch", {"binds": [
                {"namespace": ns, "name": n, "node_name": node}
                for ns, n, node in binds]})
            results = resp["results"]
            if len(results) != len(binds):
                raise RemoteError(500, "bind_batch result count "
                                  f"{len(results)} != {len(binds)}")
        except Exception as e:  # noqa: BLE001 — whole-batch failure
            log.warning("bind_batch unavailable (%s); falling back to "
                        "per-pod binds", e)
            return super().bind_pods(binds)
        errors: List[Optional[str]] = []
        with self._mlock:
            for (ns, n, node), r in zip(binds, results):
                if r.get("ok"):
                    pod = self.pods.get(f"{ns}/{n}")
                    if pod is not None:
                        pod.node_name = node
                        pod.phase = TaskStatus.BOUND
                    errors.append(None)
                else:
                    errors.append(r.get("error", "bind failed"))
        return errors

    def evict_pod(self, namespace: str, name: str, reason: str = "") -> None:
        self._request("POST", "/evict", {
            "namespace": namespace, "name": name, "reason": reason})
        with self._mlock:
            pod = self.pods.get(f"{namespace}/{name}")
            if pod is not None:
                pod.phase = TaskStatus.RELEASING
                pod.status_message = reason

    def nominate_pod(self, namespace: str, name: str,
                     node_name: str) -> None:
        self._request("POST", "/nominate", {
            "namespace": namespace, "name": name, "node_name": node_name})
        with self._mlock:
            pod = self.pods.get(f"{namespace}/{name}")
            if pod is not None:
                pod.nominated_node = node_name

    def update_podgroup_status(self, pg) -> None:
        self._request("POST", "/podgroup_status",
                      {"obj": codec.encode(pg)})
        with self._mlock:
            self.podgroups[pg.key] = pg

    def record_event(self, obj_key: str, reason: str,
                     message: str) -> None:
        self.events.append((obj_key, reason, message))
        try:
            self._request("POST", "/record_event", {
                "obj_key": obj_key, "reason": reason, "message": message})
        except Exception:  # noqa: BLE001 — events are best-effort
            log.debug("record_event failed", exc_info=True)

    # -- command bus ---------------------------------------------------

    def add_command(self, target_key: str, action: str) -> None:
        self._request("POST", "/command",
                      {"target": target_key, "action": action})

    def drain_commands(self, target_key: str):
        resp = self._request("POST", "/drain_commands",
                             {"target": target_key})
        with self._mlock:
            self.commands = [c for c in self.commands
                             if c.get("target") != target_key]
        return resp["commands"]

    # -- test / simulation surface -------------------------------------

    def tick(self) -> None:
        self._request("POST", "/tick")

    def complete_pod(self, key: str, succeeded: bool = True,
                     exit_code=None) -> None:
        self._request("POST", "/complete_pod", {
            "key": key, "succeeded": succeeded, "exit_code": exit_code})

    # -- leader election -----------------------------------------------

    def lease(self, name: str, holder: str, ttl: float = 15.0,
              release: bool = False) -> dict:
        return self._request("POST", "/lease", {
            "name": name, "holder": holder, "ttl": ttl,
            "release": release})
