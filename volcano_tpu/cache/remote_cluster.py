"""RemoteCluster: the wire-backed Cluster implementation.

The client half of the process split (server half:
volcano_tpu/server/state_server.py).  Mirrors the reference scheduler's
informer architecture (pkg/scheduler/cache/cache.go:109,
event_handlers.go): a local object mirror is bootstrapped by one full
LIST (/snapshot) and then kept current by a background WATCH long-poll
thread; reads (list_all, store attributes) are served from the mirror
with zero RPCs, and every write goes to the server AND is echoed into
the mirror immediately so a process observes its own writes without
waiting for the watch round-trip (the reference's assume-cache
discipline, cache.go:1342 AddBindTask).

Stdlib-only: urllib over HTTP/JSON with the api/codec.py codec.
"""

from __future__ import annotations

import json
import logging
import random
import threading
import time
import urllib.error
import urllib.request
import uuid
from http.client import HTTPException
from typing import Callable, List, Optional

from volcano_tpu.api import codec
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.cache.cluster import Cluster, ClusterSnapshot
from volcano_tpu.cache.kinds import KINDS, key_for

log = logging.getLogger(__name__)

# ONE retry policy for every wire call (capped exponential backoff +
# FULL jitter + an overall deadline) instead of each caller hand-
# rolling its own: transient failures — connection refused/reset, a
# truncated response, a 5xx from a restarting server — are retried
# until the deadline; 4xx verdicts (auth, admission, conflict,
# missing) fail fast, every retry would get the same answer.  A 503
# carrying Retry-After (the server's read-only degrade) is HONOURED:
# the sleep is at least the server's ask, plus jitter — so a fleet of
# mirrors waits out a full-disk episode instead of hammering it in
# lockstep.
RETRY_BASE_S = 0.05
RETRY_CAP_S = 2.0
RETRY_DEADLINE_S = 30.0
# per-attempt budget the WATCH LOOP hands resync(): the loop's own
# exponential backoff owns the pacing between attempts — an unbounded
# resync would reset the deadline budget every iteration and turn a
# sick server's recovery into a retry storm
WATCH_RESYNC_BUDGET_S = 3.0

# POST routes a client fence never stamps: lease CAS and fence
# advances carry their terms explicitly, and traces are never fenced
_UNFENCED_POSTS = frozenset({"/lease", "/fence", "/trace"})


def _retry_sleep(delay: float, e: Exception, remain: float) -> float:
    """One backoff sleep under the shared policy: full jitter over the
    current delay, floored at the server's Retry-After when it sent
    one, capped by the remaining deadline."""
    retry_after = float(getattr(e, "retry_after", 0.0) or 0.0)
    return min(max(remain, 0.0),
               retry_after + random.uniform(0, delay))


def _transient(e: Exception) -> bool:
    """Worth retrying?  Connection failures (URLError IS an OSError),
    truncated/garbled responses (HTTPException), and server-side 5xx
    (a restarting or overloaded server).  4xx — including 401/403
    auth and 409/422 verdicts, already mapped to their own exception
    types — would fail identically forever."""
    return isinstance(e, (OSError, HTTPException)) or \
        (isinstance(e, RemoteError) and e.code >= 500)


class StaleReplicaError(RuntimeError):
    """A same-lineage full re-list came back OLDER than the mirror
    (lagging read replica): the rewind was refused and the sticky
    read endpoint rotated.  resync() swallows this (the mirror just
    stays put for a beat); it exists as a type so the refusal is
    never mistaken for a wire failure."""


class RemoteError(RuntimeError):
    def __init__(self, code: int, message: str,
                 retry_after: float = 0.0, leader: str = ""):
        super().__init__(message)
        self.code = code
        # parsed from the Retry-After header (seconds); 0 = none.
        # The read-only degrade's 503s carry it so clients pace their
        # retries to the server's heal cadence.
        self.retry_after = retry_after
        # a follower's 503 carries the current leader's URL: the
        # retry re-routes the write instead of hammering the replica
        self.leader = leader


class RemoteCluster(Cluster):
    def __init__(self, base_url: str, start_watch: bool = True,
                 timeout: float = 10.0, token: str = "",
                 ca_cert: str = "", insecure: bool = False,
                 tolerate_unreachable: bool = False,
                 retry_deadline: float = RETRY_DEADLINE_S):
        """tolerate_unreachable: a dead server at construction time
        leaves the mirror empty instead of raising — the watch loop's
        resync-on-reconnect self-heals once the server returns (the
        hub's member-cluster clients must survive a member outage).
        retry_deadline: overall per-call budget for the shared
        transient-retry policy (backoff + jitter).

        base_url may name a replica GROUP — a comma-separated URL
        list (or a list/tuple).  Writes route to the leader (tracked
        via the follower 503s' leader hints + /replication
        discovery, re-routing in-flight retries across a failover);
        reads stick to ONE randomly-chosen replica — sticky, so the
        watch revision stays on one rv timeline — rotating to the
        next replica on failure.  A fleet of mirrors thereby spreads
        its read load across the followers while every write still
        funnels through the single elected writer."""
        if isinstance(base_url, str):
            endpoints = [u for u in base_url.split(",") if u.strip()]
        else:
            endpoints = list(base_url)
        self.endpoints = [u.strip().rstrip("/") for u in endpoints]
        self.base_url = self.endpoints[0]      # current WRITE target
        # sticky read replica (random: a fleet self-spreads); single-
        # endpoint configs keep the exact legacy behavior
        self._read_idx = random.randrange(len(self.endpoints)) \
            if len(self.endpoints) > 1 else 0
        self.timeout = timeout
        self.token = token
        self._retry_deadline = retry_deadline
        # optional fencing token (set_fence): every mutating request
        # carries it so the server refuses this client once a newer
        # tenancy (higher term) has written — the deposed-router guard
        self._fence: Optional[tuple] = None   # (name, term)
        from volcano_tpu.server.tlsutil import client_ssl_context
        self._ssl_ctx = client_ssl_context(ca_cert, insecure)
        self._mlock = threading.RLock()        # mirror + watchers
        self._watchers: List[Callable[[str, object], None]] = []
        self._rv = 0
        self._epoch = ""
        self._stop = threading.Event()
        # mirror stores, same attribute names as FakeCluster
        for spec in KINDS.values():
            setattr(self, spec.attr, {})
        self.commands: List[dict] = []
        self.events: List[tuple] = []          # local record only
        try:
            if tolerate_unreachable:
                # a dead member must not stall the hub's boot for the
                # whole retry budget: one attempt, the watch loop's
                # backoff owns the healing from here
                self.resync(_deadline=0.0)
            else:
                self.resync()
        except Exception as e:  # noqa: BLE001 — classified below
            # Tolerable: anything the watch loop could heal once the
            # server is back (the shared _transient classification).
            # NOT tolerable: 4xx auth/config errors — every retry
            # would 401 forever, so fail fast even in tolerant mode.
            if not tolerate_unreachable or not _transient(e):
                raise
            log.warning("state server %s unreachable at startup (%s); "
                        "mirror starts empty and the watch loop will "
                        "resync when it returns", self.base_url, e)
        self._watch_thread = None
        if start_watch:
            self._watch_thread = threading.Thread(
                target=self._watch_loop, name="cluster-watch", daemon=True)
            self._watch_thread.start()

    # -- HTTP ----------------------------------------------------------

    def _request(self, method: str, path: str, payload=None,
                 timeout: Optional[float] = None,
                 deadline: Optional[float] = None, retries: bool = True,
                 idempotency_key: bool = False):
        """One wire call under the unified retry policy.

        idempotency_key stamps the payload with a per-REQUEST id
        (stable across this call's retries): the server records the
        response it committed for that id, so a retry after a crash-
        between-commit-and-ack replays the verdict instead of double-
        applying (e.g. a re-created vcjob minting a second uid, a
        duplicated Command, a drain losing its commands).  Mutations
        without a key are replay-safe by state-compare (re-bind to the
        same node, overwrite-put, repeated evict/delete)."""
        if idempotency_key and payload is not None:
            # stable across this call's retries AND across a leader
            # failover re-route: the new leader replayed the shipped
            # _req records, so a retried write that already committed
            # gets its recorded verdict, never a double-apply
            payload = dict(payload, _req_id=uuid.uuid4().hex)
        if self._fence is not None and method == "POST" and \
                isinstance(payload, dict) and \
                path.partition("?")[0] not in _UNFENCED_POSTS:
            payload = dict(payload, _fence={
                "name": self._fence[0], "term": self._fence[1]})
        budget = self._retry_deadline if deadline is None else deadline
        t_end = time.monotonic() + budget
        delay = RETRY_BASE_S
        is_read = method == "GET"
        while True:
            base = self.endpoints[self._read_idx] if is_read \
                else self.base_url
            try:
                return self._request_once(method, path, payload,
                                          timeout, base=base)
            except Exception as e:  # noqa: BLE001 — classified
                remain = t_end - time.monotonic()
                if not retries or not _transient(e) or remain <= 0 \
                        or self._stop.is_set():
                    # budget spent: surface the failure NOW — leader
                    # discovery probes would overshoot the caller's
                    # deadline by seconds
                    raise
                if len(self.endpoints) > 1:
                    self._reroute(is_read, e)
                from volcano_tpu import metrics
                metrics.inc("client_retries_total",
                            route=path.partition("?")[0])
                log.debug("wire %s %s failed (%s); retrying",
                          method, path, e)
                time.sleep(_retry_sleep(delay, e, remain))
                delay = min(delay * 2, RETRY_CAP_S)

    def _reroute(self, is_read: bool, e: Exception) -> None:
        """Failover routing on a transient error in a replica group:
        reads rotate to the next sticky replica; writes follow the
        follower 503's leader hint when one came, else re-discover
        the leader via GET /replication across the group."""
        if is_read:
            self._read_idx = (self._read_idx + 1) % len(self.endpoints)
            return
        hint = getattr(e, "leader", "")
        if hint and hint.rstrip("/") != self.base_url:
            self.base_url = hint.rstrip("/")
            log.debug("write path re-routed to hinted leader %s",
                      self.base_url)
            return
        self._discover_leader()

    def _discover_leader(self) -> None:
        best, best_term = "", -1
        for url in self.endpoints:
            try:
                doc = self._request_once("GET", "/replication",
                                         timeout=2.0, base=url)
            except Exception:  # noqa: BLE001 — candidate down
                # vtplint: disable=except-pass (leader-discovery scan: a dark endpoint cannot be the leader; the loop keeps probing)
                continue
            term = int(doc.get("term", 0) or 0)
            if doc.get("role") == "leader" and term > best_term:
                best, best_term = url, term
            elif doc.get("leader") and term > best_term:
                best, best_term = doc["leader"].rstrip("/"), term
        if best and best != self.base_url:
            self.base_url = best
            log.info("write path re-routed to discovered leader %s",
                     best)

    def _request_once(self, method: str, path: str, payload=None,
                      timeout: Optional[float] = None,
                      base: Optional[str] = None):
        data = None
        if payload is not None:
            data = json.dumps(payload, separators=(",", ":")).encode()
        headers = {"Content-Type": "application/json",
                   # big GET bodies (snapshot/watch/delta) come back
                   # gzip'd; the server leaves small ones plain
                   "Accept-Encoding": "gzip"}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        req = urllib.request.Request(
            (base or self.base_url) + path, data=data, method=method,
            headers=headers)
        try:
            with urllib.request.urlopen(
                    req, timeout=timeout or self.timeout,
                    context=self._ssl_ctx) as resp:
                from volcano_tpu.server.httputil import read_json_body
                return read_json_body(resp)
        except urllib.error.HTTPError as e:
            leader = ""
            try:
                doc = json.loads(e.read())
                msg = doc.get("error", str(e))
                leader = doc.get("leader") or ""
            except Exception:  # noqa: BLE001
                msg = str(e)
            if e.code == 422:
                from volcano_tpu.webhooks.admission import AdmissionError
                raise AdmissionError(msg) from None
            if e.code == 409:
                raise ValueError(msg) from None
            if e.code == 404:
                raise KeyError(msg) from None
            try:
                retry_after = float(e.headers.get("Retry-After") or 0.0)
            except (TypeError, ValueError):
                retry_after = 0.0
            raise RemoteError(e.code, msg, retry_after=retry_after,
                              leader=leader) from None

    # -- mirror maintenance --------------------------------------------

    @staticmethod
    def _epoch_base(epoch: str) -> str:
        """Durable servers stamp "BASE.BOOT" epochs: the BASE survives
        restarts as long as the rv history is WAL-continuous, the BOOT
        half bumps each boot.  Legacy/non-durable epochs are opaque
        uuids (base == whole epoch), so any restart changes the
        base."""
        return epoch.rsplit(".", 1)[0]

    def resync(self, _deadline: Optional[float] = None) -> None:
        """Reconcile the mirror with the server, delta-first.

        A mirror that already holds a revision asks the watch endpoint
        (timeout=0: no long-poll, same payload shape, works against
        any server vintage) for the events since it: O(churn) work
        and bytes, not O(cluster) — at a few thousand hosts the full
        snapshot is megabytes while a churn window is a handful of
        events.  The delta is also taken ACROSS a server restart when
        the epoch BASE matches (a durable server replayed its WAL: the
        rv space is continuous and nothing any mirror ever saw was
        lost, since the server only releases fsync'd events) — that is
        the O(churn) recovery path after a kill -9.  Falls back to the
        full LIST when the mirror is empty (bootstrap), the revision
        fell off the server's compaction horizon (resync verdict), the
        server is a different incarnation lineage (epoch BASE change:
        its rv space is unrelated), the server's rv is BEHIND the
        mirror's (a restart that lost unacked tail events the snapshot
        briefly exposed), or the delta request itself fails."""
        # _epoch marks "bootstrapped at least once" — rv 0 is a valid
        # revision (a mirror synced before the first event), so gate on
        # the epoch, not the revision
        if self._epoch:
            try:
                payload = self._request(
                    "GET", f"/watch?since={self._rv}&timeout=0",
                    deadline=_deadline)
            except Exception as e:  # noqa: BLE001 — fall back to LIST
                log.debug("delta resync failed (%s); full re-list", e)
                payload = None
            epoch = payload.get("epoch", "") if payload else ""
            if payload is not None and not payload.get("resync") \
                    and payload["rv"] >= self._rv \
                    and (epoch == self._epoch or
                         (epoch and self._epoch_base(epoch) ==
                          self._epoch_base(self._epoch))):
                from volcano_tpu import metrics
                metrics.inc("mirror_resync_total", mode="delta")
                # fold like a watch batch (copy-on-write swap) and
                # NOTIFY: these are real missed events, and watchers
                # (controllers) level-trigger off them exactly as if
                # the watch stream had delivered them
                for kind, obj in self._apply_batch(payload["events"]):
                    self._notify(kind, obj)
                with self._mlock:
                    self._rv = max(self._rv, payload["rv"])
                    self._epoch = epoch or self._epoch
                return
        try:
            self._full_resync(_deadline=_deadline)
        except StaleReplicaError as e:
            # the sticky replica lags the mirror: keep the mirror as
            # is (it is AHEAD — nothing stale about it), let the
            # rotated endpoint or the replica's catch-up win the next
            # round.  Swallowed here so bare resync() callers (tools,
            # tests) never crash on a routine failover transient.
            log.debug("full resync skipped: %s", e)
            time.sleep(0.2)

    def _full_resync(self, _deadline: Optional[float] = None) -> None:
        """Full LIST: replace the mirror (bootstrap + ring fall-off +
        server restart).  A snapshot from the SAME history lineage
        (epoch BASE) that is OLDER than the mirror is refused — with
        sticky reads rotating across replicas on failure, a re-list
        could otherwise land on a lagging follower and REWIND the
        mirror (deleted objects resurrected, phases rolled back);
        refusing makes the caller back off and retry, by which time
        the rotation found a caught-up replica or this one caught
        up.  A different BASE really is a new history: accepted."""
        from volcano_tpu import metrics
        payload = self._request("GET", "/snapshot", deadline=_deadline)
        epoch = payload.get("epoch", "")
        if self._epoch and epoch and \
                self._epoch_base(epoch) == self._epoch_base(self._epoch) \
                and payload["rv"] < self._rv:
            metrics.inc("mirror_resync_total", mode="stale-refused")
            if len(self.endpoints) > 1:
                self._read_idx = (self._read_idx + 1) % \
                    len(self.endpoints)
            raise StaleReplicaError(
                f"replica snapshot rv {payload['rv']} is behind "
                f"the mirror's rv {self._rv} (lagging replica); "
                "refusing the rewind")
        metrics.inc("mirror_resync_total", mode="full")
        with self._mlock:
            self._rv = payload["rv"]
            self._epoch = payload.get("epoch", "")
            stores = payload["stores"]
            for kind, spec in KINDS.items():
                # whole-store swap, never in-place clear: readers on
                # other threads keep iterating their consistent copy
                setattr(self, spec.attr, {
                    k: codec.decode(enc)
                    for k, enc in stores.get(kind, {}).items()})
            self.commands = codec.decode(stores.get("_commands", [])) or []

    def _apply_batch(self, events) -> list:
        """Fold a watch batch into the mirror copy-on-write: each
        affected store is rebuilt as a fresh dict and swapped in, so a
        controller iterating `cluster.pods` on another thread never
        sees a dict mutate under it.  Returns (kind, obj) pairs for
        watcher notification."""
        decoded = []
        for ev in events:
            try:
                decoded.append((ev["kind"], codec.decode(ev["obj"])))
            except Exception:  # noqa: BLE001
                log.exception("watch event %s undecodable", ev["kind"])
        updated: dict = {}          # attr -> new dict
        new_commands = None
        notifications = []
        with self._mlock:           # copies + swap atomic vs local echo
            for kind, obj in decoded:
                deleted = kind.endswith("_deleted")
                base = kind[:-len("_deleted")] if deleted else kind
                spec = KINDS.get(base)
                if spec is not None:
                    key = obj["key"] if spec.key_of is None \
                        else spec.key_of(obj)
                    store = updated.get(spec.attr)
                    if store is None:
                        store = dict(getattr(self, spec.attr))
                        updated[spec.attr] = store
                    if deleted:
                        store.pop(key, None)
                    else:
                        store[key] = obj if spec.key_of else obj["obj"]
                elif base == "command":
                    if new_commands is None:
                        new_commands = list(self.commands)
                    new_commands.append(obj)
                notifications.append((kind, obj))
            for attr, store in updated.items():
                setattr(self, attr, store)
            if new_commands is not None:
                self.commands = new_commands
        return notifications

    def _watch_loop(self) -> None:
        delay = 0.2
        while not self._stop.is_set():
            try:
                # the loop IS the retry policy here (retries=False):
                # its backoff must keep ticking between long-polls,
                # not nest another backoff inside each one
                payload = self._request(
                    "GET", f"/watch?since={self._rv}&timeout=25",
                    timeout=60.0, retries=False)
            except Exception as e:  # noqa: BLE001 — classified
                if not _transient(e):
                    # same transient-vs-fatal split the startup path
                    # applies: a 4xx (revoked token, bad config) would
                    # 401 on every long-poll forever — stop loudly
                    # instead of burning a retry loop in the dark
                    log.error("watch stream got a non-transient error "
                              "(%s); stopping the watch loop — the "
                              "mirror will go stale until "
                              "reconfigured", e)
                    return
                # FULL jitter, floored at any Retry-After the server
                # sent: a read-only (healing) server told every
                # mirror when to come back — spreading the retries
                # stops the whole fleet reconnecting in lockstep
                if self._stop.wait(_retry_sleep(delay, e,
                                                float("inf"))):
                    return
                delay = min(delay * 2, 5.0)
                continue
            epoch = payload.get("epoch", "")
            if payload.get("resync") or payload["rv"] < self._rv or \
                    (self._epoch and epoch and epoch != self._epoch):
                # ring fall-off, rv regression, or a NEW server
                # incarnation (epoch change — catches a restarted
                # server whose counter already passed ours).  resync()
                # recovers the stream: O(churn) delta when the epoch
                # BASE matches (durable restart), full re-list
                # otherwise.  The attempt budget is BOUNDED: an
                # unbounded resync would re-arm its own 30s retry
                # storm every loop iteration, so the loop's backoff —
                # not resync's — owns the pacing between attempts.
                try:
                    self.resync(_deadline=WATCH_RESYNC_BUDGET_S)
                except Exception as e:  # noqa: BLE001
                    log.debug("watch resync attempt failed (%s); "
                              "backing off", e)
                    if self._stop.wait(_retry_sleep(delay, e,
                                                    float("inf"))):
                        return
                    delay = min(delay * 2, 5.0)
                    continue
                delay = 0.2
                continue
            delay = 0.2
            for kind, obj in self._apply_batch(payload["events"]):
                self._notify(kind, obj)
            self._rv = max(self._rv, payload["rv"])

    def close(self) -> None:
        self._stop.set()

    def _notify(self, kind: str, obj) -> None:
        for w in list(self._watchers):
            try:
                w(kind, obj)
            except Exception:  # noqa: BLE001
                log.exception("watcher failed on %s", kind)

    # -- Cluster interface: reads --------------------------------------

    def list_all(self) -> ClusterSnapshot:
        with self._mlock:
            return ClusterSnapshot(
                pods=list(self.pods.values()),
                nodes=list(self.nodes.values()),
                podgroups=list(self.podgroups.values()),
                queues=list(self.queues.values()),
                hypernodes=list(self.hypernodes.values()),
                priority_classes=list(self.priority_classes.values()),
                vcjobs=list(self.vcjobs.values()),
            )

    def watch(self, fn) -> None:
        self._watchers.append(fn)

    def unwatch(self, fn) -> None:
        try:
            self._watchers.remove(fn)
        except ValueError:
            pass

    # -- Cluster interface: writes (server + local echo) ---------------

    def put_object(self, kind: str, obj, key: Optional[str] = None):
        # keyed: a retried CREATE must not re-run create-side effects
        # (a vcjob minting a fresh uid, admission mutations) after the
        # first attempt committed — the server replays the recorded
        # response instead
        resp = self._request("POST", f"/objects/{kind}",
                             {"obj": codec.encode(obj), "key": key},
                             idempotency_key=True)
        stored = codec.decode(resp["obj"])
        spec = KINDS[kind]
        k = key_for(kind, stored if spec.key_of else obj, key)
        with self._mlock:
            getattr(self, spec.attr)[k] = stored
        self._notify(kind, stored if spec.key_of
                     else {"key": k, "obj": stored})
        return stored

    def delete_object(self, kind: str, key: str) -> None:
        from urllib.parse import quote
        path = f"/objects/{kind}?key={quote(key, safe='')}"
        if self._fence is not None:
            # DELETE has no body: the fence rides as query params
            path += (f"&fence_name={quote(self._fence[0], safe='')}"
                     f"&fence_term={self._fence[1]}")
        self._request("DELETE", path)
        spec = KINDS[kind]
        with self._mlock:
            obj = getattr(self, spec.attr).pop(key, None)
        if obj is not None:
            self._notify(f"{kind}_deleted",
                         obj if spec.key_of else {"key": key, "obj": obj})

    # typed conveniences matching the FakeCluster surface ---------------

    def add_node(self, node):
        return self.put_object("node", node)

    def remove_node(self, name: str):
        self.delete_object("node", name)

    def add_pod(self, pod) -> None:
        self.put_object("pod", pod)

    def delete_pod(self, key: str) -> None:
        self.delete_object("pod", key)

    def add_podgroup(self, pg) -> None:
        self.put_object("podgroup", pg)

    def delete_podgroup(self, key: str) -> None:
        self.delete_object("podgroup", key)

    def add_queue(self, queue):
        return self.put_object("queue", queue)

    def add_hypernode(self, hn) -> None:
        self.put_object("hypernode", hn)

    def delete_hypernode(self, name: str) -> None:
        self.delete_object("hypernode", name)

    def add_numatopology(self, topo) -> None:
        self.put_object("numatopology", topo)

    def add_priority_class(self, pc) -> None:
        self.put_object("priority_class", pc)

    def add_vcjob(self, job):
        return self.put_object("vcjob", job)

    def update_vcjob(self, job) -> None:
        # explicit key marks this as an UPDATE: the server must not
        # re-run create admission on a status flush (e.g. a job whose
        # queue has closed since creation would 422 forever)
        self.put_object("vcjob", job, key=job.key)

    def delete_vcjob(self, key: str) -> None:
        self.delete_object("vcjob", key)

    # -- scheduler write path ------------------------------------------

    def bind_pod(self, namespace: str, name: str, node_name: str,
                 ts_alloc: Optional[float] = None) -> None:
        body = {"namespace": namespace, "name": name,
                "node_name": node_name}
        if ts_alloc is not None:
            # decision stamp for the `allocated` lifecycle phase;
            # servers that predate it ignore unknown body fields
            body["ts_alloc"] = ts_alloc
        # vtplint: disable=req-id (replay-safe by state-compare: a re-bind to the same node re-verdicts as success, never double-applies)
        self._request("POST", "/bind", body)
        with self._mlock:
            pod = self.pods.get(f"{namespace}/{name}")
            if pod is not None:
                pod.node_name = node_name
                pod.phase = TaskStatus.BOUND

    def bind_pods(self, binds) -> List[Optional[str]]:
        """A gang's binds as ONE /bind_batch request instead of N bind
        POSTs — the client half of the wire fast lane.  Per-item error
        strings mirror the per-pod path (Cluster.bind_pods contract);
        successes are echoed into the mirror under one lock.  A server
        that predates the route (rolling upgrade: 404s the path) or a
        transport failure falls back to the per-pod loop — bind_pod
        re-sent for an already-applied bind is idempotent (same-node
        rebind is accepted), so the fallback never double-faults."""
        binds = [tuple(b) + (None,) * (4 - len(b)) for b in binds]
        if not binds:
            return []
        try:
            # keyed: a batch whose ack died with the old leader must
            # replay its recorded per-item verdicts on the promoted
            # one (exactly-once commit across a failover), not re-run
            # the capacity checks against a half-applied world
            resp = self._request("POST", "/bind_batch", {"binds": [
                dict({"namespace": ns, "name": n, "node_name": node},
                     **({"ts_alloc": ts} if ts is not None else {}))
                for ns, n, node, ts in binds]}, idempotency_key=True)
            results = resp["results"]
            if len(results) != len(binds):
                raise RemoteError(500, "bind_batch result count "
                                  f"{len(results)} != {len(binds)}")
        except Exception as e:  # noqa: BLE001 — whole-batch failure
            log.warning("bind_batch unavailable (%s); falling back to "
                        "per-pod binds", e)
            return super().bind_pods(binds)
        errors: List[Optional[str]] = []
        with self._mlock:
            for (ns, n, node, _ts), r in zip(binds, results):
                if r.get("ok"):
                    pod = self.pods.get(f"{ns}/{n}")
                    if pod is not None:
                        pod.node_name = node
                        pod.phase = TaskStatus.BOUND
                    errors.append(None)
                else:
                    errors.append(r.get("error", "bind failed"))
        return errors

    def evict_pod(self, namespace: str, name: str, reason: str = "") -> None:
        # vtplint: disable=req-id (replay-safe by state-compare: re-evicting a Releasing/gone pod converges)
        self._request("POST", "/evict", {
            "namespace": namespace, "name": name, "reason": reason})
        with self._mlock:
            pod = self.pods.get(f"{namespace}/{name}")
            if pod is not None:
                pod.phase = TaskStatus.RELEASING
                pod.status_message = reason

    def nominate_pod(self, namespace: str, name: str,
                     node_name: str) -> None:
        # vtplint: disable=req-id (replay-safe overwrite: nominating the same node twice is the same state)
        self._request("POST", "/nominate", {
            "namespace": namespace, "name": name, "node_name": node_name})
        with self._mlock:
            pod = self.pods.get(f"{namespace}/{name}")
            if pod is not None:
                pod.nominated_node = node_name

    def update_podgroup_status(self, pg) -> None:
        # vtplint: disable=req-id (replay-safe overwrite-put of the full status object)
        self._request("POST", "/podgroup_status",
                      {"obj": codec.encode(pg)})
        with self._mlock:
            self.podgroups[pg.key] = pg

    def record_event(self, obj_key: str, reason: str,
                     message: str) -> None:
        self.events.append((obj_key, reason, message))
        try:
            # best-effort AND often on failure paths: a short budget,
            # never the full retry deadline
            # vtplint: disable=req-id (best-effort observability append; a rare duplicate event line is harmless)
            self._request("POST", "/record_event", {
                "obj_key": obj_key, "reason": reason,
                "message": message}, deadline=2.0)
        except Exception:  # noqa: BLE001 — events are best-effort
            log.debug("record_event failed", exc_info=True)

    # -- command bus ---------------------------------------------------

    def add_command(self, target_key: str, action: str) -> None:
        # keyed: a retried Command would otherwise double-queue (two
        # RestartJobs = two restarts)
        self._request("POST", "/command",
                      {"target": target_key, "action": action},
                      idempotency_key=True)

    def drain_commands(self, target_key: str):
        # keyed: a retried drain whose first attempt committed would
        # find an empty bus and LOSE the commands — the replayed
        # response carries what the first attempt drained
        resp = self._request("POST", "/drain_commands",
                             {"target": target_key},
                             idempotency_key=True)
        with self._mlock:
            self.commands = [c for c in self.commands
                             if c.get("target") != target_key]
        return resp["commands"]

    # -- test / simulation surface -------------------------------------

    def tick(self) -> None:
        # vtplint: disable=req-id (test/simulation surface: a duplicate kubelet tick only advances the simulated clock)
        self._request("POST", "/tick")

    def complete_pod(self, key: str, succeeded: bool = True,
                     exit_code=None) -> None:
        # vtplint: disable=req-id (replay-safe by state-compare: completing a completed pod is a no-op)
        self._request("POST", "/complete_pod", {
            "key": key, "succeeded": succeeded, "exit_code": exit_code})

    # -- leader election -----------------------------------------------

    def lease(self, name: str, holder: str, ttl: float = 15.0,
              release: bool = False,
              deadline: Optional[float] = None) -> dict:
        """deadline bounds the retry budget: a renewal must fail
        before the caller's next renewal slot, not block past the
        lease TTL and forfeit leadership to a slow wire."""
        # vtplint: disable=req-id (lease CAS is idempotent for the same holder; a replayed acquire/renew returns the same verdict)
        return self._request("POST", "/lease", {
            "name": name, "holder": holder, "ttl": ttl,
            "release": release}, deadline=deadline)

    def leases(self) -> dict:
        """{name: {holder, expires_in, term}} — the election surface
        `vtpctl routers` and the chaos conductor render."""
        return self._request("GET", "/leases")

    # -- fencing tokens ------------------------------------------------

    def set_fence(self, name: str, term: int) -> None:
        """Stamp every subsequent mutation with (name, term): once a
        newer term has written to the server, this client's writes are
        atomically refused (409) — the deposed-holder guard.  name=""
        clears the fence."""
        self._fence = (name, int(term)) if name else None

    def advance_fence(self, name: str, term: int,
                      deadline: Optional[float] = None) -> dict:
        """Raise the server's fence floor explicitly (a promoted
        holder calls this on every plane BEFORE its first write, so
        the predecessor's in-flight writes are already refusable)."""
        # vtplint: disable=req-id (fence advance is monotonic max(): any replay converges)
        return self._request("POST", "/fence", {
            "name": name, "term": int(term)}, deadline=deadline)

    def fences(self) -> dict:
        """{name: {term, refused}} — fence floors + refusal counts."""
        return self._request("GET", "/fences")
