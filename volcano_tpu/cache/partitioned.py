"""PartitionedCluster: keyspace-partitioned write plane over N
quorum-replicated leader groups.

PR 9 gave the state server quorum replication: one elected leader per
replica group absorbs every write.  At fleet scale that single leader
group is the write bottleneck — every node heartbeat, pod phase flush
and gang bind funnels through one process.  This module splits the
keyspace across N independent leader groups, each running PR 9's
machinery UNCHANGED:

  * nodes — and the pods bound to them — are partitioned by the same
    deterministic topology-subtree plan the scheduler shards use
    (volcano_tpu/shardmap.py), so a gang's bind batch lands on the
    leader group that owns its subtree;
  * group 0 is additionally the META group: queues, podgroups,
    hypernodes, priority classes, vcjobs, commands, leases, and every
    PENDING (nodeless) pod live there;
  * a bind RELOCATES the pod from the meta group to the node's group:
    the /bind_batch item carries the encoded pod, the owning server
    admits-then-binds it atomically under its bind mutex (so its chip
    accounting sees node and occupant together), and the client then
    deletes the pending copy from the meta group.  A crash between
    those two steps leaves a benign duplicate whose meta copy is
    Pending and nodeless; the bound copy (merged LAST, see
    __getattr__) wins every read, and the next bind retry's
    state-compare deletes the leftover.

Capacity arbitration is therefore PER GROUP and exactly as strong as
before: two scheduler shards racing for chips on one node are racing
on ONE leader group's atomic check-and-bind, whichever shard's batch
arrives second collects the per-item 409.

Reads merge the N mirrors (meta first, node groups override), so the
scheduler cache, controllers and tools see one cluster.  The merge
builds fresh dicts per access — the partitioned plane trades read-
view construction cost for N-way write throughput, which is the
right trade for the write-bound fleets it exists for.

Endpoint syntax (CLI --cluster-url): semicolon-separated groups, each
a comma-separated replica list routed by RemoteCluster's own
leader-follower logic:

    http://a1,http://a2,http://a3;http://b1,http://b2;http://c1
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

from volcano_tpu import shardmap
from volcano_tpu.api import codec
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.cache.cluster import Cluster, ClusterSnapshot
from volcano_tpu.cache.kinds import KINDS
from volcano_tpu.cache.remote_cluster import RemoteCluster, RemoteError

log = logging.getLogger(__name__)

# kinds that always live in the meta group regardless of content
_META_GROUP = 0


def split_endpoint_groups(spec: str) -> List[str]:
    """'a1,a2;b1;c1,c2' -> ['a1,a2', 'b1', 'c1,c2'] (whitespace ok)."""
    return [part.strip() for part in spec.split(";") if part.strip()]


class PartitionedCluster(Cluster):
    def __init__(self, groups, start_watch: bool = True,
                 timeout: float = 10.0, token: str = "",
                 ca_cert: str = "", insecure: bool = False,
                 tolerate_unreachable: bool = False):
        """groups: endpoint spec string ('g0;g1;g2', each group a
        comma-separated replica list), or a prebuilt list of
        RemoteCluster instances (tests)."""
        if isinstance(groups, str):
            groups = split_endpoint_groups(groups)
        built: List[RemoteCluster] = []
        for g in groups:
            if isinstance(g, RemoteCluster):
                built.append(g)
            else:
                built.append(RemoteCluster(
                    g, start_watch=start_watch, timeout=timeout,
                    token=token, ca_cert=ca_cert, insecure=insecure,
                    tolerate_unreachable=tolerate_unreachable))
        if not built:
            raise ValueError("PartitionedCluster needs >= 1 group")
        self.groups = built
        self._route_lock = threading.Lock()

    # -- routing -------------------------------------------------------

    def _group_of_node(self, node_name: str) -> int:
        """The group whose mirror holds the node (authoritative: the
        object IS where it lives), else the deterministic plan's owner
        for a node we are about to create."""
        for i, g in enumerate(self.groups):
            if node_name in g.nodes:
                return i
        subtrees: Dict[str, str] = {}
        for g in self.groups:
            for n in g.nodes.values():
                subtrees[n.name] = shardmap.subtree_of(
                    getattr(n, "labels", None))
        if node_name not in subtrees:
            return _META_GROUP
        return shardmap.owner_index(
            subtrees, len(self.groups)).get(node_name, _META_GROUP)

    def _route_new_node(self, node) -> int:
        """Owner group for a node being created: recompute the plan
        over the union of every mirror's nodes plus this one, so all
        writers agree without a coordination round."""
        subtrees: Dict[str, str] = {}
        for g in self.groups:
            for n in g.nodes.values():
                subtrees[n.name] = shardmap.subtree_of(
                    getattr(n, "labels", None))
        subtrees[node.name] = shardmap.subtree_of(
            getattr(node, "labels", None))
        return shardmap.owner_index(
            subtrees, len(self.groups)).get(node.name, _META_GROUP)

    def _group_of_pod(self, key: str) -> Optional[int]:
        # node groups first: during a relocation overlap the BOUND
        # copy, not the stale pending one, must answer routing
        for i in range(len(self.groups) - 1, -1, -1):
            if key in self.groups[i].pods:
                return i
        return None

    def _group_of_key(self, kind: str, key: str) -> int:
        attr = KINDS[kind].attr
        for i in range(len(self.groups) - 1, -1, -1):
            if key in getattr(self.groups[i], attr):
                return i
        return _META_GROUP

    @property
    def meta(self) -> RemoteCluster:
        return self.groups[_META_GROUP]

    def _request(self, method: str, path: str, payload=None, **kw):
        """Observability traffic (trace.publish duck-types on
        `_request`) rides the meta group; keyspace-routed writes never
        come through here — bind_pods targets each group directly."""
        return self.meta._request(method, path, payload, **kw)

    def shard_layout(self) -> List[dict]:
        """Ownership table for tools: one row per group with its node
        count and subtree count (vtpctl shards)."""
        rows = []
        for i, g in enumerate(self.groups):
            subtrees = {shardmap.subtree_of(getattr(n, "labels", None))
                        for n in g.nodes.values()}
            rows.append({"group": i, "endpoints": g.endpoints,
                         "nodes": len(g.nodes),
                         "subtrees": len(subtrees),
                         "meta": i == _META_GROUP})
        return rows

    # -- merged read surface -------------------------------------------

    def __getattr__(self, name: str):
        # merged store views (pods, nodes, podgroups, ...): meta group
        # first so a node group's copy of a relocating pod overrides
        # the meta leftover.  __getattr__ only fires when the instance
        # lacks the attribute, so real attributes stay cheap.
        for spec in KINDS.values():
            if spec.attr == name:
                merged: dict = {}
                for g in self.groups:
                    merged.update(getattr(g, name))
                return merged
        if name == "commands":
            return list(self.meta.commands)
        if name == "events":
            return list(self.meta.events)
        raise AttributeError(name)

    def list_all(self) -> ClusterSnapshot:
        return ClusterSnapshot(
            pods=list(self.pods.values()),
            nodes=list(self.nodes.values()),
            podgroups=list(self.podgroups.values()),
            queues=list(self.queues.values()),
            hypernodes=list(self.hypernodes.values()),
            priority_classes=list(self.priority_classes.values()),
            vcjobs=list(self.vcjobs.values()),
        )

    def watch(self, fn) -> None:
        for g in self.groups:
            g.watch(fn)

    def unwatch(self, fn) -> None:
        for g in self.groups:
            g.unwatch(fn)

    def resync(self) -> None:
        for g in self.groups:
            g.resync()

    def close(self) -> None:
        for g in self.groups:
            g.close()

    # -- generic object store ------------------------------------------

    def put_object(self, kind: str, obj, key: Optional[str] = None):
        if kind == "node":
            gi = self._group_of_node(obj.name) \
                if obj.name in self.nodes else self._route_new_node(obj)
            return self.groups[gi].put_object(kind, obj, key=key)
        if kind == "pod":
            node = getattr(obj, "node_name", None)
            pod_key = key or getattr(obj, "key", None)
            if pod_key is not None:
                held = self._group_of_pod(pod_key)
                if held is not None:
                    # status flushes follow the object, wherever the
                    # bind relocation put it
                    return self.groups[held].put_object(kind, obj,
                                                        key=key)
            gi = self._group_of_node(node) if node else _META_GROUP
            return self.groups[gi].put_object(kind, obj, key=key)
        return self.meta.put_object(kind, obj, key=key)

    def delete_object(self, kind: str, key: str) -> None:
        self.groups[self._group_of_key(kind, key)].delete_object(
            kind, key)

    # -- scheduler write path ------------------------------------------

    def bind_pod(self, namespace: str, name: str, node_name: str,
                 ts_alloc: Optional[float] = None) -> None:
        err = self.bind_pods(
            [(namespace, name, node_name, ts_alloc)])[0]
        if err is not None:
            raise ValueError(err)

    def bind_pods(self, binds) -> List[Optional[str]]:
        """Split the gang's binds by owning leader group — one
        idempotency-keyed /bind_batch per group per cycle — carrying
        the encoded pod on items whose pod lives elsewhere (the
        relocation payload).  Per-item verdicts keep flush_binds'
        bookkeeping identical to the single-group plane."""
        binds = [tuple(b) + (None,) * (4 - len(b)) for b in binds]
        if not binds:
            return []
        errors: List[Optional[str]] = [None] * len(binds)
        by_group: Dict[int, List[int]] = {}
        for pos, (_ns, _name, node, _ts) in enumerate(binds):
            by_group.setdefault(self._group_of_node(node),
                                []).append(pos)
        for gi, positions in sorted(by_group.items()):
            group = self.groups[gi]
            items = []
            relocations: Dict[int, int] = {}     # position -> src group
            for pos in positions:
                ns, name, node, ts = binds[pos]
                pod_key = f"{ns}/{name}"
                item = {"namespace": ns, "name": name,
                        "node_name": node}
                if ts is not None:
                    item["ts_alloc"] = ts
                src = self._group_of_pod(pod_key)
                if src is not None and src != gi:
                    pod = self.groups[src].pods.get(pod_key)
                    if pod is not None:
                        item["pod"] = codec.encode(pod)
                        relocations[pos] = src
                items.append(item)
            try:
                resp = group._request("POST", "/bind_batch",
                                      {"binds": items},
                                      idempotency_key=True)
                results = resp["results"]
                if len(results) != len(items):
                    raise RemoteError(
                        500, f"bind_batch result count {len(results)} "
                             f"!= {len(items)}")
            except Exception as e:  # noqa: BLE001 — per-group failure
                msg = str(e) or type(e).__name__
                for pos in positions:
                    errors[pos] = msg
                continue
            for pos, r in zip(positions, results):
                ns, name, node, _ts = binds[pos]
                pod_key = f"{ns}/{name}"
                if not r.get("ok"):
                    errors[pos] = r.get("error", "bind failed")
                    continue
                # echo into the owning group's mirror (relocated pods
                # aren't there until the watch round-trip otherwise)
                with group._mlock:
                    pod = group.pods.get(pod_key)
                    if pod is None:
                        src = relocations.get(pos)
                        src_pod = self.groups[src].pods.get(pod_key) \
                            if src is not None else None
                        if src_pod is not None:
                            pod = codec.decode(codec.encode(src_pod))
                            group.pods[pod_key] = pod
                    if pod is not None:
                        pod.node_name = node
                        pod.phase = TaskStatus.BOUND
                src = relocations.get(pos)
                if src is not None:
                    # retire the meta-group pending copy; best-effort,
                    # the bound copy already wins every merged read
                    try:
                        self.groups[src].delete_object("pod", pod_key)
                    except Exception:  # noqa: BLE001
                        log.debug("pending-copy cleanup for %s failed",
                                  pod_key, exc_info=True)
        return errors

    def _pod_group(self, namespace: str, name: str) -> RemoteCluster:
        gi = self._group_of_pod(f"{namespace}/{name}")
        return self.groups[gi if gi is not None else _META_GROUP]

    def evict_pod(self, namespace: str, name: str,
                  reason: str = "") -> None:
        self._pod_group(namespace, name).evict_pod(namespace, name,
                                                   reason)

    def nominate_pod(self, namespace: str, name: str,
                     node_name: str) -> None:
        self._pod_group(namespace, name).nominate_pod(namespace, name,
                                                      node_name)

    def update_podgroup_status(self, pg) -> None:
        self.meta.update_podgroup_status(pg)

    def record_event(self, obj_key: str, reason: str,
                     message: str) -> None:
        self.meta.record_event(obj_key, reason, message)

    # -- typed conveniences (FakeCluster surface) ----------------------

    def add_node(self, node):
        return self.put_object("node", node)

    def remove_node(self, name: str):
        self.delete_object("node", name)

    def add_pod(self, pod) -> None:
        self.put_object("pod", pod)

    def delete_pod(self, key: str) -> None:
        self.delete_object("pod", key)

    def add_podgroup(self, pg) -> None:
        self.put_object("podgroup", pg)

    def delete_podgroup(self, key: str) -> None:
        self.delete_object("podgroup", key)

    def add_queue(self, queue):
        return self.put_object("queue", queue)

    def add_hypernode(self, hn) -> None:
        self.put_object("hypernode", hn)

    def delete_hypernode(self, name: str) -> None:
        self.delete_object("hypernode", name)

    def add_numatopology(self, topo) -> None:
        self.put_object("numatopology", topo)

    def add_priority_class(self, pc) -> None:
        self.put_object("priority_class", pc)

    def add_vcjob(self, job):
        return self.put_object("vcjob", job)

    def update_vcjob(self, job) -> None:
        self.put_object("vcjob", job, key=job.key)

    def delete_vcjob(self, key: str) -> None:
        self.delete_object("vcjob", key)

    # -- command bus / lease / simulation ------------------------------

    def add_command(self, target_key: str, action: str) -> None:
        self.meta.add_command(target_key, action)

    def drain_commands(self, target_key: str):
        return self.meta.drain_commands(target_key)

    def lease(self, name: str, holder: str, ttl: float = 15.0,
              release: bool = False,
              deadline: Optional[float] = None) -> dict:
        return self.meta.lease(name, holder, ttl, release=release,
                               deadline=deadline)

    def tick(self) -> None:
        # every group's simulated kubelet advances: bound pods start
        # running on the group that owns their node
        for g in self.groups:
            g.tick()

    def complete_pod(self, key: str, succeeded: bool = True,
                     exit_code=None) -> None:
        gi = self._group_of_pod(key)
        self.groups[gi if gi is not None else _META_GROUP].complete_pod(
            key, succeeded=succeeded, exit_code=exit_code)
