"""Registry of object kinds the cluster store holds.

One table shared by FakeCluster (in-memory store), the state server
(HTTP apiserver analogue) and RemoteCluster (client mirror), so the
three never drift on what kinds exist, which attribute holds them, and
how an object keys itself.  Reference analogue: the CRD scheme
registration in staging/src/volcano.sh/apis (one Group/Version/Kind
table driving clientsets, informers and the apiserver alike).

Dict-kinds (services, config maps, secrets, PVCs, PVs, datasources)
hold plain dicts whose key the writer supplies; typed kinds derive the
key from the object.
"""

from __future__ import annotations

from typing import Callable, Dict, NamedTuple, Optional


class KindSpec(NamedTuple):
    attr: str                                # store attribute on Cluster
    key_of: Optional[Callable[[object], str]]  # None => caller supplies


def _key(obj) -> str:
    return obj.key


def _name(obj) -> str:
    return obj.name


KINDS: Dict[str, KindSpec] = {
    "pod": KindSpec("pods", _key),
    "node": KindSpec("nodes", _name),
    "podgroup": KindSpec("podgroups", _key),
    "queue": KindSpec("queues", _name),
    "hypernode": KindSpec("hypernodes", _name),
    "priority_class": KindSpec("priority_classes", _name),
    "vcjob": KindSpec("vcjobs", _key),
    "jobflow": KindSpec("jobflows", _key),
    "jobtemplate": KindSpec("jobtemplates", _key),
    "cronjob": KindSpec("cronjobs", _key),
    "hyperjob": KindSpec("hyperjobs", _key),
    "nodeshard": KindSpec("nodeshards", _name),
    "numatopology": KindSpec("numatopologies", _name),
    # per-node DCN bandwidth accounting report (api/netusage.py):
    # posted by the node agent, folded into node annotations by the
    # store so scheduler mirrors see saturation without decoding it
    "bandwidthreport": KindSpec("bandwidthreports", _name),
    # per-host chip-health verdict (api/slicehealth.py): posted by the
    # node agent's hysteresis, folded into node annotations by the
    # store; the failover controller declares slice failures from it
    "slicehealthreport": KindSpec("slicehealthreports", _name),
    # per-node workload step-progress report (api/goodput.py): posted
    # by the node agent, folded into PODGROUP annotations by the store
    # so scheduler mirrors learn per-job step rates / goodput from
    # ordinary podgroup events
    "goodputreport": KindSpec("goodputreports", _name),
    # per-node serving traffic report (api/serving.py): posted by the
    # node agent, folded into PODGROUP annotations by the store so the
    # serving autoscaler reads QPS/p99 from ordinary podgroup events
    "servingreport": KindSpec("servingreports", _name),
    # plain-dict kinds (plugin/operator supplied payloads)
    # namespace -> annotations dict (podgroup mutate webhook reads the
    # per-namespace default-queue annotation)
    "namespace": KindSpec("namespaces", None),
    # federation region registry (api/federation.py): region name ->
    # record dict {url, price, locality, heartbeat...}, held by the
    # GLOBAL store and reconciled by the federation router
    "region": KindSpec("regions", None),
    # stitched cross-plane episode trace (federation/stitch.py):
    # episode ID -> the latest stitched span-tree doc, written by the
    # leaseholder router into the GLOBAL store so `GET /fleet_trace`
    # and a promoted standby both read the same durable artifact
    "fleet_trace": KindSpec("fleet_traces", None),
    # router circuit-breaker snapshots (federation/retry.py): region
    # name -> {state, failures, opens, retry_in_s, last_trip_ts},
    # written on trip/close so a promoted standby adopts learned
    # region health instead of re-probing from closed
    "router_breaker": KindSpec("router_breakers", None),
    # fleet SLO snapshot (federation/slo.py): "global" -> burn-rate /
    # attainment doc the router recomputes each pass (vtpctl slo)
    "slo": KindSpec("slos", None),
    "service": KindSpec("services", None),
    "config_map": KindSpec("config_maps", None),
    "secret": KindSpec("secrets", None),
    "pvc": KindSpec("pvcs", None),
    "pv": KindSpec("pvs", None),
    "datasource": KindSpec("datasources", None),
}


def key_for(kind: str, obj, key: Optional[str] = None) -> str:
    spec = KINDS[kind]
    if key is not None:
        return key
    if spec.key_of is None:
        raise ValueError(f"kind {kind!r} needs an explicit key")
    return spec.key_of(obj)
