"""In-process fake cluster — the apiserver + kubelet stand-in.

Plays the role the reference fills with Kind+KWOK fake nodes
(benchmark/scripts/create-kwok-nodes.sh) and with the mock cache in unit
tests (pkg/scheduler/cache/cache_mock.go): holds the CRD objects,
accepts binds/evictions, and simulates pod lifecycle transitions so
controllers and the scheduler can be exercised end-to-end with zero real
machines.  Thread-safe: the scheduler loop and controllers may share it.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from volcano_tpu.api.hypernode import HyperNode
from volcano_tpu.api.node_info import Node
from volcano_tpu.api.pod import Pod
from volcano_tpu.api.podgroup import PodGroup
from volcano_tpu.api.queue import Queue
from volcano_tpu.api.types import (DEFAULT_QUEUE, RUN_TICKS_ANNOTATION,
                                   TaskStatus)
from volcano_tpu.cache.cluster import Cluster, ClusterSnapshot, PriorityClass


class FakeCluster(Cluster):
    def __init__(self, admission=None):
        self._lock = threading.RLock()
        self.pods: Dict[str, Pod] = {}            # key: ns/name
        self.nodes: Dict[str, Node] = {}
        self.podgroups: Dict[str, PodGroup] = {}  # key: ns/name
        self.queues: Dict[str, Queue] = {DEFAULT_QUEUE: Queue(name=DEFAULT_QUEUE)}
        self.hypernodes: Dict[str, HyperNode] = {}
        self.priority_classes: Dict[str, PriorityClass] = {}
        self.vcjobs: Dict[str, object] = {}       # key: ns/name -> VCJob
        self.commands: List[dict] = []            # bus/v1alpha1 analogue
        # namespace -> annotations (the podgroup mutate webhook reads
        # the namespace's default-queue annotation from here)
        self.namespaces: Dict[str, Dict[str, str]] = {}
        self.jobflows: Dict[str, object] = {}     # flow/v1alpha1 JobFlow
        self.jobtemplates: Dict[str, object] = {} # flow/v1alpha1 JobTemplate
        self.cronjobs: Dict[str, object] = {}     # batch/v1alpha1 CronJob
        self.hyperjobs: Dict[str, object] = {}    # training/v1alpha1 HyperJob
        self.nodeshards: Dict[str, object] = {}   # shard/v1alpha1 NodeShard
        self.numatopologies: Dict[str, object] = {}  # nodeinfo/v1alpha1
        self.bandwidthreports: Dict[str, object] = {}  # api/netusage.py
        self.slicehealthreports: Dict[str, object] = {}  # api/slicehealth.py
        self.goodputreports: Dict[str, object] = {}    # api/goodput.py
        self.servingreports: Dict[str, object] = {}    # api/serving.py
        self.services: Dict[str, dict] = {}       # svc plugin artifacts
        self.config_maps: Dict[str, dict] = {}
        self.secrets: Dict[str, dict] = {}
        self.pvcs: Dict[str, dict] = {}           # volumebinding claims
        self.pvs: Dict[str, dict] = {}            # volumebinding volumes
        self.datasources: Dict[str, dict] = {}    # datadependency/v1alpha1
        self.regions: Dict[str, dict] = {}        # api/federation.py registry
        self.fleet_traces: Dict[str, dict] = {}   # federation/stitch.py docs
        self.router_breakers: Dict[str, dict] = {}  # federation/retry.py snaps
        self.slos: Dict[str, dict] = {}           # federation/slo.py doc
        self.events: List[Tuple[str, str, str]] = []
        self._run_progress: Dict[str, int] = {}   # pod uid -> ticks run
        self.binds: List[Tuple[str, str]] = []    # (pod key, node) history
        self.evictions: List[str] = []
        # admission chain applied on vcjob/queue create (webhooks)
        self.admission = admission
        # leader-election lease CAS + fencing-token floors: the
        # in-process analogue of StateServer.lease/advance_fence so
        # elections (router HA, sharded schedulers) unit-test with
        # zero wire.  lease_now is injectable: tests drive expiry
        # with a fake clock instead of sleeping out real TTLs.
        self.lease_now: Callable[[], float] = time.monotonic
        self._fake_leases: Dict[str, list] = {}  # name->[holder,exp,term]
        self._lease_terms: Dict[str, int] = {}
        self._fences: Dict[str, int] = {}
        self._fenced_counts: Dict[str, int] = {}
        # watchers notified on any mutation (controllers use this)
        self._watchers: List[Callable[[str, object], None]] = []

    # picklable for CLI state files: locks and watcher callbacks are
    # process-local and recreated on load
    def __getstate__(self):
        state = self.__dict__.copy()
        state.pop("_lock", None)
        state.pop("_watchers", None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.RLock()
        self._watchers = []
        # stores added after old state files were written
        from volcano_tpu.cache.kinds import KINDS
        self.__dict__.setdefault("commands", [])
        self.__dict__.setdefault("_run_progress", {})
        self.__dict__.setdefault("lease_now", time.monotonic)
        self.__dict__.setdefault("_fake_leases", {})
        self.__dict__.setdefault("_lease_terms", {})
        self.__dict__.setdefault("_fences", {})
        self.__dict__.setdefault("_fenced_counts", {})
        for spec in KINDS.values():
            self.__dict__.setdefault(spec.attr, {})

    # -- mutation helpers (the "kubectl" surface) ----------------------

    def add_node(self, node: Node):
        with self._lock:
            self.nodes[node.name] = node
        self._notify("node", node)

    def remove_node(self, name: str):
        with self._lock:
            node = self.nodes.pop(name, None)
        if node:
            self._notify("node_deleted", node)
            # same lifetime rule as delete_object("node"): the node's
            # agent reports die with it
            for kind, attr in (("bandwidthreport", "bandwidthreports"),
                               ("slicehealthreport",
                                "slicehealthreports"),
                               ("goodputreport", "goodputreports"),
                               ("servingreport", "servingreports")):
                with self._lock:
                    had = name in getattr(self, attr)
                if had:
                    self.delete_object(kind, name)

    def add_pod(self, pod: Pod):
        if self.admission is not None and pod.key not in self.pods:
            pod = self.admission.admit_pod(pod, self)
        from volcano_tpu import trace
        # store-side lifecycle stamp (first writer wins, so a retried
        # create keeps the original timestamp) — the `created` anchor
        # of the e2e phase decomposition (docs/design/tracing.md)
        trace.stamp_phase(pod.annotations, "created")
        with self._lock:
            self.pods[pod.key] = pod
        self._notify("pod", pod)

    def delete_pod(self, key: str):
        with self._lock:
            pod = self.pods.pop(key, None)
            if pod:
                self._run_progress.pop(pod.uid, None)
        if pod:
            self._notify("pod_deleted", pod)

    def add_podgroup(self, pg: PodGroup):
        from volcano_tpu import trace
        trace.stamp_phase(pg.annotations, "created")
        with self._lock:
            self.podgroups[pg.key] = pg
        self._notify("podgroup", pg)

    def delete_podgroup(self, key: str):
        with self._lock:
            pg = self.podgroups.pop(key, None)
        if pg:
            self._notify("podgroup_deleted", pg)

    def add_queue(self, queue: Queue):
        with self._lock:
            self.queues[queue.name] = queue
        self._notify("queue", queue)

    def add_hypernode(self, hn: HyperNode):
        with self._lock:
            self.hypernodes[hn.name] = hn
        self._notify("hypernode", hn)

    def add_numatopology(self, topo):
        with self._lock:
            self.numatopologies[topo.name] = topo
        self._notify("numatopology", topo)

    # -- command bus (bus/v1alpha1 Command CRD analogue) ---------------

    def add_command(self, target_key: str, action: str):
        """Queue a delegated action (abort/resume/restart/...) against a
        vcjob; the job controller consumes and deletes it.  The cid
        uniquely names this command so the state server's WAL can
        journal a drain as the exact set it consumed — replay is then
        order-independent of add events whose journal records raced
        the drain's (docs/design/durability.md)."""
        import uuid
        cmd = {"target": target_key, "action": action,
               "cid": uuid.uuid4().hex[:12]}
        with self._lock:
            self.commands.append(cmd)
        self._notify("command", cmd)

    def drain_commands(self, target_key: str):
        with self._lock:
            cmds = getattr(self, "commands", [])
            mine = [c for c in cmds if c["target"] == target_key]
            self.commands = [c for c in cmds if c["target"] != target_key]
        return mine

    # -- vcjobs (admission-gated like the apiserver webhook path) ------

    def add_vcjob(self, job):
        """Create a vcjob; the admission chain (webhooks) mutates then
        validates — a rejection raises before anything is stored."""
        if self.admission is not None:
            job = self.admission.admit_job(job, self)
        with self._lock:
            self.vcjobs[job.key] = job
        self._notify("vcjob", job)
        return job

    def update_vcjob(self, job):
        if self.admission is not None:
            # spec re-validated on update (VERDICT r1: the chain used
            # to run on create only, so a job could be mutated into an
            # invalid spec post-create)
            job = self.admission.admit_job_update(job, self)
        with self._lock:
            self.vcjobs[job.key] = job
        self._notify("vcjob", job)

    def delete_vcjob(self, key: str):
        with self._lock:
            job = self.vcjobs.pop(key, None)
        if job:
            self._notify("vcjob_deleted", job)

    def delete_hypernode(self, name: str):
        with self._lock:
            hn = self.hypernodes.pop(name, None)
        if hn:
            self._notify("hypernode_deleted", hn)

    def add_priority_class(self, pc: PriorityClass):
        with self._lock:
            self.priority_classes[pc.name] = pc
        self._notify("priority_class", pc)

    # -- generic object store ------------------------------------------

    # per-kind admission dispatch (reference router/admission.go:35):
    # create paths run mutate+validate; vcjob updates re-validate spec
    _ADMIT_CREATE = {
        "vcjob": "admit_job", "queue": "admit_queue",
        "podgroup": "admit_podgroup", "hypernode": "admit_hypernode",
        "pod": "admit_pod", "jobflow": "admit_jobflow",
        "cronjob": "admit_cronjob",
    }

    def put_object(self, kind: str, obj, key: Optional[str] = None):
        from volcano_tpu.cache.kinds import KINDS, key_for
        prev_goodput = prev_serving = None
        if kind == "goodputreport":
            # the node's PREVIOUS report is the fold's diff base (the
            # wire carries cumulative ledgers; see _fold_goodput_report)
            with self._lock:
                prev_goodput = self.goodputreports.get(
                    key_for(kind, obj, key))
        elif kind == "servingreport":
            # same cumulative-ledger diff base as goodput
            with self._lock:
                prev_serving = self.servingreports.get(
                    key_for(kind, obj, key))
        if kind == "vcjob" and key is None:
            # keep the admission-gated create path authoritative
            # (an explicit key marks an update/status flush — the
            # create chain must not re-run on those)
            return self.add_vcjob(obj)
        spec = KINDS[kind]
        k = key_for(kind, obj, key)
        if self.admission is not None:
            if k not in getattr(self, spec.attr):
                method = self._ADMIT_CREATE.get(kind)
                if method is not None:
                    obj = getattr(self.admission, method)(obj, self)
            elif kind == "vcjob":
                obj = self.admission.admit_job_update(obj, self)
        if (kind == "pod" and k not in self.pods) or \
                (kind == "podgroup" and k not in self.podgroups):
            from volcano_tpu import trace
            trace.stamp_phase(obj.annotations, "created")
        with self._lock:
            if kind == "node":
                # keep the accounting/health folds sticky: a node
                # write from a mirror that predates a fold (the
                # agent's whole-node persist) must not erase the
                # folded summary — re-apply the stored reports before
                # the write lands.  Read-stick-store under this one
                # lock hold (RLock): a fold racing a dropped-lock
                # stick would still be erased.
                rep = self.bandwidthreports.get(k)
                health = self.slicehealthreports.get(k)
                cur = self.nodes.get(k)
                if rep is not None:
                    self._apply_bandwidth_fold(obj, rep)
                if health is not None:
                    self._apply_health_fold(obj, health)
                if cur is not None:
                    self._apply_quarantine_stick(obj, cur)
            if kind == "podgroup":
                # keep the goodput fold sticky: a whole-podgroup
                # write from a mirror predating a fold (controllers
                # persist podgroups from THEIR copies every sync)
                # must not erase the accumulated accounting.  Read-
                # stick-store under this one lock hold: a fold racing
                # a dropped-lock stick would still be erased.
                cur = self.podgroups.get(k)
                if cur is not None:
                    self._apply_goodput_stick(obj, cur)
                    self._apply_serving_stick(obj, cur)
            getattr(self, spec.attr)[k] = obj
        self._notify(kind, obj if spec.key_of else {"key": k, "obj": obj})
        if kind == "bandwidthreport":
            self._fold_bandwidth_report(obj)
        elif kind == "slicehealthreport":
            self._fold_health_report(obj)
        elif kind == "goodputreport":
            self._fold_goodput_report(obj, prev_goodput)
        elif kind == "servingreport":
            self._fold_serving_report(obj, prev_serving)
        return obj

    @staticmethod
    def _apply_bandwidth_fold(node, report) -> bool:
        """Merge a BandwidthReport's node-level summary into *node*'s
        annotations; returns True when anything changed."""
        from volcano_tpu.api.netusage import (
            NODE_MEASURED_OFFLINE_ANNOTATION,
            NODE_MEASURED_ONLINE_ANNOTATION, NODE_SATURATED_ANNOTATION,
            NODE_VIOLATING_PODS_ANNOTATION)
        ann = node.annotations
        before = (ann.get(NODE_MEASURED_OFFLINE_ANNOTATION),
                  ann.get(NODE_MEASURED_ONLINE_ANNOTATION),
                  ann.get(NODE_SATURATED_ANNOTATION),
                  ann.get(NODE_VIOLATING_PODS_ANNOTATION))
        ann[NODE_MEASURED_OFFLINE_ANNOTATION] = \
            f"{report.offline_tx_mbps:.1f}"
        ann[NODE_MEASURED_ONLINE_ANNOTATION] = \
            f"{report.online_tx_mbps:.1f}"
        if report.saturated:
            ann[NODE_SATURATED_ANNOTATION] = "true"
        else:
            ann.pop(NODE_SATURATED_ANNOTATION, None)
        ann[NODE_VIOLATING_PODS_ANNOTATION] = str(report.violations)
        return before != (
            ann.get(NODE_MEASURED_OFFLINE_ANNOTATION),
            ann.get(NODE_MEASURED_ONLINE_ANNOTATION),
            ann.get(NODE_SATURATED_ANNOTATION),
            ann.get(NODE_VIOLATING_PODS_ANNOTATION))

    def _fold_bandwidth_report(self, report) -> None:
        """Fold a node agent's BandwidthReport summary into the node's
        annotations AT THE STORE — the server-side half of the
        accounting loop.  Doing it here (not in the agent) means every
        watch mirror, the scheduler's included, learns saturation from
        ordinary node events without decoding reports.  The fold is
        also re-applied on every node PUT (put_object above), so a
        whole-node persist from a mirror that hasn't seen the folded
        keys yet cannot erase them between reports."""
        with self._lock:
            node = self.nodes.get(getattr(report, "node", ""))
            if node is None:
                return
            changed = self._apply_bandwidth_fold(node, report)
        if changed:     # unchanged summary: no watch traffic
            self._notify("node", node)

    @staticmethod
    def _apply_quarantine_stick(obj, cur) -> None:
        """An ACTIVE quarantine TTL survives whole-node writes from
        mirrors that predate the stamp (the victim's own agent
        persists the full node from its mirror copy): if the incoming
        write lacks the annotation while the stored node carries an
        unexpired one, re-apply it.  An EXPIRED stamp is not sticky —
        that is exactly how the failover controller lifts it — and an
        incoming value always wins (a TTL refresh)."""
        import time as _time

        from volcano_tpu.api.slicehealth import (
            NODE_QUARANTINED_UNTIL_ANNOTATION)
        if NODE_QUARANTINED_UNTIL_ANNOTATION in obj.annotations:
            return
        raw = cur.annotations.get(NODE_QUARANTINED_UNTIL_ANNOTATION)
        if raw is None:
            return
        try:
            if float(raw) > _time.time():
                obj.annotations[NODE_QUARANTINED_UNTIL_ANNOTATION] = raw
        except (TypeError, ValueError):
            pass

    @staticmethod
    def _apply_health_fold(node, report) -> bool:
        """Merge a SliceHealthReport's verdict into *node*'s
        annotations; returns True when it changed."""
        from volcano_tpu.api.slicehealth import (NODE_HEALTH_ANNOTATION,
                                                 VERDICT_HEALTHY)
        ann = node.annotations
        before = ann.get(NODE_HEALTH_ANNOTATION)
        if report.verdict == VERDICT_HEALTHY:
            # healthy is the absence of the key, so nodes that never
            # ran an agent and nodes that recovered look identical
            ann.pop(NODE_HEALTH_ANNOTATION, None)
        else:
            ann[NODE_HEALTH_ANNOTATION] = report.verdict
        return before != ann.get(NODE_HEALTH_ANNOTATION)

    def _fold_health_report(self, report) -> None:
        """Store-side fold of a host health verdict into the node's
        annotations (same rationale as _fold_bandwidth_report: every
        watch mirror learns host health from ordinary node events)."""
        with self._lock:
            node = self.nodes.get(getattr(report, "node", ""))
            if node is None:
                return
            changed = self._apply_health_fold(node, report)
        if changed:
            self._notify("node", node)

    @staticmethod
    def _apply_goodput_stick(obj, cur) -> None:
        """A whole-podgroup write from a mirror that predates a
        goodput fold must not erase the folded summary: copy over any
        goodput key the incoming write lacks, and for the ACCUMULATED
        keys (allocated/productive pod-seconds, step, epoch) keep the
        larger value — the ledger only ever grows, so max() is the
        conflict-free merge of a stale-copy write racing a fold."""
        from volcano_tpu.api import goodput as gapi
        ann, cur_ann = obj.annotations, cur.annotations
        for key in gapi.PG_FOLD_KEYS:
            if key not in cur_ann:
                continue
            if key not in ann:
                ann[key] = cur_ann[key]
            elif key in (gapi.PG_ALLOCATED_S_ANNOTATION,
                         gapi.PG_PRODUCTIVE_S_ANNOTATION,
                         gapi.PG_STEP_ANNOTATION,
                         gapi.PG_EPOCH_ANNOTATION,
                         gapi.PG_UPDATED_TS_ANNOTATION):
                if gapi.ann_float(cur_ann, key) > \
                        gapi.ann_float(ann, key):
                    ann[key] = cur_ann[key]

    def _fold_goodput_report(self, report, prev=None) -> None:
        """Fold a node agent's GoodputReport into the owning PODGROUP
        annotations AT THE STORE — the per-job half of the goodput
        loop (docs/design/goodput.md).  Doing it here (not in the
        agent) means every watch mirror — the scheduler's throughput-
        vector estimator included — learns per-job step rates and the
        productive/allocated ledger from ordinary podgroup events.

        The wire ledger is CUMULATIVE per pod; the fold accumulates
        the per-pod diff against *prev* (this node's previous stored
        report).  That makes the fold idempotent under retries — an
        agent whose post was folded but whose ack died re-sends the
        same cumulative values and contributes only the growth — while
        several nodes hosting one gang still accumulate without
        double counting.  A cumulative value BELOW the previous one is
        a restarted collector: the new absolute value is the diff."""
        from volcano_tpu.api import goodput as gapi
        prev_by_uid = {u.uid: u for u in getattr(prev, "usages", ())} \
            if prev is not None else {}

        def ledger_diff(u, field):
            cur = getattr(u, field)
            p = prev_by_uid.get(u.uid)
            base = getattr(p, field) if p is not None else 0.0
            return cur - base if cur >= base else cur

        by_job: Dict[str, list] = {}
        for u in getattr(report, "usages", ()):
            if u.job:
                by_job.setdefault(u.job, []).append(u)
        for job_key, usages in by_job.items():
            with self._lock:
                pg = self.podgroups.get(job_key)
                if pg is None:
                    continue
                ann = pg.annotations
                before = {k: ann.get(k) for k in gapi.PG_FOLD_KEYS}
                step = max(u.step for u in usages)
                if step > gapi.ann_float(ann, gapi.PG_STEP_ANNOTATION):
                    ann[gapi.PG_STEP_ANNOTATION] = str(step)
                # the gang steps in lockstep: any healthy pod's rate
                # approximates the job's — take this report's max so
                # one straggling stale file cannot drag the estimate
                rate = max(u.steps_per_s for u in usages)
                ann[gapi.PG_STEP_RATE_ANNOTATION] = f"{rate:.3f}"
                ex_rate = max(u.examples_per_s for u in usages)
                if ex_rate > 0:
                    ann[gapi.PG_EXAMPLES_RATE_ANNOTATION] = \
                        f"{ex_rate:.3f}"
                alloc = gapi.ann_float(
                    ann, gapi.PG_ALLOCATED_S_ANNOTATION) + \
                    sum(ledger_diff(u, "allocated_s") for u in usages)
                prod = gapi.ann_float(
                    ann, gapi.PG_PRODUCTIVE_S_ANNOTATION) + \
                    sum(ledger_diff(u, "productive_s")
                        for u in usages)
                ann[gapi.PG_ALLOCATED_S_ANNOTATION] = f"{alloc:.3f}"
                ann[gapi.PG_PRODUCTIVE_S_ANNOTATION] = f"{prod:.3f}"
                if alloc > 0:
                    ann[gapi.PG_GOODPUT_ANNOTATION] = \
                        f"{min(1.0, prod / alloc):.4f}"
                ann[gapi.PG_GENERATION_ANNOTATION] = \
                    usages[0].generation
                epoch = max(u.epoch for u in usages)
                if epoch >= gapi.ann_float(ann,
                                           gapi.PG_EPOCH_ANNOTATION):
                    ann[gapi.PG_EPOCH_ANNOTATION] = str(epoch)
                ts = getattr(report, "ts", 0.0)
                # max-merge: a behind-wall-clock node's fold must not
                # regress the stamp (the estimator dedupes on it)
                if ts > gapi.ann_float(ann,
                                       gapi.PG_UPDATED_TS_ANNOTATION):
                    ann[gapi.PG_UPDATED_TS_ANNOTATION] = f"{ts:.3f}"
                changed = before != {k: ann.get(k)
                                     for k in gapi.PG_FOLD_KEYS}
            if changed:     # unchanged summary: no watch traffic
                self._notify("podgroup", pg)

    @staticmethod
    def _apply_serving_stick(obj, cur) -> None:
        """Same stale-copy protection as _apply_goodput_stick for the
        serving summary: copy keys the incoming write lacks, max-merge
        the monotone ones (request/SLO ledgers, epoch, stamp)."""
        from volcano_tpu.api import serving as sapi
        ann, cur_ann = obj.annotations, cur.annotations
        for key in sapi.PG_FOLD_KEYS:
            if key not in cur_ann:
                continue
            if key not in ann:
                ann[key] = cur_ann[key]
            elif key in (sapi.PG_REQUESTS_ANNOTATION,
                         sapi.PG_SLO_OK_ANNOTATION,
                         sapi.PG_EPOCH_ANNOTATION,
                         sapi.PG_UPDATED_TS_ANNOTATION):
                if sapi.ann_float(cur_ann, key) > \
                        sapi.ann_float(ann, key):
                    ann[key] = cur_ann[key]

    def _fold_serving_report(self, report, prev=None) -> None:
        """Fold a node agent's ServingReport into the owning PODGROUP
        annotations at the store — the serving mirror of
        _fold_goodput_report.  Request/SLO-ok ledgers are CUMULATIVE
        per replica on the wire; the fold accumulates per-pod diffs
        against *prev* (idempotent under lost-ack re-post, no double
        counting across nodes).  QPS SUMS across a group's replicas
        (each serves its own share of the traffic), latency quantiles
        take the report's max (the group's p99 is bounded by its
        slowest replica — optimistic per-replica mixing would hide a
        hot-spotted one)."""
        from volcano_tpu.api import serving as sapi
        prev_by_uid = {u.uid: u for u in getattr(prev, "usages", ())} \
            if prev is not None else {}

        def ledger_diff(u, field):
            cur = getattr(u, field)
            p = prev_by_uid.get(u.uid)
            base = getattr(p, field) if p is not None else 0
            return cur - base if cur >= base else cur

        by_job: Dict[str, list] = {}
        for u in getattr(report, "usages", ()):
            if u.job:
                by_job.setdefault(u.job, []).append(u)
        for job_key, usages in by_job.items():
            with self._lock:
                pg = self.podgroups.get(job_key)
                if pg is None:
                    continue
                ann = pg.annotations
                before = {k: ann.get(k) for k in sapi.PG_FOLD_KEYS}
                # the group summary spans EVERY node's stored report:
                # one group's replicas land on many hosts and each
                # agent reports only its own pods, so folding just the
                # incoming report would shrink the group QPS to the
                # last poster's share.  Usages are filtered to live
                # pod uids — a drained replica's final report stops
                # counting the moment its pod object is deleted
                live = {p.uid for p in self.pods.values()}
                group = [u for rep in self.servingreports.values()
                         for u in getattr(rep, "usages", ())
                         if u.job == job_key and u.uid in live]
                if not group:
                    group = usages
                qps = sum(u.qps for u in group)
                ann[sapi.PG_QPS_ANNOTATION] = f"{qps:.3f}"
                ann[sapi.PG_P50_MS_ANNOTATION] = \
                    f"{max(u.p50_ms for u in group):.3f}"
                ann[sapi.PG_P99_MS_ANNOTATION] = \
                    f"{max(u.p99_ms for u in group):.3f}"
                reqs = sapi.ann_float(
                    ann, sapi.PG_REQUESTS_ANNOTATION) + \
                    sum(ledger_diff(u, "requests") for u in usages)
                ok = sapi.ann_float(
                    ann, sapi.PG_SLO_OK_ANNOTATION) + \
                    sum(ledger_diff(u, "slo_ok") for u in usages)
                ann[sapi.PG_REQUESTS_ANNOTATION] = f"{reqs:.0f}"
                ann[sapi.PG_SLO_OK_ANNOTATION] = f"{ok:.0f}"
                ann[sapi.PG_REPLICAS_ANNOTATION] = str(len(group))
                epoch = max(u.epoch for u in group)
                if epoch >= sapi.ann_float(ann,
                                           sapi.PG_EPOCH_ANNOTATION):
                    ann[sapi.PG_EPOCH_ANNOTATION] = str(epoch)
                ts = getattr(report, "ts", 0.0)
                if ts > sapi.ann_float(ann,
                                       sapi.PG_UPDATED_TS_ANNOTATION):
                    ann[sapi.PG_UPDATED_TS_ANNOTATION] = f"{ts:.3f}"
                changed = before != {k: ann.get(k)
                                     for k in sapi.PG_FOLD_KEYS}
            if changed:     # unchanged summary: no watch traffic
                self._notify("podgroup", pg)

    def delete_object(self, kind: str, key: str) -> None:
        from volcano_tpu.cache.kinds import KINDS
        spec = KINDS[kind]
        with self._lock:
            obj = getattr(self, spec.attr).pop(key, None)
        if obj is not None:
            self._notify(f"{kind}_deleted",
                         obj if spec.key_of else {"key": key, "obj": obj})
        if kind == "node" and obj is not None:
            # the node's agent reports die with it: the sticky
            # re-fold (put_object) would otherwise resurrect stale
            # saturation/health onto a REPLACEMENT host registering
            # under the same name
            for rkind, attr in (("bandwidthreport", "bandwidthreports"),
                                ("slicehealthreport",
                                 "slicehealthreports"),
                                ("goodputreport", "goodputreports"),
                                ("servingreport", "servingreports")):
                with self._lock:
                    had = key in getattr(self, attr)
                if had:
                    self.delete_object(rkind, key)

    def watch(self, fn: Callable[[str, object], None]):
        self._watchers.append(fn)

    def unwatch(self, fn: Callable[[str, object], None]):
        try:
            self._watchers.remove(fn)
        except ValueError:
            pass

    def _notify(self, kind: str, obj: object):
        for w in self._watchers:
            w(kind, obj)

    # -- Cluster interface --------------------------------------------

    def list_all(self) -> ClusterSnapshot:
        with self._lock:
            return ClusterSnapshot(
                pods=list(self.pods.values()),
                nodes=list(self.nodes.values()),
                podgroups=list(self.podgroups.values()),
                queues=list(self.queues.values()),
                hypernodes=list(self.hypernodes.values()),
                priority_classes=list(self.priority_classes.values()),
                vcjobs=list(self.vcjobs.values()),
            )

    def bind_pod(self, namespace: str, name: str, node_name: str,
                 ts_alloc: Optional[float] = None) -> None:
        """ts_alloc: the scheduler's placement-decision wall time,
        carried on the bind request so the `allocated` lifecycle stamp
        reflects the decision, not the (possibly batched) commit."""
        from volcano_tpu import trace
        try:
            # telemetry must never fail (or half-apply) a bind: an
            # unparseable decision stamp from a hand-rolled client is
            # dropped, not raised after the pod already mutated
            ts_alloc = None if ts_alloc is None else float(ts_alloc)
        except (TypeError, ValueError):
            ts_alloc = None
        key = f"{namespace}/{name}"
        with self._lock:
            pod = self.pods.get(key)
            if pod is None:
                raise KeyError(f"bind: pod {key} not found")
            if pod.node_name and pod.node_name != node_name:
                raise ValueError(
                    f"bind conflict: pod {key} already on {pod.node_name}")
            if node_name not in self.nodes:
                raise KeyError(f"bind: node {node_name} not found")
            pod.node_name = node_name
            pod.phase = TaskStatus.BOUND
            trace.stamp_phase(pod.annotations, "allocated", ts_alloc)
            trace.stamp_phase(pod.annotations, "bound")
            self.binds.append((key, node_name))
        self._notify("pod", pod)

    def evict_pod(self, namespace: str, name: str, reason: str = "") -> None:
        key = f"{namespace}/{name}"
        with self._lock:
            pod = self.pods.get(key)
            if pod is None:
                return
            pod.phase = TaskStatus.RELEASING
            pod.status_message = reason
            self.evictions.append(key)
        self._notify("pod", pod)

    def nominate_pod(self, namespace: str, name: str, node_name: str) -> None:
        with self._lock:
            pod = self.pods.get(f"{namespace}/{name}")
            if pod is not None:
                pod.nominated_node = node_name
        if pod is not None:
            self._notify("pod", pod)

    def update_podgroup_status(self, pg: PodGroup) -> None:
        # the scheduler's per-cycle status flush is a WHOLE-podgroup
        # write from ITS mirror copy.  Normally that copy is a cycle
        # old at worst, but under gray failure (read-only degrade,
        # slow watch) it can be SECONDS stale — and without the same
        # goodput stick put_object applies, a stale flush erased the
        # folds that landed in between, visibly rewinding the
        # accumulated ledger (found by tools/chaos_conductor.py:
        # goodput_monotonic violation).  Max-merge is conflict-free,
        # so re-applying here is always safe.
        with self._lock:
            # read-stick-store under ONE lock hold: a fold landing
            # between a dropped-lock read and the store would still
            # be erased (the exact race the stick closes)
            cur = self.podgroups.get(pg.key)
            if cur is not None:
                self._apply_goodput_stick(pg, cur)
            self.podgroups[pg.key] = pg
        self._notify("podgroup", pg)

    def record_event(self, obj_key: str, reason: str, message: str) -> None:
        self.events.append((obj_key, reason, message))

    # -- leases + fencing tokens (StateServer.lease analogue) ----------

    def lease(self, name: str, holder: str, ttl: float = 15.0,
              release: bool = False, deadline=None) -> dict:
        """Same CAS + term contract as StateServer.lease: the term
        bumps on every acquisition that is not a live same-holder
        renewal, and is never reissued.  deadline is accepted for
        RemoteCluster signature parity (no wire here to bound)."""
        now = self.lease_now()
        with self._lock:
            cur = self._fake_leases.get(name)
            if release:
                if cur and cur[0] == holder:
                    del self._fake_leases[name]
                return {"acquired": False, "holder": "", "expires": 0,
                        "expires_in": 0,
                        "term": self._lease_terms.get(name, 0)}
            if cur is None or cur[1] < now or cur[0] == holder:
                if cur is not None and cur[0] == holder and \
                        cur[1] >= now:
                    term = cur[2] or self._lease_terms.get(name, 0)
                else:
                    term = self._lease_terms.get(name, 0) + 1
                    self._lease_terms[name] = term
                self._fake_leases[name] = [holder, now + ttl, term]
                return {"acquired": True, "holder": holder,
                        "expires": now + ttl,
                        "expires_in": round(ttl, 3), "term": term}
            return {"acquired": False, "holder": cur[0],
                    "expires": cur[1],
                    "expires_in": round(cur[1] - now, 3),
                    "term": cur[2]}

    def leases(self) -> dict:
        now = self.lease_now()
        with self._lock:
            return {name: {"holder": l[0],
                           "expires_in": round(l[1] - now, 3),
                           "term": l[2]}
                    for name, l in self._fake_leases.items()}

    def set_fence(self, name: str, term: int) -> None:
        """Signature parity with RemoteCluster.set_fence.  In-process
        stores don't enforce the fence on writes (no wire boundary to
        refuse at) — enforcement is proven against real servers."""
        self._fence = (name, int(term)) if name else None

    def advance_fence(self, name: str, term: int,
                      deadline=None) -> dict:
        with self._lock:
            cur = self._fences.get(name, 0)
            if int(term) > cur:
                self._fences[name] = cur = int(term)
            return {"name": name, "term": cur,
                    "refused": self._fenced_counts.get(name, 0)}

    def fences(self) -> dict:
        with self._lock:
            return {name: {"term": t,
                           "refused": self._fenced_counts.get(name, 0)}
                    for name, t in sorted(self._fences.items())}

    # -- kubelet simulation -------------------------------------------

    def tick(self):
        """Advance simulated pod lifecycle one step:
        Bound -> Running; Releasing -> deleted; and a RUNNING pod whose
        spec declares a finite workload (RUN_TICKS_ANNOTATION) succeeds
        once it has run that many ticks — the kubelet running a batch
        container to completion (reference: kubelet drives the pod
        phase, the job controller reacts,
        job_controller.go:415-542)."""
        with self._lock:
            to_delete = []
            started = []
            completed = []
            progress = self._run_progress
            from volcano_tpu import trace
            for key, pod in self.pods.items():
                if pod.phase is TaskStatus.BOUND:
                    pod.phase = TaskStatus.RUNNING
                    # the simulated kubelet admits and starts the
                    # container in one tick, so the two stamps
                    # coincide here; a real kubelet would separate
                    # image pull / admission from container start
                    trace.stamp_phase(pod.annotations, "admitted")
                    trace.stamp_phase(pod.annotations, "running")
                    started.append(pod)
                elif pod.phase is TaskStatus.RUNNING:
                    spec = pod.annotations.get(RUN_TICKS_ANNOTATION)
                    if spec is None:
                        continue
                    try:
                        run_ticks = int(spec)
                    except ValueError:
                        continue            # malformed: run forever
                    ran = progress.get(pod.uid, 0) + 1
                    if ran >= run_ticks:
                        pod.phase = TaskStatus.SUCCEEDED
                        pod.exit_code = 0
                        progress.pop(pod.uid, None)
                        completed.append(pod)
                    else:
                        progress[pod.uid] = ran
                elif pod.phase is TaskStatus.RELEASING:
                    progress.pop(pod.uid, None)
                    to_delete.append(key)
        for pod in started:
            self._notify("pod", pod)
        for pod in completed:
            self._notify("pod", pod)
        for key in to_delete:
            self.delete_pod(key)

    def complete_pod(self, key: str, succeeded: bool = True,
                     exit_code=None):
        with self._lock:
            pod = self.pods.get(key)
            if pod:
                pod.phase = (TaskStatus.SUCCEEDED if succeeded
                             else TaskStatus.FAILED)
                pod.exit_code = (exit_code if exit_code is not None
                                 else (0 if succeeded else 1))
        if pod:
            self._notify("pod", pod)
