"""Scheduling flight recorder — span trees, phase stamps, why-pending.

The metrics registry (metrics.py) can *count*; this module *attributes*:

* **Spans**: every scheduler session opens a root span; actions, the
  per-job allocation attempts inside them, and session open/close are
  timed child spans.  Plugin callbacks (predicate / nodeOrder /
  jobOrder / ...) are aggregated per (plugin, extension point) under
  the innermost open span — one span per plugin per point, carrying a
  call count, NOT one span per call (a 20k-host predicate sweep runs
  hundreds of thousands of callbacks; per-call spans would cost more
  than the scheduling they measure).
* **Phase stamps**: lifecycle timestamps stamped on pod/podgroup
  annotations (created -> enqueued -> allocated -> bound -> admitted
  -> running) that ride the existing wire objects, so any mirror can
  decompose a pod's end-to-end latency into per-phase segments whose
  sum telescopes to the total — the reconciliation invariant
  (docs/design/tracing.md).
* **Unschedulable reasons**: free-text fit-error messages are
  normalized to a BOUNDED enum for aggregation and metric labels
  (cardinality rule: enums label metrics, free text rides only in
  trace payloads), aggregated per job as reason -> distinct-node
  count, published on the podgroup for `vtpctl explain`.
* **Ring + sampling**: completed session traces land in a bounded
  in-process ring (and are POSTed to the state server's ring in wire
  mode).  Sessions with unschedulable jobs or slower than the rolling
  p95 are always kept; the rest are 1-in-SAMPLE_EVERY sampled.

Zero-dependency and always-on: the hot-path cost is two
perf_counter() reads per plugin callback, paid only while a session
span is open on the calling thread.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from volcano_tpu import metrics

# -- lifecycle phases --------------------------------------------------

TS_PREFIX = "trace.volcano-tpu.io/ts-"
PHASES = ("created", "enqueued", "allocated", "bound", "admitted",
          "running")
# segment name -> (from stamp, to stamp); gaps telescope: the segment
# sum equals running - created whenever every stamp exists
SEGMENTS: Tuple[Tuple[str, str, str], ...] = (
    ("queue", "created", "enqueued"),
    ("schedule", "enqueued", "allocated"),
    ("bind", "allocated", "bound"),
    ("admit", "bound", "admitted"),
    ("start", "admitted", "running"),
)

PENDING_REASONS_ANNOTATION = "trace.volcano-tpu.io/pending-reasons"


def stamp_phase(annotations: Dict[str, str], phase: str,
                ts: Optional[float] = None) -> None:
    """Record a phase transition timestamp once (first writer wins: a
    retried create / re-delivered watch event must not move it)."""
    key = TS_PREFIX + phase
    if key not in annotations:
        annotations[key] = f"{time.time() if ts is None else ts:.6f}"


def phase_ts(annotations: Dict[str, str], phase: str) -> Optional[float]:
    raw = annotations.get(TS_PREFIX + phase)
    if raw is None:
        return None
    try:
        return float(raw)
    except (TypeError, ValueError):
        return None


def phase_segments(pod_annotations: Dict[str, str],
                   pg_annotations: Optional[Dict[str, str]] = None
                   ) -> Dict[str, float]:
    """Per-phase latency segments for one pod, in seconds.

    The `enqueued` stamp lives on the PODGROUP (one gang admission,
    not N pod writes); pass its annotations to include the queue /
    schedule split.  Stamps missing from the middle of the chain
    collapse into the next present segment (the gap is attributed to
    the first phase that can observe it), so the telescoping sum
    `running - created` holds for any stamp subset.  Small negative
    gaps (cross-process clock skew on the allocated stamp) clamp to 0
    and push the skew into the next segment — the sum is preserved.
    """
    stamps: Dict[str, float] = {}
    for phase in PHASES:
        ts = phase_ts(pod_annotations, phase)
        if ts is None and pg_annotations is not None:
            ts = phase_ts(pg_annotations, phase)
        if ts is not None:
            stamps[phase] = ts
    out: Dict[str, float] = {}
    prev: Optional[float] = stamps.get("created")
    for seg, _frm, to in SEGMENTS:
        ts = stamps.get(to)
        if prev is None or ts is None:
            continue
        out[seg] = max(0.0, ts - prev)
        prev = max(prev, ts)
    return out


def observe_phase_metrics(pod_annotations: Dict[str, str],
                          pg_annotations: Optional[Dict[str, str]] = None
                          ) -> Dict[str, float]:
    """Feed one pod's segments into sched_phase_seconds{phase=...}."""
    segs = phase_segments(pod_annotations, pg_annotations)
    for seg, dur in segs.items():
        metrics.observe("sched_phase_seconds", dur, phase=seg)
    if segs:
        metrics.observe("sched_phase_seconds", sum(segs.values()),
                        phase="e2e")
    return segs


# -- unschedulable-reason normalization --------------------------------

# The bounded enum metric labels / aggregates use.  Free-text node
# messages NEVER become labels — they ride in trace payloads and the
# podgroup annotation's `detail` samples only.
REASON_ENUM = (
    "elastic-waiting-for-capacity",
    # a serving group's SLO burst is waiting on chips (the scale-up is
    # pending while the serving-aware shrink frees an adjacent block)
    "serving-slo-pressure",
    # a training gang shrunk to fund that scale-up, re-placing off its
    # ICI-adjacent slices (the elastic plugin's avoid filter)
    "serving-preemption-victim",
    "quarantined",
    "node-affinity-mismatch",
    "taint-not-tolerated",
    "node-not-ready",
    "insufficient-resources",
    "tpu-shape-mismatch",
    "ici-shape-mismatch",
    "port-conflict",
    "pod-limit",
    "spread-skew",
    "pod-affinity-mismatch",
    "usage-over-threshold",
    "warm-spare-reserved",
    "queue-share-exceeded",
    "scheduling-gated",
    "gang-not-ready",
    "numa-mismatch",
    # a scheduler shard lost the server's check-and-bind arbitration
    # to another shard's optimistic cross-subtree gang (per-item 409);
    # the gang re-queues through the loser's next cycle
    "cross-shard-conflict",
    # a drained gang parked by the federation router mid cross-region
    # cutover (api/elastic.py evacuate contract): the source enqueue
    # gate holds it out of INQUEUE so the local scheduler never races
    # the destination region's re-place
    "evacuating-region",
    "other",
)

# keyword -> enum, first match wins (ordered: specific before generic)
_REASON_RULES: Tuple[Tuple[Tuple[str, ...], str], ...] = (
    # before the generic rules: an elastic gang parked at its floor
    # names the wait explicitly (actions/elastic.py records it).
    # Keyed on the message PREFIX, not the bare word "elastic" — the
    # migration predicate's "slice vacated by elastic migration" must
    # not read as a capacity wait
    (("elastic: waiting", "waiting for capacity"),
     "elastic-waiting-for-capacity"),
    # before the generic rules: the serving plugin's pressure marker
    # and the avoid-filter message a shrunk victim sees while steered
    # off the slices it freed for the serving pool
    (("serving: slo pressure",), "serving-slo-pressure"),
    (("freed for serving",), "serving-preemption-victim"),
    # before the device/insufficient rules: the flush_binds loser path
    # prefixes the server's 409 refusal ("bind overcommit: node ...")
    # with this marker when a subtree shard plan is active
    (("cross-shard",), "cross-shard-conflict"),
    # before the generic rules: the enqueue hold the federation
    # cutover stamps ("evacuating to region ...")
    (("evacuat",), "evacuating-region"),
    (("quarantin",), "quarantined"),
    (("warm spare",), "warm-spare-reserved"),
    (("node selector", "node affinity", "nodegroup", "affinity "),
     "node-affinity-mismatch"),
    (("taint",), "taint-not-tolerated"),
    (("not ready",), "node-not-ready"),
    (("hypernode", "tier", "topology"), "ici-shape-mismatch"),
    # before the device rule: "Insufficient cpu, google.com/tpu" is a
    # resource shortfall even when a TPU dim is among the missing
    (("insufficient",), "insufficient-resources"),
    (("tpu", "chip"), "tpu-shape-mismatch"),
    (("port",), "port-conflict"),
    (("too many pods", "pod count"), "pod-limit"),
    (("skew", "spread"), "spread-skew"),
    (("anti-affinity", "pod affinity", "affinity term"),
     "pod-affinity-mismatch"),
    (("usage", "threshold"), "usage-over-threshold"),
    (("queue", "share", "quota", "deserved"), "queue-share-exceeded"),
    (("scheduling gate",), "scheduling-gated"),
    (("gang", "minavailable", "min available"), "gang-not-ready"),
    (("numa",), "numa-mismatch"),
    (("resource",), "insufficient-resources"),
)


def normalize_reason(text: str) -> str:
    """Free-text fit-error message -> bounded enum slug."""
    low = (text or "").lower()
    for keywords, slug in _REASON_RULES:
        if any(k in low for k in keywords):
            return slug
    return "other"


def aggregate_job_reasons(job) -> Tuple[Dict[str, int], Dict[str, str]]:
    """(reason -> distinct-node count, reason -> one sample message)
    from a JobInfo's recorded fit errors.  Node-less errors (queue
    share, scheduling gates, job-level messages) count as 1."""
    nodes_by_reason: Dict[str, set] = {}
    samples: Dict[str, str] = {}

    def note(reason_text: str, node_name: str) -> None:
        slug = normalize_reason(reason_text)
        nodes_by_reason.setdefault(slug, set()).add(node_name)
        samples.setdefault(slug, reason_text)

    for errs in job.fit_errors.values():
        for node_name, fe in errs.nodes.items():
            for r in set(fe.reasons()) or {"node(s) didn't fit"}:
                note(r, node_name)
        if errs.err:
            note(errs.err, "")
    jfe = getattr(job, "job_fit_errors", None)
    if jfe is not None and jfe.err and not nodes_by_reason:
        note(jfe.err, "")
    counts = {slug: len(nodes) for slug, nodes in nodes_by_reason.items()}
    return counts, samples


TOP_K_REASONS = 8


def pending_reasons_doc(counts: Dict[str, int],
                        samples: Dict[str, str]) -> dict:
    """The podgroup-annotation / trace payload shape: top-K reasons by
    node count, with one free-text sample each (detail)."""
    top = sorted(counts.items(), key=lambda kv: (-kv[1], kv[0]))
    top = top[:TOP_K_REASONS]
    return {
        "reasons": dict(top),
        "top": top[0][0] if top else "",
        "detail": {slug: samples.get(slug, "")[:200] for slug, _ in top},
    }


# -- span model --------------------------------------------------------

MAX_CHILDREN = 128      # per span: a churn-heavy cycle caps its tree


class Span:
    __slots__ = ("name", "kind", "labels", "start", "end", "children",
                 "agg", "dropped")

    def __init__(self, name: str, kind: str, labels: Dict[str, str]):
        self.name = name
        self.kind = kind
        self.labels = labels
        self.start = time.time()
        self.end: Optional[float] = None
        self.children: List["Span"] = []
        # (point, plugin) -> [calls, total seconds]; folded into child
        # spans when this span closes
        self.agg: Dict[Tuple[str, str], list] = {}
        self.dropped = 0

    @property
    def duration(self) -> float:
        return (self.end or time.time()) - self.start

    def add_child(self, child: "Span") -> bool:
        if len(self.children) >= MAX_CHILDREN:
            self.dropped += 1
            return False
        self.children.append(child)
        return True

    def close(self) -> None:
        self.end = time.time()
        for (point, plugin), (calls, total) in sorted(self.agg.items()):
            child = Span(plugin, "plugin",
                         {"point": point, "calls": str(calls)})
            child.start = self.start
            child.end = self.start + total
            self.add_child(child)
        self.agg.clear()

    def to_dict(self) -> dict:
        doc = {"name": self.name, "kind": self.kind,
               "labels": dict(self.labels),
               "start": round(self.start, 6),
               "dur": round(self.duration, 6)}
        if self.children:
            doc["children"] = [c.to_dict() for c in self.children]
        if self.dropped:
            doc["dropped_children"] = self.dropped
        return doc


class _SpanCtx:
    """Context manager pushing/popping one span on the thread stack."""

    __slots__ = ("span",)

    def __init__(self, span: Optional[Span]):
        self.span = span

    def __enter__(self):
        return self.span

    def __exit__(self, *exc):
        if self.span is not None:
            _pop(self.span)
        return False


# -- tracer state ------------------------------------------------------

TRACE_RING = 256         # completed session traces kept in-process
SAMPLE_EVERY = 8         # 1-in-N for unremarkable sessions
_P95_WINDOW = 128        # rolling duration window for the slow gate

_tls = threading.local()
_lock = threading.Lock()
_ring: deque = deque(maxlen=TRACE_RING)
_durations: deque = deque(maxlen=_P95_WINDOW)
_pending: Dict[str, dict] = {}      # job key -> pending_reasons_doc
_seq = 0


def _stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _pop(span: Span) -> None:
    stack = _stack()
    while stack:
        top = stack.pop()
        top.close()
        if top is span:
            break


def current() -> Optional[Span]:
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def begin_session(**labels) -> Span:
    """Open a session root span on this thread (scheduler.run_once)."""
    root = Span("session", "session",
                {k: str(v) for k, v in labels.items()})
    stack = _stack()
    del stack[:]             # a leaked previous root must not nest
    stack.append(root)
    return root


def span(name: str, kind: str = "span", **labels) -> _SpanCtx:
    """Timed child span under the innermost open span; no-op (None)
    when no session is open on this thread."""
    stack = getattr(_tls, "stack", None)
    if not stack:
        return _SpanCtx(None)
    s = Span(name, kind, {k: str(v) for k, v in labels.items()})
    if stack[-1].add_child(s):
        stack.append(s)
        return _SpanCtx(s)
    return _SpanCtx(None)


def add_plugin_time(point: str, plugin: str, dt: float) -> None:
    """Accumulate one plugin-callback timing under the innermost open
    span (the hot-path aggregation lane: O(1) dict update)."""
    stack = getattr(_tls, "stack", None)
    if not stack:
        return
    agg = stack[-1].agg
    slot = agg.get((point, plugin))
    if slot is None:
        agg[(point, plugin)] = [1, dt]
    else:
        slot[0] += 1
        slot[1] += dt


def note_pending(job_key: str, counts: Dict[str, int],
                 samples: Dict[str, str]) -> dict:
    """Record a job's aggregated unschedulable reasons (called by the
    job updater once per session per blocked job).  Bumps the current
    session root's unschedulable tally for the sampling gate."""
    doc = pending_reasons_doc(counts, samples)
    with _lock:
        _pending[job_key] = doc
    stack = getattr(_tls, "stack", None)
    if stack:
        root = stack[0]
        root.labels["unschedulable_jobs"] = str(
            int(root.labels.get("unschedulable_jobs", "0")) + 1)
    top = doc["top"]
    if top:
        metrics.inc("sched_unschedulable_reasons_total", reason=top)
    return doc


def clear_pending(job_key: str) -> None:
    with _lock:
        _pending.pop(job_key, None)


def retain_pending(job_keys) -> None:
    """Drop aggregate entries for jobs no longer blocked THIS session
    (deleted jobs, jobs that placed): the job updater calls this with
    the still-blocked set each cycle so the aggregate never leaks."""
    keep = set(job_keys)
    with _lock:
        for key in [k for k in _pending if k not in keep]:
            del _pending[key]


def pending_reasons() -> Dict[str, dict]:
    """Current per-job aggregate (dumper / vtpctl explain source)."""
    with _lock:
        return {k: dict(v) for k, v in _pending.items()}


def _emit_span_metrics(root: Span) -> None:
    """sched_span_seconds observations off a finished session tree:
    action spans labeled by action, plugin aggregates by plugin+point
    (both label sets are bounded enums — registered names only)."""
    def walk(s: Span) -> None:
        if s.kind == "action":
            metrics.observe("sched_span_seconds", s.duration,
                            action=s.name)
        elif s.kind == "plugin":
            metrics.observe("sched_span_seconds", s.duration,
                            plugin=s.name,
                            point=s.labels.get("point", ""))
        for c in s.children:
            walk(c)
    walk(root)
    metrics.observe("sched_span_seconds", root.duration,
                    action="session")


def _p95(values: deque) -> float:
    if not values:
        return float("inf")
    ordered = sorted(values)
    return ordered[min(len(ordered) - 1, int(0.95 * len(ordered)))]


# per-doc embedding caps: a cluster with thousands of blocked jobs
# makes every session kept — the doc (ring entry + POST /trace body)
# must stay bounded regardless
MAX_DOC_JOBS = 256
MAX_DOC_PENDING = 64


def end_session(root: Span, jobs_pending: Optional[List[str]] = None
                ) -> Optional[dict]:
    """Close the root, emit metrics, and apply the keep policy.

    Returns the trace document when the session was kept (caller may
    publish it to the state server), else None.  Keep policy: always
    for sessions that errored, saw unschedulable jobs, or ran slower
    than the rolling p95; 1-in-SAMPLE_EVERY otherwise.
    """
    global _seq
    _pop(root)               # closes any spans left open by an error
    if root.end is None:
        root.close()
    _emit_span_metrics(root)
    dur = root.duration
    unsched = int(root.labels.get("unschedulable_jobs", "0"))
    errored = "error" in root.labels
    # federated episodes are rare (in-flight cross-region gangs) but
    # every fragment is load-bearing for the /fleet_trace stitch: a
    # sampled-away placement session would leave a hole in the
    # cross-plane tree, so episode-labelled sessions are always kept
    episodic = bool(root.labels.get("episode"))
    keys = sorted(set(jobs_pending or []))
    with _lock:
        _seq += 1
        seq = _seq
        slow = dur >= _p95(_durations) and len(_durations) >= 16
        _durations.append(dur)
        keep = errored or unsched > 0 or slow or episodic \
            or seq % SAMPLE_EVERY == 1
        if not keep:
            return None
        # embed only THIS session's jobs and their aggregates, capped:
        # the global _pending can be huge and belongs to the dumper,
        # not to every per-cycle wire payload
        pending = {k: dict(_pending[k])
                   for k in keys[:MAX_DOC_PENDING] if k in _pending}
        doc = {"seq": seq, "kept_because":
               ("error" if errored else
                "unschedulable" if unsched else
                "slow" if slow else
                "episode" if episodic else "sampled"),
               "jobs": keys[:MAX_DOC_JOBS],
               "pending": pending,
               "root": root.to_dict()}
        if len(keys) > MAX_DOC_JOBS:
            doc["jobs_truncated"] = len(keys) - MAX_DOC_JOBS
        _ring.append(doc)
    metrics.inc("sched_traces_total", kept=doc["kept_because"])
    return doc


def recent_traces(limit: int = 0, job: str = "",
                  episode: str = "") -> List[dict]:
    """Newest-last kept traces; job filters to traces that touched or
    pended the given job key, episode to this plane's fragments of
    one federated causal episode."""
    with _lock:
        out = list(_ring)
    if job:
        out = [t for t in out if matches_job(t, job)]
    if episode:
        out = [t for t in out if matches_episode(t, episode)]
    if limit:
        out = out[-limit:]
    return out


def is_complete_span(span_doc) -> bool:
    """A span tree is complete when every node carries a name and a
    duration — the single definition of the never-serve-half-a-tree
    rule (state server POST /trace gate; soak drill assertion)."""
    if not isinstance(span_doc, dict) or "dur" not in span_doc \
            or "name" not in span_doc:
        return False
    return all(is_complete_span(c)
               for c in span_doc.get("children", ()))


def matches_job(trace_doc: dict, job: str) -> bool:
    """Did this kept session trace touch / pend the given job key?"""
    return (job in trace_doc.get("jobs", [])
            or job in trace_doc.get("pending", {})
            or _mentions_job(trace_doc.get("root"), job))


def _mentions_job(span_doc: Optional[dict], job: str) -> bool:
    if not span_doc:
        return False
    if span_doc.get("labels", {}).get("job") == job:
        return True
    return any(_mentions_job(c, job)
               for c in span_doc.get("children", ()))


def matches_episode(trace_doc: dict, episode: str) -> bool:
    """Is this doc a local fragment of the given causal episode?  A
    session root may carry several episodes (comma-joined label) —
    one scheduling cycle can place gangs from distinct episodes."""
    if not episode:
        return False
    if trace_doc.get("episode") == episode:
        return True
    return _mentions_episode(trace_doc.get("root"), episode)


def _mentions_episode(span_doc: Optional[dict], episode: str) -> bool:
    if not span_doc:
        return False
    raw = span_doc.get("labels", {}).get("episode", "")
    if episode in [e.strip() for e in raw.split(",") if e.strip()]:
        return True
    return any(_mentions_episode(c, episode)
               for c in span_doc.get("children", ()))


def episode_label(episodes) -> str:
    """The bounded session-root `episode` label value: sorted unique
    comma join, capped — labels ride every trace doc, so one cycle
    placing many federated gangs must not grow an unbounded string."""
    eps = sorted({e for e in episodes if e})
    return ",".join(eps[:8])


def fragment_doc(name: str, plane: str, episode: str, start: float,
                 end: float, hop: int = 0, jobs=(), labels=None,
                 children=()) -> dict:
    """A complete single-plane episode fragment in ring-doc shape —
    how the router and controllers (which run no scheduler session)
    contribute their slice of a causal episode to /traces.  Children
    are (name, start, end) triples; everything is closed at build
    time so the state server's is_complete_span gate always passes."""
    lbl = {"plane": plane, "episode": episode, "hop": str(int(hop))}
    lbl.update(labels or {})
    end = max(end, start)
    root = {"name": name, "kind": "fragment", "labels": lbl,
            "start": start, "dur": end - start}
    kids = []
    for cname, cs, ce in children:
        kids.append({"name": cname, "kind": "span", "labels": {},
                     "start": cs, "dur": max(0.0, ce - cs)})
    if kids:
        root["children"] = kids
    return {"seq": 0, "kept_because": "episode", "episode": episode,
            "jobs": sorted(set(jobs)), "pending": {}, "root": root}


def publish(cluster, doc: Optional[dict]) -> None:
    """Best-effort POST of a kept trace to the state server's ring
    (wire mode only; in-process clusters read recent_traces())."""
    if doc is None:
        return
    request = getattr(cluster, "_request", None)
    if request is None:
        return
    try:
        request("POST", "/trace", {"trace": doc}, deadline=2.0)
    except Exception:  # noqa: BLE001 — traces are advisory telemetry
        pass


def reset() -> None:
    """Test isolation: drop ring, pending aggregate and thread stack."""
    global _seq
    with _lock:
        _ring.clear()
        _durations.clear()
        _pending.clear()
        _seq = 0
    _tls.stack = []


# -- rendering (vtpctl trace / trace_report) ---------------------------

def render_waterfall(span_doc: dict, total: Optional[float] = None,
                     indent: int = 0, width: int = 28) -> List[str]:
    """Text waterfall of one span tree: offset bars + durations."""
    lines = []
    total = total or max(span_doc.get("dur", 0.0), 1e-9)
    t0 = span_doc.get("start", 0.0)

    def walk(doc: dict, depth: int) -> None:
        off = max(0.0, doc.get("start", t0) - t0)
        dur = doc.get("dur", 0.0)
        lead = int(width * min(1.0, off / total))
        bar = max(1, int(width * min(1.0, dur / total)))
        gauge = " " * lead + "#" * min(bar, width - lead)
        label = doc.get("name", "?")
        extras = [f"{k}={v}" for k, v in sorted(
            doc.get("labels", {}).items()) if v]
        lines.append(
            f"{'  ' * depth}{label:<{max(4, 24 - 2 * depth)}} "
            f"|{gauge:<{width}}| {dur * 1e3:8.2f}ms"
            + (f"  {' '.join(extras)}" if extras else ""))
        for child in doc.get("children", ()):
            walk(child, depth + 1)
        if doc.get("dropped_children"):
            lines.append(f"{'  ' * (depth + 1)}"
                         f"(+{doc['dropped_children']} spans dropped)")

    walk(span_doc, indent)
    return lines


def to_chrome_trace(traces: List[dict]) -> dict:
    """Chrome-trace/Perfetto JSON (trace event format, complete 'X'
    events in microseconds) from a list of kept session trace docs —
    load the output at chrome://tracing or ui.perfetto.dev."""
    events = []

    def walk(doc: dict, pid: int, tid: int) -> None:
        args = {k: v for k, v in doc.get("labels", {}).items() if v}
        events.append({
            "name": doc.get("name", "?"),
            "cat": doc.get("kind", "span"),
            "ph": "X",
            "ts": round(doc.get("start", 0.0) * 1e6, 1),
            "dur": round(doc.get("dur", 0.0) * 1e6, 1),
            "pid": pid, "tid": tid, "args": args,
        })
        for child in doc.get("children", ()):
            walk(child, pid, tid)

    for i, trace in enumerate(traces):
        root = trace.get("root") or {}
        walk(root, 1, i + 1)
        events.append({"name": "thread_name", "ph": "M", "pid": 1,
                       "tid": i + 1, "args": {
                           "name": f"session seq={trace.get('seq')}"
                                   f" ({trace.get('kept_because')})"}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def dump_state() -> dict:
    """The dumper's (SIGUSR2) trace section: last-N kept traces +
    the live per-job unschedulable aggregate."""
    return {"recent_traces": recent_traces(limit=8),
            "pending_reasons": pending_reasons()}


def parse_annotation(raw: str) -> Optional[dict]:
    """Tolerant parse of the pending-reasons podgroup annotation."""
    try:
        doc = json.loads(raw)
    except (TypeError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None
