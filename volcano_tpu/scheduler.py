"""Scheduler main loop.

Reference parity: pkg/scheduler/scheduler.go:71-245 (NewScheduler, Run,
runOnce, conf hot-reload via file watching).
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import time
from typing import Optional

from volcano_tpu.cache.cache import SchedulerCache
from volcano_tpu.cache.cluster import Cluster
from volcano_tpu.conf import SchedulerConf, load_conf
from volcano_tpu.framework.framework import close_session, open_session
from volcano_tpu.framework.plugins import get_action
from volcano_tpu import goodput, metrics, trace

log = logging.getLogger(__name__)

DEFAULT_SCHEDULE_PERIOD = 1.0


class Scheduler:
    def __init__(self, cluster: Cluster, conf=None,
                 conf_path: Optional[str] = None,
                 schedule_period: float = DEFAULT_SCHEDULE_PERIOD,
                 scheduler_name: str = "volcano-tpu",
                 shard_index: Optional[int] = None,
                 shard_count: Optional[int] = None):
        self.cluster = cluster
        self.cache = SchedulerCache(cluster, scheduler_name)
        self.conf_path = conf_path
        self._conf_mtime = 0.0
        # subtree-partition identity (--shard-index/--shard-count):
        # survives conf hot-reloads by being re-applied in _load, so a
        # reloaded file can change plugins but not silently merge two
        # shards onto one subtree
        self._shard = (shard_index, shard_count) \
            if shard_index is not None and shard_count else None
        self.conf: SchedulerConf = self._load(conf)
        self.schedule_period = schedule_period
        self._stop = threading.Event()
        self.cycles = 0

    def _load(self, conf) -> SchedulerConf:
        if self.conf_path and os.path.exists(self.conf_path):
            self._conf_mtime = os.path.getmtime(self.conf_path)
            with open(self.conf_path) as f:
                loaded = load_conf(f.read())
        else:
            loaded = load_conf(conf)
        if self._shard is not None:
            idx, count = self._shard
            alloc = loaded.configurations.setdefault("allocate", {})
            alloc["shard-mode"] = "subtree"
            alloc["shard-index"] = idx
            alloc["shard-count"] = count
        return loaded

    def _maybe_reload_conf(self):
        """Hot reload on file change (scheduler.go:219-245)."""
        if not self.conf_path or not os.path.exists(self.conf_path):
            return
        mtime = os.path.getmtime(self.conf_path)
        if mtime != self._conf_mtime:
            log.info("scheduler conf changed, reloading")
            self.conf = self._load(None)   # re-applies shard identity

    def run_once(self):
        """One scheduling cycle (scheduler.go runOnce).  The whole
        cycle runs under a trace root span: open/close and every
        action are timed children, plugin callbacks aggregate under
        whichever span is innermost when they fire (trace.py)."""
        self._maybe_reload_conf()
        start = time.perf_counter()
        root = trace.begin_session(cycle=self.cycles)
        shard_conf = self.conf.configurations.get("allocate", {})
        if str(shard_conf.get("shard-mode", "")) == "subtree":
            # vtpctl shards reads per-shard cycle time off /traces by
            # this label; the conductor REPRODUCE line replays it
            root.labels["shard"] = (f"{shard_conf.get('shard-index', 0)}"
                                    f"/{shard_conf.get('shard-count', 1)}")
        ssn = None
        try:
            with trace.span("open_session", kind="action"):
                ssn = open_session(self.cache, self.conf)
            root.labels["session"] = ssn.uid
            # federated causal episodes riding this session's gangs
            # (podgroup annotation inherited from the router's
            # regional copy): the label makes this session a
            # /traces?episode= fragment the fleet stitcher can pull.
            # Only NOT-YET-RUNNING gangs qualify — once the gang runs,
            # later cycles are steady-state housekeeping, and labeling
            # them would extend the stitched episode wall past the
            # actual submit->running interval forever
            from volcano_tpu.api import federation as fedapi
            from volcano_tpu.api.types import PodGroupPhase
            episodic = [j.podgroup for j in ssn.jobs.values()
                        if j.podgroup is not None
                        and j.podgroup.phase in (PodGroupPhase.PENDING,
                                                 PodGroupPhase.INQUEUE)
                        and fedapi.episode_of(j.podgroup)]
            eps = trace.episode_label(
                fedapi.episode_of(pg) for pg in episodic)
            if eps:
                root.labels["episode"] = eps
                # the hop must be stamped HERE, off the gang's own
                # annotation: the stitcher's fallback is the global
                # job's CURRENT hop, which after a cutover would drag
                # this region's old admission-time sessions into the
                # destination's hop group and clamp-shift the whole
                # group forward past the real wall time
                root.labels["hop"] = str(min(
                    fedapi.episode_hop(pg) for pg in episodic))
            for name in self.conf.actions:
                action = get_action(name)
                if action is None:
                    log.warning("unknown action %s (skipped)", name)
                    continue
                t0 = time.perf_counter()
                with trace.span(name, kind="action"):
                    action.execute(ssn)
                metrics.observe("action_latency_seconds",
                                time.perf_counter() - t0, action=name)
            # goodput observatory: per-session fragmentation /
            # starvation / fleet-throughput gauges off the post-action
            # state (one O(nodes)+O(jobs) pass; volcano_tpu/goodput.py).
            # Degrade-don't-crash: a metrics-only bug must never stop
            # scheduling — same posture as the agent-side handlers.
            try:
                with trace.span("observe", kind="action"):
                    goodput.observe_session(ssn)
            except Exception:  # noqa: BLE001
                log.exception("goodput session observation failed")
        finally:
            # a cycle that crashed ANYWHERE (open_session, an action,
            # close_session below) is exactly what the recorder must
            # capture: label it so the keep policy always records it
            exc = sys.exc_info()[1]
            if exc is not None:
                root.labels["error"] = type(exc).__name__
            jobs_pending = []
            try:
                if ssn is not None:
                    with trace.span("close_session", kind="action"):
                        close_session(ssn)
                    jobs_pending = list(ssn.touched_jobs
                                        | ssn.dirty_jobs)
            finally:
                exc = sys.exc_info()[1]
                if exc is not None and "error" not in root.labels:
                    root.labels["error"] = type(exc).__name__
                doc = trace.end_session(root,
                                        jobs_pending=jobs_pending)
                trace.publish(self.cache.cluster, doc)
        self.cycles += 1
        metrics.observe("e2e_scheduling_latency_seconds",
                        time.perf_counter() - start)
        return ssn

    def run(self, max_cycles: Optional[int] = None):
        while not self._stop.is_set():
            self.run_once()
            if max_cycles is not None and self.cycles >= max_cycles:
                break
            self._stop.wait(self.schedule_period)

    def stop(self):
        self._stop.set()
