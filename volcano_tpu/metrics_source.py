"""External usage metrics sources for the node agent / usage plugin.

Reference parity: pkg/scheduler/metrics/source
(metrics_client_{prometheus,elasticsearch}.go) — pulls real node
utilization from a metrics backend.  Here the Prometheus client reads
exposition-format text over HTTP and feeds the agent's UsageProvider
protocol; metric names are configurable:

    node_cpu_usage_fraction{node="sa-w0"} 0.42
    node_memory_usage_fraction{node="sa-w0"} 0.61
"""

from __future__ import annotations

import logging
import re
import urllib.request
from typing import Dict, Tuple

from volcano_tpu.agent.agent import NodeUsage, UsageProvider

log = logging.getLogger(__name__)

# 'name{labels} value [timestamp]' — federation endpoints append the
# millisecond timestamp
_SAMPLE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)\{(?P<labels>[^}]*)\}\s+'
    r'(?P<value>[-+0-9.eEna]+)(?:\s+\d+)?\s*$')
_LABEL = re.compile(r'(\w+)="([^"]*)"')


def parse_exposition(text: str) -> Dict[Tuple[str, str], float]:
    """{(metric, node): value} for node-labeled samples."""
    out: Dict[Tuple[str, str], float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE.match(line)
        if not m:
            continue
        labels = dict(_LABEL.findall(m.group("labels")))
        node = labels.get("node") or labels.get("instance")
        if not node:
            continue
        try:
            out[(m.group("name"), node)] = float(m.group("value"))
        except ValueError:
            continue
    return out


class PrometheusUsageSource(UsageProvider):
    """Scrapes a Prometheus-format endpoint for per-node usage."""

    def __init__(self, url: str,
                 cpu_metric: str = "node_cpu_usage_fraction",
                 mem_metric: str = "node_memory_usage_fraction",
                 timeout: float = 2.0,
                 stale_after: float = 60.0):
        self.url = url
        self.cpu_metric = cpu_metric
        self.mem_metric = mem_metric
        self.timeout = timeout
        self.stale_after = stale_after
        self._samples: Dict[Tuple[str, str], float] = {}
        self._last_success = 0.0

    def refresh(self) -> bool:
        import time
        try:
            with urllib.request.urlopen(self.url,
                                        timeout=self.timeout) as resp:
                self._samples = parse_exposition(resp.read().decode())
            self._last_success = time.time()
            return True
        except Exception as e:  # noqa: BLE001 - degrade, don't crash
            log.warning("usage scrape of %s failed: %s", self.url, e)
            return False

    def usage(self, node_name: str) -> NodeUsage:
        import time
        if time.time() - self._last_success > self.stale_after:
            # bound the damage of a dead endpoint: past the TTL report
            # "unknown" (zeros) rather than acting on stale pressure
            return NodeUsage()
        return NodeUsage(
            cpu_fraction=self._samples.get(
                (self.cpu_metric, node_name), 0.0),
            memory_fraction=self._samples.get(
                (self.mem_metric, node_name), 0.0),
        )
