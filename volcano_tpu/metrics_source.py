"""External usage metrics sources for the node agent / usage plugin.

Reference parity: pkg/scheduler/metrics/source
(metrics_client_{prometheus,elasticsearch}.go) — pulls real node
utilization from a metrics backend.  Here the Prometheus client reads
exposition-format text over HTTP and feeds the agent's UsageProvider
protocol; metric names are configurable:

    node_cpu_usage_fraction{node="sa-w0"} 0.42
    node_memory_usage_fraction{node="sa-w0"} 0.61
"""

from __future__ import annotations

import logging
import re
import urllib.request
from typing import Dict, Tuple

from volcano_tpu.agent.agent import NodeUsage, UsageProvider

log = logging.getLogger(__name__)

# 'name{labels} value [timestamp]' — federation endpoints append the
# millisecond timestamp
_SAMPLE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)\{(?P<labels>[^}]*)\}\s+'
    r'(?P<value>[-+0-9.eEna]+)(?:\s+\d+)?\s*$')
_LABEL = re.compile(r'(\w+)="([^"]*)"')


def parse_exposition(text: str) -> Dict[Tuple[str, str], float]:
    """{(metric, node): value} for node-labeled samples."""
    out: Dict[Tuple[str, str], float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE.match(line)
        if not m:
            continue
        labels = dict(_LABEL.findall(m.group("labels")))
        node = labels.get("node") or labels.get("instance")
        if not node:
            continue
        try:
            out[(m.group("name"), node)] = float(m.group("value"))
        except ValueError:
            continue
    return out


class ElasticsearchUsageSource(UsageProvider):
    """Queries an Elasticsearch metricbeat-style index for per-node
    usage (reference: metrics_client_elasticsearch.go — avg of
    system.cpu/memory pct over a trailing window, one search per
    refresh).

    Issues one `_search` POST with a terms-by-hostname aggregation and
    avg sub-aggregations, so a cluster of N nodes costs one round trip:

        POST {url}/{index}/_search
        {"size": 0, "query": {"range": {"@timestamp": {"gte": "now-Xs"}}},
         "aggs": {"nodes": {"terms": {"field": "host.hostname", ...},
                  "aggs": {"cpu": {"avg": {"field": <cpu_field>}},
                           "mem": {"avg": {"field": <mem_field>}}}}}}
    """

    def __init__(self, url: str, index: str = "metricbeat-*",
                 cpu_field: str = "system.cpu.total.norm.pct",
                 mem_field: str = "system.memory.actual.used.pct",
                 hostname_field: str = "host.hostname",
                 window_s: float = 300.0,
                 timeout: float = 5.0,
                 stale_after: float = 120.0):
        self.url = url.rstrip("/")
        self.index = index
        self.cpu_field = cpu_field
        self.mem_field = mem_field
        self.hostname_field = hostname_field
        self.window_s = window_s
        self.timeout = timeout
        self.stale_after = stale_after
        self._usage: Dict[str, NodeUsage] = {}
        self._last_success = 0.0

    def _query(self) -> bytes:
        import json
        return json.dumps({
            "size": 0,
            "query": {"range": {"@timestamp": {
                "gte": f"now-{int(self.window_s)}s"}}},
            "aggs": {"nodes": {
                "terms": {"field": self.hostname_field, "size": 10000},
                "aggs": {"cpu": {"avg": {"field": self.cpu_field}},
                         "mem": {"avg": {"field": self.mem_field}}}}},
        }).encode()

    def refresh(self) -> bool:
        import json
        import time
        req = urllib.request.Request(
            f"{self.url}/{self.index}/_search", data=self._query(),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                body = json.loads(resp.read().decode())
        except Exception as e:  # noqa: BLE001 - degrade, don't crash
            log.warning("es usage query to %s failed: %s", self.url, e)
            return False
        usage: Dict[str, NodeUsage] = {}
        buckets = (body.get("aggregations", {})
                   .get("nodes", {}).get("buckets", []))
        for b in buckets:
            name = b.get("key")
            if not name:
                continue
            cpu = (b.get("cpu") or {}).get("value")
            mem = (b.get("mem") or {}).get("value")
            usage[name] = NodeUsage(
                cpu_fraction=float(cpu) if cpu is not None else 0.0,
                memory_fraction=float(mem) if mem is not None else 0.0)
        self._usage = usage
        self._last_success = time.time()
        return True

    def usage(self, node_name: str) -> NodeUsage:
        import time
        if time.time() - self._last_success > self.stale_after:
            # same TTL contract as the Prometheus source: a dead
            # backend must read as "unknown", never as stale pressure
            return NodeUsage()
        return self._usage.get(node_name, NodeUsage())


class PrometheusUsageSource(UsageProvider):
    """Scrapes a Prometheus-format endpoint for per-node usage."""

    def __init__(self, url: str,
                 cpu_metric: str = "node_cpu_usage_fraction",
                 mem_metric: str = "node_memory_usage_fraction",
                 timeout: float = 2.0,
                 stale_after: float = 60.0):
        self.url = url
        self.cpu_metric = cpu_metric
        self.mem_metric = mem_metric
        self.timeout = timeout
        self.stale_after = stale_after
        self._samples: Dict[Tuple[str, str], float] = {}
        self._last_success = 0.0

    def refresh(self) -> bool:
        import time
        try:
            with urllib.request.urlopen(self.url,
                                        timeout=self.timeout) as resp:
                self._samples = parse_exposition(resp.read().decode())
            self._last_success = time.time()
            return True
        except Exception as e:  # noqa: BLE001 - degrade, don't crash
            log.warning("usage scrape of %s failed: %s", self.url, e)
            return False

    def usage(self, node_name: str) -> NodeUsage:
        import time
        if time.time() - self._last_success > self.stale_after:
            # bound the damage of a dead endpoint: past the TTL report
            # "unknown" (zeros) rather than acting on stale pressure
            return NodeUsage()
        return NodeUsage(
            cpu_fraction=self._samples.get(
                (self.cpu_metric, node_name), 0.0),
            memory_fraction=self._samples.get(
                (self.mem_metric, node_name), 0.0),
        )
