"""Cache dumper — SIGUSR2-triggered JSON dump of scheduler state.

Reference parity: pkg/scheduler/cache/dumper.go (+ the unix-socket
klog-level endpoint, pkg/scheduler/util.go:95 — here exposed as
set_log_level()).
"""

from __future__ import annotations

import json
import logging
import signal
import time

log = logging.getLogger(__name__)


def snapshot_to_dict(snapshot) -> dict:
    return {
        "timestamp": time.time(),
        "nodes": {
            name: {
                "idle": node.idle.to_dict(),
                "used": node.used.to_dict(),
                "releasing": node.releasing.to_dict(),
                "pipelined": node.pipelined.to_dict(),
                "tasks": sorted(t.key for t in node.tasks.values()),
                "bind_generation": node.bind_generation,
            } for name, node in snapshot.nodes.items()
        },
        "jobs": {
            job.key: {
                "queue": job.queue,
                "min_available": job.min_available,
                "ready": job.ready_task_num(),
                "tasks": {t.key: {"status": t.status.value,
                                  "node": t.node_name}
                          for t in job.tasks.values()},
                "sub_jobs": {name: {"allocated": s.allocated_hypernode,
                                    "nominated": s.nominated_hypernode}
                             for name, s in job.sub_jobs.items()},
            } for job in snapshot.jobs.values()
        },
        "queues": sorted(snapshot.queues),
        "hypernodes": {
            name: {"tier": info.tier, "nodes": sorted(info.nodes)}
            for name, info in (snapshot.hypernodes.members.items()
                               if snapshot.hypernodes else {}.items())
        },
    }


class Dumper:
    """Dump the scheduler's latest snapshot to disk on SIGUSR2."""

    def __init__(self, scheduler, path: str = "/tmp/volcano-tpu-dump.json"):
        self.scheduler = scheduler
        self.path = path

    def dump(self) -> str:
        from volcano_tpu import trace
        snapshot = self.scheduler.cache.snapshot()
        payload = snapshot_to_dict(snapshot)
        # flight-recorder section: the last kept session span trees
        # and the live per-job unschedulable-reason aggregate, so a
        # wedged scheduler is diagnosable post-hoc from ONE artifact
        # (what was it doing, and why is work pending)
        payload["trace"] = trace.dump_state()
        # goodput observatory section: the learned per-(job,
        # generation) throughput vectors and per-world-size rates —
        # what the grow gate and (later) a Gavel policy would decide
        # from (volcano_tpu/goodput.py)
        book = getattr(self.scheduler.cache, "goodput_book", None)
        if book is not None:
            payload["goodput"] = book.dump_state()
        with open(self.path, "w") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
        log.info("cache dumped to %s", self.path)
        return self.path

    def listen_for_signal(self):
        signal.signal(signal.SIGUSR2, lambda *_: self.dump())


def set_log_level(level: str):
    """Runtime log-level change (klog socket analogue)."""
    logging.getLogger("volcano_tpu").setLevel(
        getattr(logging, level.upper(), logging.INFO))
