"""Queue CRD type (scheduling/v1beta1 Queue analogue).

Reference parity: staging/.../scheduling/v1beta1/types.go:459-519
(weight, capability, reclaimable, guarantee, deserved, priority, parent,
dequeue strategy) + Queue status state machine.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from volcano_tpu.api.pod import new_uid
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import QueueState

DEQUEUE_FIFO = "fifo"
DEQUEUE_TRAVERSE = "traverse"


@dataclass
class Queue:
    name: str
    uid: str = field(default_factory=new_uid)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)

    # spec
    weight: int = 1
    capability: Optional[Resource] = None      # hard cap (unset dim = unlimited)
    guarantee: Optional[Resource] = None       # floor reserved for this queue
    deserved: Optional[Resource] = None        # capacity-plugin deserved share
    reclaimable: bool = True
    priority: int = 0
    parent: str = ""                           # hierarchical queues
    # reference default is traverse (types.go:503,519): a blocked head
    # job does NOT starve the rest of the queue unless fifo is chosen
    dequeue_strategy: str = DEQUEUE_TRAVERSE

    # status
    state: QueueState = QueueState.OPEN
    creation_time: float = field(default_factory=time.time)

    @property
    def key(self) -> str:
        return self.name

    def is_open(self) -> bool:
        return self.state == QueueState.OPEN

    def clone(self) -> "Queue":
        import copy
        return copy.deepcopy(self)
