"""Multi-dimensional resource vectors.

Reference parity: pkg/scheduler/api/resource_info.go (Resource with
MilliCPU/Memory/ScalarResources).  Rebuilt as a single flat mapping of
resource-name -> float; CPU is counted in millicores and memory in bytes
to match the reference's accounting conventions, and TPU chips live in
the same mapping under ``google.com/tpu`` so every fair-share / fit /
preemption computation treats chips exactly like any other dimension.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Mapping, Optional

CPU = "cpu"          # millicores
MEMORY = "memory"    # bytes
PODS = "pods"        # pod-count capacity
TPU = "google.com/tpu"  # TPU chips

# Comparison slack: resource quantities are floats; mirror the reference's
# minResource epsilon (resource_info.go minResource = 0.1).
MIN_RESOURCE = 0.1

_UNITS = {
    "k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12, "P": 1e15,
    "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50,
}
_SORTED_UNITS = sorted(_UNITS.items(), key=lambda kv: -len(kv[0]))


def parse_quantity(value) -> float:
    """Parse a k8s-style quantity ("250m", "4Gi", 2) into a float.

    CPU "m" suffix means millicores; callers decide whether the dimension
    is milli-scaled (see :func:`parse_cpu`).
    """
    if isinstance(value, (int, float)):
        return float(value)
    s = str(value).strip()
    if not s:
        return 0.0
    for suffix, mult in _SORTED_UNITS:
        if s.endswith(suffix):
            return float(s[: -len(suffix)]) * mult
    if s.endswith("m"):
        return float(s[:-1]) / 1000.0
    return float(s)


def parse_cpu(value) -> float:
    """Parse CPU quantity into millicores ("250m" -> 250, "2" -> 2000)."""
    if isinstance(value, (int, float)):
        return float(value) * 1000.0
    s = str(value).strip()
    if s.endswith("m"):
        return float(s[:-1])
    return parse_quantity(s) * 1000.0


class Resource:
    """A resource vector: {resource-name: amount}.

    Zero-valued dimensions are dropped eagerly so emptiness checks and
    iteration stay O(active dimensions).
    """

    __slots__ = ("res",)

    def __init__(self, res: Optional[Mapping[str, float]] = None):
        self.res: Dict[str, float] = {}
        if res:
            for name, value in res.items():
                if value:
                    self.res[name] = float(value)

    # -- constructors -------------------------------------------------

    @classmethod
    def from_resource_list(cls, rl: Mapping[str, object]) -> "Resource":
        """Build from a k8s-style resource list with string quantities.

        e.g. {"cpu": "250m", "memory": "1Gi", "google.com/tpu": 4}.
        """
        r = cls()
        for name, value in rl.items():
            if name == CPU:
                r.res[CPU] = parse_cpu(value)
            else:
                r.res[name] = parse_quantity(value)
            if not r.res[name]:
                del r.res[name]
        return r

    def clone(self) -> "Resource":
        c = Resource.__new__(Resource)
        c.res = dict(self.res)
        return c

    @classmethod
    def empty(cls) -> "Resource":
        return cls()

    # -- accessors ----------------------------------------------------

    def get(self, name: str) -> float:
        return self.res.get(name, 0.0)

    @property
    def milli_cpu(self) -> float:
        return self.res.get(CPU, 0.0)

    @property
    def memory(self) -> float:
        return self.res.get(MEMORY, 0.0)

    @property
    def tpu(self) -> float:
        return self.res.get(TPU, 0.0)

    def resource_names(self) -> List[str]:
        return list(self.res.keys())

    def is_empty(self) -> bool:
        return all(v < MIN_RESOURCE for v in self.res.values())

    def is_zero(self, name: str) -> bool:
        return self.res.get(name, 0.0) < MIN_RESOURCE

    # -- arithmetic (in place, returning self — matches reference style)

    def add(self, other: "Resource") -> "Resource":
        for name, value in other.res.items():
            self.res[name] = self.res.get(name, 0.0) + value
        return self

    def sub(self, other: "Resource") -> "Resource":
        """Subtract; raises if other is not <= self (reference panics)."""
        if not other.less_equal(self):
            raise ValueError(f"resource underflow: {other} > {self}")
        return self.sub_unchecked(other)

    def sub_unchecked(self, other: "Resource") -> "Resource":
        """Subtract clamping at zero (reference sub without assert)."""
        for name, value in other.res.items():
            left = self.res.get(name, 0.0) - value
            if left > 0:
                self.res[name] = left
            else:
                self.res.pop(name, None)
        return self

    def multi(self, ratio: float) -> "Resource":
        for name in list(self.res):
            self.res[name] *= ratio
        return self

    def set_max(self, other: "Resource") -> "Resource":
        """Per-dimension max (reference SetMaxResource)."""
        for name, value in other.res.items():
            if value > self.res.get(name, 0.0):
                self.res[name] = value
        return self

    def min_dim(self, other: "Resource") -> "Resource":
        """Per-dimension min over the union of dimensions."""
        for name in list(self.res):
            self.res[name] = min(self.res[name], other.res.get(name, 0.0))
            if not self.res[name]:
                del self.res[name]
        return self

    # -- comparisons --------------------------------------------------

    def less_equal(self, other: "Resource", zero: str = "defaultZero") -> bool:
        """self <= other per dimension.

        zero="defaultZero": dimensions missing from *other* are treated as
        zero (strict).  zero="defaultInfinity": dimensions missing from
        *other* are unconstrained — used for queue capability checks where
        an unset capability means unlimited (resource_info.go LessEqual
        with defaultValue semantics).
        """
        for name, value in self.res.items():
            if name not in other.res:
                if zero == "defaultInfinity":
                    continue
                if value >= MIN_RESOURCE:
                    return False
            elif value > other.res[name] + MIN_RESOURCE:
                return False
        return True

    def less_equal_strict(self, other: "Resource") -> bool:
        return self.less_equal(other, zero="defaultZero")

    def less_partly(self, other: "Resource") -> bool:
        """True if ANY dimension of self < the same dimension of other."""
        for name, value in other.res.items():
            if self.res.get(name, 0.0) < value - MIN_RESOURCE:
                return True
        return False

    def less_equal_with_dimensions(self, other: "Resource",
                                   dims: Iterable[str]) -> bool:
        return all(self.res.get(d, 0.0) <= other.res.get(d, 0.0) + MIN_RESOURCE
                   for d in dims)

    def diff(self, other: "Resource") -> ("Resource", "Resource"):
        """Return (increased, decreased) per-dimension deltas."""
        inc, dec = Resource(), Resource()
        for name in set(self.res) | set(other.res):
            d = self.res.get(name, 0.0) - other.res.get(name, 0.0)
            if d > 0:
                inc.res[name] = d
            elif d < 0:
                dec.res[name] = -d
        return inc, dec

    def fit_delta(self, req: "Resource") -> "Resource":
        """Dimensions in which *req* does not fit into self (for FitError)."""
        missing = Resource()
        for name, value in req.res.items():
            have = self.res.get(name, 0.0)
            if value > have + MIN_RESOURCE:
                missing.res[name] = value - have
        return missing

    def equal(self, other: "Resource") -> bool:
        for name in set(self.res) | set(other.res):
            if abs(self.res.get(name, 0.0) - other.res.get(name, 0.0)) >= MIN_RESOURCE:
                return False
        return True

    # -- python protocol ----------------------------------------------

    def __eq__(self, other) -> bool:
        return isinstance(other, Resource) and self.equal(other)

    def __hash__(self):  # resources are mutable; identity hash
        return id(self)

    def __repr__(self) -> str:
        parts = ", ".join(f"{k}={v:g}" for k, v in sorted(self.res.items()))
        return f"Resource({parts})"

    def to_dict(self) -> Dict[str, float]:
        return dict(self.res)
