"""Goodput wire types — measure what the scheduler allocates.

The elastic/failover subsystems (PRs 3/6) can *move* chips; nothing so
far measures what the workloads DO with them.  These types carry the
measurement half of the Pollux/Gavel loop (arxiv 2008.12260 /
2008.09213):

  workload   workers publish step progress (step counter, examples,
             wall timestamp, restart/resize epoch) to a per-pod
             progress file — workloads/progress.py writes it, the
             jax job plugin injects its path as VTP_PROGRESS_FILE;

  agent      the GoodputCollector (agent/collect.py) turns progress
             into EWMA step rates and productive-vs-allocated time
             accounting; the GoodputHandler posts one GoodputReport
             per node per sync (change-elided);

  store      the report is folded into PODGROUP annotations (the
             per-job summary every watch mirror sees, same pattern as
             BandwidthReport -> node annotations), accumulating
             allocated/productive pod-seconds so goodput =
             productive / allocated reconciles with wall-clock
             chip-residency.  Drains, failover MTTR and restore ramps
             debit it: chips held while the step counter stalls are
             allocated-but-unproductive time;

  scheduler  the cache folds annotated rates into an online
             per-(job, slice-generation) throughput-vector estimator
             (volcano_tpu/goodput.py) keyed by the node generation
             label below — the substrate Gavel-style policy reads
             (observation-only in this PR; policy stays later).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

# -- TPU generation attribute ------------------------------------------
# Node label naming the hardware generation; the simulator stamps it
# from the accelerator kind, real deployments inherit it from the node
# pool.  Metric labels use ONLY the bounded enum below — an unknown
# generation string maps to "other", never mints a new series.
GENERATION_LABEL = "volcano-tpu.io/tpu-generation"
GENERATIONS = ("v2", "v3", "v4", "v5e", "v5p", "v6e", "other")

# GKE accelerator name -> generation (the derivation used when the
# label is absent; cloud.google.com/gke-tpu-accelerator values)
_ACCELERATOR_GENERATION = {
    "tpu-v2-podslice": "v2",
    "tpu-v3-podslice": "v3",
    "tpu-v4-podslice": "v4",
    "tpu-v5-lite-podslice": "v5e",
    "tpu-v5p-slice": "v5p",
    "tpu-v6e-slice": "v6e",
}

ACCELERATOR_LABEL = "cloud.google.com/gke-tpu-accelerator"


def generation_of(labels: Dict[str, str]) -> str:
    """A node's generation as the BOUNDED enum value: the explicit
    label wins, else derived from the GKE accelerator name, else
    "other".  Never returns a string outside GENERATIONS."""
    gen = (labels or {}).get(GENERATION_LABEL, "")
    if not gen:
        gen = _ACCELERATOR_GENERATION.get(
            (labels or {}).get(ACCELERATOR_LABEL, ""), "")
    return gen if gen in GENERATIONS else "other"


# -- workload progress contract ----------------------------------------
# Env injected by the jax job plugin when the vcjob declares a
# progress dir (annotation below): the file THIS worker writes its
# progress record to, and the restart/resize epoch stamped by the
# control plane (failover generation + elastic generation) so the
# collector can tell a resumed worker from a rolled-back counter.
ENV_PROGRESS_FILE = "VTP_PROGRESS_FILE"
ENV_EPOCH = "VTP_EPOCH"
# Job annotation (submitter): directory workers publish progress
# under; one file per pod, named PROGRESS_FILE_PREFIX + <pod uid> +
# ".json" — the same uid-keyed-dir convention the enforcer/net
# accounting use for cgroups.
PROGRESS_DIR_ANNOTATION = "goodput.volcano-tpu.io/progress-dir"
PROGRESS_FILE_PREFIX = "vtp-"
PROGRESS_FILE_SUFFIX = ".json"

# Progress record fields (JSON object, atomically replaced per step):
#   step      int   global optimizer step (monotonic per epoch)
#   examples  float cumulative examples/tokens processed
#   ts        float wall-clock seconds of the last step
#   epoch     int   restart/resize epoch (VTP_EPOCH passthrough)


def progress_file_for(root: str, uid: str) -> str:
    import os
    return os.path.join(
        root, f"{PROGRESS_FILE_PREFIX}{uid}{PROGRESS_FILE_SUFFIX}")


# -- pod-level annotations (written by the agent's GoodputHandler) -----
POD_STEP_ANNOTATION = "goodput.volcano-tpu.io/step"
POD_STEP_RATE_ANNOTATION = "goodput.volcano-tpu.io/steps-per-s"

# -- podgroup-level annotations (folded from GoodputReport by the
#    STORE, so every watch mirror sees the per-job summary via
#    ordinary podgroup events) -----------------------------------------
PG_STEP_ANNOTATION = "goodput.volcano-tpu.io/step"
PG_STEP_RATE_ANNOTATION = "goodput.volcano-tpu.io/steps-per-s"
PG_EXAMPLES_RATE_ANNOTATION = "goodput.volcano-tpu.io/examples-per-s"
PG_GOODPUT_ANNOTATION = "goodput.volcano-tpu.io/goodput"
# Cumulative pod-residency accounting (pod-seconds; multiply by
# chips-per-pod for chip-seconds).  ACCUMULATED across reports — each
# report carries only the deltas since the node's previous report, so
# several nodes hosting one gang never double-count.
PG_ALLOCATED_S_ANNOTATION = "goodput.volcano-tpu.io/allocated-pod-s"
PG_PRODUCTIVE_S_ANNOTATION = "goodput.volcano-tpu.io/productive-pod-s"
PG_GENERATION_ANNOTATION = "goodput.volcano-tpu.io/generation"
PG_EPOCH_ANNOTATION = "goodput.volcano-tpu.io/epoch"
PG_UPDATED_TS_ANNOTATION = "goodput.volcano-tpu.io/updated-ts"

# every accumulated/maxed fold key, for the sticky re-apply
# (cache/fake_cluster.py): a whole-podgroup write from a mirror that
# predates a fold must not erase the accounting
PG_FOLD_KEYS = (
    PG_STEP_ANNOTATION, PG_STEP_RATE_ANNOTATION,
    PG_EXAMPLES_RATE_ANNOTATION, PG_GOODPUT_ANNOTATION,
    PG_ALLOCATED_S_ANNOTATION, PG_PRODUCTIVE_S_ANNOTATION,
    PG_GENERATION_ANNOTATION, PG_EPOCH_ANNOTATION,
    PG_UPDATED_TS_ANNOTATION,
)


def ann_float(obj_or_ann, key: str, default: float = 0.0) -> float:
    """Tolerant float read of an annotation (podgroup or dict)."""
    ann = getattr(obj_or_ann, "annotations", obj_or_ann) or {}
    try:
        return float(ann.get(key, default))
    except (TypeError, ValueError):
        return default


@dataclass
class PodGoodput:
    """One pod's measured training progress, as the agent saw it."""

    pod_key: str = ""            # ns/name
    uid: str = ""
    job: str = ""                # owning podgroup key (ns/name)
    generation: str = "other"    # node generation (bounded enum)
    epoch: int = 0               # restart/resize epoch of the record
    step: int = 0                # last observed global step
    steps_per_s: float = 0.0     # windowed EWMA step rate
    examples_per_s: float = 0.0
    goodput: float = 0.0         # cumulative productive/allocated
    # CUMULATIVE ledger (seconds over this pod's lifetime on this
    # node).  The store folds the per-pod diff against the node's
    # previous report, so a re-posted report after a lost ack is
    # idempotent — deltas on the wire would double-count whenever the
    # server folded a report whose response never arrived.
    allocated_s: float = 0.0
    productive_s: float = 0.0
    stalled: bool = False        # allocated but no step progress


@dataclass
class GoodputReport:
    """Per-node progress summary the agent posts to the state server
    (one per sync, change-elided; keyed by node like BandwidthReport)."""

    node: str = ""
    ts: float = 0.0
    usages: List[PodGoodput] = field(default_factory=list)

    @property
    def name(self) -> str:       # kinds.py keys goodputreport by name
        return self.node
