"""QueueInfo — scheduler-side queue view.

Reference parity: pkg/scheduler/api/queue_info.go:36.
"""

from __future__ import annotations

from typing import Dict, Optional

from volcano_tpu.api.queue import Queue
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import QueueState


class QueueInfo:
    def __init__(self, queue: Queue):
        self.queue = queue
        self.name = queue.name
        self.uid = queue.uid
        self.weight = max(1, queue.weight)
        self.reclaimable = queue.reclaimable
        self.priority = queue.priority
        self.parent = queue.parent

    @property
    def capability(self) -> Optional[Resource]:
        return self.queue.capability

    @property
    def guarantee(self) -> Resource:
        return self.queue.guarantee.clone() if self.queue.guarantee else Resource()

    @property
    def deserved_spec(self) -> Optional[Resource]:
        return self.queue.deserved

    def is_open(self) -> bool:
        return self.queue.state == QueueState.OPEN

    def is_leaf(self, all_queues: Dict[str, "QueueInfo"]) -> bool:
        return not any(q.parent == self.name for q in all_queues.values())

    def clone(self) -> "QueueInfo":
        return QueueInfo(self.queue)

    def __repr__(self):
        return f"QueueInfo({self.name}, weight={self.weight})"
