"""Federation-tier annotation schema + region-record helpers.

The federation tier treats N regional control planes as one fungible
accelerator pool behind ONE global queue (Singularity's global
scheduler, arxiv 2202.07848).  The moving parts:

  global store   an ordinary durable state server holding the global
                 job queue (vcjobs) plus the REGION REGISTRY (the
                 `region` dict-kind below: name -> record).  It runs
                 no scheduler and no regional controllers — regions
                 keep their existing planes unchanged.

  router         federation/router.py: admits unadmitted global jobs
                 into the region scoring best on learned
                 goodput-per-generation x capacity x price x data
                 locality, folds regional phase back onto the global
                 record, requeues gangs out of lost regions, and
                 drives cross-region migration (the PR-6 elastic
                 checkpoint/resume drain pointed at another region).

  mirror         federation/mirror.py: the PR-9 WAL-shipping lane
                 reused as an ASYNC object mirror (`/wal?mirror=1` —
                 advertised staleness, never part of the commit
                 quorum) so job records and checkpoint metadata are
                 readable in the destination region before cutover.

Contract (who writes what):

  submitter   `data-locality` (preferred regions, comma list) on the
              GLOBAL job; everything else a normal vcjob.
  router      stamps `admission-key` (deterministic — survives a
              router restart mid-admission), `admitted-region` +
              `admitted-ts` on the global job; stamps `home` (the
              global job key) + `origin-region` on the REGIONAL copy;
              folds the regional phase into `regional-phase`.
  elastic     an `evacuate` resize decision (api/elastic.py
              RESIZE_EVACUATE) drains the gang via the checkpointed
              restart; the `evacuated` hold annotation parks the
              drained gang so the source scheduler never re-places it
              while the router cuts it over.
"""

from __future__ import annotations

import hashlib
import time
from typing import List, Optional

# -- global job (router <-> submitter) ---------------------------------
FED_DATA_LOCALITY_ANNOTATION = "federation.volcano-tpu.io/data-locality"
FED_ADMISSION_KEY_ANNOTATION = "federation.volcano-tpu.io/admission-key"
FED_ADMITTED_REGION_ANNOTATION = \
    "federation.volcano-tpu.io/admitted-region"
FED_ADMITTED_TS_ANNOTATION = "federation.volcano-tpu.io/admitted-ts"
# regional phase folded onto the global record by the router (bounded:
# PodGroupPhase/JobPhase values), so `vtpctl federate` renders fleet
# state from the global store alone
FED_REGIONAL_PHASE_ANNOTATION = \
    "federation.volcano-tpu.io/regional-phase"
# migration provenance: where the gang ran before the current region,
# and how many cross-region moves it has survived
FED_MIGRATED_FROM_ANNOTATION = "federation.volcano-tpu.io/migrated-from"
FED_MIGRATIONS_ANNOTATION = "federation.volcano-tpu.io/migrations"
# admission attempt counter: the deterministic admission key is
# derived from (job key, attempt), so every requeue/migration bumps it
# — a router restart re-derives the SAME key for the SAME attempt
FED_ATTEMPT_ANNOTATION = "federation.volcano-tpu.io/admission-attempt"
# cross-region migration trigger on the GLOBAL job: a region name, or
# "auto" to let the router pick the best destination.  `vtpctl
# federate migrate` stamps it; draining a whole region stamps it on
# every gang admitted there (follow-the-sun)
FED_EVACUATE_ANNOTATION = "federation.volcano-tpu.io/evacuate"
# while a migration is in flight: the chosen destination (cleared at
# cutover or on abort) — restart-safe episode state
FED_EVACUATING_TO_ANNOTATION = "federation.volcano-tpu.io/evacuating-to"
# pending-arbitrage damping: a gang is only migrated off its queue
# after sitting pending this long with another region able to take it
ARBITRAGE_PENDING_S = 30.0

# -- regional copy (router-owned) --------------------------------------
# the global job key this regional job reconciles back to; its
# PRESENCE marks a job as router-placed (the regional plane treats it
# as any other job)
FED_HOME_ANNOTATION = "federation.volcano-tpu.io/home"
FED_ORIGIN_REGION_ANNOTATION = "federation.volcano-tpu.io/origin-region"

# -- causal episode (one ID from global submit to running pod) ---------
# minted by the router the first time it sees an unadmitted global
# job, then carried on EVERY downstream wire write: the regional copy,
# its podgroup (annotation inheritance in the job controller), its
# pods, scheduler session root spans, controller episodes, and both
# sides of a cross-region cutover.  `GET /traces?episode=` on any
# plane returns that plane's local fragment; the router's stitcher
# joins them into one /fleet_trace span tree.
FED_EPISODE_ANNOTATION = "federation.volcano-tpu.io/episode"
# hop index: 0 at first admission, +1 per cross-region move (requeue,
# arbitrage, cutover).  Both cutover sides carry the SAME episode with
# the destination stamped at hop+1 — the create-then-delete pair is
# distinguishable in the stitched tree.
FED_EPISODE_HOP_ANNOTATION = "federation.volcano-tpu.io/episode-hop"
# wall-clock mint timestamp: the stitched tree's t0 (submit-side edge)
FED_EPISODE_TS_ANNOTATION = "federation.volcano-tpu.io/episode-ts"

# -- region registry (the `region` dict-kind) --------------------------
# record shape: {"name", "url", "price", "locality", "token",
#                "heartbeat_ts", "state", "capacity_chips",
#                "idle_chips", "mirror_url"}
REGION_STATE_READY = "ready"
REGION_STATE_LOST = "lost"
# operator cordon (`vtpctl federate drain <region>`): no new
# admissions; the router evacuates every RUNNING federated gang out
REGION_STATE_DRAINING = "draining"
REGION_STATES = (REGION_STATE_READY, REGION_STATE_LOST,
                 REGION_STATE_DRAINING)
# a region silent past this is declared lost: its gangs requeue
# globally (the global store is the source of truth — nothing acked
# is lost with the region)
REGION_TTL_S = 15.0

# mirror staleness bound: reads through RegionMirror.read_checked()
# refuse (MirrorStaleError) once the advertised age exceeds this —
# the migration cutover gate
MIRROR_MAX_AGE_S = 30.0

# -- router HA (leased, crash-adoptive replica set) ---------------------
# N router processes compete for this term-fenced lease in the GLOBAL
# store; only the holder mutates.  The same name doubles as the FENCE
# name on every regional plane: a promoted router advances the
# regional fence to its term before its first write, so the deposed
# holder's in-flight cross-region RPCs are atomically refused (409) —
# the cross-shard-spill refusal discipline applied to routers.
ROUTER_LEASE_NAME = "federation-router"
ROUTER_LEASE_TTL_S = 10.0


def region_record(name: str, url: str, price: float = 1.0,
                  locality: str = "", mirror_url: str = "",
                  token: str = "", metrics_url: str = "") -> dict:
    """A fresh region-registry record (state: ready, heartbeat now).
    ``metrics_url`` is the region's Prometheus exposition endpoint
    (the regional agent's --metrics-port); when set, the leaseholder
    router scrapes it into the federation_rollup_* families."""
    return {
        "name": name, "url": url, "price": float(price),
        "locality": locality, "mirror_url": mirror_url or url,
        "token": token, "metrics_url": metrics_url,
        # vtplint: disable=wall-clock (registry records cross processes; wall time is the shared clock)
        "heartbeat_ts": time.time(),
        "state": REGION_STATE_READY,
        "capacity_chips": 0.0, "idle_chips": 0.0,
    }


def region_alive(rec: dict, now: Optional[float] = None,
                 ttl: float = REGION_TTL_S) -> bool:
    """Fresh heartbeat and not declared lost — a DRAINING region is
    alive (it can still run and evacuate gangs), just not admittable."""
    if not isinstance(rec, dict) or \
            rec.get("state") == REGION_STATE_LOST:
        return False
    # vtplint: disable=wall-clock (heartbeats are cross-process wall stamps)
    now = time.time() if now is None else now
    try:
        return now - float(rec.get("heartbeat_ts", 0)) <= ttl
    except (TypeError, ValueError):
        return False


def region_ready(rec: dict, now: Optional[float] = None,
                 ttl: float = REGION_TTL_S) -> bool:
    """Admittable: fresh heartbeat AND state ready (not lost, not
    draining)."""
    return region_alive(rec, now, ttl) and \
        isinstance(rec, dict) and rec.get("state") == REGION_STATE_READY


def _ann(obj) -> dict:
    return obj.annotations if obj is not None else {}


def data_locality(obj) -> List[str]:
    raw = _ann(obj).get(FED_DATA_LOCALITY_ANNOTATION, "")
    return [r.strip() for r in raw.split(",") if r.strip()]


def admitted_region(obj) -> Optional[str]:
    return _ann(obj).get(FED_ADMITTED_REGION_ANNOTATION) or None


def home_key(obj) -> Optional[str]:
    """On a REGIONAL copy: the global job key it reconciles to."""
    return _ann(obj).get(FED_HOME_ANNOTATION) or None


def admission_key(job_key: str, attempt: int = 0) -> str:
    """Deterministic idempotency key for one (global job, admission
    attempt): a router that crashed between the regional create and
    the admitted-region stamp re-derives the SAME key on restart, so
    the regional put_object replays instead of double-creating (the
    req-id cache / idempotency-keyed mirror write path)."""
    h = hashlib.sha256(f"fed-admit:{job_key}:{attempt}".encode())
    return h.hexdigest()[:24]


def migration_count(obj) -> int:
    try:
        return int(_ann(obj).get(FED_MIGRATIONS_ANNOTATION, 0) or 0)
    except (TypeError, ValueError):
        return 0


def episode_id(job_key: str, attempt: int = 0) -> str:
    """Deterministic BOUNDED episode ID for one global job's causal
    timeline (19 chars, derived like admission_key): a router that
    crashed between minting and the stamp write re-derives the SAME
    ID on restart, so the episode never forks.  The ID is an
    annotation/trace-label value ONLY — never a metric label (it is
    per-job, i.e. unbounded as a label family)."""
    h = hashlib.sha256(f"fed-episode:{job_key}:{attempt}".encode())
    return "ep-" + h.hexdigest()[:16]


def episode_of(obj) -> Optional[str]:
    """The episode ID riding a job/podgroup/pod, if any."""
    return _ann(obj).get(FED_EPISODE_ANNOTATION) or None


def episode_hop(obj) -> int:
    try:
        return int(_ann(obj).get(FED_EPISODE_HOP_ANNOTATION, 0) or 0)
    except (TypeError, ValueError):
        return 0


def episode_ts(obj, default: float = 0.0) -> float:
    """The episode's wall mint timestamp (the stitched tree's t0)."""
    try:
        return float(_ann(obj).get(FED_EPISODE_TS_ANNOTATION,
                                   default) or default)
    except (TypeError, ValueError):
        return default


def ensure_episode(job, now: Optional[float] = None) -> str:
    """Mint (idempotently) the episode onto a GLOBAL job's
    annotations: ID from the mint-time attempt, hop 0, wall t0.
    Returns the episode ID; the caller persists the job."""
    ep = episode_of(job)
    if ep:
        return ep
    try:
        attempt = int(job.annotations.get(FED_ATTEMPT_ANNOTATION, 0)
                      or 0)
    except (TypeError, ValueError):
        attempt = 0
    ep = episode_id(job.key, attempt)
    job.annotations[FED_EPISODE_ANNOTATION] = ep
    job.annotations.setdefault(FED_EPISODE_HOP_ANNOTATION, "0")
    # vtplint: disable=wall-clock (episode t0 crosses processes; wall time is the shared clock)
    job.annotations.setdefault(FED_EPISODE_TS_ANNOTATION,
                               f"{time.time() if now is None else now:.6f}")
    return ep
