"""HyperNode CRD + topology tree (network topology model).

Reference parity: staging/.../topology/v1alpha1/hypernode_types.go:60-100
(tier + members with exact/regex selectors) and
pkg/scheduler/api/hyper_node_info.go:86 (HyperNodesInfo tree, LCA,
realNodesSet).

TPU-first semantics: a **tier-1 hypernode is one ICI slice** — an atomic
mesh whose members enjoy full ICI bandwidth; tier 2+ hypernodes group
slices reachable over DCN (pod, superpod, cluster).  Lower tier ⇒ closer.
The hypernode controller auto-discovers this tree from GKE-style TPU node
labels (see volcano_tpu.controllers.hypernode).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set

# Name of the synthetic root that unifies the hypernode forest
# (reference framework/session.go builds a virtual root at max tier + 1).
VIRTUAL_ROOT = "<root>"


@dataclass
class HyperNodeMember:
    """Member selector: either a node or a child hypernode."""

    kind: str = "Node"           # Node | HyperNode
    exact: str = ""              # exactMatch name
    regex: str = ""              # regexMatch pattern
    labels: Dict[str, str] = field(default_factory=dict)  # labelMatch

    def matches(self, name: str, labels: Optional[Dict[str, str]] = None) -> bool:
        if self.exact:
            return name == self.exact
        if self.regex:
            return re.fullmatch(self.regex, name) is not None
        if self.labels and labels is not None:
            return all(labels.get(k) == v for k, v in self.labels.items())
        return False


@dataclass
class HyperNode:
    """HyperNode CRD object."""

    name: str
    tier: int = 1
    tier_name: str = ""
    members: List[HyperNodeMember] = field(default_factory=list)
    labels: Dict[str, str] = field(default_factory=dict)

    @classmethod
    def of_nodes(cls, name: str, tier: int, nodes: Iterable[str],
                 **kwargs) -> "HyperNode":
        return cls(name=name, tier=tier,
                   members=[HyperNodeMember(kind="Node", exact=n)
                            for n in nodes], **kwargs)

    @classmethod
    def of_children(cls, name: str, tier: int, children: Iterable[str],
                    **kwargs) -> "HyperNode":
        return cls(name=name, tier=tier,
                   members=[HyperNodeMember(kind="HyperNode", exact=c)
                            for c in children], **kwargs)


class HyperNodeInfo:
    """One node of the topology tree."""

    def __init__(self, hypernode: HyperNode):
        self.hypernode = hypernode
        self.name = hypernode.name
        self.tier = hypernode.tier
        self.parent: Optional[str] = None
        self.children: Set[str] = set()
        self.nodes: Set[str] = set()      # real node names beneath (closure)
        self.direct_nodes: Set[str] = set()  # real nodes listed as members

    def __repr__(self):
        return (f"HyperNodeInfo({self.name}, tier={self.tier}, "
                f"nodes={len(self.nodes)})")


class HyperNodesInfo:
    """The assembled topology forest with a virtual root.

    Built from HyperNode CRs + the set of real node names; maintains the
    descendant real-node set per hypernode and answers LCA queries used
    for ICI-distance scoring.
    """

    def __init__(self, hypernodes: Iterable[HyperNode],
                 real_nodes: Iterable[str] = (),
                 node_labels: Optional[Dict[str, Dict[str, str]]] = None):
        self.members: Dict[str, HyperNodeInfo] = {}
        self.node_to_leaf: Dict[str, str] = {}   # real node -> tier-1 hypernode
        self._lca_tier_cache: Dict[tuple, int] = {}
        self._tier_row_cache: Dict[Optional[str], tuple] = {}
        real = list(real_nodes)
        node_labels = node_labels or {}

        hns = list(hypernodes)
        for hn in hns:
            self.members[hn.name] = HyperNodeInfo(hn)

        # Resolve membership: wire children and direct node members.
        # A child keeps its first parent; an edge that would close a
        # cycle (malformed CRs whose selectors match each other) is
        # dropped rather than hanging later tree walks.  Exact-match
        # members resolve by dict lookup — only regex/label selectors
        # pay a scan (the wiring is on the per-session snapshot path).
        real_set = set(real)
        for hn in hns:
            info = self.members[hn.name]
            for m in hn.members:
                if m.kind == "HyperNode":
                    if m.exact:
                        candidates = ([m.exact]
                                      if m.exact in self.members
                                      and m.exact != hn.name else [])
                    else:
                        candidates = [c for c in self.members
                                      if c != hn.name and m.matches(c)]
                    for cand in candidates:
                        if self.members[cand].parent is not None:
                            continue
                        if cand in self.ancestors(hn.name):
                            continue  # would create a cycle
                        info.children.add(cand)
                        self.members[cand].parent = hn.name
                else:
                    if m.exact:
                        if m.exact in real_set:
                            info.direct_nodes.add(m.exact)
                    else:
                        for node in real:
                            if m.matches(node, node_labels.get(node)):
                                info.direct_nodes.add(node)
            info.nodes |= info.direct_nodes

        # Virtual root above all parentless hypernodes.
        max_tier = max((h.tier for h in hns), default=0)
        root = HyperNode(name=VIRTUAL_ROOT, tier=max_tier + 1)
        root_info = HyperNodeInfo(root)
        self.members[VIRTUAL_ROOT] = root_info
        for name, info in self.members.items():
            if name != VIRTUAL_ROOT and info.parent is None:
                info.parent = VIRTUAL_ROOT
                root_info.children.add(name)

        # Propagate real-node sets bottom-up and index each real node to
        # its lowest-tier DIRECT owner (a hypernode may list nodes as
        # members while also having hypernode children).
        self._propagate_nodes(VIRTUAL_ROOT)
        for name, info in self.members.items():
            if name == VIRTUAL_ROOT:
                continue
            for n in info.direct_nodes:
                cur = self.node_to_leaf.get(n)
                if cur is None or info.tier < self.members[cur].tier:
                    self.node_to_leaf[n] = name

        # Any real node not covered by the tree hangs off the root.
        uncovered = set(real) - set(self.node_to_leaf)
        root_info.nodes |= uncovered

    def _propagate_nodes(self, name: str, _seen: Optional[Set[str]] = None) -> Set[str]:
        seen = _seen if _seen is not None else set()
        if name in seen:
            return set()
        seen.add(name)
        info = self.members[name]
        for child in info.children:
            info.nodes |= self._propagate_nodes(child, seen)
        return info.nodes

    # -- queries -------------------------------------------------------

    @property
    def tiers(self) -> List[int]:
        """Ascending tiers present (excluding the virtual root's)."""
        return sorted({i.tier for n, i in self.members.items()
                       if n != VIRTUAL_ROOT})

    def real_nodes(self, name: str) -> Set[str]:
        info = self.members.get(name)
        return set(info.nodes) if info else set()

    def at_tier(self, tier: int) -> List[HyperNodeInfo]:
        return [i for n, i in self.members.items()
                if i.tier == tier and n != VIRTUAL_ROOT]

    def up_to_tier(self, tier: int) -> List[HyperNodeInfo]:
        return [i for n, i in self.members.items()
                if i.tier <= tier and n != VIRTUAL_ROOT]

    def leaf_of_node(self, node_name: str) -> Optional[str]:
        return self.node_to_leaf.get(node_name)

    def leaves(self) -> List[Optional[str]]:
        """Distinct tier-1 leaf hypernodes, plus None for nodes outside
        any hypernode (the per-leaf scoring key space)."""
        out: List[Optional[str]] = sorted(set(self.node_to_leaf.values()))
        out.append(None)
        return out

    def ancestors(self, name: str) -> List[str]:
        """Path from *name* (inclusive) up to the virtual root.

        Cycle-guarded: a malformed parent chain terminates the walk
        instead of looping forever.
        """
        path: List[str] = []
        seen: Set[str] = set()
        cur: Optional[str] = name
        while cur is not None and cur not in seen:
            seen.add(cur)
            path.append(cur)
            cur = self.members[cur].parent if cur in self.members else None
        return path

    def lca(self, a: str, b: str) -> Optional[str]:
        """Lowest common ancestor of two hypernodes."""
        if a not in self.members or b not in self.members:
            return None
        set_a = set(self.ancestors(a))
        for cur in self.ancestors(b):
            if cur in set_a:
                return cur
        return None

    def lca_tier_of_leaves(self, la: Optional[str],
                           lb: Optional[str]) -> int:
        """Memoized LCA tier between two leaf hypernodes (None = outside
        the tree, scoring the virtual-root tier)."""
        root_tier = self.members[VIRTUAL_ROOT].tier
        if la is None or lb is None:
            return root_tier
        if la == lb:
            return self.members[la].tier
        key = (la, lb) if la < lb else (lb, la)
        cached = self._lca_tier_cache.get(key)
        if cached is None:
            lca = self.lca(la, lb)
            cached = self.members[lca].tier if lca else root_tier
            # vtplint: disable=snapshot-write (idempotent memo: the tier is pure in the immutable member tree, so a racing GIL-atomic store publishes an equal value; a lost update only recomputes)
            self._lca_tier_cache[key] = cached
        return cached

    def _leaf_buckets(self) -> Dict[str, List[str]]:
        """hypernode -> leaf hypernodes under it (leaves inclusive),
        built once per topology object."""
        buckets = getattr(self, "_leaf_bucket_cache", None)
        if buckets is None:
            buckets = {}
            for leaf in set(self.node_to_leaf.values()):
                for anc in self.ancestors(leaf):
                    buckets.setdefault(anc, []).append(leaf)
            # vtplint: disable=snapshot-write (idempotent memo: pure in the immutable member tree; a lost GIL-atomic update only recomputes)
            self._leaf_bucket_cache = buckets
        return buckets

    def leaf_tier_row(self, leaf: Optional[str],
                      leaf_names: List[Optional[str]]) -> tuple:
        """Tuple of LCA tiers between *leaf* and every leaf in
        ``leaves()`` order.

        Built by one root-to-leaf descendant-bucket walk — each
        ancestor overwrites its leaf bucket with its (tighter) tier —
        which is O(leaves) total instead of O(leaves) pairwise LCA
        walks, and memoized on the topology object, which incremental
        snapshots reuse while the CR set is unchanged (profiled: the
        dominant ssn.allocate cost of an 8k-gang batched commit at
        100k hosts was rebuilding these rows pairwise per session)."""
        row = self._tier_row_cache.get(leaf)
        if row is None:
            root_tier = self.members[VIRTUAL_ROOT].tier
            vals = [root_tier] * len(leaf_names)
            if leaf is not None and leaf in self.members:
                idx = {name: i for i, name in enumerate(leaf_names)}
                buckets = self._leaf_buckets()
                for anc in reversed(self.ancestors(leaf)):
                    tier = self.members[anc].tier
                    for other in buckets.get(anc, ()):
                        i = idx.get(other)
                        if i is not None:
                            vals[i] = tier
            row = tuple(vals)
            # vtplint: disable=snapshot-write (idempotent memo: pure in the immutable member tree; a racing GIL-atomic store publishes an equal tuple and a lost update only recomputes)
            self._tier_row_cache[leaf] = row
        return row

    def lca_tier_of_nodes(self, node_a: str, node_b: str) -> int:
        """Tier of the LCA of the leaf hypernodes containing two real
        nodes — the ICI/DCN 'distance' between them.  Nodes in the same
        tier-1 hypernode (same ICI slice) score tier 1; anything
        unresolvable scores the virtual-root tier."""
        return self.lca_tier_of_leaves(self.node_to_leaf.get(node_a),
                                       self.node_to_leaf.get(node_b))

    def hypernodes_covering(self, nodes: Set[str]) -> List[str]:
        """All hypernodes whose real-node set covers *nodes*, sorted by
        (tier, size) — i.e. tightest domains first."""
        out = [(i.tier, len(i.nodes), n) for n, i in self.members.items()
               if n != VIRTUAL_ROOT and nodes <= i.nodes]
        return [n for _, _, n in sorted(out)]

    def clone(self) -> "HyperNodesInfo":
        import copy
        return copy.deepcopy(self)

    def __repr__(self):
        return (f"HyperNodesInfo({len(self.members) - 1} hypernodes, "
                f"tiers={self.tiers})")
