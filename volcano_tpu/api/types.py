"""Core enums and constants.

Reference parity: pkg/scheduler/api/types.go (TaskStatus & helpers),
scheduling/v1beta1 PodGroupPhase, batch/v1alpha1 JobPhase, bus/v1alpha1
actions/events.
"""

from __future__ import annotations

import enum


class TaskStatus(enum.Enum):
    """Lifecycle status of a task (pod) as the scheduler sees it."""

    PENDING = "Pending"        # waiting to be scheduled
    ALLOCATED = "Allocated"    # resources assigned in-session, not bound
    PIPELINED = "Pipelined"    # assigned onto releasing resources
    BINDING = "Binding"        # bind RPC in flight
    BOUND = "Bound"            # bound to a node, not yet running
    RUNNING = "Running"
    RELEASING = "Releasing"    # being evicted / deleted
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    UNKNOWN = "Unknown"


# Statuses that hold (or will hold) node resources, mirroring
# types.go AllocatedStatus().
ALLOCATED_TASK_STATUSES = frozenset({
    TaskStatus.ALLOCATED, TaskStatus.BINDING, TaskStatus.BOUND,
    TaskStatus.RUNNING,
})

# Statuses counted as "ready" for gang readiness (reference
# job_info.go ReadyTaskNum): holding resources or already succeeded.
READY_TASK_STATUSES = frozenset({
    TaskStatus.BOUND, TaskStatus.BINDING, TaskStatus.RUNNING,
    TaskStatus.ALLOCATED, TaskStatus.SUCCEEDED,
})

# Statuses counted as "alive" for gang accounting.
ALIVE_TASK_STATUSES = frozenset({
    TaskStatus.PENDING, TaskStatus.ALLOCATED, TaskStatus.PIPELINED,
    TaskStatus.BINDING, TaskStatus.BOUND, TaskStatus.RUNNING,
})


def occupied(status: TaskStatus) -> bool:
    """Does a task in this status occupy cluster resources now or soon?"""
    return status in ALLOCATED_TASK_STATUSES or status is TaskStatus.RELEASING


class PodGroupPhase(enum.Enum):
    """scheduling/v1beta1 PodGroup phase machine."""

    PENDING = "Pending"      # created, not admitted by a queue
    INQUEUE = "Inqueue"      # admitted — allocate may consider it
    RUNNING = "Running"      # minMember tasks running
    UNKNOWN = "Unknown"      # partially running, gang broken
    COMPLETED = "Completed"


class PodGroupConditionType(enum.Enum):
    SCHEDULED = "Scheduled"
    UNSCHEDULABLE = "Unschedulable"


class QueueState(enum.Enum):
    OPEN = "Open"
    CLOSED = "Closed"
    CLOSING = "Closing"
    UNKNOWN = "Unknown"


class JobPhase(enum.Enum):
    """batch/v1alpha1 vcjob phase machine (8 states, state/factory.go)."""

    PENDING = "Pending"
    RUNNING = "Running"
    RESTARTING = "Restarting"
    COMPLETING = "Completing"
    TERMINATING = "Terminating"
    ABORTING = "Aborting"
    ABORTED = "Aborted"
    COMPLETED = "Completed"
    FAILED = "Failed"


# Terminal vcjob phases — single source of truth for the job
# controller, the garbage collector and cron history pruning.
FINISHED_JOB_PHASES = (JobPhase.COMPLETED, JobPhase.FAILED,
                       JobPhase.ABORTED)


class JobEvent(enum.Enum):
    """Pod/job events that lifecycle policies match on (bus/v1alpha1)."""

    ANY = "*"
    POD_FAILED = "PodFailed"
    POD_EVICTED = "PodEvicted"
    POD_PENDING = "PodPending"
    POD_RUNNING = "PodRunning"
    TASK_COMPLETED = "TaskCompleted"
    TASK_FAILED = "TaskFailed"
    JOB_UNKNOWN = "Unknown"
    OUT_OF_SYNC = "OutOfSync"
    COMMAND_ISSUED = "CommandIssued"
    JOB_UPDATED = "JobUpdated"


class JobAction(enum.Enum):
    """Actions a lifecycle policy may trigger (bus/v1alpha1/actions.go)."""

    ABORT_JOB = "AbortJob"
    RESTART_JOB = "RestartJob"
    RESTART_TASK = "RestartTask"
    RESTART_POD = "RestartPod"
    TERMINATE_JOB = "TerminateJob"
    COMPLETE_JOB = "CompleteJob"
    RESUME_JOB = "ResumeJob"
    SYNC_JOB = "SyncJob"
    ENQUEUE_JOB = "EnqueueJob"
    SYNC_QUEUE = "SyncQueue"
    OPEN_QUEUE = "OpenQueue"
    CLOSE_QUEUE = "CloseQueue"


class NetworkTopologyMode(enum.Enum):
    """Job networkTopology.mode (batch/v1alpha1 job.go:54-126)."""

    HARD = "hard"   # must fit within highestTierAllowed
    SOFT = "soft"   # prefer low tiers, allow spill


# Well-known annotations / labels (TPU-native namespace).
GROUP_NAME_ANNOTATION = "scheduling.volcano-tpu.io/group-name"
QUEUE_NAME_ANNOTATION = "scheduling.volcano-tpu.io/queue-name"
PREEMPTABLE_ANNOTATION = "volcano-tpu.io/preemptable"
# Simulated workload duration: a RUNNING pod carrying this annotation
# succeeds after N kubelet-sim ticks — the stand-in for a batch
# container that exits (the reference e2e stress jobs run busybox
# `sleep N`; a pod with no terminating workload never completes, in
# real Kubernetes too).  Absent = runs until evicted/deleted.
RUN_TICKS_ANNOTATION = "volcano-tpu.io/run-ticks"
REVOCABLE_ZONE_ANNOTATION = "volcano-tpu.io/revocable-zone"
JOB_NAME_LABEL = "volcano-tpu.io/job-name"
JOB_NAMESPACE_LABEL = "volcano-tpu.io/job-namespace"
TASK_SPEC_LABEL = "volcano-tpu.io/task-spec"
TASK_INDEX_LABEL = "volcano-tpu.io/task-index"
SUBGROUP_LABEL = "volcano-tpu.io/subgroup-name"
NODEGROUP_LABEL = "volcano-tpu.io/nodegroup-name"

# GKE-style TPU node labels consumed by the tpu device layer and the
# hypernode discoverer (SURVEY.md §5 "TPU-native equivalent").
TPU_ACCELERATOR_LABEL = "cloud.google.com/gke-tpu-accelerator"   # e.g. tpu-v5-lite-podslice
TPU_TOPOLOGY_LABEL = "cloud.google.com/gke-tpu-topology"          # e.g. 16x16
TPU_SLICE_LABEL = "cloud.google.com/gke-tpu-slice"                # slice name/id
TPU_WORKER_ID_LABEL = "cloud.google.com/gke-tpu-worker-id"        # host index in slice
TPU_COORDS_LABEL = "volcano-tpu.io/ici-coords"                    # "x,y,z" of host in mesh

# QoS level annotation shared by the scheduler's BE fit path and the
# agent's BE eviction path; value "BE" marks best-effort colocation pods.
QOS_LEVEL_ANNOTATION = "volcano-tpu.io/qos-level"
QOS_BEST_EFFORT = "BE"
# the reference's full class ladder (pkg/agent/apis/extension/qos.go:
# LC/HLS=2, LS=1, BE=-1); unannotated pods are treated as LS
QOS_LATENCY_CRITICAL = "LC"
QOS_HIGHLY_LATENCY_SENSITIVE = "HLS"
QOS_LATENCY_SENSITIVE = "LS"

# Node annotation: reclaimable millicores published by the node agent,
# consumed by the scheduler's BE fit path.
OVERSUBSCRIPTION_CPU_ANNOTATION = \
    "oversubscription.volcano-tpu.io/cpu-millis"

# PodGroup annotation carrying gangpreempt's domain nominations across
# sessions: JSON {subgroup-name: hypernode-name} ("" = whole job).
NOMINATED_HYPERNODES_ANNOTATION = \
    "scheduling.volcano-tpu.io/nominated-hypernodes"

DEFAULT_QUEUE = "default"
