"""Predicate status & unschedulable-reason bookkeeping.

Reference parity: pkg/scheduler/api/{types.go Status/StatusCode,
unschedule_info.go FitError/FitErrors}.
"""

from __future__ import annotations

import enum
from collections import Counter
from typing import Dict, List, Optional


class StatusCode(enum.IntEnum):
    SUCCESS = 0
    ERROR = 1                      # internal error, retriable
    UNSCHEDULABLE = 2              # doesn't fit, preemption might help
    UNSCHEDULABLE_AND_UNRESOLVABLE = 3  # preemption cannot help
    SKIP = 4


class Status:
    __slots__ = ("code", "reason", "plugin", "evict_curable")

    def __init__(self, code: StatusCode = StatusCode.SUCCESS,
                 reason: str = "", plugin: str = "",
                 evict_curable: bool = False):
        self.code = code
        self.reason = reason
        self.plugin = plugin
        # True when evicting victims THIS session can flip the verdict
        # (the plugin tracks in-session eviction effects, e.g.
        # numaaware's cell crediting).  Resolvable-but-not-curable
        # failures (usage thresholds, host ports held by RELEASING
        # victims) are skipped by preempt rather than churned on.
        self.evict_curable = evict_curable

    @property
    def ok(self) -> bool:
        return self.code in (StatusCode.SUCCESS, StatusCode.SKIP)

    def is_unschedulable(self) -> bool:
        return self.code in (StatusCode.UNSCHEDULABLE,
                             StatusCode.UNSCHEDULABLE_AND_UNRESOLVABLE)

    def __repr__(self):
        return f"Status({self.code.name}, {self.plugin}: {self.reason})"


SUCCESS = Status()


def unschedulable(reason: str, plugin: str = "",
                  resolvable: bool = True,
                  evict_curable: bool = False) -> Status:
    code = (StatusCode.UNSCHEDULABLE if resolvable
            else StatusCode.UNSCHEDULABLE_AND_UNRESOLVABLE)
    return Status(code, reason, plugin,
                  evict_curable=resolvable and evict_curable)


class FitError:
    """Why one task failed on one node."""

    __slots__ = ("task_namespace", "task_name", "node_name", "statuses")

    def __init__(self, task=None, node=None, statuses: Optional[List[Status]] = None,
                 reasons: Optional[List[str]] = None):
        self.task_namespace = getattr(task, "namespace", "")
        self.task_name = getattr(task, "name", "")
        self.node_name = getattr(node, "name", node or "")
        self.statuses: List[Status] = list(statuses or [])
        for r in reasons or []:
            self.statuses.append(unschedulable(r))

    def reasons(self) -> List[str]:
        return [s.reason for s in self.statuses if s.reason]

    def __str__(self):
        return (f"task {self.task_namespace}/{self.task_name} on node "
                f"{self.node_name}: {', '.join(self.reasons()) or 'fit failed'}")


class FitErrors:
    """Aggregated fit errors for one job across nodes."""

    def __init__(self):
        self.nodes: Dict[str, FitError] = {}
        self.err: str = ""

    def set_node_error(self, node_name: str, fe: FitError):
        self.nodes[node_name] = fe

    def set_error(self, err: str):
        self.err = err

    def error(self) -> str:
        # Compress to "N node(s) reason" histogram like the reference.
        # A job/queue-level err is PREFIXED, not exclusive: per-node
        # entries recorded later in the session (preempt/reclaim
        # retries) must stay visible.
        reason_counts = Counter()
        for fe in self.nodes.values():
            for r in set(fe.reasons()) or {"node(s) didn't fit"}:
                reason_counts[r] += 1
        if not reason_counts:
            return self.err or "no fit errors recorded"
        parts = [f"{n} node(s) {r}" for r, n in
                 sorted(reason_counts.items(), key=lambda kv: (-kv[1], kv[0]))]
        histogram = f"all nodes are unavailable: {', '.join(parts)}."
        return f"{self.err}; {histogram}" if self.err else histogram

    def __str__(self):
        return self.error()
