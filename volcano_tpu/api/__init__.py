"""Scheduler object model (reference: pkg/scheduler/api)."""

from volcano_tpu.api.resource import Resource, CPU, MEMORY, PODS, TPU
from volcano_tpu.api.types import (
    TaskStatus,
    PodGroupPhase,
    QueueState,
    JobPhase,
    ALIVE_TASK_STATUSES,
    ALLOCATED_TASK_STATUSES,
    occupied,
)
from volcano_tpu.api.pod import Pod
from volcano_tpu.api.job_info import TaskInfo, JobInfo, SubJobInfo
from volcano_tpu.api.node_info import NodeInfo
from volcano_tpu.api.queue_info import QueueInfo
from volcano_tpu.api.podgroup import PodGroup, NetworkTopologySpec, SubGroupPolicy
from volcano_tpu.api.queue import Queue
from volcano_tpu.api.vcjob import VCJob, TaskSpec, LifecyclePolicy
from volcano_tpu.api.hypernode import HyperNode, HyperNodeInfo, HyperNodesInfo
from volcano_tpu.api.fit_error import FitError, FitErrors, Status, StatusCode

__all__ = [
    "Resource", "CPU", "MEMORY", "PODS", "TPU",
    "TaskStatus", "PodGroupPhase", "QueueState", "JobPhase",
    "ALIVE_TASK_STATUSES", "ALLOCATED_TASK_STATUSES", "occupied",
    "Pod", "TaskInfo", "JobInfo", "SubJobInfo", "NodeInfo", "QueueInfo",
    "PodGroup", "NetworkTopologySpec", "SubGroupPolicy", "Queue",
    "VCJob", "TaskSpec", "LifecyclePolicy",
    "HyperNode", "HyperNodeInfo", "HyperNodesInfo",
    "FitError", "FitErrors", "Status", "StatusCode",
]
