"""TaskInfo / SubJobInfo / JobInfo — the scheduler's in-memory job model.

Reference parity: pkg/scheduler/api/job_info.go (TaskInfo:118,
JobInfo:363, gang counting helpers), sub_job_info.go:40 (SubJobInfo with
AllocatedHyperNode / NominatedHyperNode for subgroup topology gang).
"""

from __future__ import annotations

import time
from collections import defaultdict
from typing import Dict, List, Optional

from volcano_tpu.api.fit_error import FitError, FitErrors
from volcano_tpu.api.pod import Pod
from volcano_tpu.api.podgroup import NetworkTopologySpec, PodGroup
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import (
    ALIVE_TASK_STATUSES,
    READY_TASK_STATUSES,
    PREEMPTABLE_ANNOTATION,
    SUBGROUP_LABEL,
    TASK_SPEC_LABEL,
    TaskStatus,
    occupied,
)

# Default max wait before a pipelined job is considered stuck
# (reference JobWaitingTime default).
DEFAULT_JOB_WAITING_TIME = 60.0

# Subgroup name used when a job has no subGroupPolicy: every task belongs
# to the implicit root subjob.
ROOT_SUB_JOB = ""


class TaskInfo:
    """One schedulable pod within a job."""

    __slots__ = (
        "uid", "job", "name", "namespace", "resreq", "init_resreq",
        "node_name", "status", "priority", "best_effort", "preemptable",
        "revocable", "pod", "task_spec", "sub_job", "nominated_node",
        "last_tx_node", "last_tx_status",
    )

    def __init__(self, pod: Pod, job_uid: str = ""):
        self.uid = pod.uid
        self.job = job_uid or pod.owner
        self.name = pod.name
        self.namespace = pod.namespace
        self.resreq = pod.resource_requests()
        self.init_resreq = self.resreq.clone()
        self.node_name = pod.node_name
        self.status = pod.phase
        self.priority = pod.priority
        self.best_effort = self.resreq.is_empty()
        self.preemptable = _pod_preemptable(pod)
        self.revocable = False
        self.pod = pod
        self.task_spec = pod.task_spec or pod.labels.get(TASK_SPEC_LABEL, "")
        self.sub_job = pod.labels.get(SUBGROUP_LABEL, ROOT_SUB_JOB)
        self.nominated_node = pod.nominated_node
        # transaction context for Statement save/recover
        self.last_tx_node = ""
        self.last_tx_status: Optional[TaskStatus] = None

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def occupies_resources(self) -> bool:
        return occupied(self.status)

    def is_alive(self) -> bool:
        return self.status in ALIVE_TASK_STATUSES

    def clone(self) -> "TaskInfo":
        c = TaskInfo.__new__(TaskInfo)
        c.uid = self.uid
        c.job = self.job
        c.name = self.name
        c.namespace = self.namespace
        c.resreq = self.resreq.clone()
        c.init_resreq = self.init_resreq.clone()
        c.node_name = self.node_name
        c.status = self.status
        c.priority = self.priority
        c.best_effort = self.best_effort
        c.preemptable = self.preemptable
        c.revocable = self.revocable
        c.pod = self.pod
        c.task_spec = self.task_spec
        c.sub_job = self.sub_job
        c.nominated_node = self.nominated_node
        c.last_tx_node = self.last_tx_node
        c.last_tx_status = self.last_tx_status
        return c

    def save_tx_context(self):
        self.last_tx_node = self.node_name
        self.last_tx_status = self.status

    def __repr__(self):
        return (f"TaskInfo({self.key}, {self.status.value}, "
                f"node={self.node_name or '-'}, req={self.resreq})")


def _pod_preemptable(pod: Pod) -> bool:
    v = pod.annotations.get(PREEMPTABLE_ANNOTATION)
    if v is not None:
        return str(v).lower() == "true"
    return pod.preemptable


class SubJobInfo:
    """Subgroup gang state: a named slice of the job's tasks with its own
    minMember and (optionally) its own topology constraint.  On TPU this
    is the unit that must land inside one ICI slice."""

    def __init__(self, name: str, min_member: int = 0,
                 network_topology: Optional[NetworkTopologySpec] = None):
        self.name = name
        self.min_member = min_member
        self.network_topology = network_topology
        self.tasks: Dict[str, TaskInfo] = {}
        # Set when allocate commits this subjob into a hypernode domain;
        # recovered from running pods after scheduler restart.
        self.allocated_hypernode: str = ""
        # Set by gangpreempt nomination; consumed by next allocate cycle.
        self.nominated_hypernode: str = ""

    def ready_task_num(self) -> int:
        return sum(1 for t in self.tasks.values()
                   if t.status in READY_TASK_STATUSES)

    def waiting_task_num(self) -> int:
        return sum(1 for t in self.tasks.values()
                   if t.status is TaskStatus.PIPELINED)

    def is_ready(self) -> bool:
        return self.ready_task_num() >= self.min_member

    def is_pipelined(self) -> bool:
        return self.ready_task_num() + self.waiting_task_num() >= self.min_member

    def clone(self) -> "SubJobInfo":
        c = SubJobInfo(self.name, self.min_member, self.network_topology)
        c.allocated_hypernode = self.allocated_hypernode
        c.nominated_hypernode = self.nominated_hypernode
        return c

    def __repr__(self):
        return (f"SubJobInfo({self.name or '<root>'}, min={self.min_member}, "
                f"tasks={len(self.tasks)})")


class JobInfo:
    """All scheduler state for one PodGroup's worth of tasks."""

    def __init__(self, uid: str, podgroup: Optional[PodGroup] = None):
        self.uid = uid
        self.podgroup = podgroup
        self.name = podgroup.name if podgroup else uid
        self.namespace = podgroup.namespace if podgroup else "default"
        self.queue = podgroup.queue if podgroup else "default"
        self.priority = 0
        self.priority_class = podgroup.priority_class if podgroup else ""
        self.min_available = podgroup.min_member if podgroup else 1
        self.task_min_available: Dict[str, int] = dict(
            podgroup.min_task_member) if podgroup else {}
        self.creation_time = podgroup.creation_time if podgroup else time.time()
        self.waiting_time = DEFAULT_JOB_WAITING_TIME
        self.preemptable = True
        self.revocable_zone = ""

        self.tasks: Dict[str, TaskInfo] = {}
        self.task_status_index: Dict[TaskStatus, Dict[str, TaskInfo]] = \
            defaultdict(dict)
        self.sub_jobs: Dict[str, SubJobInfo] = {}
        if podgroup:
            for sg in podgroup.sub_group_policies:
                self.sub_jobs[sg.name] = SubJobInfo(
                    sg.name, sg.min_member, sg.network_topology)
            self._recover_nominations(podgroup)

        self.total_request = Resource()
        # resources held by occupying tasks, carried incrementally at
        # the task mutation seams (add/remove/update_task_status) the
        # same way total_request is: allocated() used to re-walk every
        # task per call, and the share plugins call it per job per
        # session — a fifth of the idle cycle at 40k hosts
        self._allocated = Resource()
        # min_request memo (see min_request for the box rationale)
        self._min_req_box: list = [None]
        self.fit_errors: Dict[str, FitErrors] = {}   # per-task-uid node errors
        self.job_fit_errors: Optional[FitErrors] = None
        self.scheduling_start = 0.0

    def _recover_nominations(self, podgroup: PodGroup):
        """Rehydrate gangpreempt's domain nominations from the PodGroup
        annotation (they must survive snapshot rebuilds between the
        evict cycle and the allocate cycle that consumes them)."""
        import json
        from volcano_tpu.api.types import NOMINATED_HYPERNODES_ANNOTATION
        raw = podgroup.annotations.get(NOMINATED_HYPERNODES_ANNOTATION)
        if not raw:
            return
        try:
            nominations = json.loads(raw)
        except ValueError:
            return
        for sub_name, domain in nominations.items():
            sub = self.sub_jobs.get(sub_name)
            if sub is None:
                sub = SubJobInfo(sub_name, 0)
                self.sub_jobs[sub_name] = sub
            sub.nominated_hypernode = domain

    def persist_nominations(self):
        """Write current nominations back into the PodGroup annotation
        (empty mapping removes it)."""
        import json
        from volcano_tpu.api.types import NOMINATED_HYPERNODES_ANNOTATION
        if self.podgroup is None:
            return
        nominations = {name: sub.nominated_hypernode
                       for name, sub in self.sub_jobs.items()
                       if sub.nominated_hypernode}
        if nominations:
            self.podgroup.annotations[NOMINATED_HYPERNODES_ANNOTATION] = \
                json.dumps(nominations, sort_keys=True)
        else:
            self.podgroup.annotations.pop(
                NOMINATED_HYPERNODES_ANNOTATION, None)

    # -- spec accessors ------------------------------------------------

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    @property
    def network_topology(self) -> Optional[NetworkTopologySpec]:
        return self.podgroup.network_topology if self.podgroup else None

    def is_hard_topology(self) -> bool:
        nt = self.network_topology
        from volcano_tpu.api.types import NetworkTopologyMode
        return nt is not None and nt.mode == NetworkTopologyMode.HARD

    def has_topology_constraint(self) -> bool:
        """Job-level hard topology OR any subgroup with hard topology —
        either routes allocation through the topology-domain search."""
        from volcano_tpu.api.types import NetworkTopologyMode
        if self.is_hard_topology():
            return True
        return any(
            sub.network_topology is not None
            and sub.network_topology.mode == NetworkTopologyMode.HARD
            and sub.min_member > 0
            for sub in self.sub_jobs.values())

    @property
    def min_resources(self) -> Resource:
        if self.podgroup and self.podgroup.min_resources:
            return self.podgroup.min_resources.clone()
        return Resource()

    @property
    def has_min_resources(self) -> bool:
        """Did the user declare spec.minResources?  Admission gates only
        apply to jobs that did (reference: 'MinResources == nil =>
        Permit' in overcommit/proportion/capacity enqueue fns)."""
        return bool(self.podgroup and self.podgroup.min_resources)

    # -- task management ----------------------------------------------

    def add_task(self, task: TaskInfo):
        task.job = self.uid
        self.tasks[task.uid] = task
        self.task_status_index[task.status][task.uid] = task
        if not task.best_effort:
            self.total_request.add(task.resreq)
            self._min_req_box[0] = None
        if task.occupies_resources():
            self._allocated.add(task.resreq)
        sub = self.sub_jobs.get(task.sub_job)
        if sub is None:
            sub = SubJobInfo(task.sub_job, 0)
            self.sub_jobs[task.sub_job] = sub
        sub.tasks[task.uid] = task

    def remove_task(self, task: TaskInfo):
        existing = self.tasks.pop(task.uid, None)
        if existing is None:
            return
        self.task_status_index[existing.status].pop(task.uid, None)
        if not existing.best_effort:
            self.total_request.sub_unchecked(existing.resreq)
            self._min_req_box[0] = None
        if existing.occupies_resources():
            self._allocated.sub_unchecked(existing.resreq)
        sub = self.sub_jobs.get(existing.sub_job)
        if sub:
            sub.tasks.pop(task.uid, None)

    def update_task_status(self, task: TaskInfo, status: TaskStatus):
        self.task_status_index[task.status].pop(task.uid, None)
        was_occupying = task.uid in self.tasks and occupied(task.status)
        task.status = status
        self.tasks[task.uid] = task
        self.task_status_index[status][task.uid] = task
        now_occupying = occupied(status)
        if now_occupying and not was_occupying:
            self._allocated.add(task.resreq)
        elif was_occupying and not now_occupying:
            self._allocated.sub_unchecked(task.resreq)
        sub = self.sub_jobs.get(task.sub_job)
        if sub:
            sub.tasks[task.uid] = task

    def tasks_in_status(self, status: TaskStatus) -> List[TaskInfo]:
        return list(self.task_status_index.get(status, {}).values())

    # -- gang counting (job_info.go ReadyTaskNum et al.) ---------------

    def ready_task_num(self) -> int:
        return sum(len(self.task_status_index.get(s, ()))
                   for s in READY_TASK_STATUSES)

    def waiting_task_num(self) -> int:
        return len(self.task_status_index.get(TaskStatus.PIPELINED, ()))

    def valid_task_num(self) -> int:
        """Tasks capable of becoming ready (alive)."""
        return sum(1 for t in self.tasks.values() if t.is_alive())

    def pending_best_effort_task_num(self) -> int:
        return sum(1 for t in self.tasks_in_status(TaskStatus.PENDING)
                   if t.best_effort)

    def is_ready(self) -> bool:
        """ready + pending-best-effort >= minAvailable (job_info.go:1202
        — best-effort tasks always place via backfill, so they count
        toward the floor)."""
        return (self.ready_task_num() + self.pending_best_effort_task_num()
                >= self.min_available)

    def is_pipelined(self) -> bool:
        return (self.ready_task_num() + self.waiting_task_num()
                + self.pending_best_effort_task_num() >= self.min_available)

    def is_starving(self) -> bool:
        """waiting + ready < minAvailable (job_info.go:1210): a job with
        enough pipelined reservations is no longer starving — stops
        preempt/reclaim from over-evicting past the gang floor."""
        return (self.ready_task_num() + self.waiting_task_num()
                < self.min_available)

    def check_task_min_available(self) -> bool:
        """Per-task-spec minima (minTaskMember) are satisfiable by alive
        tasks (reference CheckTaskValid)."""
        if not self.task_min_available:
            return True
        if self.min_available < sum(self.task_min_available.values()):
            # job-level floor below the per-task total: per-task minima
            # don't bind (job_info.go:1026-1029) — this is what lets
            # dependsOn jobs gang on their first stage only
            return True
        alive_per_spec: Dict[str, int] = defaultdict(int)
        for t in self.tasks.values():
            if t.is_alive():
                alive_per_spec[t.task_spec] += 1
        return all(alive_per_spec.get(spec, 0) >= need
                   for spec, need in self.task_min_available.items())

    def check_task_min_available_ready(self) -> bool:
        """Per-task-spec minima met by READY tasks (CheckTaskReady)."""
        if not self.task_min_available:
            return True
        if self.min_available < sum(self.task_min_available.values()):
            # job-level floor below the per-task total: per-task minima
            # don't bind (job_info.go:1026-1029) — this is what lets
            # dependsOn jobs gang on their first stage only
            return True
        ready_per_spec: Dict[str, int] = defaultdict(int)
        for t in self.tasks.values():
            if t.status in READY_TASK_STATUSES:
                ready_per_spec[t.task_spec] += 1
        return all(ready_per_spec.get(spec, 0) >= need
                   for spec, need in self.task_min_available.items())

    def check_task_min_available_pipelined(self) -> bool:
        if not self.task_min_available:
            return True
        if self.min_available < sum(self.task_min_available.values()):
            # job-level floor below the per-task total: per-task minima
            # don't bind (job_info.go:1026-1029) — this is what lets
            # dependsOn jobs gang on their first stage only
            return True
        per_spec: Dict[str, int] = defaultdict(int)
        for t in self.tasks.values():
            if (t.status in READY_TASK_STATUSES
                    or t.status is TaskStatus.PIPELINED):
                per_spec[t.task_spec] += 1
        return all(per_spec.get(spec, 0) >= need
                   for spec, need in self.task_min_available.items())

    # -- resources -----------------------------------------------------

    def allocated(self) -> Resource:
        """Resources currently held by this job's occupying tasks.
        Carried incrementally at the task mutation seams; callers own
        the returned clone (the share plugins fold into it)."""
        return self._allocated.clone()

    def min_request(self) -> Resource:
        """Aggregate request of the cheapest min_available task set
        (approximation: sum of the smallest min_available task requests;
        used for enqueue admission like the reference's
        GetMinResources).  Memoized in a one-slot box: the inputs only
        move at add/remove_task (which clear the box), and the share
        plugins call this per job per session — the per-call task sort
        was a fifth of the idle cycle at 40k hosts.  A box, not an
        attribute, so the lazy fill of this pure-function-of-frozen-
        state memo is invisible to the freeze auditor's __setattr__
        guard (idempotent build-then-publish, same argument as the
        Session dispatch memos)."""
        if self.podgroup and self.podgroup.min_resources is not None:
            return self.podgroup.min_resources.clone()
        cached = self._min_req_box[0]
        if cached is None:
            reqs = sorted(
                (t.resreq for t in self.tasks.values()
                 if not t.best_effort),
                key=lambda r: (r.milli_cpu, r.memory))
            total = Resource()
            for r in reqs[: self.min_available]:
                total.add(r)
            cached = self._min_req_box[0] = total
        return cached.clone()

    def elastic_resources(self, allocated: Optional[Resource] = None
                          ) -> Resource:
        """Resources held beyond the gang floor (reference
        GetElasticResources, job_info.go:654: ExceededPart(allocated,
        minResources)) — reclaimable without breaking the gang."""
        alloc = allocated if allocated is not None else self.allocated()
        return alloc.clone().sub_unchecked(self.min_request())

    # -- fit errors ----------------------------------------------------

    def record_fit_error(self, task: TaskInfo, node_name: str, fe: FitError):
        errs = self.fit_errors.get(task.uid)
        if errs is None:
            errs = FitErrors()
            self.fit_errors[task.uid] = errs
        errs.set_node_error(node_name, fe)

    def set_job_fit_errors(self, errs) -> None:
        """Publish the job-level fit-error summary.  A designated
        reporting seam (like record_fit_error): the freeze auditor
        and the static race pass admit snapshot writes only through
        these, so allocate's _finish does not poke the attribute
        directly."""
        self.job_fit_errors = errs

    def task_has_fit_errors(self, task: TaskInfo) -> bool:
        """Fit-error memoization: a pending task whose identical spec
        already failed everywhere need not be retried this session
        (reference TaskHasFitErrors + fit-error cache)."""
        return task.uid in self.fit_errors

    def fit_error(self) -> str:
        if self.job_fit_errors is not None:
            return self.job_fit_errors.error()
        reasons = {uid: fe.error() for uid, fe in self.fit_errors.items()}
        return "; ".join(sorted(set(reasons.values()))) if reasons else ""

    # -- clone ---------------------------------------------------------

    def clone(self) -> "JobInfo":
        c = JobInfo.__new__(JobInfo)
        c.uid = self.uid
        c.podgroup = self.podgroup
        c.name = self.name
        c.namespace = self.namespace
        c.queue = self.queue
        c.priority = self.priority
        c.priority_class = self.priority_class
        c.min_available = self.min_available
        c.task_min_available = dict(self.task_min_available)
        c.creation_time = self.creation_time
        c.waiting_time = self.waiting_time
        c.preemptable = self.preemptable
        c.revocable_zone = self.revocable_zone
        c.tasks = {}
        c.task_status_index = defaultdict(dict)
        c.sub_jobs = {name: sj.clone() for name, sj in self.sub_jobs.items()}
        c.total_request = Resource()
        c._allocated = Resource()
        c._min_req_box = [None]
        c.fit_errors = {}
        c.job_fit_errors = None
        c.scheduling_start = self.scheduling_start
        for t in self.tasks.values():
            c.add_task(t.clone())
        return c

    def __repr__(self):
        return (f"JobInfo({self.key}, queue={self.queue}, "
                f"min={self.min_available}, tasks={len(self.tasks)}, "
                f"ready={self.ready_task_num()})")
