"""NodeShard CRD (shard/v1alpha1 analogue).

Reference parity: staging/.../shard/v1alpha1/types.go:32-54 — partitions
nodes between the batch scheduler and the agent (fast-path) scheduler.
Shard modes (pkg/util/util.go:41-43): none | soft | hard.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

from volcano_tpu.api.pod import new_uid

BATCH_SCHEDULER = "volcano-tpu"
AGENT_SCHEDULER = "volcano-tpu-agent"

SHARD_MODE_NONE = "none"
SHARD_MODE_SOFT = "soft"    # prefer own shard, may spill
SHARD_MODE_HARD = "hard"    # own shard only


@dataclass
class NodeShard:
    name: str
    uid: str = field(default_factory=new_uid)
    scheduler: str = BATCH_SCHEDULER
    nodes: List[str] = field(default_factory=list)

    @property
    def key(self) -> str:
        return self.name
