"""PodGroup CRD type (scheduling/v1beta1 analogue).

Reference parity: staging/.../scheduling/v1beta1/types.go:173-223
(PodGroupSpec incl. networkTopology + subGroupPolicy) and PodGroupStatus.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from volcano_tpu.api.pod import new_uid
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import (
    DEFAULT_QUEUE,
    NetworkTopologyMode,
    PodGroupPhase,
)


@dataclass
class NetworkTopologySpec:
    """Topology placement constraint for a (sub)group.

    mode=hard: all tasks must land within one hypernode domain at tier
    <= highest_tier_allowed.  mode=soft: prefer lower tiers, allow spill.
    On TPU, tier 0 is a single ICI slice; tier 1+ crosses DCN.
    highest_tier_allowed=None means unbounded: the gradient search still
    prefers the lowest tier that fits, so the group stays ICI-local when
    possible but never becomes unschedulable by spanning.
    """

    mode: NetworkTopologyMode = NetworkTopologyMode.HARD
    highest_tier_allowed: Optional[int] = 1


@dataclass
class SubGroupPolicy:
    """Secondary gang: named subgroup with its own minMember + topology
    (types.go:217-223).  Tasks opt in via the subgroup label."""

    name: str = ""
    min_member: int = 0
    network_topology: Optional[NetworkTopologySpec] = None


@dataclass
class PodGroupCondition:
    type: str = ""
    status: str = ""
    reason: str = ""
    message: str = ""
    transition_id: str = ""


@dataclass
class PodGroup:
    name: str
    namespace: str = "default"
    uid: str = field(default_factory=new_uid)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)

    # spec
    min_member: int = 1
    min_task_member: Dict[str, int] = field(default_factory=dict)
    min_resources: Optional[Resource] = None
    queue: str = DEFAULT_QUEUE
    priority_class: str = ""
    network_topology: Optional[NetworkTopologySpec] = None
    sub_group_policies: List[SubGroupPolicy] = field(default_factory=list)

    # status
    phase: PodGroupPhase = PodGroupPhase.PENDING
    conditions: List[PodGroupCondition] = field(default_factory=list)
    running: int = 0
    succeeded: int = 0
    failed: int = 0
    creation_time: float = field(default_factory=time.time)

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def clone(self) -> "PodGroup":
        import copy
        return copy.deepcopy(self)
