"""Elastic-gang annotation schema + helpers.

An elastic vcjob declares a SLICE range instead of a fixed world
size: the scheduler may grow it into idle slices up to `max-slices`
and shrink it toward `min-slices` under pressure — world size becomes
a *scheduler decision*, following Singularity's transparent
checkpoint-based resize/migrate (arxiv 2202.07848) and the
elastic-gang semantics of goodput schedulers (Pollux, arxiv
2008.12260).

Contract (who writes what):

  submitter   `elastic.volcano-tpu.io/min-slices` / `max-slices` on
              the vcjob; task replicas size the SUBMIT-time world
              (`slices`, defaulted to min-slices by admission:
              replicas must divide evenly into slices — the quotient
              is the job's pods-per-slice, invariant across resizes).
              Validated in webhooks/admission.py; the podgroup
              inherits the annotations so every watch mirror sees the
              elastic range.

  scheduler   actions/elastic.py stamps the DECISION on the podgroup:
              `desired-slices` + `resize-reason` (grow|shrink|
              migrate), and for migrations `avoid-slices` (the slices
              the re-placement must leave).  Decisions only — no
              object surgery in the scheduling hot path.

  controller  controllers/elastic.py EXECUTES the decision by
              generalizing the failover drain: scale the task
              replicas to desired x pods-per-slice, stamp resume
              metadata (resume step floor-guarded against regress,
              elastic generation), drain with ONE job-level
              RestartJob, let the scheduler re-place at the new world
              size, and observe shrink-latency / grow-latency /
              migration-MTTR into the elastic_* metric families.
              `slices` is updated to the executed size; `history`
              keeps the last resizes for `vtpctl elastic`.

  workload    the jax plugin injects TPU_NUM_SLICES/TPU_SLICE_ID from
              the CURRENT slice count so the worker builds its hybrid
              dcn x ici mesh at the new world size; checkpoint.
              resume_state restores onto the resized mesh (dp-
              dimension resize is loss-continuous when the global
              batch is held constant — asserted by the dryrun e2e).
"""

from __future__ import annotations

import json
from typing import List, Optional, Tuple

# -- spec (submitter) --------------------------------------------------
ELASTIC_MIN_SLICES_ANNOTATION = "elastic.volcano-tpu.io/min-slices"
ELASTIC_MAX_SLICES_ANNOTATION = "elastic.volcano-tpu.io/max-slices"
# current world size in slices (admission defaults it to min-slices;
# the elastic controller re-stamps it after every executed resize)
ELASTIC_SLICES_ANNOTATION = "elastic.volcano-tpu.io/slices"

# optional: global batch (samples/step) the workload holds constant
# across resizes; defaults to one sample per device at the FLOOR world
# (min-slices x pods-per-slice x chips-per-pod) — the jax plugin
# injects it as WORKER_GLOBAL_BATCH
ELASTIC_GLOBAL_BATCH_ANNOTATION = "elastic.volcano-tpu.io/global-batch"

# -- decision (scheduler -> controller) --------------------------------
ELASTIC_DESIRED_SLICES_ANNOTATION = \
    "elastic.volcano-tpu.io/desired-slices"
ELASTIC_RESIZE_REASON_ANNOTATION = "elastic.volcano-tpu.io/resize-reason"
# wall time the CURRENT desired value was first stamped.  A decision
# this old with no controller executing it is STALE: the plugin's
# shrink-before-preempt veto and the action's convergence guard both
# ignore it, so a dead/disabled elastic controller degrades the
# subsystem to a no-op instead of freezing preemption fleet-wide.
ELASTIC_DECIDED_TS_ANNOTATION = "elastic.volcano-tpu.io/decided-ts"
STALE_DECISION_S = 120.0
# migration only: slices the re-placement must avoid (comma list);
# the elastic plugin filters their hosts for this gang until resume
ELASTIC_AVOID_SLICES_ANNOTATION = "elastic.volcano-tpu.io/avoid-slices"

# -- execution record (controller) -------------------------------------
# set (to the resize kind) while the controller is executing a resize,
# popped at resume: the durable in-flight marker episode adoption
# rebuilds from after a controller restart (a purely in-memory episode
# would leave the annotation-driven in-flight guard wedged forever)
ELASTIC_RESIZING_ANNOTATION = "elastic.volcano-tpu.io/resizing"
ELASTIC_GENERATION_ANNOTATION = "elastic.volcano-tpu.io/generation"
ELASTIC_HISTORY_ANNOTATION = "elastic.volcano-tpu.io/history"
ELASTIC_LAST_RESIZE_TS_ANNOTATION = \
    "elastic.volcano-tpu.io/last-resize-ts"

RESIZE_GROW = "grow"
RESIZE_SHRINK = "shrink"
RESIZE_MIGRATE = "migrate"
# cross-region evacuation (api/federation.py): the checkpointed drain
# with NO local re-place — the gang parks under the `evacuated` hold
# until the federation router cuts it over to the destination region
RESIZE_EVACUATE = "evacuate"
RESIZE_KINDS = (RESIZE_GROW, RESIZE_SHRINK, RESIZE_MIGRATE,
                RESIZE_EVACUATE)

# -- evacuation (federation router <-> elastic controller) -------------
# stamped by the router on the SOURCE podgroup: the destination region
# name.  The elastic controller executes the drain exactly like a
# migrate, then stamps `evacuated` instead of letting the gang
# re-place; actions/enqueue.py holds an evacuated gang out of INQUEUE
# (reason: `evacuating-region`) so the source scheduler never races
# the cutover.  The router clears both after the destination accepts.
ELASTIC_EVACUATE_ANNOTATION = "elastic.volcano-tpu.io/evacuate-to"
ELASTIC_EVACUATED_ANNOTATION = "elastic.volcano-tpu.io/evacuated"

HISTORY_KEEP = 8    # resize records retained on the annotation


def evacuating(obj) -> bool:
    """True while *obj* (podgroup or vcjob) is anywhere inside a
    cross-region evacuation: decision stamped, drain in flight, or
    drained-and-held awaiting the router's cutover."""
    ann = _ann(obj)
    return bool(ann.get(ELASTIC_EVACUATE_ANNOTATION) or
                ann.get(ELASTIC_EVACUATED_ANNOTATION))


def _ann(obj) -> dict:
    return obj.annotations if obj is not None else {}


def is_elastic(obj) -> bool:
    """True when *obj* (vcjob or podgroup) declares an elastic range."""
    ann = _ann(obj)
    return ELASTIC_MIN_SLICES_ANNOTATION in ann and \
        ELASTIC_MAX_SLICES_ANNOTATION in ann


def elastic_range(obj) -> Optional[Tuple[int, int]]:
    """(min_slices, max_slices) or None when not elastic/malformed."""
    ann = _ann(obj)
    try:
        lo = int(ann[ELASTIC_MIN_SLICES_ANNOTATION])
        hi = int(ann[ELASTIC_MAX_SLICES_ANNOTATION])
    except (KeyError, TypeError, ValueError):
        return None
    if lo < 1 or hi < lo:
        return None
    return lo, hi


def current_slices(obj) -> int:
    """The object's CURRENT world size in slices (>= 1)."""
    ann = _ann(obj)
    try:
        return max(1, int(ann.get(ELASTIC_SLICES_ANNOTATION, 1)))
    except (TypeError, ValueError):
        return 1


def desired_slices(obj) -> Optional[int]:
    raw = _ann(obj).get(ELASTIC_DESIRED_SLICES_ANNOTATION)
    if raw is None:
        return None
    try:
        return max(1, int(raw))
    except (TypeError, ValueError):
        return None


def decision_stale(obj, now: float) -> bool:
    """True when a desired-slices decision has sat unexecuted past
    STALE_DECISION_S (no elastic controller alive to consume it)."""
    if desired_slices(obj) is None:
        return False
    try:
        decided = float(_ann(obj).get(ELASTIC_DECIDED_TS_ANNOTATION,
                                      0) or 0)
    except (TypeError, ValueError):
        return False
    return decided > 0 and now - decided > STALE_DECISION_S


def avoid_slices(obj) -> List[str]:
    raw = _ann(obj).get(ELASTIC_AVOID_SLICES_ANNOTATION, "")
    return [s for s in raw.split(",") if s]


def resize_history(obj) -> List[dict]:
    """Parsed resize history (oldest first); [] when absent/corrupt."""
    raw = _ann(obj).get(ELASTIC_HISTORY_ANNOTATION, "")
    if not raw:
        return []
    try:
        doc = json.loads(raw)
    except (TypeError, ValueError):
        return []
    return doc if isinstance(doc, list) else []


def append_history(ann: dict, record: dict) -> None:
    """Append one resize record, keeping the last HISTORY_KEEP."""
    hist = []
    try:
        hist = json.loads(ann.get(ELASTIC_HISTORY_ANNOTATION, "[]"))
        if not isinstance(hist, list):
            hist = []
    except (TypeError, ValueError):
        hist = []
    hist.append(record)
    ann[ELASTIC_HISTORY_ANNOTATION] = json.dumps(hist[-HISTORY_KEEP:])
