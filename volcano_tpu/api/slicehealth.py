"""Slice-health wire types — the failover loop's detect half.

A TPU slice is an atomic ICI mesh: one sick host kills the whole gang
resident on it.  The agent's TpuHealthHandler escalates chip telemetry
from an instant verdict to K-consecutive-ticks hysteresis and posts a
SliceHealthReport here (one per host, keyed by node name — the same
wire-kind pattern as api/netusage.py's BandwidthReport).  The state
server folds the verdict into node annotations so every watch mirror —
the failover controller's and the scheduler's included — sees host
health from ordinary node events without decoding reports.

The failover controller (controllers/failover.py) consumes the folded
verdicts, declares the SLICE failed when any resident host is Failed,
drains the gang with one job-level restart, stamps resume metadata on
the podgroup/job, and quarantines the slice's hosts behind a
flap-damping TTL; the scheduler's failover plugin filters quarantined
hosts and fast-tracks the requeued gang.

Verdict ladder (per host):

    Healthy --bad tick--> Suspect --K bad ticks--> Failed
    Failed  --K good ticks--> Healthy        (agent-side hysteresis)

Slice lifecycle (controller-side, docs/design/failover.md):

    Healthy -> Suspect -> Failed -> Quarantined --TTL + healthy--> Healthy
"""

from __future__ import annotations

from dataclasses import dataclass

# Host verdicts the agent publishes (SliceHealthReport.verdict and the
# folded node annotation).
VERDICT_HEALTHY = "Healthy"
VERDICT_SUSPECT = "Suspect"
VERDICT_FAILED = "Failed"

# -- node-level (folded from SliceHealthReport by the STORE, so wire
#    mirrors learn host health via node watch events) ------------------
NODE_HEALTH_ANNOTATION = "failover.volcano-tpu.io/health"
# Stamped by the failover controller on every host of a failed slice:
# unix timestamp until which the slice must not take new gangs (flap
# damping — a slice that heals immediately after failing still serves
# out the TTL before re-entering rotation).
NODE_QUARANTINED_UNTIL_ANNOTATION = \
    "failover.volcano-tpu.io/quarantined-until"

# -- podgroup / job resume metadata ------------------------------------
# Declared by the JOB (where the workload checkpoints); passed through
# to worker env as VTP_CHECKPOINT_DIR by the jax plugin.
CHECKPOINT_DIR_ANNOTATION = "failover.volcano-tpu.io/checkpoint-dir"
# Written by the workload (or its supervisor) as training progresses:
# the last durably checkpointed step.
LAST_STEP_ANNOTATION = "failover.volcano-tpu.io/last-checkpoint-step"
# Stamped by the failover controller at drain time (a snapshot of
# LAST_STEP at declaration): the step the requeued gang resumes from,
# injected into worker env as VTP_RESUME_STEP.
RESUME_STEP_ANNOTATION = "failover.volcano-tpu.io/resume-step"
# Monotonic failover count for the job — bumped once per slice-failure
# drain, so operators (and the smoke test) can tell a failover restart
# from a policy retry.
FAILOVER_GENERATION_ANNOTATION = "failover.volcano-tpu.io/generation"
# Marks a drained gang awaiting re-placement; the scheduler's failover
# plugin gives these allocation priority, and the controller clears it
# once the gang is running again.
REQUEUED_ANNOTATION = "failover.volcano-tpu.io/requeued"


@dataclass
class SliceHealthReport:
    """One host's chip-health verdict, as the agent's hysteresis saw
    it.  Keyed by node name (kinds.py) — slice membership rides the
    `slice` field so the failover controller can group hosts without
    a node lookup."""

    node: str = ""
    slice: str = ""              # TPU_SLICE_LABEL of the host ("" = none)
    verdict: str = VERDICT_HEALTHY
    chips_detected: int = 0
    chips_healthy: int = 0
    consecutive_bad: int = 0     # bad ticks so far (hysteresis position)
    consecutive_good: int = 0
    # wall-clock of the FIRST bad tick of the current episode (0 when
    # healthy): the failover controller derives detection latency from
    # declare-time minus this
    first_bad_ts: float = 0.0

    @property
    def name(self) -> str:       # kinds.py keys slicehealthreport by name
        return self.node
