"""Serving wire types — the SLO contract for inference replica groups.

Training gangs optimize throughput; serving replicas optimize a LATENCY
objective under traffic that breathes (the diurnal curve every consumer
workload rides).  The serving plane reuses the substrate the training
arcs built instead of minting parallel machinery:

  workload   serving workers (workloads/serve.py) run batched forward
             passes off a request queue and publish one cumulative
             stats record per beat (requests served, SLO-ok count,
             latency quantiles) to a per-pod stats file — the goodput
             progress-file convention, different record;

  agent      the ServingCollector (agent/collect.py) turns the
             cumulative request counter into an EWMA QPS on the shared
             util.RateWindow and carries the quantiles through; the
             ServingHandler posts one ServingReport per node per sync
             (change-elided, debt-reposted);

  store      the report folds into PODGROUP annotations exactly like
             GoodputReport: per-pod cumulative ledgers diffed against
             the node's previous report (idempotent under lost-ack
             retry), QPS summed across replicas, p99 maxed — so every
             watch mirror sees the per-group serving summary via
             ordinary podgroup events;

  scheduler  serving replica groups ARE elastic gangs (min/max
             replicas ride the elastic min/max-slices annotations with
             one pod per slice-unit): the serving autoscaler
             (controllers/serving.py) computes desired replicas from
             the folded QPS/p99 vs the declared target and writes the
             SAME desired-slices decision the elastic controller
             already executes — grow, shrink, checkpointed drain,
             floor guards and resize history all inherited, never
             reimplemented.  Topology-aware burst preemption lives in
             actions/elastic.py: the training victim whose slice sits
             nearest the serving pool (hypernode LCA tier) funds the
             scale-up through the elastic shrink path, never a kill.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

# -- submitter annotations (on the vcjob/podgroup) ---------------------
# The SLO contract a serving group declares at submit time.  A group
# carrying SLO_P99_MS is "serving-class": the autoscaler manages it and
# burst preemption may be funded on its behalf.
SLO_P99_MS_ANNOTATION = "serving.volcano-tpu.io/slo-p99-ms"
MIN_REPLICAS_ANNOTATION = "serving.volcano-tpu.io/min-replicas"
MAX_REPLICAS_ANNOTATION = "serving.volcano-tpu.io/max-replicas"
TARGET_QPS_ANNOTATION = \
    "serving.volcano-tpu.io/target-qps-per-replica"
# Directory serving workers publish stats under; one file per pod,
# named STATS_FILE_PREFIX + <pod uid> + ".json" (the goodput
# progress-dir convention).
STATS_DIR_ANNOTATION = "serving.volcano-tpu.io/stats-dir"

# Env injected by the jax job plugin for serving-class jobs: the stats
# file THIS replica writes, plus the same restart/resize epoch the
# goodput contract uses (VTP_EPOCH) so the collector can tell a
# restarted replica from a rolled-back counter.
ENV_STATS_FILE = "VTP_SERVING_STATS_FILE"

STATS_FILE_PREFIX = "vtps-"
STATS_FILE_SUFFIX = ".json"

# bounded scale-direction enum (serving_scale_decisions_total label)
SCALE_KINDS = ("up", "down")

# Stats record fields (JSON object, atomically replaced per beat):
#   requests  int   CUMULATIVE requests served by this replica
#   slo_ok    int   cumulative requests answered within the SLO
#   p50_ms    float windowed latency median
#   p99_ms    float windowed latency p99
#   ts        float wall-clock seconds of the last beat
#   epoch     int   restart/resize epoch (VTP_EPOCH passthrough)


def stats_file_for(root: str, uid: str) -> str:
    import os
    return os.path.join(
        root, f"{STATS_FILE_PREFIX}{uid}{STATS_FILE_SUFFIX}")


# -- pod-level annotations (written by the agent's ServingHandler) -----
POD_QPS_ANNOTATION = "serving.volcano-tpu.io/qps"
POD_P99_MS_ANNOTATION = "serving.volcano-tpu.io/p99-ms"

# -- podgroup-level annotations (folded from ServingReport by the
#    STORE — the per-group summary every watch mirror sees) ------------
PG_QPS_ANNOTATION = "serving.volcano-tpu.io/qps"
PG_P50_MS_ANNOTATION = "serving.volcano-tpu.io/p50-ms"
PG_P99_MS_ANNOTATION = "serving.volcano-tpu.io/p99-ms"
# Cumulative request ledgers, ACCUMULATED across reports the way the
# goodput pod-seconds ledger is: each fold contributes only the diff
# against the node's previous report, so several nodes hosting one
# group never double-count and a lost-ack re-post is idempotent.
PG_REQUESTS_ANNOTATION = "serving.volcano-tpu.io/requests"
PG_SLO_OK_ANNOTATION = "serving.volcano-tpu.io/slo-ok"
PG_REPLICAS_ANNOTATION = "serving.volcano-tpu.io/reporting-replicas"
PG_EPOCH_ANNOTATION = "serving.volcano-tpu.io/epoch"
PG_UPDATED_TS_ANNOTATION = "serving.volcano-tpu.io/updated-ts"

# -- autoscaler decision annotations (controllers/serving.py) ----------
# The last decision and its wall time, for `vtpctl serve` and the
# bench's decision->chips-free->serving latency measurement.
PG_LAST_DECISION_ANNOTATION = "serving.volcano-tpu.io/last-decision"
PG_LAST_DECISION_TS_ANNOTATION = \
    "serving.volcano-tpu.io/last-decision-ts"
# Slices currently hosting this group's replicas, stamped by the
# autoscaler from live placements — the topology anchor the
# serving-aware shrink scores training victims against.
PG_POOL_SLICES_ANNOTATION = "serving.volcano-tpu.io/pool-slices"
# Stamped (alongside avoid-slices) on a TRAINING gang whose shrink was
# funded by a serving scale-up: the elastic plugin's avoid filter
# switches to the serving-victim message (bounded reason
# `serving-preemption-victim`), and the elastic controller pops it
# with the avoid preference at resume.
VICTIM_ANNOTATION = "serving.volcano-tpu.io/preemption-victim"

# every accumulated/maxed fold key, for the sticky re-apply
# (cache/fake_cluster.py): a whole-podgroup write from a mirror that
# predates a fold must not erase the serving summary
PG_FOLD_KEYS = (
    PG_QPS_ANNOTATION, PG_P50_MS_ANNOTATION, PG_P99_MS_ANNOTATION,
    PG_REQUESTS_ANNOTATION, PG_SLO_OK_ANNOTATION,
    PG_REPLICAS_ANNOTATION, PG_EPOCH_ANNOTATION,
    PG_UPDATED_TS_ANNOTATION,
)


def ann_float(obj_or_ann, key: str, default: float = 0.0) -> float:
    """Tolerant float read of an annotation (podgroup or dict)."""
    ann = getattr(obj_or_ann, "annotations", obj_or_ann) or {}
    try:
        return float(ann.get(key, default))
    except (TypeError, ValueError):
        return default


def is_serving(obj) -> bool:
    """A podgroup/vcjob declaring a p99 SLO is serving-class."""
    return SLO_P99_MS_ANNOTATION in (
        getattr(obj, "annotations", None) or {})


def slo_p99_ms(obj) -> Optional[float]:
    ann = getattr(obj, "annotations", obj) or {}
    if SLO_P99_MS_ANNOTATION not in ann:
        return None
    try:
        v = float(ann[SLO_P99_MS_ANNOTATION])
    except (TypeError, ValueError):
        return None
    return v if v > 0 else None


def replica_range(obj) -> Optional[Tuple[int, int]]:
    """(min, max) replicas, or None when not declared/invalid."""
    ann = getattr(obj, "annotations", obj) or {}
    try:
        lo = int(ann[MIN_REPLICAS_ANNOTATION])
        hi = int(ann[MAX_REPLICAS_ANNOTATION])
    except (KeyError, TypeError, ValueError):
        return None
    if lo < 1 or hi < lo:
        return None
    return lo, hi


def target_qps_per_replica(obj, default: float = 0.0) -> float:
    return ann_float(obj, TARGET_QPS_ANNOTATION, default)


def pool_slices(obj) -> List[str]:
    ann = getattr(obj, "annotations", obj) or {}
    raw = ann.get(PG_POOL_SLICES_ANNOTATION, "")
    return [s for s in raw.split(",") if s]


@dataclass
class ReplicaServing:
    """One serving replica's measured traffic, as the agent saw it."""

    pod_key: str = ""            # ns/name
    uid: str = ""
    job: str = ""                # owning podgroup key (ns/name)
    epoch: int = 0               # restart/resize epoch of the record
    qps: float = 0.0             # windowed EWMA request rate
    p50_ms: float = 0.0          # windowed latency quantiles
    p99_ms: float = 0.0
    # CUMULATIVE ledgers (this replica's lifetime on this node).  The
    # store folds the per-pod diff against the node's previous report,
    # so a re-posted report after a lost ack is idempotent — deltas on
    # the wire would double-count whenever the server folded a report
    # whose response never arrived (the GoodputReport argument).
    requests: int = 0
    slo_ok: int = 0


@dataclass
class ServingReport:
    """Per-node serving summary the agent posts to the state server
    (one per sync, change-elided; keyed by node like GoodputReport)."""

    node: str = ""
    ts: float = 0.0
    usages: List[ReplicaServing] = field(default_factory=list)

    @property
    def name(self) -> str:      # kinds.py keys servingreport by name
        return self.node
