"""Minimal pod model.

The framework is a standalone control plane; this Pod type is the unit of
work the scheduler binds and the job controller materializes.  It carries
exactly the fields the scheduling stack consumes (reference: corev1.Pod
as used by pkg/scheduler/api/pod_info.go and job controller pod
templates) — requests, placement constraints, lifecycle phase.
"""

from __future__ import annotations

import itertools
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import TaskStatus

_uid_counter = itertools.count(1)


def new_uid() -> str:
    return f"uid-{next(_uid_counter)}-{uuid.uuid4().hex[:8]}"


@dataclass
class Toleration:
    key: str = ""
    operator: str = "Equal"      # Equal | Exists
    value: str = ""
    effect: str = ""             # NoSchedule | PreferNoSchedule | NoExecute | ""

    def tolerates(self, taint: "Taint") -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if self.operator == "Exists":
            return not self.key or self.key == taint.key
        return self.key == taint.key and self.value == taint.value


@dataclass
class Taint:
    key: str = ""
    value: str = ""
    effect: str = "NoSchedule"


@dataclass
class Container:
    name: str = "main"
    image: str = ""
    command: Optional[List[str]] = None
    requests: Dict[str, object] = field(default_factory=dict)
    limits: Dict[str, object] = field(default_factory=dict)
    env: Dict[str, str] = field(default_factory=dict)
    ports: List[int] = field(default_factory=list)


@dataclass
class PodAffinityTerm:
    """One inter-pod (anti-)affinity term (k8s PodAffinityTerm
    analogue, reference predicates.go:212-388 wrapping the upstream
    interpodaffinity plugin).

    selector: label -> allowed values (AND across keys, OR within a
    key's list — same shape as Pod.affinity_node_terms).  A node
    satisfies the term when a matching assigned pod exists in the same
    topology domain (nodes sharing the node-label `topology_key`).
    namespaces: where matching pods are searched; empty = the incoming
    pod's own namespace.  weight: only meaningful for preferred terms.
    """

    selector: Dict[str, List[str]] = field(default_factory=dict)
    topology_key: str = "kubernetes.io/hostname"
    namespaces: List[str] = field(default_factory=list)
    weight: int = 1

    def matches(self, labels: Dict[str, str]) -> bool:
        return all(labels.get(k) in vals
                   for k, vals in self.selector.items())


@dataclass
class PreferredNodeTerm:
    """One preferredDuringScheduling node-affinity term (k8s
    PreferredSchedulingTerm analogue; scored by nodeorder's
    nodeaffinity.weight scorer, reference nodeorder.go:51-52).

    term: label -> allowed values, same shape as one entry of
    Pod.affinity_node_terms.  weight: added to the node's score when
    the term matches.
    """

    weight: int = 1
    term: Dict[str, List[str]] = field(default_factory=dict)

    def matches(self, labels: Dict[str, str]) -> bool:
        return all(labels.get(k) in vals for k, vals in self.term.items())


@dataclass
class Pod:
    name: str
    namespace: str = "default"
    uid: str = field(default_factory=new_uid)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)

    containers: List[Container] = field(default_factory=list)
    init_containers: List[Container] = field(default_factory=list)

    node_name: str = ""                      # binding target once bound
    node_selector: Dict[str, str] = field(default_factory=dict)
    affinity_node_terms: Optional[List[Dict[str, List[str]]]] = None
    # ^ simplified nodeAffinity: OR over terms; each term is a map of
    #   label -> allowed values (AND within a term).
    preferred_node_affinity: List[PreferredNodeTerm] = \
        field(default_factory=list)
    tolerations: List[Toleration] = field(default_factory=list)
    # inter-pod affinity (plugins/interpodaffinity.py)
    pod_affinity: List[PodAffinityTerm] = field(default_factory=list)
    pod_anti_affinity: List[PodAffinityTerm] = field(default_factory=list)
    preferred_pod_affinity: List[PodAffinityTerm] = \
        field(default_factory=list)
    preferred_pod_anti_affinity: List[PodAffinityTerm] = \
        field(default_factory=list)
    priority: int = 0
    priority_class: str = ""
    scheduler_name: str = "volcano-tpu"
    scheduling_gates: List[str] = field(default_factory=list)
    preemptable: bool = True

    phase: TaskStatus = TaskStatus.PENDING
    exit_code: Optional[int] = None   # main container exit, when terminated
    status_message: str = ""
    nominated_node: str = ""
    owner: str = ""                          # vcjob uid that owns this pod
    task_spec: str = ""                      # task (replica-group) name
    task_index: int = 0

    def resource_requests(self) -> Resource:
        """Aggregate container requests; init containers take per-dim max
        (k8s effective-request semantics).

        The parse is memoized per pod object (requests are immutable
        after creation; watch events replace the whole instance): at
        5k-host scale the per-snapshot quantity re-parsing dominated
        snapshot cost.  A clone is returned so callers can mutate."""
        cached = self.__dict__.get("_resreq_cache")
        if cached is None:
            cached = Resource()
            for c in self.containers:
                cached.add(Resource.from_resource_list(c.requests))
            for c in self.init_containers:
                cached.set_max(Resource.from_resource_list(c.requests))
            self._resreq_cache = cached
        return cached.clone()

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def is_best_effort(self) -> bool:
        return self.resource_requests().is_empty()

    def is_terminated(self) -> bool:
        return self.phase in (TaskStatus.SUCCEEDED, TaskStatus.FAILED)

    def clone(self) -> "Pod":
        import copy
        return copy.deepcopy(self)


def make_pod(name: str, namespace: str = "default",
             requests: Optional[Dict[str, object]] = None,
             labels: Optional[Dict[str, str]] = None,
             annotations: Optional[Dict[str, str]] = None,
             node_name: str = "",
             phase: TaskStatus = TaskStatus.PENDING,
             priority: int = 0,
             **kwargs) -> Pod:
    """Test/controller helper to build a single-container pod."""
    return Pod(
        name=name, namespace=namespace,
        labels=dict(labels or {}), annotations=dict(annotations or {}),
        containers=[Container(requests=dict(requests or {}))],
        node_name=node_name, phase=phase, priority=priority, **kwargs,
    )
