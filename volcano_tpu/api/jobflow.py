"""JobFlow / JobTemplate CRD types (flow/v1alpha1 analogue).

Reference parity: staging/.../flow/v1alpha1/jobflow_types.go:34-51
(Flow{name, dependsOn{targets, probes}, patch}) and JobTemplate.
"""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from volcano_tpu.api.pod import new_uid
from volcano_tpu.api.vcjob import VCJob


class JobFlowPhase(enum.Enum):
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEED = "Succeed"
    TERMINATING = "Terminating"
    FAILED = "Failed"


@dataclass
class FlowDependsOn:
    targets: List[str] = field(default_factory=list)
    # probes relax the default "target Completed" gate: a dependency is
    # satisfied once the target reaches the probed phase (reference
    # flow/v1alpha1 DependsOn.Probes — status-based analogue of its
    # HTTP/TCP pod probes).  e.g. [{"phase": "Running"}]
    probes: List[dict] = field(default_factory=list)


@dataclass
class Flow:
    """One step of the DAG: deploy job from template *name* once every
    target dependency has Completed."""

    name: str                     # job template name
    depends_on: Optional[FlowDependsOn] = None
    patch: Dict[str, object] = field(default_factory=dict)
    # ^ shallow spec overrides applied to the template (e.g. queue)


@dataclass
class JobTemplate:
    name: str
    namespace: str = "default"
    uid: str = field(default_factory=new_uid)
    job: Optional[VCJob] = None   # the vcjob spec to stamp out

    # status: names of live jobs stamped from this template
    # (reference JobTemplateStatus.JobDependsOnList)
    job_depends_on_list: List[str] = field(default_factory=list)

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class JobFlow:
    name: str
    namespace: str = "default"
    uid: str = field(default_factory=new_uid)
    flows: List[Flow] = field(default_factory=list)
    job_retain_policy: str = "retain"   # retain | delete

    phase: JobFlowPhase = JobFlowPhase.PENDING
    deployed_jobs: List[str] = field(default_factory=list)
    creation_time: float = field(default_factory=time.time)

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def job_name(self, flow_name: str) -> str:
        return f"{self.name}-{flow_name}"
