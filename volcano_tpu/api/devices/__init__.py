"""Pluggable device layer (reference: pkg/scheduler/api/shared_device_pool.go).

A Devices implementation owns the per-node accounting for one device
class.  The deviceshare plugin bridges these into predicate + score
callbacks.  TPU is the first-class device here (reference ships
nvidia vGPU/gpushare + Ascend NPU; the TPU model replaces GPU
memory/core sharing with atomic slice-membership semantics).
"""

from __future__ import annotations

import abc
from typing import Optional

from volcano_tpu.api.fit_error import Status


class Devices(abc.ABC):
    """Per-node device state (shared_device_pool.go:33 Devices iface)."""

    name = "device"

    @abc.abstractmethod
    def has_device_request(self, task) -> bool:
        """Does this task ask for this device class?"""

    @abc.abstractmethod
    def filter_node(self, task) -> Optional[Status]:
        """None if the node can serve the task's device request."""

    @abc.abstractmethod
    def score_node(self, task) -> float:
        """Device-aware node score (higher is better)."""


from volcano_tpu.api.devices.tpu.device_info import TPUDevices  # noqa: E402,F401
