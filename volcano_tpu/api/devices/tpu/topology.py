"""ICI mesh topology math for Cloud TPU slices.

This replaces the reference's NCCL ring/tree distance model
(plugins/deviceshare + network-topology-aware hypernode binpack) with
the physical model of a TPU pod: chips sit on a 2D (v5e/v6e) or 3D
(v4/v5p) ICI mesh/torus; a *slice* is a rectangular sub-mesh carved out
of a pod, provisioned as one node pool where every host carries a fixed
number of chips (4 for the generations modeled here).  Placement quality
is ICI hop distance — hosts in one slice talk over ICI, different
slices only over DCN.

Accelerator naming follows GKE (`cloud.google.com/gke-tpu-accelerator`),
e.g. tpu-v5-lite-podslice with topology "16x16" = v5e-256.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Sequence, Tuple

# chips per host by accelerator family (GKE podslice machine shapes)
CHIPS_PER_HOST: Dict[str, int] = {
    "tpu-v4-podslice": 4,
    "tpu-v5-lite-podslice": 4,   # v5e
    "tpu-v5p-slice": 4,
    "tpu-v6e-slice": 4,
    "": 4,
}

# how one host's chips are laid out inside the chip mesh
# (v5e: 2x2 plane; 3D families: 2x2x1 brick)
HOST_SHAPE_2D = (2, 2)
HOST_SHAPE_3D = (2, 2, 1)


def parse_topology(s: str) -> Tuple[int, ...]:
    """Parse "16x16" or "4x4x8" into a dims tuple."""
    if not s:
        return ()
    try:
        dims = tuple(int(p) for p in s.lower().split("x"))
    except ValueError:
        return ()
    return dims if all(d > 0 for d in dims) else ()


def chips_in(topology: Sequence[int]) -> int:
    n = 1
    for d in topology:
        n *= d
    return n if topology else 0


def host_grid(topology: Sequence[int]) -> Tuple[int, ...]:
    """Host-granularity grid dims for a chip topology."""
    shape = HOST_SHAPE_3D if len(topology) == 3 else HOST_SHAPE_2D
    return tuple(max(1, t // s) for t, s in zip(topology, shape))


def hosts_in(topology: Sequence[int]) -> int:
    return chips_in(host_grid(topology))


def host_coords(worker_id: int, topology: Sequence[int]) -> Tuple[int, ...]:
    """Row-major host coordinates in the host grid for a worker index."""
    grid = host_grid(topology)
    coords = []
    rem = worker_id
    for d in reversed(grid):
        coords.append(rem % d)
        rem //= d
    return tuple(reversed(coords))


def ici_distance(a: Sequence[int], b: Sequence[int],
                 torus: Optional[Sequence[int]] = None) -> int:
    """Manhattan ICI hop distance between host coords; wraparound links
    if *torus* gives the grid dims (v4/v5p tori)."""
    dist = 0
    for i, (x, y) in enumerate(zip(a, b)):
        d = abs(x - y)
        if torus is not None and i < len(torus) and torus[i] > 0:
            d = min(d, torus[i] - d)
        dist += d
    return dist


@dataclass(frozen=True)
class SliceTopology:
    """Static identity of one provisioned slice."""

    name: str
    accelerator: str = "tpu-v5-lite-podslice"
    topology: Tuple[int, ...] = (4, 4)

    @property
    def chips_per_host(self) -> int:
        return CHIPS_PER_HOST.get(self.accelerator, 4)

    @property
    def num_chips(self) -> int:
        return chips_in(self.topology)

    @property
    def num_hosts(self) -> int:
        return max(1, self.num_chips // self.chips_per_host)

    @property
    def is_multi_host(self) -> bool:
        return self.num_hosts > 1

    def host_coords(self, worker_id: int) -> Tuple[int, ...]:
        return host_coords(worker_id, self.topology)

    def mesh_axes(self) -> Tuple[int, ...]:
        """Device mesh shape a JAX workload would use across this slice:
        (hosts, chips_per_host) flattened to the chip topology."""
        return self.topology

    def worker_distance(self, a: int, b: int) -> int:
        return ici_distance(self.host_coords(a), self.host_coords(b),
                            torus=host_grid(self.topology)
                            if len(self.topology) == 3 else None)


def diameter(topology: Sequence[int]) -> int:
    """Max host-to-host ICI distance within a slice (mesh assumption)."""
    grid = host_grid(topology)
    return sum(d - 1 for d in grid)


# Well-known slice shapes by common name (subset for tests/benchmarks).
WELL_KNOWN = {
    "v5e-4": SliceTopology("", "tpu-v5-lite-podslice", (2, 2)),
    "v5e-16": SliceTopology("", "tpu-v5-lite-podslice", (4, 4)),
    "v5e-64": SliceTopology("", "tpu-v5-lite-podslice", (8, 8)),
    "v5e-256": SliceTopology("", "tpu-v5-lite-podslice", (16, 16)),
    "v5p-128": SliceTopology("", "tpu-v5p-slice", (4, 4, 8)),
    "v5p-256": SliceTopology("", "tpu-v5p-slice", (4, 8, 8)),
    "v5p-1024": SliceTopology("", "tpu-v5p-slice", (8, 8, 16)),
}


def slice_for(name: str, kind: str) -> SliceTopology:
    base = WELL_KNOWN[kind]
    return SliceTopology(name, base.accelerator, base.topology)
