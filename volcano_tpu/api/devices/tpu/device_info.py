"""TPU device accounting for one node.

Reference parity: pkg/scheduler/api/devices/nvidia/vgpu/device_info.go
(GPUDevices implementing the Devices interface) — rebuilt for TPU
semantics: chips are NOT shareable or partitionable at schedule time;
a host in a multi-host slice must be consumed whole (all its chips by
one pod) because the XLA runtime owns the full ICI mesh; single-host
slices may pack multiple small-chip pods only when the accelerator
supports sub-host granularity (1, 2, or 4 chips on v5e 1-host slices).
"""

from __future__ import annotations

import logging
from typing import Optional

from volcano_tpu.api.devices import Devices
from volcano_tpu.api.fit_error import Status, unschedulable
from volcano_tpu.api.resource import TPU
from volcano_tpu.api.devices.tpu.topology import SliceTopology, parse_topology

log = logging.getLogger(__name__)

_VALID_SUBHOST_CHIPS = {1, 2, 4, 8}


class TPUDevices(Devices):
    name = "tpu"

    def __init__(self, node_info):
        self.node = node_info
        # the label-derived identity is static per Node object (watch
        # events replace nodes wholesale), so memoize it there — same
        # pattern as node_info._parsed_res; rebuilding it per snapshot
        # showed up in the 5k-host cycle profile
        raw = node_info.node
        static = raw.__dict__.get("_tpu_static") if raw else None
        if static is None:
            slice_name = node_info.tpu_slice
            accelerator = node_info.labels.get(
                "cloud.google.com/gke-tpu-accelerator", "")
            topology = parse_topology(node_info.tpu_topology)
            static = (slice_name, accelerator, topology,
                      node_info.tpu_worker_id,
                      SliceTopology(slice_name, accelerator, topology)
                      if topology else None)
            if raw is not None:
                raw._tpu_static = static
        (self.slice_name, self.accelerator, self.topology,
         self.worker_id, self.slice) = static
        self.chips_total = node_info.allocatable.get(TPU)

    @property
    def chips_free(self) -> float:
        return self.node.idle.get(TPU)

    @property
    def chips_free_future(self) -> float:
        """Free chips once in-flight releases complete — the filter
        must not veto placements that pipeline onto releasing hosts
        (preempt/reclaim victims)."""
        return self.node.future_idle().get(TPU)

    @property
    def is_tpu_node(self) -> bool:
        return self.chips_total > 0

    def has_device_request(self, task) -> bool:
        return task.resreq.get(TPU) > 0

    @staticmethod
    def task_requests_device(task) -> bool:
        """Class-level twin of has_device_request (the request is
        task-only for TPUs): lets deviceshare's prepared sweep skip
        the per-node device walk for chipless tasks."""
        return task.resreq.get(TPU) > 0

    def filter_node(self, task) -> Optional[Status]:
        req = task.resreq.get(TPU)
        if req <= 0:
            return None
        if not self.is_tpu_node:
            return unschedulable("node has no TPU chips", "tpu",
                                 resolvable=False)
        if self.slice and self.slice.is_multi_host:
            # multi-host slice: a pod takes a whole host's chips —
            # the XLA runtime on each worker drives all local chips.
            if req != self.slice.chips_per_host:
                return unschedulable(
                    f"multi-host TPU slice requires whole-host requests "
                    f"of {self.slice.chips_per_host} chips, got {req:g}",
                    "tpu", resolvable=False)
            if self.chips_free_future < req:
                # evicting the occupant frees the whole host — preempt/
                # reclaim may wave this through and re-check post-evict
                return unschedulable(
                    "TPU host already occupied", "tpu",
                    evict_curable=True)
        else:
            if req not in _VALID_SUBHOST_CHIPS:
                return unschedulable(
                    f"invalid TPU chip request {req:g} "
                    f"(must be one of {sorted(_VALID_SUBHOST_CHIPS)})",
                    "tpu", resolvable=False)
            if req > self.chips_total:
                return unschedulable(
                    f"node has only {self.chips_total:g} TPU chips",
                    "tpu", resolvable=False)
            if req > self.chips_free_future:
                return unschedulable("not enough free TPU chips", "tpu",
                                     evict_curable=True)
        return None

    def score_node(self, task) -> float:
        """Pack partially-used single-host slices first so whole hosts
        (and whole slices) stay free for gang jobs."""
        req = task.resreq.get(TPU)
        if req <= 0 or not self.is_tpu_node:
            return 0.0
        used_frac = 1.0 - (self.chips_free / self.chips_total
                           if self.chips_total else 0.0)
        return 100.0 * used_frac
