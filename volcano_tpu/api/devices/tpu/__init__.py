"""TPU slice device model."""

from volcano_tpu.api.devices.tpu.topology import (
    SliceTopology, parse_topology, chips_in, ici_distance,
)
from volcano_tpu.api.devices.tpu.device_info import TPUDevices

__all__ = ["SliceTopology", "parse_topology", "chips_in", "ici_distance",
           "TPUDevices"]
