"""NodeInfo — per-node scheduling state.

Reference parity: pkg/scheduler/api/node_info.go:52-101 (Idle / Used /
Releasing / Pipelined accounting, FutureIdle, oversubscription, task
add/remove/status transitions, taints).  TPU-first addition: each node
carries its TPU slice membership + ICI coordinates so the device layer
and topology plugin can do mesh math without re-parsing labels.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, TYPE_CHECKING

from volcano_tpu.api.pod import Taint
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import (
    TPU_COORDS_LABEL,
    TPU_SLICE_LABEL,
    TPU_TOPOLOGY_LABEL,
    TPU_WORKER_ID_LABEL,
    TaskStatus,
)

if TYPE_CHECKING:
    from volcano_tpu.api.job_info import TaskInfo  # noqa: F401


@dataclass
class Node:
    """Cluster node object (corev1.Node analogue)."""

    name: str
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    allocatable: Dict[str, object] = field(default_factory=dict)
    capacity: Dict[str, object] = field(default_factory=dict)
    taints: List[Taint] = field(default_factory=list)
    ready: bool = True
    unschedulable: bool = False
    images: List[str] = field(default_factory=list)
    # ^ container images present in the node's local cache
    #   (corev1.NodeStatus.Images analogue; scored by nodeorder's
    #   imagelocality.weight scorer)


class NodeInfo:
    """Scheduler-side view of one node with resource accounting.

    Invariant maintained across task transitions:
      allocatable == idle + used            (used includes releasing)
      futureIdle() == idle + releasing - pipelined
    """

    def __init__(self, node: Optional[Node] = None, name: str = ""):
        self.node: Optional[Node] = node
        self.name: str = node.name if node else name
        if node is not None:
            # memoize the quantity parse on the node object (replaced
            # wholesale by watch events, so staleness is impossible);
            # re-parsing every snapshot dominated 5k-host cycle cost
            parsed = node.__dict__.get("_parsed_res")
            if parsed is None:
                parsed = (Resource.from_resource_list(node.allocatable),
                          Resource.from_resource_list(
                              node.capacity or node.allocatable))
                node._parsed_res = parsed
            self.allocatable = parsed[0].clone()
            self.capability = parsed[1].clone()
        else:
            self.allocatable = Resource()
            self.capability = Resource()
        self.idle = self.allocatable.clone()
        # reclaimable slack the node agent measured from real usage —
        # usable ONLY by best-effort-QoS tasks (reference
        # node_info.go:83-89 OversubscriptionResource)
        self.oversubscription = Resource()
        if node is not None:
            from volcano_tpu.api.types import (
                OVERSUBSCRIPTION_CPU_ANNOTATION,
            )
            raw = node.annotations.get(OVERSUBSCRIPTION_CPU_ANNOTATION)
            if raw:
                try:
                    extra = float(raw)
                    if extra > 0:
                        self.oversubscription = Resource({"cpu": extra})
                except ValueError:
                    pass
        self.used = Resource()
        self.releasing = Resource()
        self.pipelined = Resource()
        self.tasks: Dict[str, "TaskInfo"] = {}
        # host-port multiset (port -> holder count) maintained by
        # add/remove_task so the ports predicate is O(task ports), not
        # O(tasks on node) per check
        self.occupied_ports: Dict[int, int] = {}
        # Conflict-aware binder optimistic-concurrency token
        # (reference api/node_info.go:100 BindGeneration).
        self.bind_generation: int = 0
        self.others: Dict[str, object] = {}   # device registry payloads

    # -- TPU identity --------------------------------------------------

    @property
    def labels(self) -> Dict[str, str]:
        return self.node.labels if self.node else {}

    @property
    def tpu_slice(self) -> str:
        return self.labels.get(TPU_SLICE_LABEL, "")

    @property
    def tpu_topology(self) -> str:
        return self.labels.get(TPU_TOPOLOGY_LABEL, "")

    @property
    def tpu_worker_id(self) -> int:
        try:
            return int(self.labels.get(TPU_WORKER_ID_LABEL, "-1"))
        except ValueError:
            return -1

    @property
    def ici_coords(self) -> Optional[tuple]:
        raw = self.labels.get(TPU_COORDS_LABEL)
        if not raw:
            return None
        try:
            return tuple(int(x) for x in raw.split(","))
        except ValueError:
            return None

    # -- state --------------------------------------------------------

    @property
    def ready(self) -> bool:
        return bool(self.node and self.node.ready and not self.node.unschedulable)

    @property
    def taints(self) -> List[Taint]:
        return self.node.taints if self.node else []

    def future_idle(self) -> Resource:
        """Resources available after in-flight releases complete, minus
        resources already promised to pipelined tasks."""
        return (self.idle.clone().add(self.releasing)
                .sub_unchecked(self.pipelined))

    def oversub_remaining(self) -> Resource:
        """Unconsumed oversubscription slack: the published slack minus
        whatever BE work has already overdrafted past allocatable."""
        overdraft, _ = self.used.diff(self.allocatable)
        return self.oversubscription.clone().sub_unchecked(overdraft)

    # -- task accounting ----------------------------------------------

    def add_task(self, task: "TaskInfo"):
        """Account *task* onto this node.

        The node stores a CLONE of the task so later job-side status
        mutations cannot desync node accounting (reference node_info.go
        AddTask "Node will hold a copy of task").  Scheduler-initiated
        placements (ALLOCATED/BINDING) must fit exactly and raise on
        overflow; replayed pods (RUNNING/BOUND observed from the
        cluster) clamp instead so cache rebuild survives a node whose
        allocatable shrank under existing pods.
        """
        if task.uid in self.tasks:
            raise KeyError(f"task {task.key} already on node {self.name}")
        req = task.resreq
        if task.status is TaskStatus.RELEASING:
            self.releasing.add(req)
            self.idle.sub_unchecked(req)
            self.used.add(req)
        elif task.status is TaskStatus.PIPELINED:
            self.pipelined.add(req)
        elif task.occupies_resources():
            from volcano_tpu.api.types import (
                QOS_BEST_EFFORT, QOS_LEVEL_ANNOTATION,
            )
            budget = self.idle
            if task.pod.annotations.get(QOS_LEVEL_ANNOTATION) == \
                    QOS_BEST_EFFORT:
                # only BE tasks may overdraft into measured slack
                budget = self.idle.clone().add(self.oversub_remaining())
            if task.status in (TaskStatus.ALLOCATED, TaskStatus.BINDING) \
                    and not req.less_equal(budget):
                raise ValueError(
                    f"node {self.name} has insufficient idle "
                    f"{self.idle} for task {task.key} requiring {req}")
            self.idle.sub_unchecked(req)
            self.used.add(req)
        task.node_name = self.name
        self.tasks[task.uid] = task.clone()
        for c in task.pod.containers:
            for port in c.ports:
                self.occupied_ports[port] = \
                    self.occupied_ports.get(port, 0) + 1

    def remove_task(self, task: "TaskInfo"):
        existing = self.tasks.pop(task.uid, None)
        if existing is None:
            return
        for c in existing.pod.containers:
            for port in c.ports:
                left = self.occupied_ports.get(port, 0) - 1
                if left > 0:
                    self.occupied_ports[port] = left
                else:
                    self.occupied_ports.pop(port, None)
        req = existing.resreq
        if existing.status is TaskStatus.RELEASING:
            self.releasing.sub_unchecked(req)
            self.idle.add(req)
            self.used.sub_unchecked(req)
        elif existing.status is TaskStatus.PIPELINED:
            self.pipelined.sub_unchecked(req)
        elif existing.occupies_resources():
            self.idle.add(req)
            self.used.sub_unchecked(req)

    def update_task_status(self, task: "TaskInfo", status: TaskStatus):
        """Remove+re-add under the new status to keep accounting exact.

        Dispatches the removal on the node's OWN copy of the task (whose
        status may lag the caller's), then re-adds under *status*.
        """
        self.remove_task(task)
        task.status = status
        self.add_task(task)

    def clone(self) -> "NodeInfo":
        c = NodeInfo.__new__(NodeInfo)
        c.node = self.node
        c.name = self.name
        c.allocatable = self.allocatable.clone()
        c.capability = self.capability.clone()
        c.idle = self.idle.clone()
        c.used = self.used.clone()
        c.releasing = self.releasing.clone()
        c.pipelined = self.pipelined.clone()
        c.oversubscription = self.oversubscription.clone()
        c.tasks = dict(self.tasks)
        c.occupied_ports = dict(self.occupied_ports)
        c.bind_generation = self.bind_generation
        c.others = dict(self.others)
        return c

    def __repr__(self):
        return (f"NodeInfo({self.name}, idle={self.idle}, used={self.used}, "
                f"tasks={len(self.tasks)})")
