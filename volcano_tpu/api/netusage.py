"""Bandwidth accounting wire types — measure what the enforcer shapes.

The agent's tc/net_cls enforcement (agent/enforcer.py) SHAPES per-pod
DCN traffic; these types carry what the agent MEASURES back through
the control plane, closing the enforce→measure→react loop the
reference closes with pinned eBPF watermark maps
(pkg/networkqos/utils/ebpf/map.go:64-79).

One BandwidthReport per node per agent sync (posted only when it
materially changes): per-pod EWMA rates keyed by the enforcer's
net_cls classids, the node-level online/offline totals, and the
violation tally.  The state server folds the node-level summary into
node annotations (cache/fake_cluster.py put_object hook) so every
watch mirror — the scheduler's included — sees saturation without
decoding reports; the full per-pod detail stays on the report object
for vtpctl / GET /bandwidth consumers.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

# -- measured-side annotations (the published half of the loop) --------
# Pod-level (written by the agent's netaccounting handler, persisted
# through the agent's pod-annotation sync):
POD_TX_ANNOTATION = "networkqos.volcano-tpu.io/tx-mbps"
POD_RX_ANNOTATION = "networkqos.volcano-tpu.io/rx-mbps"
POD_VIOLATING_ANNOTATION = "networkqos.volcano-tpu.io/violating"
POD_VIOLATIONS_ANNOTATION = "networkqos.volcano-tpu.io/violations"
# Declared online watermark: an online pod carrying this annotation
# asserts it should stay under N mbps (offline pods' watermark is the
# enforced per-pod cap, networkqos.volcano-tpu.io/pod-limit-mbps).
POD_WATERMARK_ANNOTATION = "networkqos.volcano-tpu.io/watermark-mbps"
# Node-level (folded from BandwidthReport by the STORE, not the agent,
# so wire mirrors see them via node watch events):
NODE_MEASURED_OFFLINE_ANNOTATION = \
    "networkqos.volcano-tpu.io/measured-offline-mbps"
NODE_MEASURED_ONLINE_ANNOTATION = \
    "networkqos.volcano-tpu.io/measured-online-mbps"
NODE_SATURATED_ANNOTATION = "networkqos.volcano-tpu.io/saturated"
NODE_VIOLATING_PODS_ANNOTATION = \
    "networkqos.volcano-tpu.io/violating-pods"

# Measured total / DCN budget fraction past which the agent marks the
# node saturated (nodeorder penalizes placements, bandwidthPressure
# considers victims there).
SATURATION_FRACTION = 0.85


@dataclass
class PodBandwidthUsage:
    """One pod's measured DCN usage, as the agent collector saw it."""

    pod_key: str = ""            # ns/name
    uid: str = ""
    classid: int = 0             # HTB minor the enforcer tagged (0 = online)
    tier: str = "online"         # "online" | "offline"
    tx_mbps: float = 0.0         # windowed EWMA egress rate
    rx_mbps: float = 0.0
    watermark_mbps: float = 0.0  # declared/enforced cap (0 = none)
    violating: bool = False      # currently past hysteresis threshold
    violations: int = 0          # cumulative over-watermark syncs


@dataclass
class BandwidthReport:
    """Per-node usage summary the agent posts to the state server."""

    node: str = ""
    usages: List[PodBandwidthUsage] = field(default_factory=list)
    offline_tx_mbps: float = 0.0   # sum over offline-tier pods
    online_tx_mbps: float = 0.0    # sum over online-tier pods
    total_mbps: float = 0.0        # node DCN budget the split ran on
    violations: int = 0            # pods currently violating
    saturated: bool = False        # measured total past the pressure line

    @property
    def name(self) -> str:         # kinds.py keys bandwidthreport by name
        return self.node
