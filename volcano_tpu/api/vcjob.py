"""Batch Job CRD type (batch/v1alpha1 Job analogue — "vcjob").

Reference parity: staging/.../batch/v1alpha1/job.go:54-126 (JobSpec:
minAvailable, tasks, policies, plugins, queue, maxRetry, ttl,
priorityClassName, minSuccess, networkTopology) and JobStatus.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from volcano_tpu.api.pod import Container, Pod, new_uid
from volcano_tpu.api.podgroup import NetworkTopologySpec
from volcano_tpu.api.types import (
    DEFAULT_QUEUE,
    JobAction,
    JobEvent,
    JobPhase,
)


@dataclass
class LifecyclePolicy:
    """Map a pod/job event (or exit code) to an action.

    Reference: batch/v1alpha1 LifecyclePolicy {action, event, events,
    exitCode, timeout}.
    """

    action: JobAction = JobAction.SYNC_JOB
    event: Optional[JobEvent] = None
    events: List[JobEvent] = field(default_factory=list)
    exit_code: Optional[int] = None
    timeout_seconds: Optional[float] = None

    def matches(self, event: JobEvent, exit_code: Optional[int] = None) -> bool:
        if self.exit_code is not None:
            return exit_code is not None and exit_code == self.exit_code
        evs = set(self.events)
        if self.event is not None:
            evs.add(self.event)
        return event in evs or JobEvent.ANY in evs


@dataclass
class DependsOn:
    """Task-level DAG dependency inside one job (tasks[].dependsOn)."""

    name: List[str] = field(default_factory=list)
    iteration: str = "any"  # any | all


@dataclass
class TaskSpec:
    """One replica group of the job (tasks[] entry)."""

    name: str
    replicas: int = 1
    min_available: Optional[int] = None
    template: Optional[Pod] = None      # pod template (name ignored)
    policies: List[LifecyclePolicy] = field(default_factory=list)
    depends_on: Optional[DependsOn] = None
    max_retry: int = 3
    subgroup: str = ""                  # subGroupPolicy membership
    # explicit subgroup topology (scheduling/v1beta1 types.go:217-223);
    # None + TPU requests => controller defaults to ICI-local hard
    network_topology: Optional["NetworkTopologySpec"] = None

    def template_pod(self) -> Pod:
        if self.template is not None:
            return self.template
        return Pod(name=self.name, containers=[Container()])


@dataclass
class JobCondition:
    status: JobPhase
    last_transition_time: float = field(default_factory=time.time)


@dataclass
class VCJob:
    name: str
    namespace: str = "default"
    uid: str = field(default_factory=new_uid)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)

    # spec
    scheduler_name: str = "volcano-tpu"
    min_available: int = 1
    min_success: Optional[int] = None
    tasks: List[TaskSpec] = field(default_factory=list)
    policies: List[LifecyclePolicy] = field(default_factory=list)
    plugins: Dict[str, List[str]] = field(default_factory=dict)
    queue: str = DEFAULT_QUEUE
    max_retry: int = 3
    ttl_seconds_after_finished: Optional[int] = None
    priority_class: str = ""
    network_topology: Optional[NetworkTopologySpec] = None

    # status
    phase: JobPhase = JobPhase.PENDING
    state_message: str = ""
    pending: int = 0
    running: int = 0
    succeeded: int = 0
    failed: int = 0
    terminating: int = 0
    unknown: int = 0
    version: int = 0            # incremented on restart
    retry_count: int = 0
    conditions: List[JobCondition] = field(default_factory=list)
    creation_time: float = field(default_factory=time.time)
    finish_time: Optional[float] = None
    controlled_resources: Dict[str, str] = field(default_factory=dict)

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def total_replicas(self) -> int:
        return sum(t.replicas for t in self.tasks)

    def task_by_name(self, name: str) -> Optional[TaskSpec]:
        for t in self.tasks:
            if t.name == name:
                return t
        return None

    def clone(self) -> "VCJob":
        import copy
        return copy.deepcopy(self)
