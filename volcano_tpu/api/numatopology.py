"""Numatopology — per-node NUMA inventory object.

Reference parity: staging nodeinfo/v1alpha1 Numatopology CRD
(numatopology_types.go: spec.policies, spec.numares with per-NUMA
allocatable, spec.resReserved) consumed by plugins/numaaware.
TPU-first reading: on a TPU host the inventory that matters is which
cpu NUMA node each PCIe-attached chip group hangs off, so `numa_res`
carries both "cpu" (millicores) and "google.com/tpu" per NUMA cell.

The node agent (or a kubelet shim) publishes one Numatopology per
node; the numaaware plugin prefers it over the legacy annotation.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict

# node-side kubelet policies, mirroring the reference's spec.policies
TOPOLOGY_MANAGER_POLICY = "TopologyManagerPolicy"
CPU_MANAGER_POLICY = "CPUManagerPolicy"

POLICY_NONE = "none"
POLICY_BEST_EFFORT = "best-effort"
POLICY_RESTRICTED = "restricted"
POLICY_SINGLE_NUMA = "single-numa-node"


@dataclass
class Numatopology:
    """NUMA inventory of one node (name == node name).

    `numa_res` carries the node's CURRENT free amount per cell as of
    the exporter's last refresh (reference semantics: the
    resource-exporter republishes from live cgroup state) — not the
    static capacity.  The numaaware plugin layers its own in-session
    deductions on top, so placements made between refreshes are
    accounted for too.
    """

    name: str
    # resource -> numa cell id -> CURRENTLY FREE amount
    # (cpu in MILLIcores to match Resource's internal unit)
    numa_res: Dict[str, Dict[str, float]] = field(default_factory=dict)
    # kubelet policies: {"TopologyManagerPolicy": "single-numa-node", ...}
    policies: Dict[str, str] = field(default_factory=dict)
    # resources the kubelet holds back per node (not per cell)
    res_reserved: Dict[str, float] = field(default_factory=dict)
    # static per-cell capacity; when set, the node agent acts as the
    # exporter and recomputes numa_res from it each sync (see
    # recompute_free)
    capacity_res: Dict[str, Dict[str, float]] = field(default_factory=dict)

    def cell_free(self, resource: str, cell: str) -> float:
        return self.numa_res.get(resource, {}).get(cell, 0.0)

    def cells(self):
        out = set()
        for per_cell in self.numa_res.values():
            out.update(per_cell)
        return sorted(out)

    def recompute_free(self, pod_requests) -> None:
        """Exporter refresh: numa_res = capacity_res minus the running
        pods' requests packed with deduct_request — the SAME algorithm
        the numaaware plugin applies in-session, so the exporter's
        published free cells and the scheduler's deductions agree by
        construction.  pod_requests: iterable of (cpu_millis, tpu_chips).

        No-op when capacity_res is unset — then numa_res is operator-
        published and whoever publishes it owns its freshness.
        """
        if not self.capacity_res:
            return
        cells = sorted({c for per in self.capacity_res.values()
                        for c in per})
        free = [[self.capacity_res.get("cpu", {}).get(c, 0.0),
                 self.capacity_res.get("google.com/tpu", {}).get(c, 0.0)]
                for c in cells]
        for cpu_m, tpu in sorted(pod_requests,
                                 key=lambda r: -(r[0] + r[1])):
            deduct_request(free, cpu_m, tpu)
        # only the two tracked resources are recomputed; anything else
        # published in capacity_res (or operator-set in numa_res) is
        # carried through untouched rather than dropped
        recomputed = {
            "cpu": {c: free[i][0] for i, c in enumerate(cells)},
            "google.com/tpu": {c: free[i][1]
                               for i, c in enumerate(cells)},
        }
        for res, per_cell in self.capacity_res.items():
            if res not in recomputed:
                recomputed[res] = dict(per_cell)
        for res, per_cell in self.numa_res.items():
            if res not in recomputed:
                recomputed[res] = per_cell
        self.numa_res = recomputed


def deduct_request(cells, need_cpu: float, need_tpu: float):
    """Deduct one request from `cells` ([[cpu_free, tpu_free], ...])
    in place: best-fit into the tightest cell that holds it whole,
    else drain largest-first (how the kubelet would spread a request
    no single cell can satisfy).  Returns [(index, dcpu, dtpu)]
    actually taken — the exact-reversal record.

    Single source of truth for the packing heuristic: the numaaware
    plugin's in-session deductions and the node agent's exporter
    refresh both call this, so their views never drift.
    """
    taken = []
    fitting = [(cpu + tpu, i) for i, (cpu, tpu) in enumerate(cells)
               if need_cpu <= cpu and need_tpu <= tpu]
    if fitting:
        _, i = min(fitting)
        cells[i][0] -= need_cpu
        cells[i][1] -= need_tpu
        taken.append((i, need_cpu, need_tpu))
        return taken
    for i in sorted(range(len(cells)),
                    key=lambda j: -(cells[j][0] + cells[j][1])):
        if need_cpu <= 0 and need_tpu <= 0:
            break
        d_cpu = min(need_cpu, cells[i][0])
        d_tpu = min(need_tpu, cells[i][1])
        if d_cpu <= 0 and d_tpu <= 0:
            continue
        cells[i][0] -= d_cpu
        cells[i][1] -= d_tpu
        need_cpu -= d_cpu
        need_tpu -= d_tpu
        taken.append((i, d_cpu, d_tpu))
    return taken


def tpu_host_numatopology(node_name: str, cpu_millis: float,
                          tpu_chips: int, numa_cells: int = 2,
                          policy: str = POLICY_BEST_EFFORT) -> Numatopology:
    """Fresh-host inventory for a typical TPU host: chips and cores
    split evenly across NUMA cells (v5e/v5p hosts are 2-socket, 2
    chips per socket on 4-chip hosts).  "Fresh" = everything free; an
    exporter republishing for a busy host passes live free values."""
    cells = [str(i) for i in range(max(1, numa_cells))]
    per_cpu = cpu_millis / len(cells)
    base, extra = divmod(tpu_chips, len(cells))
    numa_res = {
        "cpu": {c: per_cpu for c in cells},
        "google.com/tpu": {c: float(base + (1 if i < extra else 0))
                           for i, c in enumerate(cells)},
    }
    return Numatopology(name=node_name, numa_res=numa_res,
                        policies={TOPOLOGY_MANAGER_POLICY: policy},
                        capacity_res={k: dict(v)
                                      for k, v in numa_res.items()})
