"""Numatopology — per-node NUMA inventory object.

Reference parity: staging nodeinfo/v1alpha1 Numatopology CRD
(numatopology_types.go: spec.policies, spec.numares with per-NUMA
allocatable, spec.resReserved) consumed by plugins/numaaware.
TPU-first reading: on a TPU host the inventory that matters is which
cpu NUMA node each PCIe-attached chip group hangs off, so `numa_res`
carries both "cpu" (millicores) and "google.com/tpu" per NUMA cell.

The node agent (or a kubelet shim) publishes one Numatopology per
node; the numaaware plugin prefers it over the legacy annotation.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict

# node-side kubelet policies, mirroring the reference's spec.policies
TOPOLOGY_MANAGER_POLICY = "TopologyManagerPolicy"
CPU_MANAGER_POLICY = "CPUManagerPolicy"

POLICY_NONE = "none"
POLICY_BEST_EFFORT = "best-effort"
POLICY_RESTRICTED = "restricted"
POLICY_SINGLE_NUMA = "single-numa-node"


@dataclass
class Numatopology:
    """NUMA inventory of one node (name == node name).

    `numa_res` carries the node's CURRENT free amount per cell as of
    the exporter's last refresh (reference semantics: the
    resource-exporter republishes from live cgroup state) — not the
    static capacity.  The numaaware plugin layers its own in-session
    deductions on top, so placements made between refreshes are
    accounted for too.
    """

    name: str
    # resource -> numa cell id -> CURRENTLY FREE amount
    # (cpu in MILLIcores to match Resource's internal unit)
    numa_res: Dict[str, Dict[str, float]] = field(default_factory=dict)
    # kubelet policies: {"TopologyManagerPolicy": "single-numa-node", ...}
    policies: Dict[str, str] = field(default_factory=dict)
    # resources the kubelet holds back per node (not per cell)
    res_reserved: Dict[str, float] = field(default_factory=dict)

    def cell_free(self, resource: str, cell: str) -> float:
        return self.numa_res.get(resource, {}).get(cell, 0.0)

    def cells(self):
        out = set()
        for per_cell in self.numa_res.values():
            out.update(per_cell)
        return sorted(out)


def tpu_host_numatopology(node_name: str, cpu_millis: float,
                          tpu_chips: int, numa_cells: int = 2,
                          policy: str = POLICY_BEST_EFFORT) -> Numatopology:
    """Fresh-host inventory for a typical TPU host: chips and cores
    split evenly across NUMA cells (v5e/v5p hosts are 2-socket, 2
    chips per socket on 4-chip hosts).  "Fresh" = everything free; an
    exporter republishing for a busy host passes live free values."""
    cells = [str(i) for i in range(max(1, numa_cells))]
    per_cpu = cpu_millis / len(cells)
    base, extra = divmod(tpu_chips, len(cells))
    numa_res = {
        "cpu": {c: per_cpu for c in cells},
        "google.com/tpu": {c: float(base + (1 if i < extra else 0))
                           for i, c in enumerate(cells)},
    }
    return Numatopology(name=node_name, numa_res=numa_res,
                        policies={TOPOLOGY_MANAGER_POLICY: policy})
