"""JSON codec for API objects crossing the wire boundary.

The control plane splits into separate OS processes (state server,
scheduler, controller manager, agents) that exchange CRD-analogue
objects over HTTP/JSON — the stand-in for the reference's apiserver
serialization (staging/src/volcano.sh/apis generated deepcopy/JSON
round-trip).  Rather than hand-writing marshal functions per type, the
codec reflects over the dataclass/enum registry:

  dataclass  -> {"#T": "ClassName", "f": {field: value...}}
  Enum       -> {"#E": ["EnumName", value]}
  Resource   -> {"#R": {dim: amount}}
  plain dict -> passed through ({"#D": {...}} wrapper only if a key
                collides with a tag)
  list/tuple -> list

Decoding tolerates missing/extra fields (forward/backward compat the
way k8s JSON does): unknown keys are dropped, absent ones take the
dataclass default.

Hot-path discipline (the wire fast lane): the control plane ships
pods/podgroups by the thousand through /snapshot, /watch and /delta,
so encode() runs off a per-class PLAN built once — interned type/field
name strings (every payload shares the same key objects instead of
re-allocating "annotations" 5k times per snapshot) and the field's
declared default.  Fields still equal to their default are elided from
the wire body entirely; decode() fills them back from the dataclass
default, which the compat contract above already guarantees.  A
default-shaped pod encodes to a handful of keys instead of ~30.

COROLLARY: a dataclass field's declared default is now part of the
wire contract.  Changing a default between versions was ALWAYS
decode-visible for absent fields; elision widens that to fields the
sender holds at its (old) default — so treat a default change on a
registered wire type as a breaking wire change and ship it as a new
field instead.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import sys
from typing import Any, Dict, Tuple

_TAGS = ("#T", "#E", "#R", "#D")

_CLASSES: Dict[str, type] = {}
_ENUMS: Dict[str, type] = {}
_FIELDS: Dict[str, frozenset] = {}
# cls -> (interned name, ((interned field name, default | _MISSING), ...))
# built lazily per class on first encode; default_factory fields get ONE
# sample value (compared against, never handed out) and only when the
# factory yields an empty builtin container or an immutable scalar —
# anything richer (random uids, Resource objects) never elides
_ENC_PLANS: Dict[type, Tuple[str, tuple]] = {}
_MISSING = dataclasses.MISSING
_built = False


def register_class(cls: type) -> type:
    """Register a dataclass or Enum for wire round-trips."""
    if isinstance(cls, type) and issubclass(cls, enum.Enum):
        _ENUMS[cls.__name__] = cls
    elif dataclasses.is_dataclass(cls):
        _CLASSES[cls.__name__] = cls
        _FIELDS[cls.__name__] = frozenset(
            f.name for f in dataclasses.fields(cls))
    return cls


def _scan(module) -> None:
    for obj in vars(module).values():
        if isinstance(obj, type) and (
                dataclasses.is_dataclass(obj)
                or issubclass(obj, enum.Enum)):
            register_class(obj)


def _build_registry() -> None:
    """Import every module holding wire types and index them.

    Lazy so that importing the codec never drags the controller stack
    into processes that only need the API layer.
    """
    global _built
    if _built:
        return
    from volcano_tpu.api import (goodput, hypernode, jobflow, netusage,
                                 node_info, numatopology, pod, podgroup,
                                 queue, serving, shard, slicehealth,
                                 types, vcjob)
    from volcano_tpu.cache import cluster as cluster_mod
    from volcano_tpu.controllers import cronjob, hyperjob
    for mod in (types, pod, node_info, podgroup, queue, hypernode,
                vcjob, jobflow, netusage, goodput, serving,
                numatopology, shard, slicehealth, cluster_mod, cronjob,
                hyperjob):
        _scan(mod)
    _built = True


def _enc_plan(cls: type) -> Tuple[str, tuple]:
    plan = _ENC_PLANS.get(cls)
    if plan is not None:
        return plan
    _build_registry()
    name = cls.__name__
    if name not in _CLASSES:
        register_class(cls)
    entries = []
    for f in dataclasses.fields(cls):
        default = _MISSING
        if f.default is not _MISSING:
            default = f.default
        elif f.default_factory is not _MISSING:
            sample = f.default_factory()
            # only an EMPTY builtin container is a safe elision
            # anchor for a factory: a factory returning scalars is
            # typically non-deterministic (new_uid, time.time) — its
            # one sampled value must never stand in as "the default",
            # or a value colliding with the sample would decode to a
            # freshly generated DIFFERENT one on the receiver
            if type(sample) in (dict, list, set, tuple) and not sample:
                default = sample
        entries.append((sys.intern(f.name), default))
    plan = (sys.intern(name), tuple(entries))
    _ENC_PLANS[cls] = plan
    return plan


def _is_default(v: Any, default: Any) -> bool:
    if v is default:
        return True
    # exact type match guards bool-vs-int (True == 1) and subclasses
    # whose equality lies about payload differences
    if type(v) is not type(default):
        return False
    try:
        return bool(v == default)
    except Exception:  # noqa: BLE001 — exotic __eq__: never elide
        return False


def encode(obj: Any) -> Any:
    """Encode an API object into JSON-serializable data."""
    from volcano_tpu.api.resource import Resource
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, Resource):
        return {"#R": dict(obj.res)}
    if isinstance(obj, enum.Enum):
        return {"#E": [type(obj).__name__, obj.value]}
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        name, entries = _enc_plan(type(obj))
        fields = {}
        for fname, default in entries:
            v = getattr(obj, fname)
            if default is not _MISSING and _is_default(v, default):
                continue        # decode() restores it from the default
            fields[fname] = encode(v)
        return {"#T": name, "f": fields}
    if isinstance(obj, dict):
        out = {str(k): encode(v) for k, v in obj.items()}
        if any(t in out for t in _TAGS):
            return {"#D": out}
        return out
    if isinstance(obj, (list, tuple, set, frozenset)):
        return [encode(v) for v in obj]
    raise TypeError(f"codec: cannot encode {type(obj).__name__}: {obj!r}")


def decode(data: Any) -> Any:
    """Decode JSON data produced by :func:`encode`."""
    if data is None or isinstance(data, (bool, int, float, str)):
        return data
    if isinstance(data, list):
        return [decode(v) for v in data]
    if isinstance(data, dict):
        if "#R" in data and len(data) == 1:
            from volcano_tpu.api.resource import Resource
            return Resource(data["#R"])
        if "#E" in data and len(data) == 1:
            _build_registry()
            name, value = data["#E"]
            cls = _ENUMS.get(name)
            if cls is None:
                raise KeyError(f"codec: unknown enum {name!r}")
            return cls(value)
        if "#T" in data:
            _build_registry()
            name = data["#T"]
            cls = _CLASSES.get(name)
            if cls is None:
                raise KeyError(f"codec: unknown class {name!r}")
            known = _FIELDS[name]
            kwargs = {k: decode(v) for k, v in data.get("f", {}).items()
                      if k in known}
            return cls(**kwargs)
        if "#D" in data and len(data) == 1:
            return {k: decode(v) for k, v in data["#D"].items()}
        return {k: decode(v) for k, v in data.items()}
    raise TypeError(f"codec: cannot decode {type(data).__name__}")


def dumps(obj: Any) -> str:
    return json.dumps(encode(obj), separators=(",", ":"))


def loads(text: str) -> Any:
    return decode(json.loads(text))
