"""JSON codec for API objects crossing the wire boundary.

The control plane splits into separate OS processes (state server,
scheduler, controller manager, agents) that exchange CRD-analogue
objects over HTTP/JSON — the stand-in for the reference's apiserver
serialization (staging/src/volcano.sh/apis generated deepcopy/JSON
round-trip).  Rather than hand-writing marshal functions per type, the
codec reflects over the dataclass/enum registry:

  dataclass  -> {"#T": "ClassName", "f": {field: value...}}
  Enum       -> {"#E": ["EnumName", value]}
  Resource   -> {"#R": {dim: amount}}
  plain dict -> passed through ({"#D": {...}} wrapper only if a key
                collides with a tag)
  list/tuple -> list

Decoding tolerates missing/extra fields (forward/backward compat the
way k8s JSON does): unknown keys are dropped, absent ones take the
dataclass default.
"""

from __future__ import annotations

import dataclasses
import enum
import json
from typing import Any, Dict

_TAGS = ("#T", "#E", "#R", "#D")

_CLASSES: Dict[str, type] = {}
_ENUMS: Dict[str, type] = {}
_FIELDS: Dict[str, frozenset] = {}
_built = False


def register_class(cls: type) -> type:
    """Register a dataclass or Enum for wire round-trips."""
    if isinstance(cls, type) and issubclass(cls, enum.Enum):
        _ENUMS[cls.__name__] = cls
    elif dataclasses.is_dataclass(cls):
        _CLASSES[cls.__name__] = cls
        _FIELDS[cls.__name__] = frozenset(
            f.name for f in dataclasses.fields(cls))
    return cls


def _scan(module) -> None:
    for obj in vars(module).values():
        if isinstance(obj, type) and (
                dataclasses.is_dataclass(obj)
                or issubclass(obj, enum.Enum)):
            register_class(obj)


def _build_registry() -> None:
    """Import every module holding wire types and index them.

    Lazy so that importing the codec never drags the controller stack
    into processes that only need the API layer.
    """
    global _built
    if _built:
        return
    from volcano_tpu.api import (hypernode, jobflow, node_info,
                                 numatopology, pod, podgroup, queue,
                                 shard, types, vcjob)
    from volcano_tpu.cache import cluster as cluster_mod
    from volcano_tpu.controllers import cronjob, hyperjob
    for mod in (types, pod, node_info, podgroup, queue, hypernode,
                vcjob, jobflow, numatopology, shard, cluster_mod,
                cronjob, hyperjob):
        _scan(mod)
    _built = True


def encode(obj: Any) -> Any:
    """Encode an API object into JSON-serializable data."""
    from volcano_tpu.api.resource import Resource
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, Resource):
        return {"#R": dict(obj.res)}
    if isinstance(obj, enum.Enum):
        return {"#E": [type(obj).__name__, obj.value]}
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        _build_registry()
        name = type(obj).__name__
        if name not in _CLASSES:
            register_class(type(obj))
        fields = {f.name: encode(getattr(obj, f.name))
                  for f in dataclasses.fields(obj)}
        return {"#T": name, "f": fields}
    if isinstance(obj, dict):
        out = {str(k): encode(v) for k, v in obj.items()}
        if any(t in out for t in _TAGS):
            return {"#D": out}
        return out
    if isinstance(obj, (list, tuple, set, frozenset)):
        return [encode(v) for v in obj]
    raise TypeError(f"codec: cannot encode {type(obj).__name__}: {obj!r}")


def decode(data: Any) -> Any:
    """Decode JSON data produced by :func:`encode`."""
    if data is None or isinstance(data, (bool, int, float, str)):
        return data
    if isinstance(data, list):
        return [decode(v) for v in data]
    if isinstance(data, dict):
        if "#R" in data and len(data) == 1:
            from volcano_tpu.api.resource import Resource
            return Resource(data["#R"])
        if "#E" in data and len(data) == 1:
            _build_registry()
            name, value = data["#E"]
            cls = _ENUMS.get(name)
            if cls is None:
                raise KeyError(f"codec: unknown enum {name!r}")
            return cls(value)
        if "#T" in data:
            _build_registry()
            name = data["#T"]
            cls = _CLASSES.get(name)
            if cls is None:
                raise KeyError(f"codec: unknown class {name!r}")
            known = _FIELDS[name]
            kwargs = {k: decode(v) for k, v in data.get("f", {}).items()
                      if k in known}
            return cls(**kwargs)
        if "#D" in data and len(data) == 1:
            return {k: decode(v) for k, v in data["#D"].items()}
        return {k: decode(v) for k, v in data.items()}
    raise TypeError(f"codec: cannot decode {type(data).__name__}")


def dumps(obj: Any) -> str:
    return json.dumps(encode(obj), separators=(",", ":"))


def loads(text: str) -> Any:
    return decode(json.loads(text))
