"""RouterElector: the term-fenced lease that makes the federation
router a crash-adoptive replica set.

N router processes contend for ONE lease (`federation-router`) in the
GLOBAL store.  The store mints a monotonic TERM on every acquisition
that is not a live same-holder renewal — terms are never reissued,
even across a store reboot — and the holder carries that term as the
FENCE on every mutating cross-region RPC.  The split of duties:

  elector    who may mutate (this module): lease CAS in the global
             store, synchronous ``renew()`` the router calls at the
             top of every reconcile pass, plus an optional background
             renewal thread for process deployments where a pass can
             outlive ttl.

  fence      what happens to the loser (server substrate): every
             regional plane tracks a per-name fence floor; a write
             stamped with term < floor is refused 409 BEFORE the
             idempotency-replay lookup, so a deposed router's
             in-flight retries die atomically — no matter how its
             clock drifts or how long its GC pause was.

  adoption   what the winner does first (router._adopt): advance the
             fence on every region to its term, then reconstruct
             in-flight work from region mirrors + durable job
             annotations — the deterministic admission key, the
             evacuating-to episode state, and the create-then-delete
             cutover order make every half-done mutation resumable.

When NO router holds the lease (all crashed, or the global store is
partitioned away), nothing mutates: regions run autonomously on their
admitted gangs and the global queue simply accumulates — admission is
delayed, never lost.
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from typing import Callable, Optional

from volcano_tpu.api import federation as fedapi

log = logging.getLogger(__name__)


class RouterElector:
    """Contends for the router lease; exposes ``is_leader``/``term``
    and a ``take_promotion()`` edge the router consumes to run its
    adoption pass exactly once per won term."""

    def __init__(self, cluster, holder: str = "",
                 name: str = fedapi.ROUTER_LEASE_NAME,
                 ttl: float = fedapi.ROUTER_LEASE_TTL_S,
                 now: Callable[[], float] = time.monotonic):
        self.cluster = cluster
        self.holder = holder or f"router-{uuid.uuid4().hex[:8]}"
        self.name = name
        self.ttl = ttl
        self._now = now
        self._term = 0
        self._leader = False
        self._promoted = False      # edge: won (or re-won) a term
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- synchronous contention ----------------------------------------

    def renew(self) -> bool:
        """One lease CAS against the global store.  Returns leadership
        AFTER this call.  A wire failure toward the store demotes
        conservatively: a router that cannot prove its lease must stop
        mutating before the ttl lets someone else win."""
        try:
            res = self.cluster.lease(self.name, self.holder,
                                     ttl=self.ttl,
                                     deadline=max(1.0, self.ttl / 3.0))
        except Exception as e:  # noqa: BLE001 — any failure demotes
            if self._leader:
                log.warning("router lease renewal failed (%s); "
                            "standing by", e)
            self._leader = False
            return False
        acquired = bool(res.get("acquired"))
        if acquired:
            term = int(res.get("term", 0) or 0)
            if not self._leader or term != self._term:
                # fresh win OR a new term under the same holder (our
                # lease lapsed and we re-acquired): adopt again — the
                # world may have moved while we were not the holder
                self._promoted = True
                log.info("router %s promoted: term %d", self.holder,
                         term)
            self._term = term
        self._leader = acquired
        return acquired

    @property
    def is_leader(self) -> bool:
        return self._leader

    @property
    def term(self) -> int:
        return self._term

    def take_promotion(self) -> bool:
        """Consume the promotion edge (True exactly once per won
        term)."""
        if self._promoted:
            self._promoted = False
            return True
        return False

    def step_down(self) -> None:
        """Local demotion after a fence refusal proved a newer term
        exists: stop mutating NOW and let renew() re-contend.  The
        lease itself is left to expire — releasing it would hand the
        new holder a redundant term bump."""
        if self._leader:
            log.warning("router %s stepping down (term %d fenced "
                        "off)", self.holder, self._term)
        self._leader = False

    def release(self) -> None:
        """Graceful shutdown: drop the lease so a standby wins within
        one renew interval instead of a full ttl."""
        try:
            self.cluster.lease(self.name, self.holder, ttl=self.ttl,
                               release=True, deadline=1.0)
        except Exception:  # noqa: BLE001 — best-effort on the way out
            pass
        self._leader = False

    # -- background renewal (process deployments) ----------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="router-elector", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        self.release()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.renew()
            # leaders renew eagerly (ttl/3); standbys probe at ttl/2 —
            # the LeaderElector cadence
            self._stop.wait(self.ttl / 3.0 if self._leader
                            else self.ttl / 2.0)
