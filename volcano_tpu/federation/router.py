"""FederationRouter: one global queue over N regional planes.

The router is deliberately THIN (Singularity's global scheduler,
arxiv 2202.07848): it owns placement of whole GANGS into regions and
nothing below that — each region keeps its existing scheduler,
controllers and server plane unchanged, and the global store is an
ordinary durable state server holding only the global job queue plus
the region registry (the `region` dict-kind).

One reconcile pass:

  liveness   a region is alive while its mirror keeps proving itself
             fresh (the mirror tails the region's WAL — a successful
             poll IS a heartbeat); silent past REGION_TTL_S the region
             is declared lost and every gang admitted there requeues
             GLOBALLY.  Nothing acked is lost with a region: the
             global store is the source of truth, and the router folds
             checkpoint/resume metadata onto the global record as it
             lands, so the re-placed gang resumes from the last folded
             step.

  admission  unadmitted global jobs are scored into the READY region
             maximizing

                 locality x learned-goodput(generation) / price

             gated on the region actually fitting the gang (idle
             chips from the mirror).  The admission key is
             DETERMINISTIC over (job key, attempt): a router that
             crashed between the regional create and the
             admitted-region stamp re-derives the same key on restart
             and finds its own half-finished admission instead of
             double-placing the gang.

  goodput    per-(region, generation) EWMA of observed steps/sec/chip
             learned from the mirrors' LAST_STEP deltas — the
             "goodput-per-generation" term of the score, so a region
             whose v5p fleet measurably outruns another's v5e fleet
             attracts the next gang even at equal price.

  arbitrage  a gang pending in its region past ARBITRAGE_PENDING_S
             while another ready region could run it NOW is
             re-admitted there (delete the pending copy, bump the
             attempt, place again) — burst capacity is bought where
             it exists.

  migration  a RUNNING gang moves via the elastic evacuate drain
             (api/elastic.py RESIZE_EVACUATE): the router stamps the
             decision on the SOURCE podgroup, the regional elastic
             controller checkpoints + drains and parks the gang under
             the `evacuated` hold, and the router cuts over — create
             the destination copy carrying the resume metadata, THEN
             delete the source.  The cutover refuses to act through a
             stale destination mirror (MirrorStaleError): acting on
             state older than MIRROR_MAX_AGE_S could double-place.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, Optional

from volcano_tpu import metrics, trace
from volcano_tpu.api import elastic as eapi
from volcano_tpu.api import federation as fedapi
from volcano_tpu.api.goodput import generation_of
from volcano_tpu.api.resource import TPU
from volcano_tpu.api.types import (GROUP_NAME_ANNOTATION, JobPhase,
                                   PodGroupPhase)
from volcano_tpu.api.vcjob import VCJob
from volcano_tpu.federation.ha import RouterElector
from volcano_tpu.federation.mirror import MirrorStaleError, RegionMirror
from volcano_tpu.federation import slo as slomod
from volcano_tpu.federation.retry import (FED_RPC_DEADLINE_S, STATE_CODES,
                                          FedRPC, FedRPCError,
                                          RouterFencedError)
from volcano_tpu.federation.stitch import EpisodeStitcher

log = logging.getLogger(__name__)

# goodput EWMA smoothing for the learned steps/sec/chip signal
GOODPUT_ALPHA = 0.3
# score boost for a region named in the job's data-locality list
LOCALITY_BOOST = 2.0
# serving-aware placement: a SERVING gang's goodput term scales with
# the destination region's measured QPS headroom (folded from the
# serving autoscaler's podgroup stats through the mirror), floored so
# a saturated region is dispreferred, not blacklisted — it may still
# be the only one fitting the gang
SERVING_HEADROOM_FLOOR = 0.25
# resume/progress annotations folded regional -> global every pass,
# so a region loss never loses acked training progress
_FOLD_KEYS = ()     # filled below (import-cycle-free)


def _fold_keys():
    global _FOLD_KEYS
    if not _FOLD_KEYS:
        from volcano_tpu.api.slicehealth import (
            CHECKPOINT_DIR_ANNOTATION, LAST_STEP_ANNOTATION,
            RESUME_STEP_ANNOTATION)
        _FOLD_KEYS = (LAST_STEP_ANNOTATION, RESUME_STEP_ANNOTATION,
                      CHECKPOINT_DIR_ANNOTATION,
                      eapi.ELASTIC_GENERATION_ANNOTATION,
                      eapi.ELASTIC_SLICES_ANNOTATION)
    return _FOLD_KEYS


def job_chips(job: VCJob) -> float:
    """The gang's TPU demand in chips (replicas x per-pod request)."""
    total = 0.0
    for spec in job.tasks:
        pod = spec.template_pod()
        total += spec.replicas * float(
            pod.resource_requests().get(TPU) or 0)
    return total


class RegionHandle:
    """One attached region: registry record + write client + mirror.

    ``attached_ts`` anchors the mirror WARMUP grace: a router that
    just attached this handle (fresh process after a failover, or a
    region that just registered) has a mirror that hasn't completed
    its first poll, and heartbeat_ts in the registry is whatever the
    PREVIOUS leaseholder last wrote — both stale by construction, not
    by region death.  Liveness verdicts are deferred until the handle
    is older than the region ttl."""

    __slots__ = ("name", "record", "client", "mirror", "attached_ts")

    def __init__(self, name: str, record: dict, client, mirror,
                 attached_ts: float = 0.0):
        self.name = name
        self.record = record
        self.client = client
        self.mirror = mirror
        self.attached_ts = attached_ts


class FederationRouter:
    """Reconciles the global queue against the regional planes.

    *client_factory(record)* builds the region WRITE handle (defaults
    to a RemoteCluster against record["url"]); *mirror_factory(record)*
    builds the read mirror (defaults to RegionMirror tailing
    record["mirror_url"]).  Tests inject in-process fakes for both.
    """

    def __init__(self, global_cluster, now: Callable[[], float] = time.time,
                 client_factory=None, mirror_factory=None,
                 ttl: float = fedapi.REGION_TTL_S,
                 arbitrage_after: float = fedapi.ARBITRAGE_PENDING_S,
                 start_mirrors: bool = True, holder: str = "",
                 elect: bool = False,
                 lease_ttl: float = fedapi.ROUTER_LEASE_TTL_S,
                 mirror_poll_s: Optional[float] = None):
        self.cluster = global_cluster
        self.now = now
        self.ttl = ttl
        self.arbitrage_after = arbitrage_after
        self._start_mirrors = start_mirrors
        self._mirror_poll_s = mirror_poll_s
        self._client_factory = client_factory or self._default_client
        self._mirror_factory = mirror_factory or self._default_mirror
        self.handles: Dict[str, RegionHandle] = {}
        # learned goodput: (region, generation) -> EWMA steps/sec/chip
        self._goodput: Dict[tuple, float] = {}
        # per-job last observed (step, ts) for rate derivation
        self._progress: Dict[str, tuple] = {}
        # in-flight evacuation start ts (timing only; the durable
        # episode state is the evacuating-to annotation)
        self._evac_started: Dict[str, float] = {}
        # measured serving QPS headroom per region, [0, 1]
        self._serving_headroom: Dict[str, float] = {}
        # the ONE cross-region RPC policy: per-region breaker +
        # deterministic backoff + fence classification; breaker
        # trips/closes persist to the global store so a promoted
        # standby adopts learned region health
        self.rpc = FedRPC()
        self.rpc.on_transition = self._breaker_transition
        # observability plane: cross-region episode stitching + fleet
        # metric rollups + SLO burn-rate tracking (leaseholder-only)
        self.stitcher = EpisodeStitcher(global_cluster)
        self.slo = slomod.SLOTracker(now=now)
        # injectable for in-process tests (default: urllib scrape of
        # the region record's metrics_url)
        self._rollup_fetch = slomod.fetch_metrics_text
        # leased replica-set mode: contend for the router lease; only
        # the holder mutates.  elect=False keeps the legacy embedded
        # single-router behavior (in-process tests, one-router bench).
        self.elector: Optional[RouterElector] = RouterElector(
            global_cluster, holder, ttl=lease_ttl) if elect else None

    # -- region attachment ---------------------------------------------

    @staticmethod
    def _default_client(rec: dict):
        from volcano_tpu.cache.remote_cluster import RemoteCluster
        # bounded per-call budget: a dead region costs a slice of one
        # reconcile pass (then its breaker takes over), not the wire
        # client's default 30s deadline
        return RemoteCluster(rec["url"], token=rec.get("token", ""),
                             tolerate_unreachable=True,
                             retry_deadline=FED_RPC_DEADLINE_S)

    def _default_mirror(self, rec: dict):
        m = RegionMirror(rec["name"],
                         rec.get("mirror_url") or rec["url"],
                         token=rec.get("token", ""))
        if self._start_mirrors:
            if self._mirror_poll_s is not None:
                m.start(poll_s=self._mirror_poll_s)
            else:
                m.start()
        return m

    def attach_region(self, record: dict, client=None, mirror=None) -> None:
        """Register a region (tests pass explicit client/mirror)."""
        name = record["name"]
        h = RegionHandle(name, record,
                         client or self._client_factory(record),
                         mirror or self._mirror_factory(record),
                         attached_ts=self.now())
        self.handles[name] = h
        self.cluster.put_object("region", dict(record), key=name)

    def close(self) -> None:
        for h in self.handles.values():
            stop = getattr(h.mirror, "stop", None)
            if stop:
                stop()
        if self.elector is not None:
            self.elector.release()

    # -- reconcile ------------------------------------------------------

    def sync(self) -> None:
        now = self.now()
        leading = True
        if self.elector is not None:
            leading = self.elector.renew()
            metrics.set_gauge("federation_router_is_leader",
                              1.0 if leading else 0.0)
            metrics.set_gauge("federation_router_term",
                              float(self.elector.term))
        # standby (or lease-less) routers OBSERVE ONLY: keep handles
        # attached, mirrors warm and goodput learning so adoption is
        # instant — but never write.  With no leaseholder anywhere,
        # regions run autonomously and the global queue accumulates.
        self._refresh_regions(now, mutate=leading)
        self._observe_goodput(now)
        if leading:
            if self.elector is not None and \
                    self.elector.take_promotion():
                self._adopt(now)
            try:
                self._fold_and_requeue(now)
                self._reap_migrated_residuals(now)
                self._evacuations(now)
                self._arbitrage(now)
                self._admit(now)
            except RouterFencedError as e:
                # a regional plane refused our term as stale: a newer
                # router exists.  Stop mutating mid-pass and
                # re-contend — never retry a fenced write.
                log.warning("%s", e)
                if self.elector is not None:
                    self.elector.step_down()
            else:
                try:
                    self._observability(now)
                except Exception:  # noqa: BLE001 — telemetry never
                    # blocks placement
                    log.exception("observability pass failed")
        self._gauges()

    # -- adoption (first pass after winning a term) ---------------------

    def _adopt(self, now: float) -> None:
        """Make the new term safe, then resume in-flight work.  Fence
        first: advancing every region's floor to our term atomically
        refuses the deposed router's stragglers.  The reconstruction
        itself is the ordinary reconcile pass — the deterministic
        admission key re-finds half-landed creates, the evacuating-to
        annotation re-drives half-done cutovers (create-then-delete,
        idempotent), and _find_admitted_copy guarantees a gang never
        lands twice.  Only the process-local evacuation TIMING needs
        re-seeding here."""
        term = self.elector.term
        for h in list(self.handles.values()):
            self._fence_region(h, term)
        # adopt the deposed holder's learned region health: breakers
        # resume from the persisted state machine position instead of
        # re-probing a known-sick region from closed
        for region, snap in dict(getattr(
                self.cluster, "router_breakers", {})).items():
            if region in self.handles:
                self.rpc.restore(region, snap)
        for job in self._global_jobs():
            if job.annotations.get(
                    fedapi.FED_EVACUATING_TO_ANNOTATION) and \
                    job.key not in self._evac_started:
                self._evac_started[job.key] = now
        metrics.inc("federation_router_adoptions_total")
        self.cluster.record_event(
            "federation-router", "RouterPromoted",
            f"{self.elector.holder} adopted term {term} "
            f"({len(self.handles)} regions fenced)")

    def _fence_region(self, h: RegionHandle, term: int) -> None:
        """Stamp our (name, term) on every future write to this
        region and push its fence floor up-front.  The push is
        best-effort: check_fence self-advances on a HIGHER term, so
        even if it fails here, our first stamped write raises the
        floor — and the old router is refused from that moment."""
        set_fence = getattr(h.client, "set_fence", None)
        if set_fence is not None:
            set_fence(fedapi.ROUTER_LEASE_NAME, term)
        adv = getattr(h.client, "advance_fence", None)
        if adv is None:
            return
        try:
            self.rpc.call(h.name, "advance_fence",
                          lambda: adv(fedapi.ROUTER_LEASE_NAME, term))
        except FedRPCError as e:
            log.warning("fence advance on %s deferred to first "
                        "write: %s", h.name, e)

    # -- breaker persistence (trip/close seam) --------------------------

    def _breaker_transition(self, region: str, breaker,
                            event: str) -> None:
        """Snapshot the breaker into the global store on every trip
        and close, so a promoted standby adopts learned region health
        instead of hot-probing a region its predecessor already knew
        was sick.  Leaseholder-only: a standby's breakers are local
        observations, not fleet truth."""
        if self.elector is not None and not self.elector.is_leader:
            return
        snap = self.rpc.snapshot(region)
        snap["event"] = event
        snap["updated_ts"] = self.now()
        self.cluster.put_object("router_breaker", snap, key=region)

    # -- observability: stitching + rollups + SLO burn ------------------

    def _publish_fragment(self, frag: dict) -> None:
        """Router-plane episode fragments feed the in-process
        stitcher AND the global trace ring (wire mode) — either path
        alone lets a promoted standby reconstruct the stitch."""
        self.stitcher.add_fragment(frag)
        trace.publish(self.cluster, frag)

    def _observability(self, now: float) -> None:
        """Leaseholder-only telemetry pass: stitch every in-flight
        episode's cross-plane fragments into the durable fleet trace,
        fold each ready region's metric exposition into the bounded
        federation_rollup_* families, and advance the multi-window
        SLO burn-rate gauges."""
        self.stitcher.collect(self.handles, now)
        region_samples: Dict[str, list] = {}
        for h in self.handles.values():
            rec = self.cluster.regions.get(h.name, h.record)
            url = rec.get("metrics_url") or ""
            if not url or not fedapi.region_ready(rec, now, self.ttl):
                continue
            try:
                text = self._rollup_fetch(url, rec.get("token", ""))
            except Exception:  # noqa: BLE001 — a dark scrape skips
                # the region this pass; breakers govern writes, not
                # reads
                metrics.inc("federation_rollup_scrape_failures_total",
                            region=h.name)
                continue
            region_samples[h.name] = slomod.parse_samples(text)
        for name, samples in region_samples.items():
            for fam, labels, value in slomod.rollup(name, samples):
                metrics.set_gauge(fam, value, **labels)
        self.slo.ingest(region_samples, now)
        doc = self.slo.export(now)
        self.cluster.put_object("slo", doc, key="global")

    def _refresh_regions(self, now: float, mutate: bool = True) -> None:
        """Fold mirror liveness + capacity into the registry records
        (persisted to the global store so `vtpctl regions` renders the
        fleet from one place)."""
        for name, rec in list(self.cluster.regions.items()):
            if name not in self.handles:
                # registry entry with no handle yet (submitted via
                # vtpctl / another router instance): attach lazily
                h = self.handles[name] = RegionHandle(
                    name, dict(rec), self._client_factory(rec),
                    self._mirror_factory(rec), attached_ts=now)
                if self.elector is not None and self.elector.is_leader:
                    # regions joining under a live term get fenced on
                    # arrival, not at the next promotion
                    self._fence_region(h, self.elector.term)
        for name in [n for n in self.handles
                     if n not in self.cluster.regions]:
            h = self.handles.pop(name)
            stop = getattr(h.mirror, "stop", None)
            if stop:
                stop()
        for h in self.handles.values():
            rec = dict(self.cluster.regions.get(h.name, h.record))
            age = h.mirror.age_s()
            changed = False
            # observed mirror lag, capped so a never-polled mirror
            # reads as "very stale", not infinity
            stale = round(min(age, 10.0 * max(self.ttl, 1.0)), 3)
            metrics.set_gauge("federation_mirror_staleness_seconds",
                              stale, region=h.name)
            if rec.get("mirror_staleness_s") != stale:
                rec["mirror_staleness_s"] = stale
                changed = True
            if age <= self.ttl:
                # a fresh mirror poll IS the heartbeat: the region's
                # server answered with (or confirmed) its WAL horizon
                rec["heartbeat_ts"] = now
                if rec.get("state") == fedapi.REGION_STATE_LOST:
                    rec["state"] = fedapi.REGION_STATE_READY
                    log.info("region %s recovered", h.name)
                cap, idle = self._mirror_chips(h)
                if (cap, idle) != (rec.get("capacity_chips"),
                                   rec.get("idle_chips")):
                    rec["capacity_chips"], rec["idle_chips"] = cap, idle
                changed = True
            elif now - h.attached_ts > self.ttl and \
                    not fedapi.region_alive(rec, now, self.ttl) and \
                    rec.get("state") != fedapi.REGION_STATE_LOST:
                # warmup grace: only a handle OLDER than ttl whose
                # mirror still can't reach the region is a loss — a
                # freshly promoted router must not declare regions
                # dead off heartbeats its dead predecessor stopped
                # writing
                rec["state"] = fedapi.REGION_STATE_LOST
                changed = True
                log.warning("region %s lost (mirror %.1fs stale)",
                            h.name, age)
                if mutate:
                    self.cluster.record_event(
                        f"region/{h.name}", "RegionLost",
                        f"no heartbeat for {age:.1f}s; requeueing its "
                        f"gangs globally")
            # fold the breaker state into the registry record so
            # `vtpctl routers` renders write-path health fleet-wide
            breaker = self.rpc.state(h.name)
            if rec.get("router_breaker") != breaker:
                rec["router_breaker"] = breaker
                changed = True
            if changed:
                h.record = rec
                if mutate:
                    self.cluster.put_object("region", rec, key=h.name)
                metrics.set_gauge("federation_region_capacity_chips",
                                  float(rec.get("capacity_chips", 0)),
                                  region=h.name)
                metrics.set_gauge("federation_region_idle_chips",
                                  float(rec.get("idle_chips", 0)),
                                  region=h.name)

    def _mirror_chips(self, h: RegionHandle) -> tuple:
        """(capacity, idle) TPU chips from the region mirror's view."""
        c = h.mirror.cluster
        cap = sum(float((n.allocatable or {}).get(TPU) or 0)
                  for n in c.nodes.values())
        used = 0.0
        for p in c.pods.values():
            if p.node_name and not p.is_terminated():
                used += float(p.resource_requests().get(TPU) or 0)
        return cap, max(0.0, cap - used)

    def _region_generation(self, h: RegionHandle) -> str:
        """The region's dominant TPU generation (bounded enum)."""
        counts: Dict[str, float] = {}
        for n in h.mirror.cluster.nodes.values():
            chips = float((n.allocatable or {}).get(TPU) or 0)
            if chips > 0:
                gen = generation_of(n.labels)
                counts[gen] = counts.get(gen, 0.0) + chips
        if not counts:
            return "other"
        return max(counts, key=counts.get)

    # -- learned goodput ------------------------------------------------

    def _observe_goodput(self, now: float) -> None:
        """Fold LAST_STEP deltas from each mirror into the
        per-(region, generation) steps/sec/chip EWMA."""
        from volcano_tpu.api.slicehealth import LAST_STEP_ANNOTATION
        live = set()
        for h in self.handles.values():
            gen = self._region_generation(h)
            for job in h.mirror.cluster.vcjobs.values():
                raw = job.annotations.get(LAST_STEP_ANNOTATION)
                if raw is None or job.phase is not JobPhase.RUNNING:
                    continue
                try:
                    step = int(raw)
                except (TypeError, ValueError):
                    continue
                jk = f"{h.name}:{job.key}"
                live.add(jk)
                prev = self._progress.get(jk)
                self._progress[jk] = (step, now)
                if prev is None:
                    continue
                pstep, pts = prev
                dt = now - pts
                if dt <= 0 or step <= pstep:
                    continue
                chips = job_chips(job)
                if chips <= 0:
                    continue
                rate = (step - pstep) / dt / chips
                key = (h.name, gen)
                old = self._goodput.get(key)
                self._goodput[key] = rate if old is None else \
                    old + GOODPUT_ALPHA * (rate - old)
                metrics.set_gauge(
                    "federation_region_goodput_steps_per_chip",
                    self._goodput[key], region=h.name)
        for jk in [k for k in self._progress if k not in live]:
            del self._progress[jk]
        for h in self.handles.values():
            self._serving_headroom[h.name] = \
                self._region_serving_headroom(h)
            metrics.set_gauge("federation_region_serving_headroom",
                              self._serving_headroom[h.name],
                              region=h.name)

    def _region_serving_headroom(self, h: RegionHandle) -> float:
        """Measured serving QPS headroom in [0, 1]: how much of the
        region's declared serving capacity (target QPS/replica x
        reporting replicas, from the autoscaler's folded podgroup
        stats) is still unused.  1.0 when the region hosts no serving
        replica groups — training-only regions stay neutral."""
        from volcano_tpu.api import serving as sapi
        qps = target = 0.0
        for pg in h.mirror.cluster.podgroups.values():
            if not sapi.is_serving(pg):
                continue
            qps += sapi.ann_float(pg, sapi.PG_QPS_ANNOTATION)
            per = sapi.target_qps_per_replica(pg)
            reps = sapi.ann_float(pg, sapi.PG_REPLICAS_ANNOTATION)
            target += per * max(1.0, reps)
        if target <= 0:
            return 1.0
        return max(0.0, min(1.0, (target - qps) / target))

    def _goodput_factor(self, h: RegionHandle,
                        job: Optional[VCJob] = None) -> float:
        """This region's learned rate relative to the fleet mean —
        1.0 until anything has been learned (cold start is neutral).
        For a SERVING gang the term additionally scales with the
        region's measured QPS headroom: a region whose serving fleet
        is already at its target QPS makes a poor home for one more
        replica group, whatever its training goodput says."""
        if not self._goodput:
            base = 1.0
        else:
            gen = self._region_generation(h)
            mine = self._goodput.get((h.name, gen))
            if mine is None:
                base = 1.0
            else:
                mean = sum(self._goodput.values()) / len(self._goodput)
                base = mine / mean if mean > 0 else 1.0
        if job is not None:
            from volcano_tpu.api import serving as sapi
            if sapi.is_serving(job):
                head = self._serving_headroom.get(h.name, 1.0)
                base *= SERVING_HEADROOM_FLOOR + \
                    (1.0 - SERVING_HEADROOM_FLOOR) * head
        return base

    # -- admission ------------------------------------------------------

    def _global_jobs(self):
        return [j for j in self.cluster.vcjobs.values()
                if fedapi.home_key(j) is None]

    def _score(self, h: RegionHandle, job: VCJob, need: float
               ) -> float:
        rec = self.cluster.regions.get(h.name, h.record)
        if not fedapi.region_ready(rec, self.now(), self.ttl):
            return 0.0
        if not self.rpc.available(h.name):
            # breaker open: we can SEE the region (mirror) but cannot
            # WRITE to it — placing there would strand the admission
            return 0.0
        idle = float(rec.get("idle_chips", 0) or 0)
        cap = float(rec.get("capacity_chips", 0) or 0)
        if need > 0 and cap < need:
            return 0.0              # can never fit, even empty
        # fractional fit: a region that can take the gang NOW beats
        # one that must first drain something
        fit = 1.0 if need <= 0 or idle >= need else \
            0.25 * (idle / need)
        price = max(1e-9, float(rec.get("price", 1.0) or 1.0))
        locality = LOCALITY_BOOST if h.name in \
            fedapi.data_locality(job) else 1.0
        return locality * self._goodput_factor(h, job) * fit / price

    def _pick_region(self, job: VCJob, exclude=() ) -> Optional[str]:
        need = job_chips(job)
        best, best_score = None, 0.0
        for name in sorted(self.handles):
            if name in exclude:
                continue
            score = self._score(self.handles[name], job, need)
            if score > best_score:
                best, best_score = name, score
        return best

    def _attempt(self, job: VCJob) -> int:
        try:
            return int(job.annotations.get(
                fedapi.FED_ATTEMPT_ANNOTATION, 0) or 0)
        except (TypeError, ValueError):
            return 0

    def _find_admitted_copy(self, key: str) -> Optional[str]:
        """Scan every region for a copy carrying *key* — the restart
        recovery path: the create landed, the global stamp did not."""
        for h in self.handles.values():
            for view in (h.mirror.cluster, h.client):
                jobs = getattr(view, "vcjobs", {})
                for rjob in list(jobs.values()):
                    if rjob.annotations.get(
                            fedapi.FED_ADMISSION_KEY_ANNOTATION) == key:
                        return h.name
        return None

    def _regional_copy(self, job: VCJob, region: str, key: str,
                       extra: Optional[dict] = None) -> VCJob:
        copy = job.clone()
        # fresh status: the copy starts life as a new regional job
        copy.phase = JobPhase.PENDING
        copy.version = 0
        copy.retry_count = 0
        copy.conditions = []
        copy.pending = copy.running = copy.succeeded = 0
        copy.failed = copy.terminating = copy.unknown = 0
        copy.finish_time = None
        ann = copy.annotations
        ann[fedapi.FED_HOME_ANNOTATION] = job.key
        ann[fedapi.FED_ORIGIN_REGION_ANNOTATION] = region
        ann[fedapi.FED_ADMISSION_KEY_ANNOTATION] = key
        for k in (fedapi.FED_ADMITTED_REGION_ANNOTATION,
                  fedapi.FED_ADMITTED_TS_ANNOTATION,
                  fedapi.FED_EVACUATE_ANNOTATION,
                  fedapi.FED_EVACUATING_TO_ANNOTATION):
            ann.pop(k, None)
        if extra:
            ann.update(extra)
        return copy

    def _stamp_admitted(self, job: VCJob, region: str, key: str,
                        now: float) -> None:
        job.annotations[fedapi.FED_ADMISSION_KEY_ANNOTATION] = key
        job.annotations[fedapi.FED_ADMITTED_REGION_ANNOTATION] = region
        job.annotations[fedapi.FED_ADMITTED_TS_ANNOTATION] = \
            f"{now:.3f}"
        job.annotations[fedapi.FED_REGIONAL_PHASE_ANNOTATION] = \
            JobPhase.PENDING.value
        self.cluster.update_vcjob(job)

    def _admit(self, now: float) -> None:
        from volcano_tpu.api.types import FINISHED_JOB_PHASES
        for job in self._global_jobs():
            if job.phase in FINISHED_JOB_PHASES or \
                    fedapi.admitted_region(job) is not None:
                continue
            key = fedapi.admission_key(job.key, self._attempt(job))
            # restart recovery BEFORE placing: did a previous router
            # life already create this attempt's copy somewhere?
            prior = self._find_admitted_copy(key)
            if prior is not None:
                log.info("admission of %s (key %s) already landed in "
                         "%s; re-stamping", job.key, key, prior)
                self._stamp_admitted(job, prior, key, now)
                continue
            region = self._pick_region(job)
            if region is None:
                continue            # nothing ready/fitting: stay queued
            # mint (or re-derive) the causal episode ID BEFORE the
            # clone, so the regional copy — and through it the
            # podgroup and every pod — carries it on creation
            episode = fedapi.ensure_episode(job, now)
            h = self.handles[region]
            copy = self._regional_copy(job, region, key)
            try:
                self.rpc.call(region, "add_vcjob",
                              lambda: h.client.add_vcjob(copy))
            except FedRPCError as e:
                log.warning("admission of %s failed: %s", job.key, e)
                continue
            self._stamp_admitted(job, region, key, now)
            self.cluster.record_event(
                job.key, "FederationAdmitted",
                f"admitted to region {region} (key {key}, "
                f"episode {episode})")
            metrics.inc("federation_admissions_total", region=region)
            hop = fedapi.episode_hop(job)
            # hop 0's admit span starts at the episode mint (global
            # queue wait is part of the causal story); re-admissions
            # at later hops are point decisions
            start = fedapi.episode_ts(job, now) if hop == 0 else now
            self._publish_fragment(
                trace.fragment_doc(
                    f"router-admit {job.key}", "router", episode,
                    min(start, now), now, hop=hop, jobs=(job.key,),
                    labels={"region": region}))

    # -- phase folding + region-loss requeue ---------------------------

    def _copy_of(self, h: RegionHandle, key: str):
        """The regional copy as the MIRROR sees it (falling back to
        the write client's view while the mirror warms up)."""
        job = h.mirror.cluster.vcjobs.get(key)
        if job is None:
            job = getattr(h.client, "vcjobs", {}).get(key)
        return job

    def _fold_and_requeue(self, now: float) -> None:
        from volcano_tpu.api.types import FINISHED_JOB_PHASES
        for job in self._global_jobs():
            region = fedapi.admitted_region(job)
            if region is None or job.phase in FINISHED_JOB_PHASES:
                continue
            h = self.handles.get(region)
            rec = self.cluster.regions.get(region,
                                           h.record if h else None)
            # requeue rides the EXPLICIT lost transition made by
            # _refresh_regions (which owns the mirror-warmup grace) —
            # raw heartbeat staleness alone is ambiguous right after
            # a router failover
            if h is None or rec is None or \
                    rec.get("state") == fedapi.REGION_STATE_LOST:
                self._requeue(job, region, "region lost")
                continue
            copy = self._copy_of(h, job.key)
            if copy is None:
                continue            # not visible yet (mirror lag)
            changed = False
            phase = copy.phase.value
            if job.annotations.get(
                    fedapi.FED_REGIONAL_PHASE_ANNOTATION) != phase:
                job.annotations[
                    fedapi.FED_REGIONAL_PHASE_ANNOTATION] = phase
                changed = True
            # fold acked progress up: these annotations ARE the
            # migration/loss continuity story — once folded, a whole-
            # region loss resumes from this step, not from zero
            for k in _fold_keys():
                v = copy.annotations.get(k)
                if v is not None and job.annotations.get(k) != v:
                    job.annotations[k] = v
                    changed = True
            if copy.phase in FINISHED_JOB_PHASES:
                job.phase = copy.phase
                job.finish_time = copy.finish_time or now
                changed = True
            if changed:
                self.cluster.update_vcjob(job)

    def _requeue(self, job: VCJob, region: Optional[str],
                 why: str) -> None:
        ann = job.annotations
        ann.pop(fedapi.FED_ADMITTED_REGION_ANNOTATION, None)
        ann.pop(fedapi.FED_ADMITTED_TS_ANNOTATION, None)
        ann.pop(fedapi.FED_REGIONAL_PHASE_ANNOTATION, None)
        ann.pop(fedapi.FED_EVACUATING_TO_ANNOTATION, None)
        if region:
            ann[fedapi.FED_MIGRATED_FROM_ANNOTATION] = region
        ann[fedapi.FED_ATTEMPT_ANNOTATION] = \
            str(self._attempt(job) + 1)
        episode = fedapi.episode_of(job)
        hop = fedapi.episode_hop(job)
        if episode:
            # a requeue is a cross-region move: the next admission
            # lands at the next hop of the SAME episode
            hop += 1
            ann[fedapi.FED_EPISODE_HOP_ANNOTATION] = str(hop)
        self.cluster.update_vcjob(job)
        self.cluster.record_event(
            job.key, "FederationRequeued",
            f"requeued out of {region or '?'}: {why}")
        metrics.inc("federation_requeues_total",
                    region=region or "unknown")
        self._evac_started.pop(job.key, None)
        if episode:
            t = self.now()
            self._publish_fragment(
                trace.fragment_doc(
                    f"router-requeue {job.key}", "router", episode,
                    t, t, hop=hop, jobs=(job.key,),
                    labels={"from": region or "?", "why": why[:64]}))

    # -- pending-gang burst arbitrage ----------------------------------

    def _arbitrage(self, now: float) -> None:
        for job in self._global_jobs():
            region = fedapi.admitted_region(job)
            if region is None or job.annotations.get(
                    fedapi.FED_EVACUATING_TO_ANNOTATION):
                continue
            try:
                admitted_ts = float(job.annotations.get(
                    fedapi.FED_ADMITTED_TS_ANNOTATION, 0) or 0)
            except (TypeError, ValueError):
                continue
            if now - admitted_ts < self.arbitrage_after:
                continue
            h = self.handles.get(region)
            copy = self._copy_of(h, job.key) if h else None
            if copy is None or copy.phase is not JobPhase.PENDING:
                continue
            pg = h.mirror.cluster.podgroups.get(job.key)
            if pg is not None and pg.phase is PodGroupPhase.RUNNING:
                continue
            need = job_chips(job)
            cur_score = self._score(h, job, need)
            better = None
            for name in sorted(self.handles):
                if name == region:
                    continue
                cand = self.handles[name]
                rec = self.cluster.regions.get(name, cand.record)
                if float(rec.get("idle_chips", 0) or 0) < need:
                    continue        # arbitrage only to a region with
                                    # the chips idle RIGHT NOW
                if self._score(cand, job, need) > cur_score:
                    better = name
                    break
            if better is None:
                continue
            try:
                # vtplint: disable=episode-propagation (the hop bump and requeue fragment ride _requeue below, which stamps the episode)
                self.rpc.call(region, "delete_vcjob",
                              lambda: h.client.delete_vcjob(job.key))
            except FedRPCError as e:
                log.warning("arbitrage delete of %s failed: %s",
                            job.key, e)
                continue
            n = fedapi.migration_count(job) + 1
            job.annotations[fedapi.FED_MIGRATIONS_ANNOTATION] = str(n)
            self._requeue(job, region,
                          f"pending {now - admitted_ts:.0f}s while "
                          f"{better} has idle capacity")
            metrics.inc("federation_migrations_total", kind="pending")

    # -- cross-region migration of RUNNING gangs ------------------------

    def _wants_evacuation(self, job: VCJob, region: str) -> bool:
        if job.annotations.get(fedapi.FED_EVACUATE_ANNOTATION):
            return True
        rec = self.cluster.regions.get(region)
        return bool(rec) and \
            rec.get("state") == fedapi.REGION_STATE_DRAINING

    def _evacuations(self, now: float) -> None:
        for job in self._global_jobs():
            region = fedapi.admitted_region(job)
            if region is None or region not in self.handles:
                continue
            dest = job.annotations.get(
                fedapi.FED_EVACUATING_TO_ANNOTATION)
            if dest:
                self._drive_cutover(job, region, dest, now)
            elif self._wants_evacuation(job, region):
                self._start_evacuation(job, region, now)

    def _start_evacuation(self, job: VCJob, src: str,
                          now: float) -> None:
        h = self.handles[src]
        copy = self._copy_of(h, job.key)
        if copy is None or copy.phase is not JobPhase.RUNNING:
            # not running: arbitrage/requeue is the cheaper move —
            # nothing checkpointed to carry
            return
        want = job.annotations.get(fedapi.FED_EVACUATE_ANNOTATION, "")
        if want and want != "auto" and want != src and \
                want in self.handles and fedapi.region_ready(
                    self.cluster.regions.get(want, {}), now, self.ttl):
            dest = want
        else:
            dest = self._pick_region(job, exclude=(src,))
        if dest is None:
            return                  # nowhere to go yet; retry later
        pg = getattr(h.client, "podgroups", {}).get(job.key)
        if pg is None:
            pg = h.mirror.cluster.podgroups.get(job.key)
        if pg is None:
            return
        ann = pg.annotations
        ann[eapi.ELASTIC_EVACUATE_ANNOTATION] = dest
        ann[eapi.ELASTIC_DESIRED_SLICES_ANNOTATION] = \
            str(eapi.current_slices(pg))
        ann[eapi.ELASTIC_RESIZE_REASON_ANNOTATION] = \
            eapi.RESIZE_EVACUATE
        ann[eapi.ELASTIC_DECIDED_TS_ANNOTATION] = f"{now:.3f}"
        episode = fedapi.episode_of(job)
        if episode:
            # stamp the episode onto the SOURCE podgroup: the
            # regional elastic controller's drain fragment then joins
            # this causal timeline (jobs admitted before the episode
            # scheme get it retro-stamped here)
            ann[fedapi.FED_EPISODE_ANNOTATION] = episode
            ann[fedapi.FED_EPISODE_HOP_ANNOTATION] = \
                str(fedapi.episode_hop(job))
        try:
            self.rpc.call(src, "update_podgroup_status",
                          lambda: h.client.update_podgroup_status(pg))
        except FedRPCError as e:
            log.warning("evacuate stamp on %s failed: %s", job.key, e)
            return
        job.annotations[fedapi.FED_EVACUATING_TO_ANNOTATION] = dest
        self.cluster.update_vcjob(job)
        self._evac_started[job.key] = now
        self.cluster.record_event(
            job.key, "FederationEvacuating",
            f"draining out of {src} toward {dest}")

    def _reap_migrated_residuals(self, now: float) -> None:
        """Sweep migration husks out of SOURCE regions, once per pass
        until they stay gone.  The cutover's source delete races the
        regional job controller: an in-flight status flush is an
        upsert that resurrects the just-deleted copy, and the drain's
        RestartJob re-materializes pods that outlive the job as
        orphans (which the podgroup normalizer would re-adopt and the
        scheduler would then place — ghost pods eating real chips).
        Detection reads the mirror; deletes go through the write
        client and repeat next pass if anything reappears."""
        for job in self._global_jobs():
            src = job.annotations.get(
                fedapi.FED_MIGRATED_FROM_ANNOTATION)
            region = fedapi.admitted_region(job)
            if not src or src == region:
                continue
            h = self.handles.get(src)
            if h is None or not fedapi.region_alive(
                    self.cluster.regions.get(src, {}), now, self.ttl):
                continue            # dead source: nothing to reap yet
            c = h.mirror.cluster
            name = job.key.rsplit("/", 1)[-1]
            victims = [p.key for p in c.pods.values()
                       if p.annotations.get(
                           GROUP_NAME_ANNOTATION) == name]
            if c.vcjobs.get(job.key) is None and \
                    c.podgroups.get(job.key) is None and not victims:
                continue
            def _reap(c=c, h=h, key=job.key, victims=victims):
                if c.vcjobs.get(key) is not None:
                    h.client.delete_vcjob(key)
                if c.podgroups.get(key) is not None:
                    h.client.delete_podgroup(key)
                for pkey in victims:
                    h.client.delete_pod(pkey)
            try:
                self.rpc.call(src, "reap_residuals", _reap)
            except FedRPCError as e:
                log.warning("residual reap of %s failed (next pass "
                            "retries): %s", job.key, e)
                continue
            metrics.inc("federation_source_reaps_total", region=src)
            log.info("reaped migration residue of %s in %s "
                     "(%d pods, episode %s)", job.key, src,
                     len(victims), fedapi.episode_of(job) or "-")

    def _drive_cutover(self, job: VCJob, src: str, dest: str,
                       now: float) -> None:
        h = self.handles[src]
        dh = self.handles.get(dest)
        if dh is None or not fedapi.region_ready(
                self.cluster.regions.get(dest, {}), now, self.ttl):
            # destination fell over mid-drain: abort toward a re-pick
            job.annotations.pop(
                fedapi.FED_EVACUATING_TO_ANNOTATION, None)
            self.cluster.update_vcjob(job)
            return
        copy = self._copy_of(h, job.key)
        if copy is None:
            return
        pg = h.mirror.cluster.podgroups.get(job.key)
        if pg is None or pg.annotations.get(
                eapi.ELASTIC_EVACUATED_ANNOTATION) != "true":
            return                  # source drain still in flight
        # the cutover gate: BOTH mirrors must be within the staleness
        # bound — the source's for the resume metadata we carry, the
        # destination's to see what we'd collide with.  A stale mirror
        # refuses (MirrorStaleError) rather than guessing.
        try:
            h.mirror.read_checked()
            dh.mirror.read_checked()
        except MirrorStaleError as e:
            metrics.inc("federation_cutover_refusals_total",
                        region=e.region)
            self.cluster.record_event(
                job.key, "FederationCutoverRefused", str(e))
            return
        key = fedapi.admission_key(job.key, self._attempt(job) + 1)
        if dh.mirror.cluster.vcjobs.get(job.key) is None and \
                self._find_admitted_copy(key) is None:
            resume = {k: v for k in _fold_keys()
                      if (v := copy.annotations.get(k)) is not None}
            resume[fedapi.FED_MIGRATED_FROM_ANNOTATION] = src
            episode = fedapi.episode_of(job)
            if episode:
                # both cutover sides carry the SAME episode; the
                # destination copy lands at the next hop
                resume[fedapi.FED_EPISODE_ANNOTATION] = episode
                resume[fedapi.FED_EPISODE_HOP_ANNOTATION] = \
                    str(fedapi.episode_hop(job) + 1)
            dcopy = self._regional_copy(job, dest, key, extra=resume)
            dcopy.annotations.pop(eapi.ELASTIC_EVACUATE_ANNOTATION,
                                  None)
            dcopy.annotations.pop(eapi.ELASTIC_EVACUATED_ANNOTATION,
                                  None)
            try:
                self.rpc.call(dest, "add_vcjob",
                              lambda: dh.client.add_vcjob(dcopy))
            except FedRPCError as e:
                log.warning("cutover create of %s failed: %s",
                            job.key, e)
                return
        # destination accepted: the source copy (and its held pods)
        # can go — ORDER MATTERS, delete only after the create landed
        try:
            self.rpc.call(src, "delete_vcjob",
                          lambda: h.client.delete_vcjob(job.key))
        except FedRPCError as e:
            log.warning("source delete of %s failed (residual reap "
                        "retries): %s", job.key, e)
        ann = job.annotations
        n = fedapi.migration_count(job) + 1
        ann[fedapi.FED_MIGRATIONS_ANNOTATION] = str(n)
        ann[fedapi.FED_MIGRATED_FROM_ANNOTATION] = src
        ann[fedapi.FED_ATTEMPT_ANNOTATION] = \
            str(self._attempt(job) + 1)
        ann[fedapi.FED_ADMITTED_REGION_ANNOTATION] = dest
        ann[fedapi.FED_ADMITTED_TS_ANNOTATION] = f"{now:.3f}"
        ann[fedapi.FED_ADMISSION_KEY_ANNOTATION] = key
        ann.pop(fedapi.FED_EVACUATE_ANNOTATION, None)
        ann.pop(fedapi.FED_EVACUATING_TO_ANNOTATION, None)
        episode = fedapi.episode_of(job)
        old_hop = fedapi.episode_hop(job)
        if episode:
            ann[fedapi.FED_EPISODE_HOP_ANNOTATION] = str(old_hop + 1)
        self.cluster.update_vcjob(job)
        started = self._evac_started.pop(job.key, None)
        if started is not None:
            metrics.observe("federation_cutover_seconds",
                            now - started)
        self.cluster.record_event(
            job.key, "FederationMigrated",
            f"cut over {src} -> {dest} (migration #{n})")
        metrics.inc("federation_migrations_total", kind="running")
        if episode:
            # the cutover span (decision -> source drained -> dest
            # created) belongs to the SOURCE hop's timeline; the
            # destination's own fragments start the next hop
            self._publish_fragment(
                trace.fragment_doc(
                    f"router-cutover {job.key}", "router", episode,
                    started if started is not None else now, now,
                    hop=old_hop, jobs=(job.key,),
                    labels={"from": src, "to": dest}))

    # -- census ---------------------------------------------------------

    def _gauges(self) -> None:
        states = {s: 0 for s in fedapi.REGION_STATES}
        now = self.now()
        for name, rec in self.cluster.regions.items():
            state = rec.get("state", fedapi.REGION_STATE_LOST)
            if state == fedapi.REGION_STATE_READY and \
                    not fedapi.region_alive(rec, now, self.ttl):
                state = fedapi.REGION_STATE_LOST
            if state not in states:
                state = fedapi.REGION_STATE_LOST
            states[state] += 1
        for state, n in states.items():
            metrics.set_gauge("federation_regions", n, state=state)
        pending = sum(1 for j in self._global_jobs()
                      if fedapi.admitted_region(j) is None
                      and j.phase is JobPhase.PENDING)
        metrics.set_gauge("federation_pending_jobs", pending)
        for region, b in self.rpc.breakers.items():
            metrics.set_gauge("federation_router_breaker_state",
                              STATE_CODES[b.state], region=region)
            snap = self.rpc.snapshot(region)
            for fam, field in (
                    ("federation_router_breaker_failures",
                     "failures"),
                    ("federation_router_breaker_opens", "opens"),
                    ("federation_router_breaker_half_opens",
                     "half_opens"),
                    ("federation_router_breaker_last_trip_ts",
                     "last_trip_ts"),
                    ("federation_router_breaker_retry_in_seconds",
                     "retry_in_s")):
                metrics.set_gauge(fam, float(snap[field]),
                                  region=region)


def main(argv=None) -> int:
    """`python -m volcano_tpu.federation.router --store URL`"""
    import argparse

    from volcano_tpu.cache.remote_cluster import RemoteCluster
    ap = argparse.ArgumentParser(
        description="federation router: one global queue over N "
                    "regional planes")
    ap.add_argument("--store", required=True,
                    help="global state server URL (may be a comma-"
                         "separated replica group)")
    ap.add_argument("--token", default="")
    ap.add_argument("--sync-s", type=float, default=2.0)
    ap.add_argument("--ttl-s", type=float, default=fedapi.REGION_TTL_S,
                    help="region loss TTL (bench planes compress it)")
    ap.add_argument("--arbitrage-s", type=float,
                    default=fedapi.ARBITRAGE_PENDING_S)
    ap.add_argument("--metrics-port", type=int, default=0)
    ap.add_argument("--holder", default="",
                    help="router lease identity (default: "
                         "router-<pid>); N processes with distinct "
                         "holders form the HA replica set")
    ap.add_argument("--lease-ttl-s", type=float,
                    default=fedapi.ROUTER_LEASE_TTL_S,
                    help="router lease TTL (bounds failover MTTR)")
    ap.add_argument("--no-elect", action="store_true",
                    help="legacy single-router mode: mutate without "
                         "holding the lease (NO fencing)")
    ap.add_argument("--mirror-poll-s", type=float, default=0.0,
                    help="mirror tail long-poll ceiling (bench planes "
                         "compress it below the region TTL)")
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(message)s")
    cluster = RemoteCluster(args.store, token=args.token,
                            tolerate_unreachable=True)
    if args.metrics_port:
        metrics.serve(args.metrics_port)
    import os
    router = FederationRouter(cluster, ttl=args.ttl_s,
                              arbitrage_after=args.arbitrage_s,
                              holder=args.holder or
                              f"router-{os.getpid()}",
                              elect=not args.no_elect,
                              lease_ttl=args.lease_ttl_s,
                              mirror_poll_s=args.mirror_poll_s or None)
    try:
        while True:
            try:
                router.sync()
            except Exception:       # noqa: BLE001 — keep reconciling
                log.exception("router sync failed")
            time.sleep(args.sync_s)
    except KeyboardInterrupt:
        return 0
    finally:
        router.close()
        cluster.close()


if __name__ == "__main__":
    raise SystemExit(main())
