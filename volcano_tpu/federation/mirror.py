"""RegionMirror: the WAL-shipping machinery reused as an async object
mirror (ISSUE 18).

The federation router needs to READ remote regions cheaply and
constantly — regional capacity for scoring, podgroup phase folding,
drain progress and checkpoint/resume metadata before a migration
cutover.  Polling every region's /objects per reconcile round is
O(objects) per round; the replication tier already solved the "follow
one store's history" problem with CRC-framed WAL shipping (PR 9), so
the mirror reuses that exact stream over the NON-QUORUM lane
(`GET /wal?mirror=1`, StateServer.mirror_ship):

  * bootstrap from `/replica_snapshot` (stores + wal_seq horizon),
    then tail framed records and fold the object events into a local
    FakeCluster — the same parse_record CRC + sequence verification
    the replica tail runs, refusing a corrupt or gapped batch
    WHOLESALE (never a partial apply);
  * private record kinds (`_probe`/`_lease`/`_req`/`_drain`) are the
    source region's internals — skipped, like the follower apply path
    skips them for visibility;
  * the mirror is NEVER part of the source's commit quorum and keeps
    no WAL of its own: it is a read cache whose staleness is
    ADVERTISED (`age_s`), not negotiated.  `read_checked()` is the
    enforcement point — a cutover reading checkpoint metadata through
    a mirror older than the bound gets MirrorStaleError, not stale
    state.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from volcano_tpu import metrics
from volcano_tpu.api import codec
from volcano_tpu.api.federation import MIRROR_MAX_AGE_S
from volcano_tpu.cache.fake_cluster import FakeCluster

log = logging.getLogger(__name__)

# tail long-poll ceiling; also the freshness heartbeat — an idle
# source returns one empty batch per poll, which still PROVES the
# mirror is current up to the source's horizon
MIRROR_POLL_S = 5.0
# private WAL record kinds: source-internal, never object state
PRIVATE_KINDS = ("_probe", "_lease", "_req", "_drain")


class MirrorStaleError(RuntimeError):
    """A read through the mirror exceeded its advertised staleness
    bound: the caller must NOT act on the cached state (a migration
    cutover retries / re-verifies against the source instead)."""

    def __init__(self, region: str, age_s: float, bound_s: float):
        super().__init__(
            f"mirror of region {region!r} is {age_s:.1f}s stale "
            f"(bound {bound_s:.1f}s)")
        self.region = region
        self.age_s = age_s
        self.bound_s = bound_s


class RegionMirror:
    """Async read mirror of one region's state server."""

    def __init__(self, name: str, url: str, token: str = "",
                 max_age_s: float = MIRROR_MAX_AGE_S,
                 now=time.monotonic):
        self.name = name
        self.url = url.rstrip("/")
        self.token = token
        self.max_age_s = float(max_age_s)
        self._now = now
        self.cluster = FakeCluster()
        self.applied_seq = 0
        self.applied_rv = 0
        self.epoch = ""
        self._snapshot_rv = 0
        self._fresh_ts: Optional[float] = None
        self._bootstrapped = False
        self.resyncs = 0
        self.delta_resyncs = 0
        self.refused_batches = 0
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- wire ----------------------------------------------------------

    def _get(self, path: str, timeout: float):
        from volcano_tpu.server.replication import http_json
        return http_json("GET", f"{self.url}{path}", timeout=timeout,
                         token=self.token)

    def bootstrap(self) -> None:
        """Full re-sync: install the source's replica snapshot and
        resume the tail at its wal_seq horizon."""
        doc = self._get("/replica_snapshot", timeout=30.0)
        from volcano_tpu.server.durability import decode_stores_into
        cluster = FakeCluster()
        decode_stores_into(cluster, doc.get("stores", {}))
        with self._lock:
            self.cluster = cluster
            self.applied_seq = int(doc.get("wal_seq", 0))
            self.applied_rv = int(doc.get("rv", 0))
            self._snapshot_rv = int(doc.get("rv", 0))
            self.epoch = doc.get("epoch", "")
            self._fresh_ts = self._now()
            self._bootstrapped = True
        self.resyncs += 1
        metrics.inc("federation_mirror_resyncs_total",
                    region=self.name)
        log.info("mirror[%s]: bootstrapped at seq=%d rv=%d",
                 self.name, self.applied_seq, self.applied_rv)

    def poll(self, timeout: float = 0.0) -> int:
        """One tail round: bootstrap if needed, fetch records past the
        applied seq, fold them in.  Returns the number of records
        applied; raises OSError on wire failure (the caller owns the
        retry — age_s keeps growing truthfully meanwhile)."""
        if not self._bootstrapped:
            self.bootstrap()
        resp = self._get(
            f"/wal?mirror=1&since_seq={self.applied_seq}"
            f"&timeout={timeout:g}", timeout=timeout + 10.0)
        if resp.get("resync"):
            # fell off the source's ship ring (compaction, a restart
            # emptying the volatile ring, a heal).  A same-lineage
            # mirror first tries the DELTA lane — the events since
            # its rv, O(churn missed) instead of O(store); the full
            # snapshot bootstrap is the fallback for true lineage
            # breaks (epoch base change / rv fell off the event ring)
            if self._delta_resync(resp):
                return 0
            self._bootstrapped = False
            self.bootstrap()
            return 0
        applied = self._apply(resp.get("records") or [])
        with self._lock:
            self._fresh_ts = self._now()
            self.epoch = resp.get("epoch", self.epoch)
        return applied

    def _same_lineage(self, epoch: str) -> bool:
        """Epochs are BASE.BOOT: the BASE survives durable restarts
        (same store, new boot), so a delta catch-up across a restart
        is sound; a BASE change means a different history — only a
        full bootstrap is safe."""
        return bool(self.epoch) and bool(epoch) and \
            self.epoch.split(".")[0] == epoch.split(".")[0]

    def _delta_resync(self, ship_resp: dict) -> bool:
        """Incremental re-sync off the source's /watch delta lane:
        ask for the events since our applied rv (timeout=0 returns
        immediately), fold them in, and re-align the WAL cursor to
        the seq horizon the ship response advertised.  Returns False
        — caller falls back to the full snapshot bootstrap — when the
        lineage broke or our rv fell off the source's event ring."""
        if self.applied_rv <= 0 or \
                not self._same_lineage(ship_resp.get("epoch", "")):
            return False
        try:
            resp = self._get(f"/watch?since={self.applied_rv}"
                             f"&timeout=0", timeout=10.0)
        except (OSError, ValueError) as e:
            log.debug("mirror[%s]: delta resync probe failed: %s",
                      self.name, e)
            return False
        if resp.get("resync") or \
                not self._same_lineage(resp.get("epoch", "")):
            return False
        from volcano_tpu.server.durability import apply_event_obj
        events = resp.get("events") or []
        rv = int(resp.get("rv", 0))
        with self._lock:
            for ev in events:
                apply_event_obj(self.cluster, ev.get("kind", ""),
                                codec.decode(ev["obj"]))
            self.applied_rv = max(self.applied_rv, rv)
            # bootstrap-equivalent dedup point: the next shipped
            # batches may overlap records already inside this delta —
            # the erv <= _snapshot_rv guard in _apply skips them
            self._snapshot_rv = self.applied_rv
            # seq horizon captured BEFORE the delta fetch: every
            # object record at or below it has rv <= the delta's rv
            # (WAL order == rv order), so nothing between the two
            # cursors can be missed
            self.applied_seq = int(ship_resp.get("last_seq",
                                                 self.applied_seq))
            self.epoch = resp.get("epoch", self.epoch)
            self._fresh_ts = self._now()
        self.resyncs += 1
        self.delta_resyncs += 1
        metrics.inc("federation_mirror_delta_resyncs_total",
                    region=self.name)
        log.info("mirror[%s]: delta resync applied %d events -> "
                 "rv=%d seq=%d", self.name, len(events),
                 self.applied_rv, self.applied_seq)
        return True

    def _apply(self, lines) -> int:
        """Fold one shipped batch: verify EVERY record's CRC and
        sequence first — a corrupt or gapped batch is refused
        wholesale and re-requested from the durable source (applying
        a prefix would desync this mirror from the seq stream)."""
        from volcano_tpu.server.durability import (apply_event_obj,
                                                   parse_record)
        from volcano_tpu.server.replication import \
            ShippedCorruptionError
        parsed = []
        seq = self.applied_seq
        for line in lines:
            rec, bad = parse_record(line.rstrip("\n"))
            if rec is None:
                self.refused_batches += 1
                metrics.inc("federation_mirror_refused_batches_total",
                            region=self.name)
                raise ShippedCorruptionError(
                    f"mirror[{self.name}]: record after seq {seq}: "
                    f"{bad}")
            q = int(rec.get("q", 0))
            if q <= seq:
                continue                    # overlap re-ship: skip
            if q != seq + 1:
                self.refused_batches += 1
                metrics.inc("federation_mirror_refused_batches_total",
                            region=self.name)
                raise ShippedCorruptionError(
                    f"mirror[{self.name}]: sequence gap {seq}->{q}")
            seq = q
            parsed.append((q, rec))
        if not parsed:
            return 0
        with self._lock:
            for q, rec in parsed:
                kind = rec.get("k", "")
                self.applied_seq = q
                if kind in PRIVATE_KINDS or kind.startswith("_"):
                    continue
                erv = int(rec.get("rv", 0))
                if erv and erv <= self._snapshot_rv:
                    continue    # already inside the bootstrap snapshot
                apply_event_obj(self.cluster, kind,
                                codec.decode(rec["o"]))
                if erv:
                    self.applied_rv = max(self.applied_rv, erv)
        metrics.inc("federation_mirror_records_total",
                    region=self.name, value=float(len(parsed)))
        return len(parsed)

    # -- staleness contract --------------------------------------------

    def age_s(self) -> float:
        """Seconds since the mirror last PROVED itself current (a
        successful poll — empty batches count: they carry the source's
        horizon).  Infinite before the first bootstrap."""
        with self._lock:
            if self._fresh_ts is None:
                return float("inf")
            return max(0.0, self._now() - self._fresh_ts)

    def read_checked(self, max_age_s: Optional[float] = None
                     ) -> FakeCluster:
        """The mirror's store, IF within the staleness bound — the
        gate every cutover-critical read goes through."""
        bound = self.max_age_s if max_age_s is None else max_age_s
        age = self.age_s()
        if age > bound:
            raise MirrorStaleError(self.name, age, bound)
        return self.cluster

    def status(self) -> dict:
        age = self.age_s()
        return {"region": self.name, "url": self.url,
                "applied_seq": self.applied_seq,
                "applied_rv": self.applied_rv,
                "epoch": self.epoch,
                "age_s": (None if age == float("inf")
                          else round(age, 3)),
                # operator-facing alias of age_s: the value the router
                # exports as federation_mirror_staleness_seconds and
                # folds into the region registry (`vtpctl regions`)
                "staleness_s": (None if age == float("inf")
                                else round(age, 3)),
                "resyncs": self.resyncs,
                "delta_resyncs": self.delta_resyncs,
                "refused_batches": self.refused_batches}

    # -- background tail -----------------------------------------------

    def start(self, poll_s: float = MIRROR_POLL_S) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def _loop():
            from volcano_tpu.federation.retry import backoff_delay
            from volcano_tpu.server.replication import \
                ShippedCorruptionError
            failures = 0
            while not self._stop.is_set():
                try:
                    self.poll(timeout=poll_s)
                    failures = 0
                except ShippedCorruptionError as e:
                    # refuse-and-re-request: the durable source serves
                    # the same records again, clean — but a source
                    # that KEEPS shipping corrupt batches backs off
                    # like any other failure
                    failures += 1
                    log.warning("%s (re-requesting)", e)
                    self._stop.wait(backoff_delay(
                        failures, f"mirror:{self.name}",
                        base=0.2, cap=5.0))
                except (OSError, ValueError) as e:
                    # the shared federation backoff policy (capped
                    # exponential, deterministic jitter) — age_s keeps
                    # growing truthfully while the source is away
                    failures += 1
                    delay = backoff_delay(
                        failures, f"mirror:{self.name}",
                        base=0.2, cap=5.0)
                    log.debug("mirror[%s]: poll failed: %s (retry "
                              "in %.1fs)", self.name, e, delay)
                    self._stop.wait(delay)

        self._thread = threading.Thread(
            target=_loop, name=f"mirror-{self.name}", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
