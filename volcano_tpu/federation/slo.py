"""Fleet metric rollups + SLO burn-rate gauges (router-side).

The router is the only process that can see every region, so it is
where fleet health becomes ONE exposition instead of N: each pass it
scrapes every ready region's /metrics text, folds the bounded
families into `federation_rollup_*` gauges (sum for counters and
histogram sums/counts, sum AND max for gauges — `region` is the only
label added, `family` values are closed over bundle.FAMILIES), and
feeds the samples into multi-window burn-rate tracking over the SLOs
the system already claims:

  serving-p99    serving_slo_attainment_min >= SERVING_ATTAINMENT_TARGET
                 (the PR-14 autoscaler's p99 attainment contract)
  failover-mttr  mean of new failover_mttr_seconds observations
                 <= FAILOVER_MTTR_BOUND_S (the PR-16 recovery bound)
  sched-e2e-p95  mean of new e2e_scheduling_latency_seconds
                 observations <= SCHED_E2E_TARGET_S (the PR-5 flight-
                 recorder latency claim; a mean proxy — the text
                 exposition carries count/sum, not quantiles)

Burn rate is the standard multi-window form: the fraction of polls
inside the window that violated the SLO, divided by the error budget
— 1.0 means the budget is being spent exactly as fast as it accrues,
anything sustained above it means the SLO will be missed.  Episode
IDs and job keys NEVER appear here: every label is a closed enum or
an operator-bounded region name.
"""

from __future__ import annotations

import time
import urllib.request
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from volcano_tpu import metrics

# rollup scrape budget per region per pass (a slow region must not
# stall the reconcile loop)
ROLLUP_FETCH_TIMEOUT_S = 2.0

# burn-rate windows (label values of slo_burn_rate{window=})
SLO_WINDOWS = ("5m", "1h")
WINDOW_S = {"5m": 300.0, "1h": 3600.0}

# the SLOs (label values of slo_burn_rate{slo=})
SLO_SERVING = "serving-p99"
SLO_FAILOVER = "failover-mttr"
SLO_SCHED = "sched-e2e-p95"
SLO_NAMES = (SLO_SERVING, SLO_FAILOVER, SLO_SCHED)

SERVING_ATTAINMENT_TARGET = 0.99
FAILOVER_MTTR_BOUND_S = 120.0
SCHED_E2E_TARGET_S = 1.0

# error budget: tolerated bad-poll fraction per window
ERROR_BUDGETS = {SLO_SERVING: 0.01, SLO_FAILOVER: 0.05,
                 SLO_SCHED: 0.05}


def fetch_metrics_text(url: str, token: str = "",
                       timeout: float = ROLLUP_FETCH_TIMEOUT_S) -> str:
    """One region's Prometheus text exposition (read-only; breakers
    govern mutations, not scrapes — a failed scrape just skips the
    region this pass)."""
    req = urllib.request.Request(url.rstrip("/") + "/metrics")
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        body = resp.read()
    return body.decode("utf-8", "replace")


def parse_samples(text: str) -> List[Tuple[str, Dict[str, str], float]]:
    """(family, labels, value) per exposition line; histogram
    _count/_sum suffixes are kept verbatim (the rollup folds them)."""
    from volcano_tpu.analysis.schema import _LABEL_RE, _LINE_RE
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _LINE_RE.match(line)
        if m is None:
            continue
        try:
            value = float(m.group("value"))
        except (TypeError, ValueError):
            continue
        labels = {lm.group("k"): lm.group("v")
                  for lm in _LABEL_RE.finditer(m.group("labels") or "")}
        out.append((m.group("name"), labels, value))
    return out


def rollup(region: str, samples) -> List[Tuple[str, dict, float]]:
    """One region's samples folded to (rollup family, labels, value)
    rows: sums for counters/histograms, sum AND max for gauges.
    Families outside bundle.FAMILIES are dropped — the rollup is the
    bounded-cardinality contract applied fleet-wide."""
    from volcano_tpu.bundle import FAMILIES
    sums: Dict[str, float] = {}
    maxes: Dict[str, float] = {}
    counts: Dict[str, float] = {}
    for name, _labels, value in samples:
        base, suffix = name, ""
        for s in ("_count", "_sum"):
            if name.endswith(s) and name[:-len(s)] in FAMILIES:
                base, suffix = name[:-len(s)], s
                break
        kind = FAMILIES.get(base)
        if kind is None:
            continue
        if kind == "histogram":
            if suffix == "_sum":
                sums[base] = sums.get(base, 0.0) + value
            elif suffix == "_count":
                counts[base] = counts.get(base, 0.0) + value
            continue
        sums[base] = sums.get(base, 0.0) + value
        if kind == "gauge":
            maxes[base] = max(maxes.get(base, value), value)
    rows = []
    for fam, v in sums.items():
        rows.append(("federation_rollup_sum",
                     {"family": fam, "region": region}, v))
    for fam, v in maxes.items():
        rows.append(("federation_rollup_max",
                     {"family": fam, "region": region}, v))
    for fam, v in counts.items():
        rows.append(("federation_rollup_count",
                     {"family": fam, "region": region}, v))
    return rows


class SLOTracker:
    """Multi-window burn-rate accounting over per-pass region samples.

    Each ingest() is one poll: the fleet-wide indicator per SLO is
    computed from the freshly scraped samples (histogram indicators
    use the DELTA against the previous poll, so one old spike does
    not poison the window), classified good/bad, and appended to a
    time-bounded ring.  burn_rates() is then pure arithmetic."""

    def __init__(self, now: Callable[[], float] = time.time):
        self.now = now
        self._polls: Dict[str, deque] = {
            slo: deque() for slo in SLO_NAMES}
        # (region, family) -> (count, sum) at the previous poll
        self._prev_hist: Dict[Tuple[str, str], Tuple[float, float]] = {}

    # -- indicator extraction ------------------------------------------

    def _hist_delta_mean(self, region_samples, family: str
                         ) -> Optional[float]:
        """Mean of the observations ADDED since the previous poll,
        across regions (None = no new observations anywhere)."""
        dc_total = ds_total = 0.0
        for region, samples in region_samples.items():
            count = total = None
            for name, _labels, value in samples:
                if name == family + "_count":
                    count = (count or 0.0) + value
                elif name == family + "_sum":
                    total = (total or 0.0) + value
            if count is None or total is None:
                continue
            pc, ps = self._prev_hist.get((region, family), (0.0, 0.0))
            if count < pc:
                pc, ps = 0.0, 0.0       # region process restarted
            dc_total += count - pc
            ds_total += total - ps
            self._prev_hist[(region, family)] = (count, total)
        if dc_total <= 0:
            return None
        return ds_total / dc_total

    def _attainment_min(self, region_samples) -> Optional[float]:
        worst = None
        for samples in region_samples.values():
            for name, _labels, value in samples:
                if name == "serving_slo_attainment_min":
                    worst = value if worst is None \
                        else min(worst, value)
        return worst

    # -- poll ingest ---------------------------------------------------

    def ingest(self, region_samples: Dict[str, list],
               now: Optional[float] = None) -> Dict[str, Optional[bool]]:
        """One poll over {region: parse_samples(...)}.  Returns the
        per-SLO verdict (True=good, False=bad, None=no data)."""
        now = self.now() if now is None else now
        verdicts: Dict[str, Optional[bool]] = {}
        att = self._attainment_min(region_samples)
        verdicts[SLO_SERVING] = None if att is None \
            else att >= SERVING_ATTAINMENT_TARGET
        mttr = self._hist_delta_mean(region_samples,
                                     "failover_mttr_seconds")
        verdicts[SLO_FAILOVER] = None if mttr is None \
            else mttr <= FAILOVER_MTTR_BOUND_S
        e2e = self._hist_delta_mean(region_samples,
                                    "e2e_scheduling_latency_seconds")
        verdicts[SLO_SCHED] = None if e2e is None \
            else e2e <= SCHED_E2E_TARGET_S
        horizon = now - max(WINDOW_S.values())
        for slo, ok in verdicts.items():
            ring = self._polls[slo]
            if ok is not None:
                ring.append((now, ok))
            while ring and ring[0][0] < horizon:
                ring.popleft()
        return verdicts

    # -- burn math -----------------------------------------------------

    def burn_rates(self, now: Optional[float] = None
                   ) -> Dict[Tuple[str, str], float]:
        """{(slo, window): burn rate}; 0.0 when the window holds no
        polls (no data is not a burning budget)."""
        now = self.now() if now is None else now
        out = {}
        for slo in SLO_NAMES:
            ring = self._polls[slo]
            for window in SLO_WINDOWS:
                cutoff = now - WINDOW_S[window]
                polls = [ok for ts, ok in ring if ts >= cutoff]
                if not polls:
                    out[(slo, window)] = 0.0
                    continue
                bad_frac = polls.count(False) / len(polls)
                out[(slo, window)] = bad_frac / ERROR_BUDGETS[slo]
        return out

    def export(self, now: Optional[float] = None) -> dict:
        """Emit slo_burn_rate gauges and return the durable doc the
        router writes to the global store (vtpctl slo)."""
        now = self.now() if now is None else now
        burns = self.burn_rates(now)
        doc: dict = {"ts": now, "slos": {}}
        targets = {SLO_SERVING: SERVING_ATTAINMENT_TARGET,
                   SLO_FAILOVER: FAILOVER_MTTR_BOUND_S,
                   SLO_SCHED: SCHED_E2E_TARGET_S}
        for slo in SLO_NAMES:
            windows = {}
            for window in SLO_WINDOWS:
                burn = burns[(slo, window)]
                metrics.set_gauge("slo_burn_rate", burn,
                                  slo=slo, window=window)
                cutoff = now - WINDOW_S[window]
                polls = [ok for ts, ok in self._polls[slo]
                         if ts >= cutoff]
                windows[window] = {
                    "burn": round(burn, 4),
                    "good_frac": (round(polls.count(True)
                                        / len(polls), 4)
                                  if polls else None),
                    "polls": len(polls)}
            doc["slos"][slo] = {"target": targets[slo],
                                "budget": ERROR_BUDGETS[slo],
                                "windows": windows}
        return doc
