"""Federation tier: one global queue over N regional planes.

See api/federation.py for the annotation contract, federation/mirror.py
for the async WAL object mirror, federation/router.py for the global
admission/migration reconciler, federation/ha.py + federation/retry.py
for the leased router replica set (term-fenced failover, shared
cross-region RPC policy), and docs/design/federation.md for the full
protocol (router, mirror-vs-quorum contract, cutover, HA).
"""

from volcano_tpu.federation.ha import RouterElector
from volcano_tpu.federation.mirror import MirrorStaleError, RegionMirror
from volcano_tpu.federation.retry import (FedRPC, FedRPCError,
                                          RegionBreaker,
                                          RegionTrippedError,
                                          RouterFencedError)
from volcano_tpu.federation.router import FederationRouter

__all__ = ["MirrorStaleError", "RegionMirror", "FederationRouter",
           "RouterElector", "FedRPC", "FedRPCError", "RegionBreaker",
           "RegionTrippedError", "RouterFencedError"]
