"""Federation tier: one global queue over N regional planes.

See api/federation.py for the annotation contract, federation/mirror.py
for the async WAL object mirror, federation/router.py for the global
admission/migration reconciler, and docs/design/federation.md for the
full protocol (router, mirror-vs-quorum contract, cutover).
"""

from volcano_tpu.federation.mirror import MirrorStaleError, RegionMirror
from volcano_tpu.federation.router import FederationRouter

__all__ = ["MirrorStaleError", "RegionMirror", "FederationRouter"]
