"""The ONE cross-region RPC policy for the federation tier.

Every mutating call the router makes into a regional plane goes
through FedRPC.call — replacing the scattered per-site
``except OSError: log("will retry")`` handlers with one shared
discipline:

  * transient classification reuses the wire client's rule
    (connection failures, truncated responses, 5xx); 4xx verdicts —
    including the fence's 409 — propagate typed, because retrying a
    verdict gets the same answer forever;
  * capped exponential backoff with DETERMINISTIC jitter (crc32 over
    (region, attempt), never random): under the seeded chaos
    conductor the retry schedule replays byte-identically, so a
    failure found at seed N reproduces at seed N;
  * a per-region CIRCUIT BREAKER: after ``threshold`` consecutive
    transient failures the region degrades to MIRROR-ONLY observation
    — the router keeps reading its mirror and folding goodput, but
    attempts no mutation until the cooldown elapses (half-open: one
    probe; success closes, failure re-opens with a longer cooldown).
    A partitioned region therefore costs one probe per cooldown, not
    a hot loop of doomed RPCs per reconcile pass.

The fence 409 is special-cased into RouterFencedError: it means THIS
router was deposed (a newer term wrote first), not that the region is
sick — the caller must stop mutating and re-contend for the lease,
never retry.
"""

from __future__ import annotations

import time
import zlib
from typing import Callable, Dict, Optional

from volcano_tpu import metrics

# consecutive transient failures before a region's breaker opens
BREAKER_THRESHOLD = 3
# open-state cooldown: base doubling per open, capped
BREAKER_COOLDOWN_BASE_S = 1.0
BREAKER_COOLDOWN_CAP_S = 30.0
# per-call retry budget for region write clients: one dead region
# must cost a bounded slice of a reconcile pass, not the wire
# client's default 30s deadline
FED_RPC_DEADLINE_S = 5.0

STATE_CLOSED = "closed"
STATE_OPEN = "open"
STATE_HALF_OPEN = "half-open"
BREAKER_STATES = (STATE_CLOSED, STATE_OPEN, STATE_HALF_OPEN)
# gauge encoding (federation_router_breaker_state)
STATE_CODES = {STATE_CLOSED: 0.0, STATE_OPEN: 1.0, STATE_HALF_OPEN: 2.0}


def deterministic_jitter(key: str, attempt: int) -> float:
    """[0, 1) jitter fraction from a crc32 hash — stable across runs
    so seeded chaos schedules replay exactly."""
    return (zlib.crc32(f"{key}:{attempt}".encode()) % 1000) / 1000.0


def backoff_delay(attempt: int, key: str,
                  base: float = BREAKER_COOLDOWN_BASE_S,
                  cap: float = BREAKER_COOLDOWN_CAP_S) -> float:
    """Capped exponential backoff with deterministic half-jitter:
    delay in [exp/2, exp) where exp = min(cap, base * 2^(attempt-1))."""
    exp = min(cap, base * (2 ** max(0, attempt - 1)))
    return exp * (0.5 + 0.5 * deterministic_jitter(key, attempt))


class FedRPCError(RuntimeError):
    """A cross-region RPC failed transiently (after the client's own
    bounded retries) or was refused by an open breaker.  The caller
    skips the region this pass; the next pass re-consults the
    breaker."""

    def __init__(self, region: str, op: str, why: str):
        super().__init__(f"region {region!r} {op}: {why}")
        self.region = region
        self.op = op


class RegionTrippedError(FedRPCError):
    """The region's breaker is open: no RPC was attempted at all."""


class RouterFencedError(RuntimeError):
    """A regional plane refused this router's write as STALE-TERM
    (fence 409): a newer router holds the lease.  Not a region
    failure — the caller must stop mutating and re-contend."""

    def __init__(self, region: str, op: str, why: str):
        super().__init__(
            f"deposed: region {region!r} fenced {op}: {why}")
        self.region = region
        self.op = op


def _is_fence_refusal(e: Exception) -> bool:
    return isinstance(e, ValueError) and \
        str(e).startswith("fenced")


class RegionBreaker:
    """closed -> (threshold consecutive failures) -> open -> (cooldown,
    deterministic-jittered, doubling per open) -> half-open -> one
    probe -> closed | open.  Single-writer discipline: the router's
    reconcile pass is the only caller."""

    __slots__ = ("region", "state", "failures", "opens", "half_opens",
                 "last_trip_ts", "_retry_at", "threshold", "base",
                 "cap")

    def __init__(self, region: str, threshold: int = BREAKER_THRESHOLD,
                 base: float = BREAKER_COOLDOWN_BASE_S,
                 cap: float = BREAKER_COOLDOWN_CAP_S):
        self.region = region
        self.state = STATE_CLOSED
        self.failures = 0           # consecutive transient failures
        self.opens = 0              # times opened (drives the cooldown)
        self.half_opens = 0         # cooldown expiries -> probe admitted
        self.last_trip_ts = 0.0     # WALL ts of the last open (0=never)
        self._retry_at = 0.0        # open -> half-open deadline
        self.threshold = threshold
        self.base = base
        self.cap = cap

    def allow(self, now: float) -> bool:
        """May a mutation be attempted right now?  An open breaker
        past its cooldown transitions to half-open and admits ONE
        probe."""
        if self.state == STATE_OPEN:
            if now < self._retry_at:
                return False
            self.state = STATE_HALF_OPEN
            self.half_opens += 1
        return True

    def record_success(self) -> None:
        self.state = STATE_CLOSED
        self.failures = 0
        self.opens = 0

    def record_failure(self, now: float) -> bool:
        """Returns True when this failure OPENED the breaker."""
        self.failures += 1
        if self.state == STATE_HALF_OPEN or \
                self.failures >= self.threshold:
            self.opens += 1
            self.state = STATE_OPEN
            self._retry_at = now + backoff_delay(
                self.opens, self.region, self.base, self.cap)
            return True
        return False

    def retry_in(self, now: float) -> float:
        return max(0.0, self._retry_at - now) \
            if self.state == STATE_OPEN else 0.0

    def snapshot(self, now: float) -> dict:
        """Durable state for the global store (router_breaker kind):
        the open->half-open deadline ships as a RELATIVE cooldown
        (monotonic clocks do not cross processes)."""
        return {"region": self.region, "state": self.state,
                "failures": self.failures, "opens": self.opens,
                "half_opens": self.half_opens,
                "last_trip_ts": self.last_trip_ts,
                "retry_in_s": round(self.retry_in(now), 3)}

    def restore(self, snap: dict, now: float) -> None:
        """Adopt a previous holder's learned region health (promoted
        standby): state machine position, counters, and the remaining
        cooldown re-anchored to OUR monotonic clock.  Conservative by
        construction — at worst the full snapshotted cooldown is
        served again, never a hot loop into a sick region."""
        if not isinstance(snap, dict):
            return
        state = snap.get("state")
        if state not in BREAKER_STATES:
            return
        self.state = state
        for attr in ("failures", "opens", "half_opens"):
            try:
                setattr(self, attr, max(0, int(snap.get(attr, 0) or 0)))
            except (TypeError, ValueError):
                pass
        try:
            self.last_trip_ts = float(snap.get("last_trip_ts", 0) or 0)
        except (TypeError, ValueError):
            self.last_trip_ts = 0.0
        try:
            retry_in = max(0.0, float(snap.get("retry_in_s", 0) or 0))
        except (TypeError, ValueError):
            retry_in = 0.0
        self._retry_at = now + retry_in if self.state == STATE_OPEN \
            else 0.0


class FedRPC:
    """The shared seam: breaker gate + classification + counters for
    every mutating cross-region call."""

    def __init__(self, now: Callable[[], float] = time.monotonic,
                 wall: Callable[[], float] = time.time):
        self._now = now
        self._wall = wall
        self.breakers: Dict[str, RegionBreaker] = {}
        # trip/close seam (router persistence): called as
        # on_transition(region, breaker, "open"|"close") AFTER the
        # state change — exceptions are the callback's problem, the
        # RPC verdict already stands
        self.on_transition: Optional[
            Callable[[str, RegionBreaker, str], None]] = None

    def breaker(self, region: str) -> RegionBreaker:
        b = self.breakers.get(region)
        if b is None:
            b = self.breakers[region] = RegionBreaker(region)
        return b

    def available(self, region: str) -> bool:
        """Would a mutation be attempted now?  (Does not consume the
        half-open probe — a pure read for scoring/placement.)"""
        b = self.breaker(region)
        return b.state != STATE_OPEN or \
            self._now() >= b._retry_at

    def state(self, region: str) -> str:
        return self.breaker(region).state

    def call(self, region: str, op: str, fn: Callable[[], object]):
        """Run one mutating RPC under the shared policy.  Raises
        RegionTrippedError (breaker open, nothing attempted),
        FedRPCError (transient failure, breaker fed),
        RouterFencedError (deposed — stop mutating), or the typed 4xx
        verdict (ValueError/KeyError/AdmissionError) unchanged."""
        from volcano_tpu.cache.remote_cluster import _transient
        b = self.breaker(region)
        now = self._now()
        if not b.allow(now):
            metrics.inc("federation_router_rpc_skipped_total",
                        region=region)
            raise RegionTrippedError(
                region, op, f"breaker open (retry in "
                f"{b.retry_in(now):.1f}s)")
        try:
            out = fn()
        except Exception as e:  # noqa: BLE001 — classified below
            if _is_fence_refusal(e):
                raise RouterFencedError(region, op, str(e)) from e
            if not _transient(e):
                raise               # typed 4xx verdict: caller's call
            opened = b.record_failure(self._now())
            metrics.inc("federation_router_rpc_failures_total",
                        region=region, op=op)
            if opened:
                b.last_trip_ts = self._wall()
                metrics.inc("federation_router_breaker_opens_total",
                            region=region)
                self._fire(region, b, "open")
            metrics.set_gauge("federation_router_breaker_state",
                              STATE_CODES[b.state], region=region)
            raise FedRPCError(region, op, str(e)) from e
        was_tripped = b.state != STATE_CLOSED
        b.record_success()
        if was_tripped:
            self._fire(region, b, "close")
        metrics.set_gauge("federation_router_breaker_state",
                          STATE_CODES[b.state], region=region)
        return out

    def _fire(self, region: str, b: RegionBreaker, event: str) -> None:
        if self.on_transition is None:
            return
        try:
            self.on_transition(region, b, event)
        except Exception:  # noqa: BLE001 — persistence is advisory
            pass

    def snapshot(self, region: str) -> dict:
        return self.breaker(region).snapshot(self._now())

    def restore(self, region: str, snap: dict) -> None:
        self.breaker(region).restore(snap, self._now())

    def states(self) -> Dict[str, str]:
        return {r: b.state for r, b in sorted(self.breakers.items())}
