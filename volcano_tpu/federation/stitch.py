"""Cross-plane episode trace stitching (leaseholder router only).

Every plane that touches a federated gang leaves a LOCAL fragment of
its causal episode: the router's admit/cutover spans, the regional
schedulers' session traces (root `episode` label), the controllers'
drain/recovery fragments, and the lifecycle phase stamps riding the
gang's pods.  None of them can see the whole story — the stitcher
can, because the router already holds a mirror and a client for every
region (the same machinery region heartbeats ride).

Per pass, for each in-flight episode it:

  1. pulls `/traces?episode=` fragments from each regional ring,
  2. synthesizes a per-hop `lifecycle` fragment from the phase
     stamps visible in the region's mirror (created -> enqueued ->
     allocated -> bound -> admitted -> running — mirror-fed, so a
     region whose ring rotated still contributes its placement),
  3. recovers the previously stitched tree from the global store (a
     promoted standby adopts the deposed holder's fragments instead
     of starting blind — stitches survive router failover),
  4. merges + orders fragments by (hop, start) and applies the
     PER-HOP CLOCK-SKEW CLAMP — trace.phase_segments' telescoping
     rule lifted to hops: a later hop may not begin before the
     stitched frontier, negative skew collapses to zero and the
     frontier only moves forward, so the segment sum always equals
     the stitched wall time,
  5. writes the stitched doc to the `fleet_trace` dict-kind in the
     GLOBAL store (durable; `GET /fleet_trace?episode=` serves it).

Episode IDs live in annotations and trace labels only — the single
metric here (`federation_stitched_traces_total`) is label-free.
"""

from __future__ import annotations

import logging
from collections import OrderedDict
from typing import Dict, List, Optional

from volcano_tpu import metrics, trace
from volcano_tpu.api import federation as fedapi

log = logging.getLogger(__name__)

# fragments kept per episode / episodes tracked / episodes stitched
# per pass (bounded memory; a pathological fleet degrades to stale
# stitches, never to an unbounded router)
MAX_FRAGMENTS = 64
MAX_EPISODES = 64
MAX_EPISODES_PER_PASS = 8
PULL_LIMIT = 32


def _frag_key(plane: str, root: dict) -> str:
    """Stable fragment identity across passes AND across routers
    (embedded as the `fkey` label so a recovered stitched tree dedups
    against a re-pull of the same ring doc)."""
    return (f"{plane}|{root.get('name', '?')}"
            f"|{root.get('start', 0.0):.2f}")


def _shift(span: dict, delta: float) -> dict:
    out = dict(span)
    out["start"] = span.get("start", 0.0) + delta
    kids = span.get("children")
    if kids:
        out["children"] = [_shift(c, delta) for c in kids]
    return out


def _fragment(plane: str, hop: int, root: dict, jobs=()) -> dict:
    return {"plane": plane, "hop": int(hop), "root": root,
            "key": root.get("labels", {}).get("fkey")
            or _frag_key(plane, root), "jobs": list(jobs)}


def stitch(episode: str, fragments: List[dict],
           t0: Optional[float] = None, jobs=()) -> Optional[dict]:
    """One cross-plane span tree from this episode's fragments.

    Pure function: fragments are {"plane", "hop", "root", ...} with
    COMPLETE roots (incomplete ones are dropped — the global store's
    is_complete_span gate must always pass).  Returns None when
    nothing stitchable remains."""
    frags = [f for f in fragments if trace.is_complete_span(f["root"])]
    if not frags:
        return None
    frags.sort(key=lambda f: (f["hop"],
                              f["root"].get("start", 0.0)))
    base = min(f["root"].get("start", 0.0) for f in frags)
    if t0 is not None:
        base = min(base, t0)
    # per-hop clamp: each hop group shifts forward (never back) so it
    # cannot begin before the stitched frontier — the telescoping
    # rule of trace.phase_segments applied across plane clocks
    segments: Dict[str, float] = {}
    children = []
    planes = set()
    frontier = base
    hops = sorted({f["hop"] for f in frags})
    for hop in hops:
        group = [f for f in frags if f["hop"] == hop]
        gstart = min(f["root"].get("start", 0.0) for f in group)
        gend = max(f["root"].get("start", 0.0)
                   + f["root"].get("dur", 0.0) for f in group)
        shift = max(0.0, frontier - gstart) \
            if gstart < frontier else 0.0
        gstart += shift
        gend += shift
        segments[f"hop{hop}-wait"] = max(0.0, gstart - frontier)
        frontier = max(frontier, gstart)
        segments[f"hop{hop}-active"] = max(0.0, gend - frontier)
        frontier = max(frontier, gend)
        for f in group:
            planes.add(f["plane"])
            root = _shift(f["root"], shift)
            lbl = dict(root.get("labels", {}))
            # the fragment's resolved plane is authoritative — a ring
            # doc's own label says "controllers", but the stitched
            # tree must carry the per-region rename so the Perfetto
            # track matches the doc's planes list
            lbl["plane"] = f["plane"]
            lbl["hop"] = str(hop)
            lbl["episode"] = episode
            lbl["fkey"] = f["key"]
            if shift:
                # the clamp is visible, not silent: how far this
                # plane's clock was pushed to honour causality
                lbl["skew_clamp_s"] = f"{shift:.3f}"
            root["labels"] = lbl
            children.append(root)
    wall = frontier - base
    root = {"name": f"episode {episode}", "kind": "fleet",
            "labels": {"episode": episode}, "start": base,
            "dur": wall, "children": children}
    return {"seq": 0, "kept_because": "stitched", "episode": episode,
            "jobs": sorted(set(jobs)), "pending": {},
            "planes": sorted(planes), "hops": hops,
            "segments": {k: round(v, 6) for k, v in segments.items()},
            "wall_s": round(wall, 6), "root": root}


class EpisodeStitcher:
    """The collector: owns local router fragments, the per-region
    pulls, lifecycle synthesis from mirrors, and the durable stitched
    doc in the global store."""

    def __init__(self, cluster, now=None):
        self.cluster = cluster          # GLOBAL store client
        self._local: "OrderedDict[str, OrderedDict[str, dict]]" = \
            OrderedDict()
        self._published: Dict[str, tuple] = {}

    # -- router-side fragments -----------------------------------------

    def add_fragment(self, doc: dict) -> None:
        """A router-plane fragment (admit / requeue / cutover span)
        in ring-doc shape, as built by trace.fragment_doc."""
        episode = doc.get("episode")
        root = doc.get("root")
        if not episode or not trace.is_complete_span(root):
            return
        frags = self._local.setdefault(episode, OrderedDict())
        lbl = root.get("labels", {})
        frag = _fragment(lbl.get("plane", "router"),
                         int(lbl.get("hop", 0) or 0), root,
                         jobs=doc.get("jobs", ()))
        frags[frag["key"]] = frag
        while len(frags) > MAX_FRAGMENTS:
            frags.popitem(last=False)
        self._local.move_to_end(episode)
        while len(self._local) > MAX_EPISODES:
            self._local.popitem(last=False)

    # -- regional pulls ------------------------------------------------

    def _pull_ring(self, name: str, handle, episode: str,
                   default_hop: int) -> List[dict]:
        """This region's /traces fragments for one episode (wire mode
        only — in-process regional planes contribute via mirrors)."""
        request = getattr(handle.client, "_request", None)
        if request is None:
            return []
        try:
            resp = request(
                "GET", f"/traces?episode={episode}&limit={PULL_LIMIT}",
                deadline=2.0)
        except Exception:  # noqa: BLE001 — a dark ring skips a pass
            return []
        out = []
        for doc in (resp or {}).get("traces", ()):
            root = doc.get("root")
            if not trace.is_complete_span(root):
                continue
            lbl = root.get("labels", {})
            plane = lbl.get("plane") or f"region-{name}"
            if plane == "controllers":
                plane = f"controllers-{name}"
            try:
                hop = int(lbl.get("hop", default_hop) or default_hop)
            except (TypeError, ValueError):
                hop = default_hop
            out.append(_fragment(plane, hop, root,
                                 jobs=doc.get("jobs", ())))
        return out

    def _lifecycle(self, name: str, handle, episode: str
                   ) -> List[dict]:
        """Synthesized per-hop lifecycle fragment from the phase
        stamps visible in the region's mirror — the mirror-fed leg of
        the stitch (covers destination placement + resume even when
        the regional ring rotated the session away)."""
        try:
            rc = handle.mirror.read_checked(max_age_s=float("inf"))
        except Exception:  # noqa: BLE001 — no mirror, no lifecycle
            return []
        out = []
        for pg in list(getattr(rc, "podgroups", {}).values()):
            if fedapi.episode_of(pg) != episode:
                continue
            hop = fedapi.episode_hop(pg)
            stamps: Dict[str, float] = {}
            for phase in trace.PHASES:
                ts = trace.phase_ts(pg.annotations, phase)
                if ts is not None:
                    stamps[phase] = ts
            ns, _, pgname = pg.key.partition("/")
            for pod in list(getattr(rc, "pods", {}).values()):
                if fedapi.episode_of(pod) != episode or \
                        pod.namespace != ns:
                    continue
                for phase in trace.PHASES:
                    ts = trace.phase_ts(pod.annotations, phase)
                    if ts is None:
                        continue
                    cur = stamps.get(phase)
                    stamps[phase] = ts if cur is None \
                        else min(cur, ts)
            if not stamps:
                continue
            start = min(stamps.values())
            end = max(stamps.values())
            children = []
            prev = start
            for phase in trace.PHASES:
                ts = stamps.get(phase)
                if ts is None:
                    continue
                # the telescoping rule, verbatim from phase_segments
                children.append((phase, prev, max(prev, ts)))
                prev = max(prev, ts)
            doc = trace.fragment_doc(
                f"lifecycle {pg.key}", f"region-{name}", episode,
                start, end, hop=hop, jobs=(pg.key,),
                children=children)
            out.append(_fragment(f"region-{name}", hop, doc["root"],
                                 jobs=(pg.key,)))
        return out

    def _recover(self, episode: str) -> List[dict]:
        """Fragments of the previously stitched tree in the global
        store — the failover-adoption leg (a promoted standby merges
        the deposed holder's work instead of re-deriving what it can
        and losing what it cannot)."""
        prior = getattr(self.cluster, "fleet_traces", {}).get(episode)
        if not isinstance(prior, dict):
            return []
        out = []
        for child in prior.get("root", {}).get("children", ()):
            lbl = child.get("labels", {})
            try:
                hop = int(lbl.get("hop", 0) or 0)
            except (TypeError, ValueError):
                hop = 0
            out.append(_fragment(lbl.get("plane", "?"), hop, child,
                                 jobs=prior.get("jobs", ())))
        return out

    # -- the pass ------------------------------------------------------

    def collect(self, handles: dict, now: float) -> int:
        """One leaseholder pass: stitch every in-flight episode whose
        fragments changed.  Returns the number of stitched writes."""
        jobs = [j for j in
                list(getattr(self.cluster, "vcjobs", {}).values())
                if fedapi.episode_of(j)]
        # newest episodes first; bounded work per pass
        jobs.sort(key=lambda j: -(float(j.annotations.get(
            fedapi.FED_EPISODE_TS_ANNOTATION, 0) or 0)))
        wrote = 0
        for job in jobs[:MAX_EPISODES_PER_PASS]:
            episode = fedapi.episode_of(job)
            try:
                if self._stitch_one(job, episode, handles, now):
                    wrote += 1
            except Exception:  # noqa: BLE001 — advisory telemetry
                log.exception("stitch failed for episode %s", episode)
        return wrote

    def _stitch_one(self, job, episode: str, handles: dict,
                    now: float) -> bool:
        merged: Dict[str, dict] = {}

        def fold(frags):
            for f in frags:
                cur = merged.get(f["key"])
                if cur is None or \
                        f["root"].get("dur", 0.0) >= \
                        cur["root"].get("dur", 0.0):
                    merged[f["key"]] = f

        fold(self._recover(episode))
        fold(self._local.get(episode, {}).values())
        default_hop = fedapi.episode_hop(job)
        for name, h in handles.items():
            fold(self._pull_ring(name, h, episode, default_hop))
            fold(self._lifecycle(name, h, episode))
        try:
            t0 = float(job.annotations.get(
                fedapi.FED_EPISODE_TS_ANNOTATION, 0) or 0) or None
        except (TypeError, ValueError):
            t0 = None
        job_keys = {job.key}
        for f in merged.values():
            job_keys.update(f.get("jobs") or ())
        doc = stitch(episode, list(merged.values()), t0=t0,
                     jobs=job_keys)
        if doc is None:
            return False
        fp = (len(merged), doc["wall_s"])
        if self._published.get(episode) == fp:
            return False
        self.cluster.put_object("fleet_trace", doc, key=episode)
        self._published[episode] = fp
        while len(self._published) > MAX_EPISODES:
            self._published.pop(next(iter(self._published)))
        metrics.inc("federation_stitched_traces_total")
        return True
