"""Feature gates.

Reference parity: pkg/features/volcano_features.go (k8s component-base
featuregate).  A process-wide mutable registry of named boolean gates
with defaults; configured from a ``--feature-gates A=true,B=false``
style string or programmatically.  Components consult `enabled(name)`
where the reference checks `utilfeature.DefaultFeatureGate.Enabled`.

TPU-native gate set: the reference's GPU/CSI-specific gates map onto
their TPU/standalone analogues; gates keep the reference names where
the concept carries over so operators find familiar switches.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

# name -> (default, description)
_DEFINITIONS: Dict[str, tuple] = {
    # reference gates that carry over directly
    "WorkLoadSupport": (True, "reconcile bare workload pods into "
                              "podgroups (podgroup controller)"),
    "VolcanoJobSupport": (True, "vcjob controller + lifecycle policies"),
    "PodDisruptionBudgetsSupport": (True, "pdb plugin vetoes evictions"),
    "QueueCommandSync": (True, "queue open/close via command bus"),
    "PriorityClass": (True, "priority-class ordering and preemption"),
    "ResourceTopology": (True, "numaaware NUMA topology scheduling"),
    "CronVolcanoJobSupport": (True, "cronjob controller"),
    "SchedulingGatesQueueAdmission": (False, "create pods gated until "
                                            "their queue admits"),
    # TPU-native gates (CSIStorage analogue + new surface)
    "VolumeBinding": (True, "zone-affine PV/PVC binding plugin "
                            "(CSIStorage analogue)"),
    "TPUDeviceAtomicity": (True, "whole-host chip atomicity on "
                                 "multi-host slices"),
    "IncrementalSnapshot": (True, "dirty-tracked snapshot reuse "
                                  "between cycles (16k-host headroom); "
                                  "false = full rebuild every cycle"),
    # DRA feature-gate surface (reference predicates.go:154-162)
    "DRADeviceTaints": (True, "devices may carry taints; claims need "
                              "matching tolerations"),
    "DRAPrioritizedList": (True, "claims may list device classes in "
                                 "preference order (firstAvailable)"),
    "DRAAdminAccess": (False, "admin claims attach to owned devices "
                              "without consuming capacity"),
}

_lock = threading.Lock()
_overrides: Dict[str, bool] = {}


class UnknownFeatureError(ValueError):
    pass


def enabled(name: str) -> bool:
    """Is the gate on?  Unknown names raise (matching featuregate)."""
    with _lock:
        if name in _overrides:
            return _overrides[name]
    try:
        return _DEFINITIONS[name][0]
    except KeyError:
        raise UnknownFeatureError(f"unknown feature gate {name!r}") \
            from None


def set_gate(name: str, value: bool) -> None:
    if name not in _DEFINITIONS:
        raise UnknownFeatureError(f"unknown feature gate {name!r}")
    with _lock:
        _overrides[name] = bool(value)


def parse(spec: str) -> None:
    """Apply a 'A=true,B=false' flag string (cmd-line / conf)."""
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise UnknownFeatureError(
                f"feature gate spec {part!r} is not name=bool")
        name, _, raw = part.partition("=")
        raw = raw.strip().lower()
        if raw not in ("true", "false"):
            raise UnknownFeatureError(
                f"feature gate {name!r}: value {raw!r} is not true/false")
        set_gate(name.strip(), raw == "true")


def reset(name: Optional[str] = None) -> None:
    """Drop overrides (tests)."""
    with _lock:
        if name is None:
            _overrides.clear()
        else:
            _overrides.pop(name, None)


def known() -> Dict[str, bool]:
    """Current effective values for every defined gate."""
    with _lock:
        return {n: _overrides.get(n, d[0])
                for n, d in sorted(_DEFINITIONS.items())}
