"""Topology-subtree partition plan shared by both sharded planes.

One deterministic function of (node name -> subtree key, N) drives
every consumer of the partition:

  * scheduler shards restrict their candidate nodes to the subtrees
    they own (actions/allocate.py `shard-mode: subtree`);
  * the keyspace-partitioned client routes node/pod writes to the
    leader group owning the subtree (cache/partitioned.py);
  * bench / chaos planes seed each leader group's store with exactly
    its owned nodes, and vtpctl renders the ownership table.

Because all of them recompute the plan from the same inputs, there is
no shard-map object to replicate or to go stale: two processes with
the same node set and the same shard count agree on ownership without
coordination.  The partition key is the node's topology subtree (its
TPU slice / tier-1 hypernode), never a bare hash of the node name —
a gang placed ICI-compact lands inside one subtree, so keeping whole
subtrees on one shard keeps gang placement (and its write batch)
single-owner in the common case (Tesserae-style ownership; cross-
subtree gangs go through optimistic arbitration instead).

Assignment is greedy least-loaded over subtrees sorted by name: stable
under iteration order, balanced to within one subtree's host count.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence

from volcano_tpu.api.types import TPU_SLICE_LABEL

# nodes outside any slice (CPU-only hosts) share one pseudo-subtree
FLAT_SUBTREE = "_flat"


def subtree_of(labels: Optional[Dict[str, str]]) -> str:
    """Partition key for one node: its TPU slice label (= tier-1
    hypernode in label discovery), or the flat pseudo-subtree."""
    if labels:
        slice_name = labels.get(TPU_SLICE_LABEL)
        if slice_name:
            return slice_name
    return FLAT_SUBTREE


def subtree_map(nodes: Iterable) -> Dict[str, str]:
    """node name -> subtree key for any iterable of Node/NodeInfo-like
    objects (anything with .name and .labels)."""
    return {n.name: subtree_of(getattr(n, "labels", None)) for n in nodes}


def plan_partition(node_subtrees: Dict[str, str], n_shards: int
                   ) -> List[Dict[str, object]]:
    """Deterministic subtree -> shard assignment.

    Returns one row per shard: {"shard": i, "subtrees": [names...],
    "nodes": [node names...], "hosts": count}.  Subtrees are assigned
    whole (never split) to the least-loaded shard in sorted-name
    order, so any two processes that agree on the node set and N
    agree on the whole plan.
    """
    if n_shards < 1:
        raise ValueError(f"n_shards must be >= 1 (got {n_shards})")
    by_subtree: Dict[str, List[str]] = {}
    for name in sorted(node_subtrees):
        by_subtree.setdefault(node_subtrees[name], []).append(name)
    shards: List[Dict[str, object]] = [
        {"shard": i, "subtrees": [], "nodes": [], "hosts": 0}
        for i in range(n_shards)]
    for subtree in sorted(by_subtree):
        hosts = by_subtree[subtree]
        # least-loaded, ties to the lowest index: deterministic
        target = min(shards, key=lambda s: (s["hosts"], s["shard"]))
        target["subtrees"].append(subtree)
        target["nodes"].extend(hosts)
        target["hosts"] += len(hosts)
    return shards


def owned_nodes(node_subtrees: Dict[str, str], n_shards: int,
                shard_index: int) -> set:
    """The node-name set shard *shard_index* owns under the plan."""
    if not 0 <= shard_index < n_shards:
        raise ValueError(
            f"shard_index {shard_index} out of range for {n_shards}")
    return set(plan_partition(node_subtrees, n_shards)
               [shard_index]["nodes"])


def owner_index(node_subtrees: Dict[str, str], n_shards: int
                ) -> Dict[str, int]:
    """node name -> owning shard index (the write-routing table)."""
    out: Dict[str, int] = {}
    for row in plan_partition(node_subtrees, n_shards):
        for name in row["nodes"]:
            out[name] = row["shard"]
    return out


def home_shard(job_key: str, n_shards: int) -> int:
    """Which scheduler shard drives a job's placement.  Stable string
    hash (not hash(): randomized per process) so every shard agrees
    which one of them owns a pending gang; the others leave it alone
    and only the server's check-and-bind arbitrates the optimistic
    spill cases."""
    acc = 0
    for ch in job_key:
        acc = (acc * 131 + ord(ch)) & 0x7FFFFFFF
    return acc % max(1, n_shards)


def split_by_owner(items: Sequence, node_of, node_subtrees: Dict[str, str],
                   n_shards: int) -> Dict[int, list]:
    """Group *items* by the shard owning node_of(item) (unknown nodes
    go to shard 0, the meta group)."""
    owners = owner_index(node_subtrees, n_shards)
    out: Dict[int, list] = {}
    for item in items:
        out.setdefault(owners.get(node_of(item), 0), []).append(item)
    return out
