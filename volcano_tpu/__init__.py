"""volcano-tpu: a TPU-native batch scheduling framework.

A ground-up rebuild of the capabilities of volcano-sh/volcano (gang
scheduling, queue fair-share, topology-aware placement, job lifecycle
controllers, admission, CLI, node agent) designed TPU-first:

- TPU slices are atomic ICI-mesh resources (``google.com/tpu`` chips),
  not shareable GPU fractions.
- Network topology is the ICI x/y/z mesh + DCN tiers, scored by ICI hop
  distance rather than NCCL ring/tree distance.
- Job plugins bootstrap JAX/XLA workloads (``TPU_WORKER_ID``,
  ``TPU_WORKER_HOSTNAMES``, ``coordinator_address``) instead of
  ``MASTER_ADDR``/``NCCL_*``.
- The validation workload layer (``volcano_tpu.workloads``) is pure
  JAX/pjit/pallas: sharded training steps over a ``jax.sharding.Mesh``.

Layer map (mirrors SURVEY.md §1 for the reference):
  api/          object model: Resource, JobInfo, NodeInfo, QueueInfo, ...
  cache/        cluster cache + snapshot + bind/evict queues
  framework/    Session, Statement, plugin registry
  actions/      enqueue, allocate, elastic, backfill, preempt,
                reclaim, gang*
  plugins/      gang, drf, proportion, capacity, predicates, topology, ...
  controllers/  job, podgroup, queue, jobflow, cronjob, hypernode, ...
  webhooks/     admission validate/mutate
  workloads/    JAX training stack scheduled by the framework
  cli/          vtpctl
  agent/        node agent (chip inventory, oversubscription)
"""

__version__ = "0.1.0"

# Opt-in runtime lock-order auditing (analysis/lockaudit.py): arming
# must happen here — before ANY repo module creates a lock — so the
# chaos conductor's --lock-audit child processes and audited test
# runs wrap every threading.Lock/RLock/Condition site in the package.
import os as _os

if _os.environ.get("VTP_LOCK_AUDIT"):
    from volcano_tpu.analysis import lockaudit as _lockaudit
    _lockaudit.install_from_env()

# Opt-in runtime snapshot-freeze/data-race auditing (the `-race`
# analog, analysis/freezeaudit.py): armed here so every process in a
# chaos conductor --race-audit plane freezes its scheduler sessions
# and reports to VTP_RACE_AUDIT_OUT.
if _os.environ.get("VTP_RACE_AUDIT"):
    from volcano_tpu.analysis import freezeaudit as _freezeaudit
    _freezeaudit.install_from_env()
