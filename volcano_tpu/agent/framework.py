"""Agent event framework — probe -> event queue -> registered handlers.

Reference parity: pkg/agent/events/framework/factory.go (probes feed
typed event queues consumed by registered handlers) +
pkg/agent/events/handlers/registry.go (handlers self-register; the
agent loop dispatches, it does not enumerate).  VERDICT r4 missing #1:
the rebuild's agent was one hand-written sync loop — adding a handler
meant editing it.  Now a handler is a class with an `events`
subscription tuple registered via @register_handler; the NodeAgent
builds the default pipeline from the registry and dispatches every
sync's events through it in registration order.

Event flow per sync:

    UsageProbe  -> Event(USAGE,  node, usage)        (sample)
    PodProbe    -> Event(PODS,   node, usage, pods)  (population)
                -> Event(PRESSURE, ...)              (threshold cross)

Handlers subscribed to PODS fill a shared PodQoSDecision set (cpu
knobs from one handler, memory knobs from another) which the
enforcement handler applies once — so knob families compose without
the handlers knowing about each other.
"""

from __future__ import annotations

import abc
import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Type

log = logging.getLogger(__name__)

# event types (reference: NodeResourcesEvent / PodLifeCycleEvent /
# NodeMonitorEvent families)
EVENT_USAGE = "NodeUsage"          # a fresh usage sample exists
EVENT_PODS = "PodPopulation"       # this node's running pods scanned
EVENT_PRESSURE = "NodePressure"    # usage crossed the eviction line


@dataclass
class Event:
    """One unit of work on the agent's queue."""

    type: str
    node: object = None
    usage: object = None
    pods: List = field(default_factory=list)
    # uid -> PodQoSDecision, built up by QoS handlers subscribed to
    # EVENT_PODS and applied by the enforcement handler
    decisions: Dict[str, object] = field(default_factory=dict)
    # the queue this event is draining from — set by the agent at
    # dispatch so handlers can push follow-up events
    queue: Optional["EventQueue"] = None


class EventQueue:
    """FIFO per sync cycle.  Handlers may push follow-up events via
    event.queue (processed in the same drain), mirroring the
    reference's workqueue feeding."""

    def __init__(self):
        self._items: List[Event] = []

    def push(self, event: Event) -> None:
        self._items.append(event)

    def drain(self):
        while self._items:
            yield self._items.pop(0)


class Handler(abc.ABC):
    """One concern of the agent (reference: one handler package under
    pkg/agent/events/handlers/).  Instantiated per NodeAgent with the
    agent as context (config, cluster, enforcer access)."""

    name: str = ""
    events: tuple = ()              # event types this handler consumes

    def __init__(self, agent):
        self.agent = agent

    @abc.abstractmethod
    def handle(self, event: Event) -> None: ...


_REGISTRY: List[Type[Handler]] = []


def register_handler(cls: Type[Handler]) -> Type[Handler]:
    """Class decorator: adds the handler to the default pipeline.
    Registration order IS dispatch order (decision producers before
    the enforcement applier; see handlers.py)."""
    _REGISTRY.append(cls)
    return cls


def registered_handlers() -> List[Type[Handler]]:
    return list(_REGISTRY)


class Probe(abc.ABC):
    """Event source (reference: framework probes).  The agent samples
    the usage provider ONCE per sync (the provider is the sampler;
    two probes polling independently would tear the sample) and hands
    every probe the same (node, usage) snapshot to turn into events."""

    @abc.abstractmethod
    def probe(self, agent, queue: EventQueue, node, usage) -> None: ...


class UsageProbe(Probe):
    """EVENT_USAGE: a fresh sample exists."""

    def probe(self, agent, queue: EventQueue, node, usage) -> None:
        queue.push(Event(EVENT_USAGE, node=node, usage=usage))


class PodProbe(Probe):
    """EVENT_PODS from this node's running-pod scan, plus
    EVENT_PRESSURE when usage crosses the eviction threshold."""

    def probe(self, agent, queue: EventQueue, node, usage) -> None:
        pods = agent.running_pods()
        queue.push(Event(EVENT_PODS, node=node, usage=usage, pods=pods))
        if max(usage.cpu_fraction, usage.memory_fraction) >= \
                agent.eviction_threshold:
            queue.push(Event(EVENT_PRESSURE, node=node, usage=usage,
                             pods=pods))
