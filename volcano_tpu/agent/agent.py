"""Node agent — per-node colocation/QoS daemon.

Reference parity: pkg/agent (event-driven DaemonSet agent: probes feed
handlers for oversubscription, eviction, resource reporting) +
pkg/metriccollect.  TPU-first: the agent reports google.com/tpu chip
inventory and health instead of nvidia.com/gpu (SURVEY.md §2.8), and
its oversubscription/eviction math runs on usage fractions published as
node annotations (consumed by the usage plugin and the scheduler's
oversubscription resource).
"""

from __future__ import annotations

import abc
import logging
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from volcano_tpu.api.resource import TPU, Resource
from volcano_tpu.api.types import TaskStatus

log = logging.getLogger(__name__)

CPU_USAGE_ANNOTATION = "usage.volcano-tpu.io/cpu"
MEM_USAGE_ANNOTATION = "usage.volcano-tpu.io/memory"
from volcano_tpu.api.types import OVERSUBSCRIPTION_CPU_ANNOTATION
OVERSUB_ANNOTATION = OVERSUBSCRIPTION_CPU_ANNOTATION
TPU_HEALTHY_LABEL = "volcano-tpu.io/tpu-healthy"
AGENT_CORDONED_ANNOTATION = "volcano-tpu.io/cordoned-by-agent"
TPU_CHIPS_ANNOTATION = "volcano-tpu.io/tpu-chips"

# cpu QoS outputs (cgroup enforcer inputs)
CPU_BURST_ANNOTATION = "qos.volcano-tpu.io/cpu-burst-millis"
CPU_THROTTLE_ANNOTATION = "qos.volcano-tpu.io/cpu-throttled"

# DCN egress shaping (CNI/kernel enforcer inputs; the TPU reading of
# the reference's eBPF/tc online/offline bandwidth split)
DCN_BANDWIDTH_ANNOTATION = "networkqos.volcano-tpu.io/dcn-mbps"
DCN_OFFLINE_LIMIT_ANNOTATION = "networkqos.volcano-tpu.io/offline-limit-mbps"
DCN_ONLINE_GUARANTEE_ANNOTATION = \
    "networkqos.volcano-tpu.io/online-guarantee-mbps"
DCN_POD_LIMIT_ANNOTATION = "networkqos.volcano-tpu.io/pod-limit-mbps"
DEFAULT_DCN_MBPS = 100_000  # 100 Gbps per host default

from volcano_tpu.api.types import QOS_BEST_EFFORT, QOS_LEVEL_ANNOTATION

# annotation marking pods the agent may evict under pressure
PREEMPTABLE_QOS_ANNOTATION = QOS_LEVEL_ANNOTATION


@dataclass
class NodeUsage:
    cpu_fraction: float = 0.0
    memory_fraction: float = 0.0
    tpu_chips_detected: int = 0
    tpu_chips_healthy: int = 0


class UsageProvider(abc.ABC):
    """Where real usage comes from (cgroups/TPU runtime in production;
    injected values in tests — mirrors metriccollect/local)."""

    @abc.abstractmethod
    def usage(self, node_name: str) -> NodeUsage: ...


class FakeUsageProvider(UsageProvider):
    def __init__(self):
        self.values: Dict[str, NodeUsage] = {}

    def set(self, node_name: str, **kwargs):
        self.values[node_name] = NodeUsage(**kwargs)

    def usage(self, node_name: str) -> NodeUsage:
        return self.values.get(node_name, NodeUsage())


class NodeAgent:
    """One agent instance manages one node."""

    def __init__(self, cluster, node_name: str,
                 provider: Optional[UsageProvider] = None,
                 oversub_factor: float = 0.6,
                 eviction_threshold: float = 0.95,
                 enforcer=None):
        from volcano_tpu.agent.enforcer import NullEnforcer
        self.cluster = cluster
        self.node_name = node_name
        self.provider = provider or FakeUsageProvider()
        self.oversub_factor = oversub_factor
        self.eviction_threshold = eviction_threshold
        # kernel-facing half: cgroup/tc mutations driven from the
        # decisions below (enforcer.py; default publishes only)
        self.enforcer = enforcer if enforcer is not None \
            else NullEnforcer()
        # seed from the enforcer's leftover state so pods that left
        # the node while the agent was DOWN are reverted on the first
        # sync (stale cgroup dirs / tc classes must not survive a
        # restart — ADVICE r3)
        self._enforced_uids: set = set(self.enforcer.enforced_uids())
        self.last_sync: float = 0.0          # health-check freshness

    def serve_health(self, port: int = 0, stale_after: float = 30.0):
        """Expose /healthz (reference pkg/agent/healthcheck): 200 with
        {healthy, node, last_sync_age_s} while the agent syncs, 503
        once the last sync is older than *stale_after* seconds (size
        this to ~3x the daemon's sync period) or never happened.
        Returns the server; port 0 picks a free one."""
        import http.server
        import json as _json
        import threading

        agent = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - stdlib API
                if self.path != "/healthz":
                    self.send_response(404)
                    self.end_headers()
                    return
                age = (time.time() - agent.last_sync
                       if agent.last_sync else None)
                healthy = age is not None and age < stale_after
                body = _json.dumps({
                    "healthy": healthy, "node": agent.node_name,
                    "last_sync_age_s": (round(age, 3)
                                        if age is not None else None),
                }).encode()
                self.send_response(200 if healthy else 503)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # quiet
                pass

        server = http.server.ThreadingHTTPServer(("127.0.0.1", port),
                                                 Handler)
        threading.Thread(target=server.serve_forever,
                         daemon=True).start()
        return server

    # -- one reporting cycle ------------------------------------------

    def sync(self) -> None:
        self.last_sync = time.time()
        node = self.cluster.nodes.get(self.node_name)
        if node is None:
            return
        usage = self.provider.usage(self.node_name)
        # remember pre-handler state so only REAL changes are persisted
        # (a wire-backed cluster must see the kubelet-side patches, but
        # an unchanged node must not generate watch traffic every sync)
        node_before = (dict(node.annotations), dict(node.labels),
                       node.unschedulable)
        # capture the pod population ONCE: handlers and the persist
        # diff below must operate on the same objects (the mirror can
        # swap instances under us between scans in wire mode)
        pods = self._running_pods()
        pods_before = {p.key: dict(p.annotations) for p in pods}
        self._report_usage(node, usage)
        self._report_tpu_health(node, usage)
        self._report_oversubscription(node, usage)
        self._apply_cpu_qos(node, usage, pods)
        self._apply_network_qos(node, usage, pods)
        # revert enforcement for pods that left the node (completed,
        # evicted, deleted): decision -> OS mutation -> revert is one
        # observable loop
        current_uids = {p.uid for p in pods}
        for uid in self._enforced_uids - current_uids:
            self.enforcer.remove_pod(uid)
        self._enforced_uids = current_uids
        self._refresh_numatopology(pods)
        if max(usage.cpu_fraction, usage.memory_fraction) >= \
                self.eviction_threshold:
            self._evict_best_effort(node, pods)
        if (dict(node.annotations), dict(node.labels),
                node.unschedulable) != node_before:
            self._persist_node(node, node_before)
        for p in pods:
            if p.annotations != pods_before.get(p.key):
                self._persist_pod(p, pods_before[p.key])

    def _persist_node(self, node, before) -> None:
        """Read-modify-write: if the mirror swapped the node instance
        mid-sync (wire mode: a concurrent admin cordon/label write),
        apply only OUR deltas onto the freshest copy — never push a
        stale whole object over someone else's change."""
        cur = self.cluster.nodes.get(self.node_name)
        if cur is None:
            return
        if cur is not node:
            ann_before, labels_before, unsched_before = before
            for k, v in node.annotations.items():
                if ann_before.get(k) != v:
                    cur.annotations[k] = v
            for k in set(ann_before) - set(node.annotations):
                cur.annotations.pop(k, None)
            for k, v in node.labels.items():
                if labels_before.get(k) != v:
                    cur.labels[k] = v
            if node.unschedulable != unsched_before:
                # only OUR cordon/uncordon is a delta; otherwise keep
                # the freshest value (e.g. a concurrent admin cordon)
                cur.unschedulable = node.unschedulable
        self.cluster.put_object("node", cur)

    def _persist_pod(self, pod, ann_before) -> None:
        """Same discipline for pods: a pod completed/evicted mid-sync
        must keep its new phase — only the agent-owned QoS annotations
        are merged onto the current instance."""
        cur = self.cluster.pods.get(pod.key)
        if cur is None:
            return   # deleted mid-sync: nothing to annotate
        if cur is not pod:
            for k, v in pod.annotations.items():
                if ann_before.get(k) != v:
                    cur.annotations[k] = v
            for k in set(ann_before) - set(pod.annotations):
                cur.annotations.pop(k, None)
        self.cluster.put_object("pod", cur)

    def _running_pods(self) -> List:
        """Pods RUNNING on this agent's node — the population every
        QoS/eviction handler operates on."""
        return [p for p in self.cluster.pods.values()
                if p.node_name == self.node_name
                and p.phase is TaskStatus.RUNNING]

    def _allocatable(self, node) -> Resource:
        return Resource.from_resource_list(node.allocatable)

    def _report_usage(self, node, usage: NodeUsage) -> None:
        node.annotations[CPU_USAGE_ANNOTATION] = f"{usage.cpu_fraction:.3f}"
        node.annotations[MEM_USAGE_ANNOTATION] = \
            f"{usage.memory_fraction:.3f}"

    def _report_tpu_health(self, node, usage: NodeUsage) -> None:
        declared = self._allocatable(node).get(TPU)
        if usage.tpu_chips_detected == 0:
            # no chip telemetry from this provider (e.g. a usage-only
            # Prometheus source): never cordon on absence of data
            return
        node.annotations[TPU_CHIPS_ANNOTATION] = \
            f"{usage.tpu_chips_healthy}/{usage.tpu_chips_detected}"
        healthy = (usage.tpu_chips_healthy >= declared > 0) or \
            (declared == 0 and usage.tpu_chips_detected ==
             usage.tpu_chips_healthy)
        node.labels[TPU_HEALTHY_LABEL] = "true" if healthy else "false"
        if not healthy:
            # a slice host with sick chips must not take new work:
            # the whole ICI mesh is only as healthy as its worst host
            node.unschedulable = True
            node.annotations[AGENT_CORDONED_ANNOTATION] = "true"
            self.cluster.record_event(
                self.node_name, "TPUUnhealthy",
                f"{usage.tpu_chips_healthy}/{usage.tpu_chips_detected} "
                f"chips healthy (declared {declared:g})")
        elif node.unschedulable and \
                node.annotations.get(AGENT_CORDONED_ANNOTATION) == "true":
            # only undo OUR cordon — never an admin's maintenance cordon
            node.unschedulable = False
            node.annotations.pop(AGENT_CORDONED_ANNOTATION, None)

    def _report_oversubscription(self, node, usage: NodeUsage) -> None:
        """Publish reclaimable millicores in 10% steps
        (pkg/agent/oversubscription/policy/policy.go:40-61)."""
        alloc = self._allocatable(node)
        idle_frac = max(0.0, 1.0 - usage.cpu_fraction)
        stepped = int(idle_frac * 10) / 10.0   # 10% quantization
        reclaimable = alloc.milli_cpu * stepped * self.oversub_factor
        node.annotations[OVERSUB_ANNOTATION] = str(int(reclaimable))

    def _apply_cpu_qos(self, node, usage: NodeUsage, pods) -> None:
        """cpuburst/cputhrottle handlers (reference: pkg/agent/events/
        handlers/{cpuburst,cputhrottle}) — control-plane half: compute
        per-pod burst quota / throttle decisions from real usage and
        publish them as pod annotations; a kubelet-side enforcer would
        program cgroup cpu.cfs_burst_us / cfs_quota_us from these."""
        from volcano_tpu.agent.enforcer import PodQoSDecision
        idle_frac = max(0.0, 1.0 - usage.cpu_fraction)
        node_idle_m = self._allocatable(node).milli_cpu * idle_frac
        throttled = usage.cpu_fraction > self.eviction_threshold * 0.9
        for pod in pods:
            qos = pod.annotations.get(PREEMPTABLE_QOS_ANNOTATION)
            request = pod.resource_requests()
            request_m = request.milli_cpu
            if qos == QOS_BEST_EFFORT:
                # BE pods burst into the node's measured idle (requests
                # are often 0 for true best-effort — the reference sizes
                # from allocatable idle, not requests); under pressure
                # the burst is zeroed, matching the throttle flag
                burst = 0 if throttled else int(node_idle_m)
                pod.annotations[CPU_BURST_ANNOTATION] = str(burst)
                pod.annotations[CPU_THROTTLE_ANNOTATION] = (
                    "true" if throttled else "false")
                # memory.high soft cap for BE pods with a request
                # (reference memoryqos handler)
                mem = int(request.memory) or None
                self.enforcer.apply_pod_qos(PodQoSDecision(
                    pod.key, pod.uid, burst, throttled, int(request_m),
                    memory_high_bytes=mem))
            else:
                # guaranteed pods: fixed burst headroom, never throttled
                burst = int(request_m * 0.2)
                pod.annotations[CPU_BURST_ANNOTATION] = str(burst)
                pod.annotations.pop(CPU_THROTTLE_ANNOTATION, None)
                self.enforcer.apply_pod_qos(PodQoSDecision(
                    pod.key, pod.uid, burst, False, int(request_m)))

    def _apply_network_qos(self, node, usage: NodeUsage, pods) -> None:
        """networkqos handler (reference: pkg/networkqos — clsact qdisc
        + eBPF maps shaping online/offline DCN bandwidth) — control-
        plane half: split the node's DCN egress budget between online
        (guaranteed) and offline (BE) pods and publish the split; the
        CNI/kernel enforcer consumes these annotations."""
        try:
            total_mbps = float(node.annotations.get(
                DCN_BANDWIDTH_ANNOTATION, DEFAULT_DCN_MBPS))
        except (TypeError, ValueError):
            # a malformed operator annotation must never kill the sync
            # cycle (the eviction check runs after this handler)
            log.warning("node %s: invalid %s annotation; using default",
                        self.node_name, DCN_BANDWIDTH_ANNOTATION)
            total_mbps = float(DEFAULT_DCN_MBPS)
        be_pods, other_pods = [], []
        for p in pods:
            if p.annotations.get(PREEMPTABLE_QOS_ANNOTATION) == \
                    QOS_BEST_EFFORT:
                be_pods.append(p)
            else:
                other_pods.append(p)
        # offline (BE) traffic is capped at a fraction of the link,
        # shrinking to a floor under online pressure
        offline_share = 0.4 if usage.cpu_fraction < 0.8 else 0.1
        offline_mbps = int(total_mbps * offline_share)
        node.annotations[DCN_OFFLINE_LIMIT_ANNOTATION] = str(offline_mbps)
        node.annotations[DCN_ONLINE_GUARANTEE_ANNOTATION] = \
            str(int(total_mbps - offline_mbps))
        pod_limits = {}
        if be_pods:
            per_pod = offline_mbps // len(be_pods)
            for pod in be_pods:
                pod.annotations[DCN_POD_LIMIT_ANNOTATION] = str(per_pod)
                pod_limits[pod.uid] = per_pod
        for pod in other_pods:
            # a pod promoted out of BE must not keep a stale cap
            pod.annotations.pop(DCN_POD_LIMIT_ANNOTATION, None)
        self.enforcer.apply_network(int(total_mbps - offline_mbps),
                                    offline_mbps, pod_limits)

    def _refresh_numatopology(self, pods) -> None:
        """Exporter half of the Numatopology contract
        (api/numatopology.py): republish per-cell FREE amounts as
        capacity minus the running pods' requests, so the scheduler's
        single-NUMA gate sees placements from earlier cycles."""
        topo = getattr(self.cluster, "numatopologies", {}).get(
            self.node_name)
        if topo is None:
            return
        reqs = []
        for pod in pods:
            r = pod.resource_requests()
            reqs.append((r.milli_cpu, r.get(TPU)))
        before = {res: dict(cells) for res, cells in topo.numa_res.items()}
        topo.recompute_free(reqs)
        if topo.numa_res != before:
            self.cluster.put_object("numatopology", topo)

    def _evict_best_effort(self, node, pods) -> None:
        for pod in pods:
            if pod.annotations.get(PREEMPTABLE_QOS_ANNOTATION) == \
                    QOS_BEST_EFFORT:
                log.info("agent %s: evicting BE pod %s under pressure",
                         self.node_name, pod.key)
                self.cluster.evict_pod(pod.namespace, pod.name,
                                       "node resource pressure")
