"""Node agent — per-node colocation/QoS daemon.

Reference parity: pkg/agent (event-driven DaemonSet agent: probes feed
typed event queues consumed by registered handlers) +
pkg/metriccollect (pluggable collectors).  TPU-first: the agent
reports google.com/tpu chip inventory and health instead of
nvidia.com/gpu (SURVEY.md §2.8), and its oversubscription/eviction
math runs on usage fractions published as node annotations (consumed
by the usage plugin and the scheduler's oversubscription resource).

Structure (VERDICT r4 missing #1): the sync loop owns only probing,
dispatch, and persistence; every concern is a Handler registered in
agent/handlers.py (9 of them, matching the reference's handler
count), and usage comes from a UsageProvider that may be a
CompositeUsageProvider over registered Collectors (agent/collect.py,
the metriccollect analogue).  Adding a concern = registering a
handler class, not editing this loop.
"""

from __future__ import annotations

import abc
import logging
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from volcano_tpu.api.resource import Resource
from volcano_tpu.api.types import TaskStatus

log = logging.getLogger(__name__)

CPU_USAGE_ANNOTATION = "usage.volcano-tpu.io/cpu"
MEM_USAGE_ANNOTATION = "usage.volcano-tpu.io/memory"
from volcano_tpu.api.types import OVERSUBSCRIPTION_CPU_ANNOTATION
OVERSUB_ANNOTATION = OVERSUBSCRIPTION_CPU_ANNOTATION
TPU_HEALTHY_LABEL = "volcano-tpu.io/tpu-healthy"
AGENT_CORDONED_ANNOTATION = "volcano-tpu.io/cordoned-by-agent"
TPU_CHIPS_ANNOTATION = "volcano-tpu.io/tpu-chips"

# cpu QoS outputs (cgroup enforcer inputs)
CPU_BURST_ANNOTATION = "qos.volcano-tpu.io/cpu-burst-millis"
CPU_THROTTLE_ANNOTATION = "qos.volcano-tpu.io/cpu-throttled"

# DCN egress shaping (CNI/kernel enforcer inputs; the TPU reading of
# the reference's eBPF/tc online/offline bandwidth split)
DCN_BANDWIDTH_ANNOTATION = "networkqos.volcano-tpu.io/dcn-mbps"
DCN_OFFLINE_LIMIT_ANNOTATION = "networkqos.volcano-tpu.io/offline-limit-mbps"
DCN_ONLINE_GUARANTEE_ANNOTATION = \
    "networkqos.volcano-tpu.io/online-guarantee-mbps"
DCN_POD_LIMIT_ANNOTATION = "networkqos.volcano-tpu.io/pod-limit-mbps"
DEFAULT_DCN_MBPS = 100_000  # 100 Gbps per host default

# QOS_BEST_EFFORT is a RE-EXPORT: handlers.py imports it from
# here (lazily, inside functions) to avoid a module cycle
from volcano_tpu.api.types import (QOS_BEST_EFFORT,  # noqa: F401
                                   QOS_LEVEL_ANNOTATION)

# annotation marking pods the agent may evict under pressure
PREEMPTABLE_QOS_ANNOTATION = QOS_LEVEL_ANNOTATION


@dataclass
class NodeUsage:
    cpu_fraction: float = 0.0
    memory_fraction: float = 0.0
    tpu_chips_detected: int = 0
    tpu_chips_healthy: int = 0
    # False when no collector produced a cpu sample this cycle: the
    # oversubscription handler must not read absent data as "node
    # fully idle" and fabricate reclaimable capacity
    cpu_sampled: bool = True


class UsageProvider(abc.ABC):
    """Where real usage comes from (cgroups/TPU runtime in production;
    injected values in tests — mirrors metriccollect/local)."""

    @abc.abstractmethod
    def usage(self, node_name: str) -> NodeUsage: ...


class FakeUsageProvider(UsageProvider):
    def __init__(self):
        self.values: Dict[str, NodeUsage] = {}

    def set(self, node_name: str, **kwargs):
        self.values[node_name] = NodeUsage(**kwargs)

    def usage(self, node_name: str) -> NodeUsage:
        return self.values.get(node_name, NodeUsage())


class NodeAgent:
    """One agent instance manages one node."""

    def __init__(self, cluster, node_name: str,
                 provider: Optional[UsageProvider] = None,
                 oversub_factor: float = 0.6,
                 eviction_threshold: float = 0.95,
                 enforcer=None, handlers=None, probes=None,
                 net_collector=None, goodput_collector=None,
                 serving_collector=None):
        from volcano_tpu.agent import handlers as _default  # registers
        from volcano_tpu.agent.enforcer import NullEnforcer
        from volcano_tpu.agent.framework import (
            PodProbe, UsageProbe, registered_handlers)
        self.cluster = cluster
        self.node_name = node_name
        self.provider = provider or FakeUsageProvider()
        self.oversub_factor = oversub_factor
        self.eviction_threshold = eviction_threshold
        # kernel-facing half: cgroup/tc mutations driven from the
        # handlers' decisions (enforcer.py; default publishes only)
        self.enforcer = enforcer if enforcer is not None \
            else NullEnforcer()
        # explicit NetAccountingCollector handle for the netaccounting
        # handler; when None the handler discovers one inside a
        # CompositeUsageProvider's collector list (so 'collectors:
        # local,netaccounting:ROOT' needs no extra wiring)
        self.net_collector = net_collector
        # same contract for the goodput handler's progress collector
        self.goodput_collector = goodput_collector
        # ... and the serving handler's stats collector
        self.serving_collector = serving_collector
        # probe -> queue -> handler pipeline; handlers come from the
        # registry unless injected (tests can run a subset)
        self.probes = list(probes) if probes is not None \
            else [UsageProbe(), PodProbe()]
        handler_classes = handlers if handlers is not None \
            else registered_handlers()
        self.handlers = [cls(self) for cls in handler_classes]
        # seed from the enforcer's leftover state so pods that left
        # the node while the agent was DOWN are reverted on the first
        # sync (stale cgroup dirs / tc classes must not survive a
        # restart — ADVICE r3)
        self._enforced_uids: set = set(self.enforcer.enforced_uids())
        self.last_sync: float = 0.0          # health-check freshness

    def serve_health(self, port: int = 0, stale_after: float = 30.0):
        """Expose /healthz (reference pkg/agent/healthcheck): 200 with
        {healthy, node, last_sync_age_s} while the agent syncs, 503
        once the last sync is older than *stale_after* seconds (size
        this to ~3x the daemon's sync period) or never happened.
        Returns the server; port 0 picks a free one."""
        import http.server
        import json as _json
        import threading

        agent = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - stdlib API
                if self.path != "/healthz":
                    self.send_response(404)
                    self.end_headers()
                    return
                age = (time.time() - agent.last_sync
                       if agent.last_sync else None)
                healthy = age is not None and age < stale_after
                body = _json.dumps({
                    "healthy": healthy, "node": agent.node_name,
                    "last_sync_age_s": (round(age, 3)
                                        if age is not None else None),
                }).encode()
                self.send_response(200 if healthy else 503)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # quiet
                pass

        server = http.server.ThreadingHTTPServer(("127.0.0.1", port),
                                                 Handler)
        threading.Thread(target=server.serve_forever,
                         daemon=True).start()
        return server

    # -- one reporting cycle ------------------------------------------

    def sync(self) -> None:
        from volcano_tpu.agent.framework import EventQueue
        self.last_sync = time.time()
        node = self.cluster.nodes.get(self.node_name)
        if node is None:
            return
        # ONE usage sample per sync — probes share it (two probes
        # polling independently would tear the sample)
        usage = self.provider.usage(self.node_name)
        # remember pre-handler state so only REAL changes are persisted
        # (a wire-backed cluster must see the kubelet-side patches, but
        # an unchanged node must not generate watch traffic every sync)
        node_before = (dict(node.annotations), dict(node.labels),
                       node.unschedulable)
        queue = EventQueue()
        for probe in self.probes:
            probe.probe(self, queue, node, usage)
        # the pods every EVENT_PODS handler and the persist diff below
        # operate on — probes captured the population once (the mirror
        # can swap instances under us between scans in wire mode)
        pods_before: Dict[str, dict] = {}
        seen_pods: Dict[str, object] = {}
        for event in queue.drain():
            event.queue = queue     # handlers may push follow-ups
            for p in event.pods:
                if p.key not in pods_before:
                    pods_before[p.key] = dict(p.annotations)
                    seen_pods[p.key] = p
            for handler in self.handlers:
                if event.type in handler.events:
                    handler.handle(event)
        if (dict(node.annotations), dict(node.labels),
                node.unschedulable) != node_before:
            self._persist_node(node, node_before)
        for key, p in seen_pods.items():
            if p.annotations != pods_before.get(key):
                self._persist_pod(p, pods_before[key])

    def decision_for(self, event, pod):
        """The pod's PodQoSDecision in this sync's decision set,
        created on first use — how the cpu and memory handlers
        compose knobs without knowing about each other."""
        from volcano_tpu.agent.enforcer import PodQoSDecision
        d = event.decisions.get(pod.uid)
        if d is None:
            d = event.decisions[pod.uid] = PodQoSDecision(
                pod.key, pod.uid)
        return d

    def _persist_node(self, node, before) -> None:
        """Read-modify-write: if the mirror swapped the node instance
        mid-sync (wire mode: a concurrent admin cordon/label write),
        apply only OUR deltas onto the freshest copy — never push a
        stale whole object over someone else's change."""
        cur = self.cluster.nodes.get(self.node_name)
        if cur is None:
            return
        if cur is not node:
            ann_before, labels_before, unsched_before = before
            for k, v in node.annotations.items():
                if ann_before.get(k) != v:
                    cur.annotations[k] = v
            for k in set(ann_before) - set(node.annotations):
                cur.annotations.pop(k, None)
            for k, v in node.labels.items():
                if labels_before.get(k) != v:
                    cur.labels[k] = v
            if node.unschedulable != unsched_before:
                # only OUR cordon/uncordon is a delta; otherwise keep
                # the freshest value (e.g. a concurrent admin cordon)
                cur.unschedulable = node.unschedulable
        self.cluster.put_object("node", cur)

    def _persist_pod(self, pod, ann_before) -> None:
        """Same discipline for pods: a pod completed/evicted mid-sync
        must keep its new phase — only the agent-owned QoS annotations
        are merged onto the current instance."""
        cur = self.cluster.pods.get(pod.key)
        if cur is None:
            return   # deleted mid-sync: nothing to annotate
        if cur is not pod:
            for k, v in pod.annotations.items():
                if ann_before.get(k) != v:
                    cur.annotations[k] = v
            for k in set(ann_before) - set(pod.annotations):
                cur.annotations.pop(k, None)
        self.cluster.put_object("pod", cur)

    def running_pods(self) -> List:
        """Pods RUNNING on this agent's node — the population every
        QoS/eviction handler operates on."""
        return [p for p in self.cluster.pods.values()
                if p.node_name == self.node_name
                and p.phase is TaskStatus.RUNNING]

    def allocatable(self, node) -> Resource:
        return Resource.from_resource_list(node.allocatable)
