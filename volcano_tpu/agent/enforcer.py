"""Node-local QoS enforcement — the kernel-facing half of the agent.

The reference agent doesn't stop at publishing decisions: it programs
cgroups (pkg/agent/events/handlers/{cpuburst,cputhrottle,memoryqos},
cgroup-v2 adaptation per docs/design/agent-cgroup-v2-adaptation.md) and
shapes DCN traffic with a clsact qdisc + eBPF maps
(pkg/networkqos/tc/tc_linux.go:48-60, utils/ebpf/map.go:64-79).  This
module is the rebuild's enforcement layer: the NodeAgent computes
decisions (agent.py) and drives an Enforcer that mutates the OS.

Three implementations:
  * RecordingEnforcer — in-memory ledger for tests and dry runs.
  * CgroupV2Enforcer  — real cgroup-v2 file writes (cpu.max,
    cpu.max.burst, memory.high) under a configurable root, so tests
    exercise the REAL write path against a tmpdir root and production
    points it at a volcano-owned subtree (a root without a 'volcano'
    path component is narrowed to {root}/volcano; pod dirs are
    vtp-prefixed — see the class docstring).
  * TcEnforcer        — `tc` HTB program for the online/offline DCN
    split (the portable stand-in for the reference's eBPF maps).
    Commands run through an injectable runner; only a CHANGED program
    is re-executed (tc qdisc/class `replace` keeps it idempotent).

Traffic CLASSIFICATION (not just classes): the reference steers
packets per cgroup into the online/offline split with clsact + eBPF
(tc_linux.go:48-60, utils/ebpf/map.go:64-79).  The portable
equivalent here is the net_cls/cgroup pair:
  * CgroupV2Enforcer writes each offline pod's net_cls.classid so its
    sockets tag packets with 1:<class>;
  * TcEnforcer installs ONE `tc filter ... cgroup` rule on the root
    qdisc — the kernel's cgroup classifier reads the net_cls tag and
    delivers the packet to the matching HTB class.
Without both halves every packet lands in the default online class
and the offline caps are inert (VERDICT r3 missing #1).  Class minor
ids are handed out by a shared OfflineClassAllocator so the classid
the cgroup half writes is the class the tc half created.

The agent applies decisions every sync and removes enforcement for
pods that left the node — decision, OS mutation, and revert are all
observable (VERDICT r2 item 4).  enforced_uids() lets a restarting
agent reconcile away state left behind for pods that departed while
it was down.
"""

from __future__ import annotations

import abc
import heapq
import logging
import os
import shutil
import subprocess
from typing import Dict, List, Optional, Tuple

log = logging.getLogger(__name__)

CPU_PERIOD_US = 100_000      # cgroup-v2 default cpu.max period

TC_MAJOR = 1                 # HTB qdisc handle major (1:)
FIRST_POD_CLASS = 21         # 1:10 online, 1:20 offline, 1:21+ pods


class OfflineClassAllocator:
    """uid -> HTB minor class id, shared between the cgroup half
    (which writes the classid into net_cls.classid) and the tc half
    (which creates class 1:<id> and deletes it on pod removal).  One
    allocator per node/interface — build_enforcer wires the same
    instance into both enforcers."""

    def __init__(self):
        self._uid_class: Dict[str, int] = {}
        self._next = FIRST_POD_CLASS
        # released minors, reused lowest-first before bumping _next —
        # a long-lived agent must not walk off the 16-bit minor space
        self._free: List[int] = []

    def classid(self, uid: str) -> int:
        cls = self._uid_class.get(uid)
        if cls is None:
            if self._free:
                cls = heapq.heappop(self._free)
            else:
                if self._next > 0xFFFF:
                    raise RuntimeError(
                        "HTB minor space exhausted: >65k concurrent "
                        "offline pods on one interface")
                cls = self._next
                self._next += 1
            self._uid_class[uid] = cls
        return cls

    def release(self, uid: str) -> Optional[int]:
        cls = self._uid_class.pop(uid, None)
        if cls is not None:
            heapq.heappush(self._free, cls)
        return cls

    def peek(self, uid: str) -> Optional[int]:
        return self._uid_class.get(uid)

    def uids(self):
        return set(self._uid_class)


def net_cls_value(minor: int) -> str:
    """net_cls.classid file format: 0xMMMMmmmm (hex major:minor)."""
    return f"0x{(TC_MAJOR << 16) | minor:08x}"


class PodQoSDecision:
    """One pod's computed QoS knobs, filled incrementally by the
    agent's handler pipeline (cpu knobs by cpuqos, memory knobs by
    memoryqosv2) and applied once by the enforcement handler.

    Memory knob semantics (cgroup-v2; reference memoryqosv2 handler):
      memory.min  — kernel-guaranteed, never reclaimed (online pods)
      memory.low  — reclaim-protected while the node has slack
      memory.high — allocation-throttled soft cap (BE pods)"""

    __slots__ = ("pod_key", "uid", "burst_millis", "throttled",
                 "request_millis", "memory_high_bytes",
                 "memory_min_bytes", "memory_low_bytes",
                 "cpu_weight", "cpu_idle")

    def __init__(self, pod_key: str, uid: str, burst_millis: int = 0,
                 throttled: bool = False, request_millis: int = 0,
                 memory_high_bytes: Optional[int] = None,
                 memory_min_bytes: Optional[int] = None,
                 memory_low_bytes: Optional[int] = None,
                 cpu_weight: Optional[int] = None,
                 cpu_idle: bool = False):
        self.pod_key = pod_key
        self.uid = uid
        self.burst_millis = burst_millis
        self.throttled = throttled
        self.request_millis = request_millis
        self.memory_high_bytes = memory_high_bytes
        self.memory_min_bytes = memory_min_bytes
        self.memory_low_bytes = memory_low_bytes
        # qos-level scheduling class (reference cpuqos handler's
        # cpu.qos_level, mapped to the portable cgroup-v2 knobs:
        # cpu.weight proportional share + cpu.idle SCHED_IDLE)
        self.cpu_weight = cpu_weight
        self.cpu_idle = cpu_idle

    def knobs(self) -> tuple:
        """Value tuple for change detection (RecordingEnforcer)."""
        return (self.burst_millis, self.throttled, self.request_millis,
                self.memory_high_bytes, self.memory_min_bytes,
                self.memory_low_bytes, self.cpu_weight, self.cpu_idle)


class Enforcer(abc.ABC):
    """What the agent drives.  Implementations must be idempotent:
    the agent re-applies every sync."""

    @abc.abstractmethod
    def apply_pod_qos(self, decision: PodQoSDecision) -> None: ...

    @abc.abstractmethod
    def remove_pod(self, uid: str) -> None:
        """Pod left the node: revert its enforcement."""

    @abc.abstractmethod
    def apply_network(self, online_mbps: int, offline_mbps: int,
                      pod_limits: Dict[str, int]) -> None:
        """Program the online/offline DCN split; pod_limits maps pod
        uid -> per-pod offline cap (mbps)."""

    def enforced_uids(self) -> set:
        """Pod uids with enforcement state left over from a previous
        run — a restarting agent reconciles these against the current
        pod population (stale cgroup dirs / tc classes must not
        outlive their pods)."""
        return set()


class NullEnforcer(Enforcer):
    """Publish-only mode (annotations still flow; nothing is mutated)."""

    def apply_pod_qos(self, decision): pass

    def remove_pod(self, uid): pass

    def apply_network(self, online_mbps, offline_mbps, pod_limits): pass


class RecordingEnforcer(Enforcer):
    """Test double: a ledger of every mutation + the current state."""

    def __init__(self):
        self.log: List[Tuple] = []
        self.pods: Dict[str, PodQoSDecision] = {}
        self.network: Optional[Tuple[int, int, Dict[str, int]]] = None

    def apply_pod_qos(self, decision):
        prev = self.pods.get(decision.uid)
        if prev is not None and prev.knobs() == decision.knobs():
            return                      # unchanged: no ledger noise
        self.pods[decision.uid] = decision
        self.log.append(("pod_qos", decision.uid, decision.burst_millis,
                         decision.throttled))

    def remove_pod(self, uid):
        if self.pods.pop(uid, None) is not None:
            self.log.append(("remove", uid))

    def apply_network(self, online_mbps, offline_mbps, pod_limits):
        prog = (online_mbps, offline_mbps, dict(pod_limits))
        if prog == self.network:
            return
        self.network = prog
        self.log.append(("network", online_mbps, offline_mbps,
                         dict(pod_limits)))

    def enforced_uids(self) -> set:
        return set(self.pods)


class CgroupV2Enforcer(Enforcer):
    """Writes the cgroup-v2 interface files.

    Layout: {root}/{uid}/cpu.max, cpu.max.burst, memory.high, and —
    for offline pods — net_cls.classid (the classification half of
    the DCN split: packets from the pod's cgroup carry 1:<class> and
    TcEnforcer's cgroup filter delivers them to that HTB class).
    Ownership is explicit, never inferred: pod dirs are named
    'vtp-{uid}' (cgroupfs forbids regular marker files, so the name
    prefix IS the claim-time ownership mark), and a root without a
    'volcano' path component (e.g. a shared /sys/fs/cgroup) is
    additionally narrowed to {root}/volcano.  Restart reconciliation
    sweeps ONLY vtp-prefixed dirs, so foreign entries (init.scope,
    kubelet pod dirs) survive even if an operator points the
    enforcer at a shared hierarchy.  Dirs written by a pre-prefix
    agent (unprefixed {root}/{uid}) are deliberately NOT swept — an
    upgrade across the prefix change needs a one-time manual cleanup
    of the old layout.  Tests point root at a tmpdir and assert the
    actual file contents (the write path has no fake).  A failed
    kernel write degrades that one knob with a warning — enforcement
    must never kill the agent's sync loop."""

    OWNED_COMPONENT = "volcano"
    POD_DIR_PREFIX = "vtp-"

    def __init__(self, root: str,
                 classids: Optional[OfflineClassAllocator] = None):
        configured_root = os.path.normpath(root)
        if self.OWNED_COMPONENT not in configured_root.split(os.sep):
            root = os.path.join(root, self.OWNED_COMPONENT)
        self.root = root
        # pre-upgrade agents wrote pod dirs directly under the
        # CONFIGURED root (the {root}/volcano narrowing came with the
        # vtp- prefix), so legacy detection must look there too
        self._legacy_roots = [self.root]
        if os.path.normpath(self.root) != configured_root:
            self._legacy_roots.append(configured_root)
        self.classids = classids if classids is not None \
            else OfflineClassAllocator()
        # uids whose net_cls.classid WE tagged non-zero: the
        # promotion-clear path below must only touch our own writes,
        # never sweep every dir under a possibly-shared root
        self._tagged: set = set()
        os.makedirs(root, exist_ok=True)
        self._warn_legacy_dirs()

    # knob files only this enforcer family writes: their presence in an
    # unprefixed dir marks pre-upgrade enforcement state, not a foreign
    # cgroup that merely exists under a shared root
    _KNOB_FILES = ("cpu.max", "cpu.max.burst", "memory.high",
                   "net_cls.classid")

    def _warn_legacy_dirs(self) -> None:
        """Startup detection of pre-prefix enforcement state.

        The vtp- prefix (and the {root}/volcano narrowing) changed
        both the pod dir name and the effective root, so dirs written
        by a pre-upgrade agent (unprefixed {root}/{uid}) are never
        reconciled: their cpu/memory caps and net_cls tags outlive the
        pods they enforced.  That cleanup stays deliberately manual
        (sweeping unowned-looking dirs under a possibly-shared
        hierarchy is how an agent kills a kubelet's cgroups) — but it
        must not stay SILENT.  Both candidate roots are scanned — the
        owned subtree AND, when __init__ narrowed the configured root,
        the pre-narrowing root the old agent actually wrote under —
        but only dirs carrying a knob file this enforcer writes are
        flagged, so foreign entries (init.scope, kubelet dirs) on a
        shared hierarchy are reported only if they look like our
        writes; the warning never sweeps either way."""
        for base in self._legacy_roots:
            try:
                entries = os.listdir(base)
            except OSError:
                continue
            legacy = sorted(
                e for e in entries
                if not e.startswith(self.POD_DIR_PREFIX)
                and e != self.OWNED_COMPONENT
                and os.path.isdir(os.path.join(base, e))
                and any(os.path.isfile(os.path.join(base, e, k))
                        for k in self._KNOB_FILES))
            if legacy:
                shown = ", ".join(legacy[:5]) + \
                    (", ..." if len(legacy) > 5 else "")
                log.warning(
                    "cgroup root %s holds %d legacy unprefixed pod "
                    "dir(s) (%s) from a pre-upgrade agent; their cpu/"
                    "memory/net_cls limits are NOT reconciled and "
                    "will persist until removed — clean up the old "
                    "layout manually (e.g. rmdir after verifying the "
                    "pods are gone)", base, len(legacy), shown)

    def _dir(self, uid: str) -> str:
        return os.path.join(self.root, self.POD_DIR_PREFIX + uid)

    @staticmethod
    def _write(path: str, value: str) -> None:
        try:
            with open(path, "w", encoding="ascii") as f:
                f.write(value + "\n")
        except OSError as e:
            # e.g. net_cls.classid on a v2-only hierarchy, or a knob
            # the kernel rejects: degrade THIS knob, keep the sync
            # cycle (eviction + stale-pod revert still must run)
            log.warning("cgroup write %s failed: %s", path, e)

    def apply_pod_qos(self, decision: PodQoSDecision) -> None:
        d = self._dir(decision.uid)
        os.makedirs(d, exist_ok=True)
        if decision.throttled:
            # clamp to the request (millicores -> us per period)
            quota = max(1000, decision.request_millis * CPU_PERIOD_US
                        // 1000)
            self._write(os.path.join(d, "cpu.max"),
                        f"{quota} {CPU_PERIOD_US}")
        else:
            self._write(os.path.join(d, "cpu.max"),
                        f"max {CPU_PERIOD_US}")
        burst_us = decision.burst_millis * CPU_PERIOD_US // 1000
        self._write(os.path.join(d, "cpu.max.burst"), str(burst_us))
        self._write(os.path.join(d, "memory.high"),
                    str(decision.memory_high_bytes)
                    if decision.memory_high_bytes else "max")
        # memoryqosv2 guarantee knobs (kernel defaults are 0: writing
        # them explicitly keeps re-application idempotent after a
        # pod's QoS class changes)
        self._write(os.path.join(d, "memory.min"),
                    str(decision.memory_min_bytes or 0))
        self._write(os.path.join(d, "memory.low"),
                    str(decision.memory_low_bytes or 0))
        # qos-level class knobs (cpuqos handler analogue): explicit
        # defaults for the same idempotency reason.  ORDER MATTERS on
        # a real kernel: cpu.idle must be written first, and
        # cpu.weight must NOT be written while the group is idle —
        # sched_group_set_shares returns EINVAL for idle groups, so a
        # weight write against an idle BE cgroup fails every sync
        # (and a promotion's weight write would fail in its own
        # cycle if idle were cleared only afterwards)
        self._write(os.path.join(d, "cpu.idle"),
                    "1" if decision.cpu_idle else "0")
        if not decision.cpu_idle:
            self._write(os.path.join(d, "cpu.weight"),
                        str(decision.cpu_weight
                            if decision.cpu_weight is not None
                            else 100))

    def remove_pod(self, uid: str) -> None:
        d = self._dir(uid)
        if os.path.isdir(d):
            shutil.rmtree(d, ignore_errors=True)
        self.classids.release(uid)      # cgroup-only deployments leak
        self._tagged.discard(uid)       # the allocator otherwise

    def apply_network(self, online_mbps, offline_mbps, pod_limits):
        """Classification half of the DCN split: tag each offline
        pod's cgroup with its HTB class; clear the tag from pods WE
        tagged that were promoted out of the offline set (a stale
        classid would keep capping a now-guaranteed pod).  Keyed on
        our own write ledger — never a sweep of the root, which may
        hold other owners' dirs."""
        for uid in pod_limits:
            d = self._dir(uid)
            os.makedirs(d, exist_ok=True)
            self._write(os.path.join(d, "net_cls.classid"),
                        net_cls_value(self.classids.classid(uid)))
            self._tagged.add(uid)
        for uid in self._tagged - set(pod_limits):
            path = os.path.join(self._dir(uid), "net_cls.classid")
            if os.path.exists(path):
                self._write(path, "0x00000000")   # default (online) class
            self._tagged.discard(uid)
            self.classids.release(uid)

    def enforced_uids(self) -> set:
        """Only vtp-prefixed dirs — the claim-time ownership mark —
        are reported, so the restart sweep can never touch a foreign
        cgroup even under a shared root."""
        p = self.POD_DIR_PREFIX
        try:
            return {e[len(p):] for e in os.listdir(self.root)
                    if e.startswith(p)
                    and os.path.isdir(os.path.join(self.root, e))}
        except OSError:
            return set()

    # test/debug helper
    def read(self, uid: str, knob: str) -> Optional[str]:
        try:
            with open(os.path.join(self._dir(uid), knob),
                      encoding="ascii") as f:
                return f.read().strip()
        except OSError:
            return None


class TcEnforcer(Enforcer):
    """HTB online/offline split on the DCN uplink.

    Program shape (reference: online/offline bandwidth split,
    tc_linux.go:48-60 — there via clsact+eBPF, here via HTB classes):
      1:10  online  — guaranteed rate, may borrow to line rate
      1:20  offline — capped ceil, shrinks under online pressure
      1:2N  one class per BE pod under 1:20
      filter (cgroup classifier) — steers packets whose cgroup
        carries a net_cls.classid (written by CgroupV2Enforcer) into
        that class; untagged traffic falls through to `default 10`.
    `replace` verbs keep re-application idempotent; the runner is
    injectable (tests capture argv lists, production executes tc).
    The first apply after process start deletes the root qdisc
    outright so HTB classes left behind by a previous agent run
    cannot keep capping pods that are gone."""

    def __init__(self, iface: str, runner=None,
                 classids: Optional[OfflineClassAllocator] = None):
        self.iface = iface
        self.runner = runner if runner is not None else self._run_tc
        self.classids = classids if classids is not None \
            else OfflineClassAllocator()
        self._program: Optional[tuple] = None   # (argv prog, uid->class)
        # uid -> class minor actually programmed into the kernel; OUR
        # removal ledger, independent of the shared allocator (the
        # cgroup half may release an allocation first — the kernel
        # class still must be deleted)
        self._programmed: Dict[str, int] = {}
        self._cleared_stale = False

    @staticmethod
    def _run_tc(argv: List[str]) -> None:
        subprocess.run(["tc", *argv], check=True,
                       stdout=subprocess.DEVNULL,
                       stderr=subprocess.DEVNULL)

    def apply_pod_qos(self, decision): pass     # cpu is cgroup's job

    def remove_pod(self, uid: str) -> None:
        self.classids.release(uid)
        cls = self._programmed.pop(uid, None)
        if cls is not None:
            # the kernel no longer matches the cached program — and a
            # later sync could rebuild a byte-identical key if the
            # freed minor is recycled to the same uid, so the cache
            # must not survive the delete
            self._program = None
            try:
                self.runner(["class", "del", "dev", self.iface,
                             "classid", f"1:{cls}"])
            except Exception:  # noqa: BLE001 — revert must not kill sync
                log.warning("tc class del failed for %s", uid)

    def apply_network(self, online_mbps: int, offline_mbps: int,
                      pod_limits: Dict[str, int]) -> None:
        # a pod promoted OUT of the offline set while staying on the
        # node must lose its cap class, not keep a stale kernel ceil
        for uid in [u for u in self._programmed if u not in pod_limits]:
            self.remove_pod(uid)
        if not self._cleared_stale:
            # first program after start: tear down whatever a previous
            # run left on the interface (classes for departed pods)
            try:
                self.runner(["qdisc", "del", "dev", self.iface, "root"])
            except Exception:  # noqa: BLE001 — absent qdisc is fine
                pass
            self._cleared_stale = True
        total = online_mbps + offline_mbps
        prog = [
            ["qdisc", "replace", "dev", self.iface, "root",
             "handle", "1:", "htb", "default", "10"],
            ["class", "replace", "dev", self.iface, "parent", "1:",
             "classid", "1:10", "htb", "rate", f"{online_mbps}mbit",
             "ceil", f"{total}mbit"],
            ["class", "replace", "dev", self.iface, "parent", "1:",
             "classid", "1:20", "htb", "rate",
             f"{max(1, offline_mbps // 10)}mbit",
             "ceil", f"{offline_mbps}mbit"],
            # the classifier: packets tagged by net_cls.classid (the
            # cgroup half) land in their 1:2N class; everything else
            # falls through to `default 10` (online)
            ["filter", "replace", "dev", self.iface, "parent", "1:",
             "protocol", "ip", "prio", "10", "handle", "1:", "cgroup"],
        ]
        classes = {uid: self.classids.classid(uid)
                   for uid in pod_limits}
        for uid in sorted(pod_limits):
            prog.append(
                ["class", "replace", "dev", self.iface, "parent",
                 "1:20", "classid", f"1:{classes[uid]}", "htb",
                 "rate", f"{max(1, pod_limits[uid])}mbit",
                 "ceil", f"{max(1, pod_limits[uid])}mbit"])
        # the cache key carries uid->class, not just argv: minor
        # RECYCLING can hand a new pod the class a departed pod just
        # freed, yielding byte-identical argv right after that class
        # was `del`ed above — an argv-only compare would skip the
        # reprogram and leave the new pod unshaped forever
        key = (prog, sorted(classes.items()))
        if key == self._program:
            return                      # unchanged: no kernel churn
        for argv in prog:
            try:
                self.runner(argv)
            except Exception:  # noqa: BLE001
                log.warning("tc %s failed", " ".join(argv))
                return                  # keep old program marker
        self._program = key
        self._programmed.update(classes)

    def enforced_uids(self) -> set:
        return set(self._programmed)


class CompositeEnforcer(Enforcer):
    """cgroup + tc together (the usual real deployment)."""

    def __init__(self, *enforcers: Enforcer):
        self.enforcers = enforcers

    def apply_pod_qos(self, decision):
        for e in self.enforcers:
            e.apply_pod_qos(decision)

    def remove_pod(self, uid):
        for e in self.enforcers:
            e.remove_pod(uid)

    def apply_network(self, online_mbps, offline_mbps, pod_limits):
        for e in self.enforcers:
            e.apply_network(online_mbps, offline_mbps, pod_limits)

    def enforced_uids(self) -> set:
        out = set()
        for e in self.enforcers:
            out |= e.enforced_uids()
        return out


def build_enforcer(spec: str) -> Enforcer:
    """CLI factory: 'none', 'record', or a comma list of
    'cgroup:/sys/fs/cgroup' (narrowed to the volcano-owned subtree
    inside it) and 'tc:eth0'.  When both
    halves are present they share one OfflineClassAllocator so the
    classid written into net_cls.classid is the HTB class tc built —
    that pairing IS the packet classification."""
    if not spec or spec == "none":
        return NullEnforcer()
    if spec == "record":
        return RecordingEnforcer()
    classids = OfflineClassAllocator()
    parts = []
    for item in spec.split(","):
        kind, _, arg = item.partition(":")
        if kind == "cgroup":
            parts.append(CgroupV2Enforcer(arg or "/sys/fs/cgroup",
                                          classids=classids))
        elif kind == "tc":
            parts.append(TcEnforcer(arg or "eth0", classids=classids))
        else:
            raise ValueError(f"unknown enforcer {item!r}")
    return parts[0] if len(parts) == 1 else CompositeEnforcer(*parts)
