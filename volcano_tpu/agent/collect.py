"""Pluggable metric collection for the node agent.

Reference parity: pkg/metriccollect (841 LoC of local collectors
behind a plugin interface, VERDICT r4 missing #1's second half).  A
Collector contributes named samples for one node; the
CompositeUsageProvider merges every registered collector's output
into the NodeUsage the agent's probes consume — so a deployment
mixes sources (local /proc for cpu/memory, the TPU runtime for chip
health, Prometheus for fleet-level overrides) by listing collector
names, not by writing a new provider.

Sample keys (a collector contributes any subset):
    cpu_fraction, memory_fraction        (0..1)
    tpu_chips_detected, tpu_chips_healthy (counts)
Later collectors in the list override earlier ones per key.
"""

from __future__ import annotations

import abc
import glob
import logging
import os
from typing import Callable, Dict, List, Optional

from volcano_tpu.agent.agent import NodeUsage, UsageProvider
from volcano_tpu.agent.enforcer import CgroupV2Enforcer

log = logging.getLogger(__name__)

_COLLECTORS: Dict[str, Callable[..., "Collector"]] = {}


def register_collector(name: str):
    """Class decorator: makes the collector buildable by name via
    build_provider('local,tpu')."""
    def deco(cls):
        _COLLECTORS[name] = cls
        cls.name = name
        return cls
    return deco


def registered_collectors() -> Dict[str, Callable[..., "Collector"]]:
    return dict(_COLLECTORS)


class Collector(abc.ABC):
    """One metric source (reference: a metriccollect local plugin)."""

    name: str = ""

    @abc.abstractmethod
    def collect(self, node_name: str) -> Dict[str, float]:
        """Named samples for this node; {} when the source has no
        data (absence must never be reported as zeros — a usage-only
        source reporting tpu_chips_detected=0 would cordon the
        node)."""


class CompositeUsageProvider(UsageProvider):
    """UsageProvider over an ordered collector list.  A collector
    that raises degrades to {} with a warning — one broken source
    must not take down the whole agent sync."""

    def __init__(self, collectors: List[Collector]):
        self.collectors = list(collectors)

    def refresh(self) -> bool:
        """Fan out to collectors with a refresh seam (the network-
        backed adapters) — called off the agent loop by the daemon's
        refresh thread, same contract as the metrics_source
        providers.  Local collectors sample at collect() time and
        have nothing to do here."""
        ok = True
        for c in self.collectors:
            fn = getattr(c, "refresh", None)
            if callable(fn):
                try:
                    ok = bool(fn()) and ok
                except Exception as e:  # noqa: BLE001
                    log.warning("collector %s refresh failed: %s",
                                c.name, e)
                    ok = False
        return ok

    def usage(self, node_name: str) -> NodeUsage:
        merged: Dict[str, float] = {}
        for c in self.collectors:
            try:
                merged.update(c.collect(node_name) or {})
            except Exception as e:  # noqa: BLE001
                log.warning("collector %s failed: %s", c.name, e)
        return NodeUsage(
            cpu_fraction=float(merged.get("cpu_fraction", 0.0)),
            memory_fraction=float(merged.get("memory_fraction", 0.0)),
            tpu_chips_detected=int(merged.get("tpu_chips_detected", 0)),
            tpu_chips_healthy=int(merged.get("tpu_chips_healthy", 0)),
            cpu_sampled="cpu_fraction" in merged,
        )


@register_collector("local")
class LocalProcCollector(Collector):
    """cpu/memory from the kernel: /proc/stat deltas between calls
    (first call has no delta -> no cpu sample) and /proc/meminfo
    MemAvailable.  Paths injectable for tests; the parse is the real
    one either way."""

    def __init__(self, stat_path: str = "/proc/stat",
                 meminfo_path: str = "/proc/meminfo"):
        self.stat_path = stat_path
        self.meminfo_path = meminfo_path
        # per-node delta windows: one provider instance may serve
        # several simulated agents (sync_node_agents loops them over
        # a shared provider); a single window would be torn to a
        # zero-jiffy delta by every agent after the first
        self._last: Dict[str, tuple] = {}    # node -> (busy, total)

    def _read_stat(self) -> Optional[tuple]:
        try:
            with open(self.stat_path, encoding="ascii") as f:
                for line in f:
                    if line.startswith("cpu "):
                        fields = [int(x) for x in line.split()[1:]]
                        idle = fields[3] + (fields[4] if len(fields) > 4
                                            else 0)   # idle + iowait
                        total = sum(fields)
                        return (total - idle, total)
        except (OSError, ValueError, IndexError):
            return None
        return None

    def collect(self, node_name: str) -> Dict[str, float]:
        out: Dict[str, float] = {}
        cur = self._read_stat()
        last = self._last.get(node_name)
        if cur is not None and last is not None:
            dbusy = cur[0] - last[0]
            dtotal = cur[1] - last[1]
            if dtotal > 0:
                out["cpu_fraction"] = max(0.0, min(1.0, dbusy / dtotal))
        if cur is not None:
            self._last[node_name] = cur
        try:
            info = {}
            with open(self.meminfo_path, encoding="ascii") as f:
                for line in f:
                    parts = line.split()
                    if len(parts) >= 2 and parts[0].rstrip(":") in (
                            "MemTotal", "MemAvailable"):
                        info[parts[0].rstrip(":")] = int(parts[1])
            if info.get("MemTotal"):
                out["memory_fraction"] = max(0.0, min(1.0, 1.0 - (
                    info.get("MemAvailable", 0) / info["MemTotal"])))
        except (OSError, ValueError):
            # vtplint: disable=except-pass (proc-file sampling: a missing/garbled /proc/meminfo just omits the optional gauge this round)
            pass
        return out


class PodNetRate:
    """One pod's accounting state kept by NetAccountingCollector.
    Windows are per direction (util.RateWindow): a one-sided failed
    read (exporter mid-rewrite) must not advance the other counter's
    window, or the returning counter's next delta would span two
    windows over one window's dt and read ~2x hot."""

    __slots__ = ("uid", "classid", "_tx", "_rx")

    def __init__(self, uid: str, alpha: float = 0.5):
        from volcano_tpu.util import RateWindow
        self.uid = uid
        self.classid = 0
        # bytes -> mbps; a reading below the last is an exporter
        # restart, so the absolute value is the delta ("absolute")
        self._tx = RateWindow(alpha=alpha, reset="absolute",
                              scale=8.0 / 1e6)
        self._rx = RateWindow(alpha=alpha, reset="absolute",
                              scale=8.0 / 1e6)

    @property
    def tx_mbps(self) -> float:      # windowed EWMA egress rate
        return self._tx.rate

    @property
    def rx_mbps(self) -> float:
        return self._rx.rate

    @property
    def tx_bytes(self) -> int:       # last raw counter reading
        return int(self._tx.last or 0)

    @property
    def rx_bytes(self) -> int:
        return int(self._rx.last or 0)


@register_collector("netaccounting")
class NetAccountingCollector(Collector):
    """Per-pod DCN byte accounting keyed by the enforcer's net_cls
    classids — the measurement half of the online/offline split
    (reference: pinned eBPF watermark maps, utils/ebpf/map.go:64-79;
    divergence note in docs/design/network-accounting.md).

    Reads, for every vtp-prefixed pod dir under the enforcer's cgroup
    root, the net_cls.classid tag the CgroupV2Enforcer wrote plus the
    per-cgroup byte counters an eBPF/conntrack exporter pins next to
    it (net_stat.tx_bytes / net_stat.rx_bytes — same file convention
    the tests' fake cgroup fs writes), and maintains a windowed EWMA
    mbps rate per pod.  Counter semantics:

      * monotonically increasing within one exporter lifetime;
      * a reading BELOW the last one is a counter reset (exporter or
        kernel restart): the new absolute value is taken as the delta
        (the bytes since the reset — the only defensible reading);
      * a vanished pod dir drops its state (classids recycle).

    collect() runs once per agent sync (the agent samples its provider
    exactly once), so the EWMA window is sync-period-spaced; rates()
    hands the per-pod table to the netaccounting handler.
    """

    # the enforcer's ownership mark IS the accounting key (shared
    # constant, so the measure half can never drift from the shape
    # half)
    POD_DIR_PREFIX = CgroupV2Enforcer.POD_DIR_PREFIX
    TX_FILE = "net_stat.tx_bytes"
    RX_FILE = "net_stat.rx_bytes"
    ALPHA = 0.5                      # EWMA weight of the newest window

    # a second collect() inside this window is a no-op returning the
    # cached totals: the netaccounting handler calls collect() every
    # sync so an explicitly-wired collector needs no provider, and
    # when the collector ALSO sits in the composite provider (sampled
    # at sync start) the handler's call microseconds later must not
    # tear the EWMA windows with a near-zero dt
    MIN_INTERVAL_S = 0.05

    def __init__(self, root: str = "/sys/fs/cgroup/volcano",
                 alpha: float = ALPHA, now=None):
        import time
        self.root = root
        self.alpha = float(alpha)
        self._now = now if now is not None else time.monotonic
        self._rates: Dict[str, PodNetRate] = {}
        self._last_walk: Optional[float] = None
        self._totals: Dict[str, float] = {}

    @staticmethod
    def _read_int(path: str) -> Optional[int]:
        try:
            with open(path, encoding="ascii") as f:
                return int(f.read().strip() or "0", 0)
        except (OSError, ValueError):
            return None

    def _sample_one(self, rate: PodNetRate, d: str, ts: float) -> None:
        tx = self._read_int(os.path.join(d, self.TX_FILE))
        rx = self._read_int(os.path.join(d, self.RX_FILE))
        cid = self._read_int(os.path.join(d, "net_cls.classid"))
        if cid is not None:
            rate.classid = cid & 0xFFFF
        # counter-delta/EWMA/reset semantics live in util.RateWindow
        # (shared with the GoodputCollector's step counters)
        rate._tx.fold(tx, ts)
        rate._rx.fold(rx, ts)

    def collect(self, node_name: str) -> Dict[str, float]:
        """Walk the pod cgroups once; returns node-level totals (the
        per-pod table is served by rates()).  The totals are extra
        keys NodeUsage ignores — harmless in the merged sample, and
        visible to custom providers that want them."""
        ts = self._now()
        if self._last_walk is not None and \
                ts - self._last_walk < self.MIN_INTERVAL_S:
            return dict(self._totals)
        self._last_walk = ts
        seen = set()
        try:
            entries = os.listdir(self.root)
        except OSError:
            return {}
        for e in entries:
            if not e.startswith(self.POD_DIR_PREFIX):
                continue
            d = os.path.join(self.root, e)
            if not os.path.isdir(d):
                continue
            uid = e[len(self.POD_DIR_PREFIX):]
            seen.add(uid)
            rate = self._rates.get(uid)
            if rate is None:
                rate = self._rates[uid] = PodNetRate(uid, self.alpha)
            self._sample_one(rate, d, ts)
        for uid in set(self._rates) - seen:   # departed: drop state
            del self._rates[uid]
        self._totals = {
            "dcn_tx_mbps": sum(r.tx_mbps
                               for r in self._rates.values()),
            "dcn_rx_mbps": sum(r.rx_mbps
                               for r in self._rates.values())}
        return dict(self._totals)

    def rates(self) -> Dict[str, PodNetRate]:
        """uid -> PodNetRate as of the last collect() (the handler's
        read surface; no re-walk)."""
        return dict(self._rates)


class PodProgressRate:
    """One pod's training-progress accounting state kept by
    GoodputCollector: step/example EWMA rates (util.RateWindow with
    the "restart" reset policy — a resumed worker's checkpoint-floor
    step count must never read as a negative or inflated delta) plus
    the productive-vs-allocated time ledger goodput is computed from.
    """

    __slots__ = ("uid", "epoch", "step", "examples", "restarts",
                 "allocated_s", "productive_s", "stalled",
                 "_steps", "_examples", "_last_rec_ts",
                 "_last_walk_ts")

    def __init__(self, uid: str, alpha: float = 0.5):
        from volcano_tpu.util import RateWindow
        self.uid = uid
        self.epoch: Optional[int] = None
        self.step = 0
        self.examples = 0.0
        self.restarts = 0            # observed epoch bumps
        # cumulative ledger over this pod's lifetime on this node; the
        # handler ships the CUMULATIVE values and the store folds the
        # per-pod diff against the node's previous report, so a
        # re-posted report after a lost ack never double-counts
        self.allocated_s = 0.0       # cumulative pod-residency seconds
        self.productive_s = 0.0      # subset with step progress
        self.stalled = False         # last window saw no step
        self._steps = RateWindow(alpha=alpha, reset="restart")
        self._examples = RateWindow(alpha=alpha, reset="restart")
        self._last_rec_ts: Optional[float] = None
        self._last_walk_ts: Optional[float] = None

    @property
    def steps_per_s(self) -> float:
        return self._steps.rate

    @property
    def examples_per_s(self) -> float:
        return self._examples.rate

    @property
    def goodput(self) -> float:
        """Cumulative productive/allocated fraction (0 when no time
        has been accounted yet)."""
        return (self.productive_s / self.allocated_s
                if self.allocated_s > 0 else 0.0)


@register_collector("goodput")
class GoodputCollector(Collector):
    """Per-pod training-progress accounting off the workload progress
    files (api/goodput.py contract: workers write one JSON record per
    step to VTP_PROGRESS_FILE under a shared root, named by pod uid —
    the same uid-keyed convention the enforcer uses for cgroup dirs).

    Per walk, for every vtp-<uid>.json under the root:

      * step/example counters fold into EWMA rates via the SHARED
        RateWindow machinery (util.py) with the "restart" policy: a
        counter below the last reading (worker resumed from a
        checkpoint floor) restarts the window with no delta;
      * an EPOCH change (the control plane bumped the restart/resize
        epoch) force-restarts the windows even when the resumed step
        count happens to be higher — the out-of-band signal beats the
        counter heuristic;
      * the time ledger: every inter-walk dt while the file exists is
        ALLOCATED time (the chip belongs to the pod); it is PRODUCTIVE
        only when the step counter advanced, credited no more than
        the worker's own inter-record wall time — so queue-adjacent
        ramps (compile, checkpoint restore) and wedged workers debit
        goodput = productive / allocated;
      * a vanished file drops its state (the pod left the node — the
        drain window itself is accounted by the control-plane side,
        which sees the gang hold no chips); a file not rewritten for
        STALE_FILE_S is treated the same (dead pods' leftovers in a
        shared per-job dir must not grow the walk forever).
    """

    FILE_PREFIX = "vtp-"
    FILE_SUFFIX = ".json"
    ALPHA = 0.5
    # second collect() inside this window is a no-op returning cached
    # totals (same double-sample guard as NetAccountingCollector)
    MIN_INTERVAL_S = 0.05
    # a record not rewritten for this long is treated as absent:
    # progress dirs are per-job and often shared (NFS) across nodes,
    # so no agent can safely unlink another pod's file — bounding by
    # write-freshness instead keeps the per-sync parse set and the
    # in-memory state proportional to LIVE pods across job churn.
    # Generous on purpose: a wedged-but-alive worker keeps debiting
    # goodput (reported stalled) for this long before it reads as dead.
    STALE_FILE_S = 3600.0

    def __init__(self, root: str = "/var/run/volcano/progress",
                 alpha: float = ALPHA, now=None):
        import time
        self.root = root
        self.alpha = float(alpha)
        self._now = now if now is not None else time.monotonic
        self._rates: Dict[str, PodProgressRate] = {}
        self._last_walk: Optional[float] = None
        self._totals: Dict[str, float] = {}

    @staticmethod
    def _read_record(path: str) -> Optional[dict]:
        import json
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return None       # mid-rewrite/corrupt: window spans on
        return doc if isinstance(doc, dict) else None

    def _sample_one(self, st: PodProgressRate, path: str,
                    ts: float) -> None:
        rec = self._read_record(path)
        if rec is None:
            return
        try:
            step = int(rec.get("step", 0))
            epoch = int(rec.get("epoch", 0))
            rec_ts = float(rec.get("ts", 0.0) or 0.0)
            examples = float(rec.get("examples", 0.0) or 0.0)
        except (TypeError, ValueError):
            return
        prev_step: Optional[int] = st.step
        prev_rec_ts = st._last_rec_ts
        if epoch != st.epoch:
            if st.epoch is not None:
                st.restarts += 1
            st.epoch = epoch
            st._steps.restart()
            st._examples.restart()
            prev_step = None        # no productive credit across it
        st._steps.fold(step, ts)
        st._examples.fold(examples, ts)
        st.step = step
        st.examples = examples
        if st._last_walk_ts is not None:
            dt = max(0.0, ts - st._last_walk_ts)
            st.allocated_s += dt
            advanced = prev_step is not None and step > prev_step
            st.stalled = not advanced
            if advanced:
                credit = dt
                if rec_ts and prev_rec_ts:
                    credit = min(dt, max(0.0, rec_ts - prev_rec_ts))
                st.productive_s += credit
        st._last_rec_ts = rec_ts
        st._last_walk_ts = ts

    def collect(self, node_name: str) -> Dict[str, float]:
        """Walk the progress files once; returns node totals (extra
        keys NodeUsage ignores); per-pod detail via rates()."""
        ts = self._now()
        if self._last_walk is not None and \
                ts - self._last_walk < self.MIN_INTERVAL_S:
            return dict(self._totals)
        self._last_walk = ts
        seen = set()
        try:
            entries = os.listdir(self.root)
        except OSError:
            return {}
        import time as _time
        wall = _time.time()
        for e in entries:
            if not (e.startswith(self.FILE_PREFIX)
                    and e.endswith(self.FILE_SUFFIX)):
                continue
            uid = e[len(self.FILE_PREFIX):-len(self.FILE_SUFFIX)]
            if not uid:
                continue
            path = os.path.join(self.root, e)
            try:
                if wall - os.stat(path).st_mtime > self.STALE_FILE_S:
                    continue        # dead pod's leftover: not ours to
            except OSError:         # unlink, but not ours to track
                continue
            seen.add(uid)
            st = self._rates.get(uid)
            if st is None:
                st = self._rates[uid] = PodProgressRate(uid, self.alpha)
            self._sample_one(st, path, ts)
        for uid in set(self._rates) - seen:   # departed: drop state
            del self._rates[uid]
        self._totals = {
            "goodput_steps_per_s": sum(r.steps_per_s
                                       for r in self._rates.values())}
        return dict(self._totals)

    def rates(self) -> Dict[str, PodProgressRate]:
        """uid -> PodProgressRate as of the last collect() (the
        GoodputHandler's read surface; no re-walk)."""
        return dict(self._rates)


class PodServingRate:
    """One serving replica's traffic accounting state kept by
    ServingCollector: the cumulative request counter folds into an
    EWMA QPS (util.RateWindow, "restart" policy — a restarted replica
    re-opens its counter at 0 and must not read as a negative delta),
    latency quantiles carry through from the replica's own window."""

    __slots__ = ("uid", "epoch", "requests", "slo_ok", "p50_ms",
                 "p99_ms", "restarts", "_requests")

    def __init__(self, uid: str, alpha: float = 0.5):
        from volcano_tpu.util import RateWindow
        self.uid = uid
        self.epoch: Optional[int] = None
        # cumulative ledgers over this replica's lifetime on this
        # node; shipped cumulative, store folds the diff (the
        # GoodputReport idempotency argument)
        self.requests = 0
        self.slo_ok = 0
        self.p50_ms = 0.0
        self.p99_ms = 0.0
        self.restarts = 0            # observed epoch bumps
        self._requests = RateWindow(alpha=alpha, reset="restart")

    @property
    def qps(self) -> float:
        return self._requests.rate


@register_collector("serving")
class ServingCollector(Collector):
    """Per-replica serving-traffic accounting off the workload stats
    files (api/serving.py contract: serving workers write one JSON
    record per beat to VTP_SERVING_STATS_FILE under a shared root,
    named vtps-<pod uid>.json — the goodput progress-file convention).

    Per walk, for every vtps-<uid>.json under the root: the
    cumulative request counter folds into an EWMA QPS on the SHARED
    RateWindow machinery ("restart" policy), an epoch change
    force-restarts the window (out-of-band restart signal beats the
    counter heuristic), quantiles and ledgers carry through for the
    ServingHandler to post.  Vanished/stale files drop their state
    (same lifetime rule as GoodputCollector)."""

    FILE_PREFIX = "vtps-"
    FILE_SUFFIX = ".json"
    ALPHA = 0.5
    MIN_INTERVAL_S = 0.05
    STALE_FILE_S = 3600.0

    def __init__(self, root: str = "/var/run/volcano/serving",
                 alpha: float = ALPHA, now=None):
        import time
        self.root = root
        self.alpha = float(alpha)
        self._now = now if now is not None else time.monotonic
        self._rates: Dict[str, PodServingRate] = {}
        self._last_walk: Optional[float] = None
        self._totals: Dict[str, float] = {}

    @staticmethod
    def _read_record(path: str) -> Optional[dict]:
        import json
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return None       # mid-rewrite/corrupt: window spans on
        return doc if isinstance(doc, dict) else None

    def _sample_one(self, st: PodServingRate, path: str,
                    ts: float) -> None:
        rec = self._read_record(path)
        if rec is None:
            return
        try:
            requests = int(rec.get("requests", 0))
            slo_ok = int(rec.get("slo_ok", 0))
            epoch = int(rec.get("epoch", 0))
            p50 = float(rec.get("p50_ms", 0.0) or 0.0)
            p99 = float(rec.get("p99_ms", 0.0) or 0.0)
        except (TypeError, ValueError):
            return
        if epoch != st.epoch:
            if st.epoch is not None:
                st.restarts += 1
            st.epoch = epoch
            st._requests.restart()
        st._requests.fold(requests, ts)
        st.requests = requests
        st.slo_ok = slo_ok
        st.p50_ms = p50
        st.p99_ms = p99

    def collect(self, node_name: str) -> Dict[str, float]:
        """Walk the stats files once; returns node totals (extra keys
        NodeUsage ignores); per-replica detail via rates()."""
        ts = self._now()
        if self._last_walk is not None and \
                ts - self._last_walk < self.MIN_INTERVAL_S:
            return dict(self._totals)
        self._last_walk = ts
        seen = set()
        try:
            entries = os.listdir(self.root)
        except OSError:
            return {}
        import time as _time
        wall = _time.time()
        for e in entries:
            if not (e.startswith(self.FILE_PREFIX)
                    and e.endswith(self.FILE_SUFFIX)):
                continue
            uid = e[len(self.FILE_PREFIX):-len(self.FILE_SUFFIX)]
            if not uid:
                continue
            path = os.path.join(self.root, e)
            try:
                if wall - os.stat(path).st_mtime > self.STALE_FILE_S:
                    continue        # dead replica's leftover
            except OSError:
                continue
            seen.add(uid)
            st = self._rates.get(uid)
            if st is None:
                st = self._rates[uid] = PodServingRate(uid, self.alpha)
            self._sample_one(st, path, ts)
        for uid in set(self._rates) - seen:   # departed: drop state
            del self._rates[uid]
        self._totals = {
            "serving_qps": sum(r.qps for r in self._rates.values())}
        return dict(self._totals)

    def rates(self) -> Dict[str, PodServingRate]:
        """uid -> PodServingRate as of the last collect() (the
        ServingHandler's read surface; no re-walk)."""
        return dict(self._rates)


@register_collector("tpu")
class TpuChipCollector(Collector):
    """Chip inventory from the accelerator device nodes (the VFIO /
    accel chardevs the TPU runtime exposes).  A chip whose device
    node vanished is detected-but-unhealthy from the scheduler's
    point of view only when a declared count says chips SHOULD exist;
    this collector reports what it can see and lets the TpuHealth
    handler compare against node.allocatable."""

    def __init__(self, device_glob: str = "/dev/accel*",
                 declared: Optional[int] = None):
        self.device_glob = device_glob
        self.declared = declared

    def collect(self, node_name: str) -> Dict[str, float]:
        chips = len(glob.glob(self.device_glob))
        if chips == 0 and self.declared is None:
            return {}    # no devices, nothing declared: no telemetry
        declared = self.declared if self.declared is not None else chips
        return {"tpu_chips_detected": max(chips, declared),
                "tpu_chips_healthy": chips}


class MetricsSourceCollector(Collector):
    """Adapter over a metrics_source provider — fleet metrics
    backends plug into the same collector list as local sources.
    refresh() is the off-loop network fetch; collect() only reads the
    cached samples."""

    def __init__(self, source):
        self.source = source

    def refresh(self) -> bool:
        return self.source.refresh()

    def collect(self, node_name: str) -> Dict[str, float]:
        u = self.source.usage(node_name)
        return {"cpu_fraction": u.cpu_fraction,
                "memory_fraction": u.memory_fraction}


@register_collector("prometheus")
class PrometheusCollector(MetricsSourceCollector):
    def __init__(self, url: str, **kwargs):
        from volcano_tpu.metrics_source import PrometheusUsageSource
        super().__init__(PrometheusUsageSource(url, **kwargs))


@register_collector("elasticsearch")
class ElasticsearchCollector(MetricsSourceCollector):
    def __init__(self, url: str, **kwargs):
        from volcano_tpu.metrics_source import ElasticsearchUsageSource
        super().__init__(ElasticsearchUsageSource(url, **kwargs))


def build_provider(spec: str) -> UsageProvider:
    """'local,tpu' or 'prometheus:http://host:9090,local' -> a
    CompositeUsageProvider over the named collectors (CLI seam, the
    metriccollect analogue of build_enforcer)."""
    collectors: List[Collector] = []
    for item in (s for s in spec.split(",") if s):
        name, _, arg = item.partition(":")
        cls = _COLLECTORS.get(name)
        if cls is None:
            raise ValueError(
                f"unknown collector {name!r} (have "
                f"{sorted(_COLLECTORS)})")
        collectors.append(cls(arg) if arg else cls())
    return CompositeUsageProvider(collectors)
