"""Pluggable metric collection for the node agent.

Reference parity: pkg/metriccollect (841 LoC of local collectors
behind a plugin interface, VERDICT r4 missing #1's second half).  A
Collector contributes named samples for one node; the
CompositeUsageProvider merges every registered collector's output
into the NodeUsage the agent's probes consume — so a deployment
mixes sources (local /proc for cpu/memory, the TPU runtime for chip
health, Prometheus for fleet-level overrides) by listing collector
names, not by writing a new provider.

Sample keys (a collector contributes any subset):
    cpu_fraction, memory_fraction        (0..1)
    tpu_chips_detected, tpu_chips_healthy (counts)
Later collectors in the list override earlier ones per key.
"""

from __future__ import annotations

import abc
import glob
import logging
import os
from typing import Callable, Dict, List, Optional

from volcano_tpu.agent.agent import NodeUsage, UsageProvider

log = logging.getLogger(__name__)

_COLLECTORS: Dict[str, Callable[..., "Collector"]] = {}


def register_collector(name: str):
    """Class decorator: makes the collector buildable by name via
    build_provider('local,tpu')."""
    def deco(cls):
        _COLLECTORS[name] = cls
        cls.name = name
        return cls
    return deco


def registered_collectors() -> Dict[str, Callable[..., "Collector"]]:
    return dict(_COLLECTORS)


class Collector(abc.ABC):
    """One metric source (reference: a metriccollect local plugin)."""

    name: str = ""

    @abc.abstractmethod
    def collect(self, node_name: str) -> Dict[str, float]:
        """Named samples for this node; {} when the source has no
        data (absence must never be reported as zeros — a usage-only
        source reporting tpu_chips_detected=0 would cordon the
        node)."""


class CompositeUsageProvider(UsageProvider):
    """UsageProvider over an ordered collector list.  A collector
    that raises degrades to {} with a warning — one broken source
    must not take down the whole agent sync."""

    def __init__(self, collectors: List[Collector]):
        self.collectors = list(collectors)

    def refresh(self) -> bool:
        """Fan out to collectors with a refresh seam (the network-
        backed adapters) — called off the agent loop by the daemon's
        refresh thread, same contract as the metrics_source
        providers.  Local collectors sample at collect() time and
        have nothing to do here."""
        ok = True
        for c in self.collectors:
            fn = getattr(c, "refresh", None)
            if callable(fn):
                try:
                    ok = bool(fn()) and ok
                except Exception as e:  # noqa: BLE001
                    log.warning("collector %s refresh failed: %s",
                                c.name, e)
                    ok = False
        return ok

    def usage(self, node_name: str) -> NodeUsage:
        merged: Dict[str, float] = {}
        for c in self.collectors:
            try:
                merged.update(c.collect(node_name) or {})
            except Exception as e:  # noqa: BLE001
                log.warning("collector %s failed: %s", c.name, e)
        return NodeUsage(
            cpu_fraction=float(merged.get("cpu_fraction", 0.0)),
            memory_fraction=float(merged.get("memory_fraction", 0.0)),
            tpu_chips_detected=int(merged.get("tpu_chips_detected", 0)),
            tpu_chips_healthy=int(merged.get("tpu_chips_healthy", 0)),
            cpu_sampled="cpu_fraction" in merged,
        )


@register_collector("local")
class LocalProcCollector(Collector):
    """cpu/memory from the kernel: /proc/stat deltas between calls
    (first call has no delta -> no cpu sample) and /proc/meminfo
    MemAvailable.  Paths injectable for tests; the parse is the real
    one either way."""

    def __init__(self, stat_path: str = "/proc/stat",
                 meminfo_path: str = "/proc/meminfo"):
        self.stat_path = stat_path
        self.meminfo_path = meminfo_path
        # per-node delta windows: one provider instance may serve
        # several simulated agents (sync_node_agents loops them over
        # a shared provider); a single window would be torn to a
        # zero-jiffy delta by every agent after the first
        self._last: Dict[str, tuple] = {}    # node -> (busy, total)

    def _read_stat(self) -> Optional[tuple]:
        try:
            with open(self.stat_path, encoding="ascii") as f:
                for line in f:
                    if line.startswith("cpu "):
                        fields = [int(x) for x in line.split()[1:]]
                        idle = fields[3] + (fields[4] if len(fields) > 4
                                            else 0)   # idle + iowait
                        total = sum(fields)
                        return (total - idle, total)
        except (OSError, ValueError, IndexError):
            return None
        return None

    def collect(self, node_name: str) -> Dict[str, float]:
        out: Dict[str, float] = {}
        cur = self._read_stat()
        last = self._last.get(node_name)
        if cur is not None and last is not None:
            dbusy = cur[0] - last[0]
            dtotal = cur[1] - last[1]
            if dtotal > 0:
                out["cpu_fraction"] = max(0.0, min(1.0, dbusy / dtotal))
        if cur is not None:
            self._last[node_name] = cur
        try:
            info = {}
            with open(self.meminfo_path, encoding="ascii") as f:
                for line in f:
                    parts = line.split()
                    if len(parts) >= 2 and parts[0].rstrip(":") in (
                            "MemTotal", "MemAvailable"):
                        info[parts[0].rstrip(":")] = int(parts[1])
            if info.get("MemTotal"):
                out["memory_fraction"] = max(0.0, min(1.0, 1.0 - (
                    info.get("MemAvailable", 0) / info["MemTotal"])))
        except (OSError, ValueError):
            pass
        return out


@register_collector("tpu")
class TpuChipCollector(Collector):
    """Chip inventory from the accelerator device nodes (the VFIO /
    accel chardevs the TPU runtime exposes).  A chip whose device
    node vanished is detected-but-unhealthy from the scheduler's
    point of view only when a declared count says chips SHOULD exist;
    this collector reports what it can see and lets the TpuHealth
    handler compare against node.allocatable."""

    def __init__(self, device_glob: str = "/dev/accel*",
                 declared: Optional[int] = None):
        self.device_glob = device_glob
        self.declared = declared

    def collect(self, node_name: str) -> Dict[str, float]:
        chips = len(glob.glob(self.device_glob))
        if chips == 0 and self.declared is None:
            return {}    # no devices, nothing declared: no telemetry
        declared = self.declared if self.declared is not None else chips
        return {"tpu_chips_detected": max(chips, declared),
                "tpu_chips_healthy": chips}


class MetricsSourceCollector(Collector):
    """Adapter over a metrics_source provider — fleet metrics
    backends plug into the same collector list as local sources.
    refresh() is the off-loop network fetch; collect() only reads the
    cached samples."""

    def __init__(self, source):
        self.source = source

    def refresh(self) -> bool:
        return self.source.refresh()

    def collect(self, node_name: str) -> Dict[str, float]:
        u = self.source.usage(node_name)
        return {"cpu_fraction": u.cpu_fraction,
                "memory_fraction": u.memory_fraction}


@register_collector("prometheus")
class PrometheusCollector(MetricsSourceCollector):
    def __init__(self, url: str, **kwargs):
        from volcano_tpu.metrics_source import PrometheusUsageSource
        super().__init__(PrometheusUsageSource(url, **kwargs))


@register_collector("elasticsearch")
class ElasticsearchCollector(MetricsSourceCollector):
    def __init__(self, url: str, **kwargs):
        from volcano_tpu.metrics_source import ElasticsearchUsageSource
        super().__init__(ElasticsearchUsageSource(url, **kwargs))


def build_provider(spec: str) -> UsageProvider:
    """'local,tpu' or 'prometheus:http://host:9090,local' -> a
    CompositeUsageProvider over the named collectors (CLI seam, the
    metriccollect analogue of build_enforcer)."""
    collectors: List[Collector] = []
    for item in (s for s in spec.split(",") if s):
        name, _, arg = item.partition(":")
        cls = _COLLECTORS.get(name)
        if cls is None:
            raise ValueError(
                f"unknown collector {name!r} (have "
                f"{sorted(_COLLECTORS)})")
        collectors.append(cls(arg) if arg else cls())
    return CompositeUsageProvider(collectors)
