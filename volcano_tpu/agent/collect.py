"""Pluggable metric collection for the node agent.

Reference parity: pkg/metriccollect (841 LoC of local collectors
behind a plugin interface, VERDICT r4 missing #1's second half).  A
Collector contributes named samples for one node; the
CompositeUsageProvider merges every registered collector's output
into the NodeUsage the agent's probes consume — so a deployment
mixes sources (local /proc for cpu/memory, the TPU runtime for chip
health, Prometheus for fleet-level overrides) by listing collector
names, not by writing a new provider.

Sample keys (a collector contributes any subset):
    cpu_fraction, memory_fraction        (0..1)
    tpu_chips_detected, tpu_chips_healthy (counts)
Later collectors in the list override earlier ones per key.
"""

from __future__ import annotations

import abc
import glob
import logging
import os
from typing import Callable, Dict, List, Optional

from volcano_tpu.agent.agent import NodeUsage, UsageProvider
from volcano_tpu.agent.enforcer import CgroupV2Enforcer

log = logging.getLogger(__name__)

_COLLECTORS: Dict[str, Callable[..., "Collector"]] = {}


def register_collector(name: str):
    """Class decorator: makes the collector buildable by name via
    build_provider('local,tpu')."""
    def deco(cls):
        _COLLECTORS[name] = cls
        cls.name = name
        return cls
    return deco


def registered_collectors() -> Dict[str, Callable[..., "Collector"]]:
    return dict(_COLLECTORS)


class Collector(abc.ABC):
    """One metric source (reference: a metriccollect local plugin)."""

    name: str = ""

    @abc.abstractmethod
    def collect(self, node_name: str) -> Dict[str, float]:
        """Named samples for this node; {} when the source has no
        data (absence must never be reported as zeros — a usage-only
        source reporting tpu_chips_detected=0 would cordon the
        node)."""


class CompositeUsageProvider(UsageProvider):
    """UsageProvider over an ordered collector list.  A collector
    that raises degrades to {} with a warning — one broken source
    must not take down the whole agent sync."""

    def __init__(self, collectors: List[Collector]):
        self.collectors = list(collectors)

    def refresh(self) -> bool:
        """Fan out to collectors with a refresh seam (the network-
        backed adapters) — called off the agent loop by the daemon's
        refresh thread, same contract as the metrics_source
        providers.  Local collectors sample at collect() time and
        have nothing to do here."""
        ok = True
        for c in self.collectors:
            fn = getattr(c, "refresh", None)
            if callable(fn):
                try:
                    ok = bool(fn()) and ok
                except Exception as e:  # noqa: BLE001
                    log.warning("collector %s refresh failed: %s",
                                c.name, e)
                    ok = False
        return ok

    def usage(self, node_name: str) -> NodeUsage:
        merged: Dict[str, float] = {}
        for c in self.collectors:
            try:
                merged.update(c.collect(node_name) or {})
            except Exception as e:  # noqa: BLE001
                log.warning("collector %s failed: %s", c.name, e)
        return NodeUsage(
            cpu_fraction=float(merged.get("cpu_fraction", 0.0)),
            memory_fraction=float(merged.get("memory_fraction", 0.0)),
            tpu_chips_detected=int(merged.get("tpu_chips_detected", 0)),
            tpu_chips_healthy=int(merged.get("tpu_chips_healthy", 0)),
            cpu_sampled="cpu_fraction" in merged,
        )


@register_collector("local")
class LocalProcCollector(Collector):
    """cpu/memory from the kernel: /proc/stat deltas between calls
    (first call has no delta -> no cpu sample) and /proc/meminfo
    MemAvailable.  Paths injectable for tests; the parse is the real
    one either way."""

    def __init__(self, stat_path: str = "/proc/stat",
                 meminfo_path: str = "/proc/meminfo"):
        self.stat_path = stat_path
        self.meminfo_path = meminfo_path
        # per-node delta windows: one provider instance may serve
        # several simulated agents (sync_node_agents loops them over
        # a shared provider); a single window would be torn to a
        # zero-jiffy delta by every agent after the first
        self._last: Dict[str, tuple] = {}    # node -> (busy, total)

    def _read_stat(self) -> Optional[tuple]:
        try:
            with open(self.stat_path, encoding="ascii") as f:
                for line in f:
                    if line.startswith("cpu "):
                        fields = [int(x) for x in line.split()[1:]]
                        idle = fields[3] + (fields[4] if len(fields) > 4
                                            else 0)   # idle + iowait
                        total = sum(fields)
                        return (total - idle, total)
        except (OSError, ValueError, IndexError):
            return None
        return None

    def collect(self, node_name: str) -> Dict[str, float]:
        out: Dict[str, float] = {}
        cur = self._read_stat()
        last = self._last.get(node_name)
        if cur is not None and last is not None:
            dbusy = cur[0] - last[0]
            dtotal = cur[1] - last[1]
            if dtotal > 0:
                out["cpu_fraction"] = max(0.0, min(1.0, dbusy / dtotal))
        if cur is not None:
            self._last[node_name] = cur
        try:
            info = {}
            with open(self.meminfo_path, encoding="ascii") as f:
                for line in f:
                    parts = line.split()
                    if len(parts) >= 2 and parts[0].rstrip(":") in (
                            "MemTotal", "MemAvailable"):
                        info[parts[0].rstrip(":")] = int(parts[1])
            if info.get("MemTotal"):
                out["memory_fraction"] = max(0.0, min(1.0, 1.0 - (
                    info.get("MemAvailable", 0) / info["MemTotal"])))
        except (OSError, ValueError):
            pass
        return out


class PodNetRate:
    """One pod's accounting state kept by NetAccountingCollector.
    Timestamps are per direction: a one-sided failed read (exporter
    mid-rewrite) must not advance the other counter's window, or the
    returning counter's next delta would span two windows over one
    window's dt and read ~2x hot."""

    __slots__ = ("uid", "classid", "tx_mbps", "rx_mbps",
                 "tx_bytes", "rx_bytes", "_last_tx", "_last_rx",
                 "_last_ts_tx", "_last_ts_rx")

    def __init__(self, uid: str):
        self.uid = uid
        self.classid = 0
        self.tx_mbps = 0.0       # windowed EWMA egress rate
        self.rx_mbps = 0.0
        self.tx_bytes = 0        # last raw counter reading
        self.rx_bytes = 0
        self._last_tx: Optional[int] = None
        self._last_rx: Optional[int] = None
        self._last_ts_tx: Optional[float] = None
        self._last_ts_rx: Optional[float] = None


@register_collector("netaccounting")
class NetAccountingCollector(Collector):
    """Per-pod DCN byte accounting keyed by the enforcer's net_cls
    classids — the measurement half of the online/offline split
    (reference: pinned eBPF watermark maps, utils/ebpf/map.go:64-79;
    divergence note in docs/design/network-accounting.md).

    Reads, for every vtp-prefixed pod dir under the enforcer's cgroup
    root, the net_cls.classid tag the CgroupV2Enforcer wrote plus the
    per-cgroup byte counters an eBPF/conntrack exporter pins next to
    it (net_stat.tx_bytes / net_stat.rx_bytes — same file convention
    the tests' fake cgroup fs writes), and maintains a windowed EWMA
    mbps rate per pod.  Counter semantics:

      * monotonically increasing within one exporter lifetime;
      * a reading BELOW the last one is a counter reset (exporter or
        kernel restart): the new absolute value is taken as the delta
        (the bytes since the reset — the only defensible reading);
      * a vanished pod dir drops its state (classids recycle).

    collect() runs once per agent sync (the agent samples its provider
    exactly once), so the EWMA window is sync-period-spaced; rates()
    hands the per-pod table to the netaccounting handler.
    """

    # the enforcer's ownership mark IS the accounting key (shared
    # constant, so the measure half can never drift from the shape
    # half)
    POD_DIR_PREFIX = CgroupV2Enforcer.POD_DIR_PREFIX
    TX_FILE = "net_stat.tx_bytes"
    RX_FILE = "net_stat.rx_bytes"
    ALPHA = 0.5                      # EWMA weight of the newest window

    # a second collect() inside this window is a no-op returning the
    # cached totals: the netaccounting handler calls collect() every
    # sync so an explicitly-wired collector needs no provider, and
    # when the collector ALSO sits in the composite provider (sampled
    # at sync start) the handler's call microseconds later must not
    # tear the EWMA windows with a near-zero dt
    MIN_INTERVAL_S = 0.05

    def __init__(self, root: str = "/sys/fs/cgroup/volcano",
                 alpha: float = ALPHA, now=None):
        import time
        self.root = root
        self.alpha = float(alpha)
        self._now = now if now is not None else time.monotonic
        self._rates: Dict[str, PodNetRate] = {}
        self._last_walk: Optional[float] = None
        self._totals: Dict[str, float] = {}

    @staticmethod
    def _read_int(path: str) -> Optional[int]:
        try:
            with open(path, encoding="ascii") as f:
                return int(f.read().strip() or "0", 0)
        except (OSError, ValueError):
            return None

    def _sample_one(self, rate: PodNetRate, d: str, ts: float) -> None:
        tx = self._read_int(os.path.join(d, self.TX_FILE))
        rx = self._read_int(os.path.join(d, self.RX_FILE))
        cid = self._read_int(os.path.join(d, "net_cls.classid"))
        if cid is not None:
            rate.classid = cid & 0xFFFF

        def fold(cur, last, last_ts, ewma):
            """-> (last reading, window start ts, ewma); a failed
            read leaves all three untouched so the direction's window
            simply spans to the next successful read."""
            if cur is None:
                return last, last_ts, ewma
            if last is None:         # first reading: no window yet
                return cur, ts, ewma
            delta = cur - last if cur >= last else cur   # reset: cur
            dt = ts - last_ts if last_ts else 0.0
            if dt > 0:
                inst = delta * 8.0 / dt / 1e6            # bytes->mbps
                ewma = inst if ewma == 0.0 else \
                    self.alpha * inst + (1 - self.alpha) * ewma
            return cur, ts, ewma

        rate._last_tx, rate._last_ts_tx, rate.tx_mbps = fold(
            tx, rate._last_tx, rate._last_ts_tx, rate.tx_mbps)
        rate._last_rx, rate._last_ts_rx, rate.rx_mbps = fold(
            rx, rate._last_rx, rate._last_ts_rx, rate.rx_mbps)
        rate.tx_bytes = rate._last_tx or 0
        rate.rx_bytes = rate._last_rx or 0

    def collect(self, node_name: str) -> Dict[str, float]:
        """Walk the pod cgroups once; returns node-level totals (the
        per-pod table is served by rates()).  The totals are extra
        keys NodeUsage ignores — harmless in the merged sample, and
        visible to custom providers that want them."""
        ts = self._now()
        if self._last_walk is not None and \
                ts - self._last_walk < self.MIN_INTERVAL_S:
            return dict(self._totals)
        self._last_walk = ts
        seen = set()
        try:
            entries = os.listdir(self.root)
        except OSError:
            return {}
        for e in entries:
            if not e.startswith(self.POD_DIR_PREFIX):
                continue
            d = os.path.join(self.root, e)
            if not os.path.isdir(d):
                continue
            uid = e[len(self.POD_DIR_PREFIX):]
            seen.add(uid)
            rate = self._rates.get(uid)
            if rate is None:
                rate = self._rates[uid] = PodNetRate(uid)
            self._sample_one(rate, d, ts)
        for uid in set(self._rates) - seen:   # departed: drop state
            del self._rates[uid]
        self._totals = {
            "dcn_tx_mbps": sum(r.tx_mbps
                               for r in self._rates.values()),
            "dcn_rx_mbps": sum(r.rx_mbps
                               for r in self._rates.values())}
        return dict(self._totals)

    def rates(self) -> Dict[str, PodNetRate]:
        """uid -> PodNetRate as of the last collect() (the handler's
        read surface; no re-walk)."""
        return dict(self._rates)


@register_collector("tpu")
class TpuChipCollector(Collector):
    """Chip inventory from the accelerator device nodes (the VFIO /
    accel chardevs the TPU runtime exposes).  A chip whose device
    node vanished is detected-but-unhealthy from the scheduler's
    point of view only when a declared count says chips SHOULD exist;
    this collector reports what it can see and lets the TpuHealth
    handler compare against node.allocatable."""

    def __init__(self, device_glob: str = "/dev/accel*",
                 declared: Optional[int] = None):
        self.device_glob = device_glob
        self.declared = declared

    def collect(self, node_name: str) -> Dict[str, float]:
        chips = len(glob.glob(self.device_glob))
        if chips == 0 and self.declared is None:
            return {}    # no devices, nothing declared: no telemetry
        declared = self.declared if self.declared is not None else chips
        return {"tpu_chips_detected": max(chips, declared),
                "tpu_chips_healthy": chips}


class MetricsSourceCollector(Collector):
    """Adapter over a metrics_source provider — fleet metrics
    backends plug into the same collector list as local sources.
    refresh() is the off-loop network fetch; collect() only reads the
    cached samples."""

    def __init__(self, source):
        self.source = source

    def refresh(self) -> bool:
        return self.source.refresh()

    def collect(self, node_name: str) -> Dict[str, float]:
        u = self.source.usage(node_name)
        return {"cpu_fraction": u.cpu_fraction,
                "memory_fraction": u.memory_fraction}


@register_collector("prometheus")
class PrometheusCollector(MetricsSourceCollector):
    def __init__(self, url: str, **kwargs):
        from volcano_tpu.metrics_source import PrometheusUsageSource
        super().__init__(PrometheusUsageSource(url, **kwargs))


@register_collector("elasticsearch")
class ElasticsearchCollector(MetricsSourceCollector):
    def __init__(self, url: str, **kwargs):
        from volcano_tpu.metrics_source import ElasticsearchUsageSource
        super().__init__(ElasticsearchUsageSource(url, **kwargs))


def build_provider(spec: str) -> UsageProvider:
    """'local,tpu' or 'prometheus:http://host:9090,local' -> a
    CompositeUsageProvider over the named collectors (CLI seam, the
    metriccollect analogue of build_enforcer)."""
    collectors: List[Collector] = []
    for item in (s for s in spec.split(",") if s):
        name, _, arg = item.partition(":")
        cls = _COLLECTORS.get(name)
        if cls is None:
            raise ValueError(
                f"unknown collector {name!r} (have "
                f"{sorted(_COLLECTORS)})")
        collectors.append(cls(arg) if arg else cls())
    return CompositeUsageProvider(collectors)
