"""Node agent (reference: pkg/agent + pkg/metriccollect)."""

from volcano_tpu.agent.agent import (
    FakeUsageProvider,
    NodeAgent,
    UsageProvider,
)
from volcano_tpu.agent.collect import (
    Collector,
    CompositeUsageProvider,
    build_provider,
    register_collector,
)
from volcano_tpu.agent.framework import (
    Event,
    Handler,
    register_handler,
    registered_handlers,
)
from volcano_tpu.agent import handlers as _handlers  # noqa: F401 — registers
                                                     # the default pipeline

__all__ = [
    "NodeAgent", "UsageProvider", "FakeUsageProvider",
    "Collector", "CompositeUsageProvider", "build_provider",
    "register_collector", "Event", "Handler", "register_handler",
    "registered_handlers",
]
