"""Node agent (reference: pkg/agent + pkg/metriccollect)."""

from volcano_tpu.agent.agent import NodeAgent, UsageProvider, FakeUsageProvider

__all__ = ["NodeAgent", "UsageProvider", "FakeUsageProvider"]
