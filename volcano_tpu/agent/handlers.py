"""The default agent handler pipeline — nine registered handlers.

Reference parity: pkg/agent/events/handlers/* (one package per
concern, self-registered via registry.go).  Each handler here carries
the logic the r4 agent kept inline in its sync loop; registration
order is dispatch order, which matters only where stated:

    UsageReporter, TpuHealth, Oversubscription   (EVENT_USAGE)
    CpuQoS, MemoryQoS, NetworkQoS, NumaExporter  (EVENT_PODS)
    Enforcement                                  (EVENT_PODS, LAST:
        applies the decision set the QoS handlers built and
        reconciles enforcement for departed pods)
    Eviction                                     (EVENT_PRESSURE)

MemoryQoS is the memoryqosv2 knob set (VERDICT r4 missing #2;
reference pkg/agent/events/handlers/memoryqosv2/ + docs/design/
agent-cgroup-v2-adaptation.md): online pods get memory.min (hard
guarantee = request) and memory.low (soft protection above it); BE
pods keep the memory.high cap.  Cpu and memory handlers never see
each other — both fill the per-sync PodQoSDecision set that the
Enforcement handler applies once per pod.
"""

from __future__ import annotations

import logging

from volcano_tpu.agent.framework import (
    EVENT_PODS,
    EVENT_PRESSURE,
    EVENT_USAGE,
    Event,
    Handler,
    register_handler,
)
from volcano_tpu.api.resource import TPU
from volcano_tpu.api.types import (
    QOS_HIGHLY_LATENCY_SENSITIVE,
    QOS_LATENCY_CRITICAL,
    QOS_LATENCY_SENSITIVE,
)

log = logging.getLogger(__name__)

# cpu qos-level ladder -> cgroup-v2 cpu.weight (extension/qos.go:
# LC/HLS=2, LS=1; BE takes weight 1 + cpu.idle instead)
CLASS_WEIGHT = {QOS_LATENCY_CRITICAL: 400,
                QOS_HIGHLY_LATENCY_SENSITIVE: 400,
                QOS_LATENCY_SENSITIVE: 100}

# agent.py owns the annotation-name constants (they are its public
# API); handlers import them inside handle() to avoid an import cycle
# (agent.py imports this module to trigger registration).


@register_handler
class UsageReporterHandler(Handler):
    """Publish cpu/memory usage fractions as node annotations
    (consumed by the usage plugin)."""

    name = "usagereporter"
    events = (EVENT_USAGE,)

    def handle(self, event: Event) -> None:
        from volcano_tpu.agent.agent import (
            CPU_USAGE_ANNOTATION, MEM_USAGE_ANNOTATION)
        event.node.annotations[CPU_USAGE_ANNOTATION] = \
            f"{event.usage.cpu_fraction:.3f}"
        event.node.annotations[MEM_USAGE_ANNOTATION] = \
            f"{event.usage.memory_fraction:.3f}"


@register_handler
class TpuHealthHandler(Handler):
    """Chip health -> label + cordon.  A slice host with sick chips
    must not take new work: the ICI mesh is only as healthy as its
    worst host."""

    name = "tpuhealth"
    events = (EVENT_USAGE,)

    def handle(self, event: Event) -> None:
        from volcano_tpu.agent.agent import (
            AGENT_CORDONED_ANNOTATION, TPU_CHIPS_ANNOTATION,
            TPU_HEALTHY_LABEL)
        node, usage = event.node, event.usage
        declared = self.agent.allocatable(node).get(TPU)
        if usage.tpu_chips_detected == 0:
            # no chip telemetry from this provider (e.g. a usage-only
            # Prometheus source): never cordon on absence of data
            return
        node.annotations[TPU_CHIPS_ANNOTATION] = \
            f"{usage.tpu_chips_healthy}/{usage.tpu_chips_detected}"
        healthy = (usage.tpu_chips_healthy >= declared > 0) or \
            (declared == 0 and usage.tpu_chips_detected ==
             usage.tpu_chips_healthy)
        node.labels[TPU_HEALTHY_LABEL] = "true" if healthy else "false"
        if not healthy:
            node.unschedulable = True
            node.annotations[AGENT_CORDONED_ANNOTATION] = "true"
            self.agent.cluster.record_event(
                self.agent.node_name, "TPUUnhealthy",
                f"{usage.tpu_chips_healthy}/{usage.tpu_chips_detected}"
                f" chips healthy (declared {declared:g})")
        elif node.unschedulable and \
                node.annotations.get(AGENT_CORDONED_ANNOTATION) == \
                "true":
            # only undo OUR cordon — never an admin's maintenance one
            node.unschedulable = False
            node.annotations.pop(AGENT_CORDONED_ANNOTATION, None)


@register_handler
class OversubscriptionHandler(Handler):
    """Publish reclaimable millicores in 10% steps
    (pkg/agent/oversubscription/policy/policy.go:40-61)."""

    name = "oversubscription"
    events = (EVENT_USAGE,)

    def handle(self, event: Event) -> None:
        from volcano_tpu.agent.agent import OVERSUB_ANNOTATION
        if not getattr(event.usage, "cpu_sampled", True):
            # no cpu telemetry this cycle: publishing from the 0.0
            # default would read as a fully idle node and hand the
            # scheduler 60% of it as phantom reclaimable capacity
            event.node.annotations[OVERSUB_ANNOTATION] = "0"
            return
        alloc = self.agent.allocatable(event.node)
        idle_frac = max(0.0, 1.0 - event.usage.cpu_fraction)
        stepped = int(idle_frac * 10) / 10.0   # 10% quantization
        reclaimable = alloc.milli_cpu * stepped * \
            self.agent.oversub_factor
        event.node.annotations[OVERSUB_ANNOTATION] = \
            str(int(reclaimable))


@register_handler
class CpuQoSHandler(Handler):
    """cpuburst + cputhrottle + cpuqos (reference handlers of the
    same names): BE pods burst into measured idle, throttle to
    request under pressure; guaranteed pods keep fixed headroom; and
    every pod gets its qos-LEVEL scheduling class — the reference
    writes a kernel cpu.qos_level int (LC/HLS=2, LS=1, BE=-1,
    extension/qos.go), mapped here to the portable cgroup-v2 pair:
    cpu.weight (LC/HLS 400, LS 100, BE 1) and cpu.idle (SCHED_IDLE
    for BE — offline work yields the CPU entirely under contention
    instead of merely weighing less)."""

    name = "cpuqos"
    events = (EVENT_PODS,)

    def handle(self, event: Event) -> None:
        from volcano_tpu.agent.agent import (
            CPU_BURST_ANNOTATION, CPU_THROTTLE_ANNOTATION,
            PREEMPTABLE_QOS_ANNOTATION, QOS_BEST_EFFORT)
        agent = self.agent
        usage = event.usage
        idle_frac = max(0.0, 1.0 - usage.cpu_fraction)
        node_idle_m = agent.allocatable(event.node).milli_cpu * \
            idle_frac
        throttled = usage.cpu_fraction > agent.eviction_threshold * 0.9
        for pod in event.pods:
            qos = pod.annotations.get(PREEMPTABLE_QOS_ANNOTATION)
            request_m = pod.resource_requests().milli_cpu
            d = agent.decision_for(event, pod)
            if qos == QOS_BEST_EFFORT:
                # requests are often 0 for true best-effort — size the
                # burst from allocatable idle, not requests; pressure
                # zeroes it, matching the throttle flag
                burst = 0 if throttled else int(node_idle_m)
                pod.annotations[CPU_BURST_ANNOTATION] = str(burst)
                pod.annotations[CPU_THROTTLE_ANNOTATION] = (
                    "true" if throttled else "false")
                d.burst_millis, d.throttled = burst, throttled
                d.cpu_weight, d.cpu_idle = 1, True
            else:
                burst = int(request_m * 0.2)
                pod.annotations[CPU_BURST_ANNOTATION] = str(burst)
                pod.annotations.pop(CPU_THROTTLE_ANNOTATION, None)
                d.burst_millis, d.throttled = burst, False
                # unannotated pods are LS (extension/qos.go default);
                # an UNRECOGNIZED level also lands on LS weight but
                # loudly — a typo'd "lc" silently demoting a
                # latency-critical pod 400 -> 100 would be invisible
                if qos and qos not in CLASS_WEIGHT:
                    log.warning("pod %s: unknown qos-level %r; "
                                "treating as LS", pod.key, qos)
                d.cpu_weight = CLASS_WEIGHT.get(qos, 100)
                d.cpu_idle = False
            d.request_millis = int(request_m)


@register_handler
class MemoryQoSHandler(Handler):
    """memoryqosv2 (reference pkg/agent/events/handlers/memoryqosv2/,
    cgroup-v2 adaptation design doc): per-QoS-class memory knobs.

      online (non-BE) pods: memory.min = request (kernel-guaranteed,
        never reclaimed) and memory.low = 1.25x request (reclaim-
        protected while the node has slack) — the guarantee the r4
        agent lacked;
      BE pods: memory.high = request (soft cap; the kernel throttles
        allocation above it instead of OOM-killing the node)."""

    name = "memoryqosv2"
    events = (EVENT_PODS,)
    LOW_FACTOR = 1.25

    def handle(self, event: Event) -> None:
        from volcano_tpu.agent.agent import (
            PREEMPTABLE_QOS_ANNOTATION, QOS_BEST_EFFORT)
        for pod in event.pods:
            mem = int(pod.resource_requests().memory)
            if not mem:
                continue
            d = self.agent.decision_for(event, pod)
            if pod.annotations.get(PREEMPTABLE_QOS_ANNOTATION) == \
                    QOS_BEST_EFFORT:
                d.memory_high_bytes = mem
            else:
                d.memory_min_bytes = mem
                d.memory_low_bytes = int(mem * self.LOW_FACTOR)


@register_handler
class NetworkQoSHandler(Handler):
    """Online/offline DCN egress split (reference pkg/networkqos):
    publish the split + per-BE-pod caps and program the enforcer's
    network half."""

    name = "networkqos"
    events = (EVENT_PODS,)

    def handle(self, event: Event) -> None:
        from volcano_tpu.agent.agent import (
            DCN_BANDWIDTH_ANNOTATION, DCN_OFFLINE_LIMIT_ANNOTATION,
            DCN_ONLINE_GUARANTEE_ANNOTATION, DCN_POD_LIMIT_ANNOTATION,
            DEFAULT_DCN_MBPS, PREEMPTABLE_QOS_ANNOTATION,
            QOS_BEST_EFFORT)
        agent, node, usage = self.agent, event.node, event.usage
        try:
            total_mbps = float(node.annotations.get(
                DCN_BANDWIDTH_ANNOTATION, DEFAULT_DCN_MBPS))
        except (TypeError, ValueError):
            # a malformed operator annotation must never kill the
            # sync cycle (eviction still runs after this handler)
            log.warning("node %s: invalid %s annotation; using "
                        "default", agent.node_name,
                        DCN_BANDWIDTH_ANNOTATION)
            total_mbps = float(DEFAULT_DCN_MBPS)
        be_pods, other_pods = [], []
        for p in event.pods:
            (be_pods if p.annotations.get(PREEMPTABLE_QOS_ANNOTATION)
             == QOS_BEST_EFFORT else other_pods).append(p)
        # offline (BE) traffic capped at a link fraction, shrinking
        # to a floor under online pressure
        offline_share = 0.4 if usage.cpu_fraction < 0.8 else 0.1
        offline_mbps = int(total_mbps * offline_share)
        node.annotations[DCN_OFFLINE_LIMIT_ANNOTATION] = \
            str(offline_mbps)
        node.annotations[DCN_ONLINE_GUARANTEE_ANNOTATION] = \
            str(int(total_mbps - offline_mbps))
        pod_limits = {}
        if be_pods:
            per_pod = offline_mbps // len(be_pods)
            for pod in be_pods:
                pod.annotations[DCN_POD_LIMIT_ANNOTATION] = str(per_pod)
                pod_limits[pod.uid] = per_pod
        for pod in other_pods:
            # a pod promoted out of BE must not keep a stale cap
            pod.annotations.pop(DCN_POD_LIMIT_ANNOTATION, None)
        agent.enforcer.apply_network(int(total_mbps - offline_mbps),
                                     offline_mbps, pod_limits)


@register_handler
class NumaExporterHandler(Handler):
    """Exporter half of the Numatopology contract: republish per-cell
    FREE amounts so the scheduler's single-NUMA gate sees placements
    from earlier cycles."""

    name = "numaexporter"
    events = (EVENT_PODS,)

    def handle(self, event: Event) -> None:
        agent = self.agent
        topo = getattr(agent.cluster, "numatopologies", {}).get(
            agent.node_name)
        if topo is None:
            return
        reqs = []
        for pod in event.pods:
            r = pod.resource_requests()
            reqs.append((r.milli_cpu, r.get(TPU)))
        before = {res: dict(cells)
                  for res, cells in topo.numa_res.items()}
        topo.recompute_free(reqs)
        if topo.numa_res != before:
            agent.cluster.put_object("numatopology", topo)


@register_handler
class EnforcementHandler(Handler):
    """LAST of the EVENT_PODS handlers: apply the decision set the
    QoS handlers built (one apply per pod, all knob families merged)
    and revert enforcement for pods that left the node — decision,
    OS mutation, and revert stay one observable loop."""

    name = "enforcement"
    events = (EVENT_PODS,)

    def handle(self, event: Event) -> None:
        agent = self.agent
        for d in event.decisions.values():
            agent.enforcer.apply_pod_qos(d)
        current_uids = {p.uid for p in event.pods}
        for uid in agent._enforced_uids - current_uids:
            agent.enforcer.remove_pod(uid)
        agent._enforced_uids = current_uids


@register_handler
class EvictionHandler(Handler):
    """Pressure eviction of best-effort pods (reference eviction
    handler)."""

    name = "eviction"
    events = (EVENT_PRESSURE,)

    def handle(self, event: Event) -> None:
        from volcano_tpu.agent.agent import (
            PREEMPTABLE_QOS_ANNOTATION, QOS_BEST_EFFORT)
        for pod in event.pods:
            if pod.annotations.get(PREEMPTABLE_QOS_ANNOTATION) == \
                    QOS_BEST_EFFORT:
                log.info("agent %s: evicting BE pod %s under pressure",
                         self.agent.node_name, pod.key)
                self.agent.cluster.evict_pod(
                    pod.namespace, pod.name, "node resource pressure")
