"""The default agent handler pipeline — eleven registered handlers.

Reference parity: pkg/agent/events/handlers/* (one package per
concern, self-registered via registry.go).  Each handler here carries
the logic the r4 agent kept inline in its sync loop; registration
order is dispatch order, which matters only where stated:

    UsageReporter, TpuHealth, Oversubscription   (EVENT_USAGE)
    CpuQoS, MemoryQoS, NetworkQoS                (EVENT_PODS)
    NetAccounting                                (EVENT_PODS, AFTER
        NetworkQoS: this sync's per-pod caps are the offline
        watermarks it verifies measured rates against)
    Goodput                                      (EVENT_PODS: workload
        step progress -> GoodputReport, docs/design/goodput.md)
    NumaExporter                                 (EVENT_PODS)
    Enforcement                                  (EVENT_PODS, LAST:
        applies the decision set the QoS handlers built and
        reconciles enforcement for departed pods)
    Eviction                                     (EVENT_PRESSURE)

MemoryQoS is the memoryqosv2 knob set (VERDICT r4 missing #2;
reference pkg/agent/events/handlers/memoryqosv2/ + docs/design/
agent-cgroup-v2-adaptation.md): online pods get memory.min (hard
guarantee = request) and memory.low (soft protection above it); BE
pods keep the memory.high cap.  Cpu and memory handlers never see
each other — both fill the per-sync PodQoSDecision set that the
Enforcement handler applies once per pod.
"""

from __future__ import annotations

import logging

from volcano_tpu.agent.framework import (
    EVENT_PODS,
    EVENT_PRESSURE,
    EVENT_USAGE,
    Event,
    Handler,
    register_handler,
)
from volcano_tpu.api.resource import TPU
from volcano_tpu.api.types import (
    QOS_HIGHLY_LATENCY_SENSITIVE,
    QOS_LATENCY_CRITICAL,
    QOS_LATENCY_SENSITIVE,
)

log = logging.getLogger(__name__)

# cpu qos-level ladder -> cgroup-v2 cpu.weight (extension/qos.go:
# LC/HLS=2, LS=1; BE takes weight 1 + cpu.idle instead)
CLASS_WEIGHT = {QOS_LATENCY_CRITICAL: 400,
                QOS_HIGHLY_LATENCY_SENSITIVE: 400,
                QOS_LATENCY_SENSITIVE: 100}

# agent.py owns the annotation-name constants (they are its public
# API); handlers import them inside handle() to avoid an import cycle
# (agent.py imports this module to trigger registration).


@register_handler
class UsageReporterHandler(Handler):
    """Publish cpu/memory usage fractions as node annotations
    (consumed by the usage plugin)."""

    name = "usagereporter"
    events = (EVENT_USAGE,)

    def handle(self, event: Event) -> None:
        from volcano_tpu.agent.agent import (
            CPU_USAGE_ANNOTATION, MEM_USAGE_ANNOTATION)
        event.node.annotations[CPU_USAGE_ANNOTATION] = \
            f"{event.usage.cpu_fraction:.3f}"
        event.node.annotations[MEM_USAGE_ANNOTATION] = \
            f"{event.usage.memory_fraction:.3f}"


@register_handler
class TpuHealthHandler(Handler):
    """Chip health -> verdict -> label/cordon + SliceHealthReport.
    A slice host with sick chips must not take new work: the ICI mesh
    is only as healthy as its worst host.

    HYSTERESIS both directions (the r6 handler cordoned on ONE bad
    telemetry sample and uncordoned on one good one — a flapping
    exporter bounced the host in and out of rotation every sync; same
    pattern as the netaccounting watermark hysteresis below):
    FAIL_SYNCS consecutive bad samples escalate Healthy -> Suspect ->
    Failed (cordon fires only on Failed), RECOVER_SYNCS consecutive
    good samples walk Failed back to Healthy (uncordon).  Every
    verdict/chip-count change posts a SliceHealthReport wire object —
    the store folds the verdict into node annotations and the
    failover controller declares slice failures from it
    (api/slicehealth.py)."""

    name = "tpuhealth"
    events = (EVENT_USAGE,)

    FAIL_SYNCS = 3
    RECOVER_SYNCS = 3

    def __init__(self, agent):
        super().__init__(agent)
        self._bad = 0
        self._good = 0
        self._first_bad_ts = 0.0
        from volcano_tpu.api.slicehealth import VERDICT_HEALTHY
        self._verdict = VERDICT_HEALTHY
        self._last_report = None       # change-elision signature

    def handle(self, event: Event) -> None:
        import time as _time

        from volcano_tpu.agent.agent import (
            AGENT_CORDONED_ANNOTATION, TPU_CHIPS_ANNOTATION,
            TPU_HEALTHY_LABEL)
        from volcano_tpu.api.slicehealth import (
            VERDICT_FAILED, VERDICT_HEALTHY, VERDICT_SUSPECT)
        node, usage = event.node, event.usage
        declared = self.agent.allocatable(node).get(TPU)
        if usage.tpu_chips_detected == 0:
            # no chip telemetry from this provider (e.g. a usage-only
            # Prometheus source): never cordon on absence of data
            return
        node.annotations[TPU_CHIPS_ANNOTATION] = \
            f"{usage.tpu_chips_healthy}/{usage.tpu_chips_detected}"
        healthy = (usage.tpu_chips_healthy >= declared > 0) or \
            (declared == 0 and usage.tpu_chips_detected ==
             usage.tpu_chips_healthy)

        if healthy:
            self._bad = 0
            self._good += 1
            if self._verdict != VERDICT_HEALTHY and \
                    self._good >= self.RECOVER_SYNCS:
                self._verdict = VERDICT_HEALTHY
                self._first_bad_ts = 0.0
                self.agent.cluster.record_event(
                    self.agent.node_name, "TPURecovered",
                    f"{usage.tpu_chips_healthy}/"
                    f"{usage.tpu_chips_detected} chips healthy for "
                    f"{self._good} syncs")
        else:
            self._good = 0
            self._bad += 1
            if self._bad == 1:
                self._first_bad_ts = _time.time()
            if self._verdict == VERDICT_HEALTHY:
                self._verdict = VERDICT_SUSPECT
            if self._verdict == VERDICT_SUSPECT and \
                    self._bad >= self.FAIL_SYNCS:
                self._verdict = VERDICT_FAILED
                self.agent.cluster.record_event(
                    self.agent.node_name, "TPUUnhealthy",
                    f"{usage.tpu_chips_healthy}/"
                    f"{usage.tpu_chips_detected} chips healthy "
                    f"(declared {declared:g}) for {self._bad} "
                    f"consecutive syncs")

        # label + cordon follow the VERDICT, not the sample: a Suspect
        # host keeps taking work until the failure is confirmed, and a
        # Failed host stays out until recovery is confirmed
        node.labels[TPU_HEALTHY_LABEL] = \
            "false" if self._verdict == VERDICT_FAILED else "true"
        if self._verdict == VERDICT_FAILED:
            node.unschedulable = True
            node.annotations[AGENT_CORDONED_ANNOTATION] = "true"
        elif self._verdict == VERDICT_HEALTHY and node.unschedulable \
                and node.annotations.get(AGENT_CORDONED_ANNOTATION) \
                == "true":
            # only undo OUR cordon — never an admin's maintenance one
            node.unschedulable = False
            node.annotations.pop(AGENT_CORDONED_ANNOTATION, None)

        self._post_report(node, usage)

    def _post_report(self, node, usage) -> None:
        from volcano_tpu.api.slicehealth import SliceHealthReport
        from volcano_tpu.api.types import TPU_SLICE_LABEL
        report = SliceHealthReport(
            node=self.agent.node_name,
            slice=node.labels.get(TPU_SLICE_LABEL, ""),
            verdict=self._verdict,
            chips_detected=usage.tpu_chips_detected,
            chips_healthy=usage.tpu_chips_healthy,
            consecutive_bad=self._bad,
            consecutive_good=self._good,
            first_bad_ts=round(self._first_bad_ts, 3))
        sig = (report.verdict, report.chips_detected,
               report.chips_healthy)
        if sig == self._last_report:
            return                    # unchanged verdict: no wire churn
        try:
            self.agent.cluster.put_object("slicehealthreport", report)
            self._last_report = sig
        except Exception as e:  # noqa: BLE001 — reporting must never
            log.warning("slice health report post failed: %s", e)  # kill sync


@register_handler
class OversubscriptionHandler(Handler):
    """Publish reclaimable millicores in 10% steps
    (pkg/agent/oversubscription/policy/policy.go:40-61)."""

    name = "oversubscription"
    events = (EVENT_USAGE,)

    def handle(self, event: Event) -> None:
        from volcano_tpu.agent.agent import OVERSUB_ANNOTATION
        if not getattr(event.usage, "cpu_sampled", True):
            # no cpu telemetry this cycle: publishing from the 0.0
            # default would read as a fully idle node and hand the
            # scheduler 60% of it as phantom reclaimable capacity
            event.node.annotations[OVERSUB_ANNOTATION] = "0"
            return
        alloc = self.agent.allocatable(event.node)
        idle_frac = max(0.0, 1.0 - event.usage.cpu_fraction)
        stepped = int(idle_frac * 10) / 10.0   # 10% quantization
        reclaimable = alloc.milli_cpu * stepped * \
            self.agent.oversub_factor
        event.node.annotations[OVERSUB_ANNOTATION] = \
            str(int(reclaimable))


@register_handler
class CpuQoSHandler(Handler):
    """cpuburst + cputhrottle + cpuqos (reference handlers of the
    same names): BE pods burst into measured idle, throttle to
    request under pressure; guaranteed pods keep fixed headroom; and
    every pod gets its qos-LEVEL scheduling class — the reference
    writes a kernel cpu.qos_level int (LC/HLS=2, LS=1, BE=-1,
    extension/qos.go), mapped here to the portable cgroup-v2 pair:
    cpu.weight (LC/HLS 400, LS 100, BE 1) and cpu.idle (SCHED_IDLE
    for BE — offline work yields the CPU entirely under contention
    instead of merely weighing less)."""

    name = "cpuqos"
    events = (EVENT_PODS,)

    def handle(self, event: Event) -> None:
        from volcano_tpu.agent.agent import (
            CPU_BURST_ANNOTATION, CPU_THROTTLE_ANNOTATION,
            PREEMPTABLE_QOS_ANNOTATION, QOS_BEST_EFFORT)
        agent = self.agent
        usage = event.usage
        idle_frac = max(0.0, 1.0 - usage.cpu_fraction)
        node_idle_m = agent.allocatable(event.node).milli_cpu * \
            idle_frac
        throttled = usage.cpu_fraction > agent.eviction_threshold * 0.9
        for pod in event.pods:
            qos = pod.annotations.get(PREEMPTABLE_QOS_ANNOTATION)
            request_m = pod.resource_requests().milli_cpu
            d = agent.decision_for(event, pod)
            if qos == QOS_BEST_EFFORT:
                # requests are often 0 for true best-effort — size the
                # burst from allocatable idle, not requests; pressure
                # zeroes it, matching the throttle flag
                burst = 0 if throttled else int(node_idle_m)
                pod.annotations[CPU_BURST_ANNOTATION] = str(burst)
                pod.annotations[CPU_THROTTLE_ANNOTATION] = (
                    "true" if throttled else "false")
                d.burst_millis, d.throttled = burst, throttled
                d.cpu_weight, d.cpu_idle = 1, True
            else:
                burst = int(request_m * 0.2)
                pod.annotations[CPU_BURST_ANNOTATION] = str(burst)
                pod.annotations.pop(CPU_THROTTLE_ANNOTATION, None)
                d.burst_millis, d.throttled = burst, False
                # unannotated pods are LS (extension/qos.go default);
                # an UNRECOGNIZED level also lands on LS weight but
                # loudly — a typo'd "lc" silently demoting a
                # latency-critical pod 400 -> 100 would be invisible
                if qos and qos not in CLASS_WEIGHT:
                    log.warning("pod %s: unknown qos-level %r; "
                                "treating as LS", pod.key, qos)
                d.cpu_weight = CLASS_WEIGHT.get(qos, 100)
                d.cpu_idle = False
            d.request_millis = int(request_m)


@register_handler
class MemoryQoSHandler(Handler):
    """memoryqosv2 (reference pkg/agent/events/handlers/memoryqosv2/,
    cgroup-v2 adaptation design doc): per-QoS-class memory knobs.

      online (non-BE) pods: memory.min = request (kernel-guaranteed,
        never reclaimed) and memory.low = 1.25x request (reclaim-
        protected while the node has slack) — the guarantee the r4
        agent lacked;
      BE pods: memory.high = request (soft cap; the kernel throttles
        allocation above it instead of OOM-killing the node)."""

    name = "memoryqosv2"
    events = (EVENT_PODS,)
    LOW_FACTOR = 1.25

    def handle(self, event: Event) -> None:
        from volcano_tpu.agent.agent import (
            PREEMPTABLE_QOS_ANNOTATION, QOS_BEST_EFFORT)
        for pod in event.pods:
            mem = int(pod.resource_requests().memory)
            if not mem:
                continue
            d = self.agent.decision_for(event, pod)
            if pod.annotations.get(PREEMPTABLE_QOS_ANNOTATION) == \
                    QOS_BEST_EFFORT:
                d.memory_high_bytes = mem
            else:
                d.memory_min_bytes = mem
                d.memory_low_bytes = int(mem * self.LOW_FACTOR)


@register_handler
class NetworkQoSHandler(Handler):
    """Online/offline DCN egress split (reference pkg/networkqos):
    publish the split + per-BE-pod caps and program the enforcer's
    network half."""

    name = "networkqos"
    events = (EVENT_PODS,)

    def handle(self, event: Event) -> None:
        from volcano_tpu.agent.agent import (
            DCN_BANDWIDTH_ANNOTATION, DCN_OFFLINE_LIMIT_ANNOTATION,
            DCN_ONLINE_GUARANTEE_ANNOTATION, DCN_POD_LIMIT_ANNOTATION,
            DEFAULT_DCN_MBPS, PREEMPTABLE_QOS_ANNOTATION,
            QOS_BEST_EFFORT)
        agent, node, usage = self.agent, event.node, event.usage
        try:
            total_mbps = float(node.annotations.get(
                DCN_BANDWIDTH_ANNOTATION, DEFAULT_DCN_MBPS))
        except (TypeError, ValueError):
            # a malformed operator annotation must never kill the
            # sync cycle (eviction still runs after this handler)
            log.warning("node %s: invalid %s annotation; using "
                        "default", agent.node_name,
                        DCN_BANDWIDTH_ANNOTATION)
            total_mbps = float(DEFAULT_DCN_MBPS)
        be_pods, other_pods = [], []
        for p in event.pods:
            (be_pods if p.annotations.get(PREEMPTABLE_QOS_ANNOTATION)
             == QOS_BEST_EFFORT else other_pods).append(p)
        # offline (BE) traffic capped at a link fraction, shrinking
        # to a floor under online pressure
        offline_share = 0.4 if usage.cpu_fraction < 0.8 else 0.1
        offline_mbps = int(total_mbps * offline_share)
        node.annotations[DCN_OFFLINE_LIMIT_ANNOTATION] = \
            str(offline_mbps)
        node.annotations[DCN_ONLINE_GUARANTEE_ANNOTATION] = \
            str(int(total_mbps - offline_mbps))
        pod_limits = {}
        if be_pods:
            # floor at 1: TcEnforcer clamps the kernel class to 1mbit
            # anyway, and a literal 0 would read as "no watermark" to
            # the netaccounting verifier — exactly the crowded-host
            # case where violations matter most
            per_pod = max(1, offline_mbps // len(be_pods))
            for pod in be_pods:
                pod.annotations[DCN_POD_LIMIT_ANNOTATION] = str(per_pod)
                pod_limits[pod.uid] = per_pod
        for pod in other_pods:
            # a pod promoted out of BE must not keep a stale cap
            pod.annotations.pop(DCN_POD_LIMIT_ANNOTATION, None)
        agent.enforcer.apply_network(int(total_mbps - offline_mbps),
                                     offline_mbps, pod_limits)


@register_handler
class NetAccountingHandler(Handler):
    """Verification half of the DCN split (reference: eBPF watermark
    maps, utils/ebpf/map.go:64-79): the NetworkQoS handler SHAPES
    traffic; this one MEASURES it and closes the loop.

    Runs right after networkqos (same sync's per-pod caps are the
    offline watermarks) off the NetAccountingCollector's per-classid
    EWMA rates:

      * publishes per-pod tx/rx mbps annotations + metrics;
      * compares each pod's rate against its watermark — offline (BE)
        pods' enforced cap, online pods' declared watermark-mbps
        annotation — with HYSTERESIS: FIRE_SYNCS consecutive
        over-watermark windows raise the violation (one burst never
        flaps), CLEAR_SYNCS consecutive windows under CLEAR_MARGIN x
        watermark lower it; the band between holds state;
      * emits BandwidthViolation / BandwidthViolationCleared events on
        the transitions and keeps a cumulative violating-sync count on
        the pod (the chronic signal bandwidthPressure reschedules on);
      * posts a BandwidthReport to the store when it materially
        changes — the server folds the node summary into node
        annotations for every watch mirror.
    """

    name = "netaccounting"
    events = (EVENT_PODS,)

    FIRE_SYNCS = 3
    CLEAR_SYNCS = 3
    CLEAR_MARGIN = 0.9
    # published rates move only when the EWMA leaves a dead-band
    # around the last published value (max of 1 mbps / 5%): raw EWMAs
    # jitter every window, and publishing the jitter would defeat the
    # agent's pod-annotation change-elision AND the report signature —
    # O(pods) PUTs per sync fanning out to every watch mirror.
    # Violation detection always uses the RAW rate.
    PUBLISH_DEADBAND_MBPS = 1.0
    PUBLISH_DEADBAND_FRAC = 0.05

    def __init__(self, agent):
        super().__init__(agent)
        # uid -> {"over", "under", "violating", "violations"}
        self._state = {}
        self._published = {}           # uid -> (tx, rx) as published
        self._last_report = None       # change-elision signature

    def _publish_rates(self, uid, tx, rx):
        pub = self._published.get(uid)
        if pub is not None:
            def inside(new, old):
                return abs(new - old) <= max(self.PUBLISH_DEADBAND_MBPS,
                                             self.PUBLISH_DEADBAND_FRAC
                                             * old)
            if inside(tx, pub[0]) and inside(rx, pub[1]):
                return pub             # steady: keep published values
        pub = (round(tx, 1), round(rx, 1))
        self._published[uid] = pub
        return pub

    def _collector(self):
        col = getattr(self.agent, "net_collector", None)
        if col is not None:
            return col
        from volcano_tpu.agent.collect import NetAccountingCollector
        for c in getattr(self.agent.provider, "collectors", ()):
            if isinstance(c, NetAccountingCollector):
                return c
        return None

    def _watermark(self, pod, offline: bool) -> float:
        from volcano_tpu.agent.agent import DCN_POD_LIMIT_ANNOTATION
        from volcano_tpu.api.netusage import POD_WATERMARK_ANNOTATION
        key = DCN_POD_LIMIT_ANNOTATION if offline \
            else POD_WATERMARK_ANNOTATION
        try:
            return float(pod.annotations.get(key, 0) or 0)
        except (TypeError, ValueError):
            return 0.0

    def handle(self, event: Event) -> None:
        from volcano_tpu import metrics
        from volcano_tpu.agent.agent import (
            DCN_BANDWIDTH_ANNOTATION, DEFAULT_DCN_MBPS,
            PREEMPTABLE_QOS_ANNOTATION, QOS_BEST_EFFORT)
        from volcano_tpu.api.netusage import (
            SATURATION_FRACTION, BandwidthReport, PodBandwidthUsage,
            POD_RX_ANNOTATION, POD_TX_ANNOTATION,
            POD_VIOLATING_ANNOTATION, POD_VIOLATIONS_ANNOTATION)
        collector = self._collector()
        if collector is None:
            return                    # accounting not deployed: no-op
        agent, node = self.agent, event.node
        # drive the sample ourselves: an explicitly-wired collector
        # needs no provider, and one that also sits in the composite
        # provider already walked this sync (MIN_INTERVAL_S no-op)
        try:
            collector.collect(agent.node_name)
        except Exception as e:  # noqa: BLE001 — degrade, keep sync
            log.warning("net accounting sample failed: %s", e)
        rates = collector.rates()
        try:
            total_mbps = float(node.annotations.get(
                DCN_BANDWIDTH_ANNOTATION, DEFAULT_DCN_MBPS))
        except (TypeError, ValueError):
            total_mbps = float(DEFAULT_DCN_MBPS)

        usages, rows = [], []
        offline_tx = online_tx = 0.0
        violating_pods = 0
        current_uids = set()
        for pod in event.pods:
            rate = rates.get(pod.uid)
            if rate is None:
                continue              # no cgroup counters for this pod
            current_uids.add(pod.uid)
            offline = pod.annotations.get(
                PREEMPTABLE_QOS_ANNOTATION) == QOS_BEST_EFFORT
            tier = "offline" if offline else "online"
            tx_pub, rx_pub = self._publish_rates(
                pod.uid, rate.tx_mbps, rate.rx_mbps)
            if offline:
                offline_tx += tx_pub
            else:
                online_tx += tx_pub
            watermark = self._watermark(pod, offline)
            st = self._state.setdefault(pod.uid, {
                "over": 0, "under": 0, "violating": False,
                "violations": 0})
            if watermark > 0 and rate.tx_mbps > watermark:
                st["over"] += 1
                st["under"] = 0
                if not st["violating"] and st["over"] >= self.FIRE_SYNCS:
                    st["violating"] = True
                    agent.cluster.record_event(
                        pod.key, "BandwidthViolation",
                        f"{tier} pod at {rate.tx_mbps:.1f} mbps > "
                        f"watermark {watermark:g} mbps for "
                        f"{st['over']} syncs")
                    metrics.inc("bandwidth_violations_total",
                                pod=pod.key, node=agent.node_name)
            elif watermark <= 0 or \
                    rate.tx_mbps <= watermark * self.CLEAR_MARGIN:
                st["under"] += 1
                st["over"] = 0
                if st["violating"] and st["under"] >= self.CLEAR_SYNCS:
                    st["violating"] = False
                    agent.cluster.record_event(
                        pod.key, "BandwidthViolationCleared",
                        f"{tier} pod back under watermark "
                        f"{watermark:g} mbps")
            else:
                # hysteresis band (CLEAR_MARGIN..1.0 of watermark):
                # neither direction makes progress
                st["over"] = st["under"] = 0
            if st["violating"]:
                st["violations"] += 1     # chronic = large cumulative
                violating_pods += 1
                pod.annotations[POD_VIOLATING_ANNOTATION] = "true"
            else:
                pod.annotations.pop(POD_VIOLATING_ANNOTATION, None)
            if st["violations"]:
                pod.annotations[POD_VIOLATIONS_ANNOTATION] = \
                    str(st["violations"])
            pod.annotations[POD_TX_ANNOTATION] = f"{tx_pub:.1f}"
            pod.annotations[POD_RX_ANNOTATION] = f"{rx_pub:.1f}"
            usages.append(PodBandwidthUsage(
                pod_key=pod.key, uid=pod.uid, classid=rate.classid,
                tier=tier, tx_mbps=tx_pub, rx_mbps=rx_pub,
                watermark_mbps=watermark,
                violating=st["violating"],
                violations=st["violations"]))
            rows.append(("pod_dcn_tx_mbps",
                         {"pod": pod.key, "node": agent.node_name,
                          "tier": tier}, tx_pub))
            rows.append(("pod_dcn_rx_mbps",
                         {"pod": pod.key, "node": agent.node_name,
                          "tier": tier}, rx_pub))
        for uid in set(self._state) - current_uids:
            del self._state[uid]      # departed pods drop hysteresis
            self._published.pop(uid, None)

        saturated = (offline_tx + online_tx) >= \
            SATURATION_FRACTION * total_mbps
        rows.append(("node_dcn_measured_mbps",
                     {"node": agent.node_name, "tier": "offline"},
                     round(offline_tx, 3)))
        rows.append(("node_dcn_measured_mbps",
                     {"node": agent.node_name, "tier": "online"},
                     round(online_tx, 3)))
        rows.append(("bandwidth_violating_pods",
                     {"node": agent.node_name}, violating_pods))
        metrics.swap_gauge_families(
            {"pod_dcn_tx_mbps", "pod_dcn_rx_mbps",
             "node_dcn_measured_mbps", "bandwidth_violating_pods"},
            rows, node=agent.node_name)

        report = BandwidthReport(
            node=agent.node_name, usages=usages,
            offline_tx_mbps=round(offline_tx, 1),
            online_tx_mbps=round(online_tx, 1),
            total_mbps=total_mbps, violations=violating_pods,
            saturated=saturated)
        sig = (report.offline_tx_mbps, report.online_tx_mbps,
               report.violations, report.saturated,
               tuple((u.pod_key, u.tx_mbps, u.violating)
                     for u in report.usages))
        if sig == self._last_report:
            return                    # unchanged: no wire churn
        try:
            agent.cluster.put_object("bandwidthreport", report)
            self._last_report = sig
        except Exception as e:  # noqa: BLE001 — reporting must never
            log.warning("bandwidth report post failed: %s", e)  # kill sync


@register_handler
class GoodputHandler(Handler):
    """Workload-progress half of the goodput observatory (docs/design/
    goodput.md): the GoodputCollector turns per-pod progress files
    into step rates and a productive-vs-allocated time ledger; this
    handler pairs that state with the node's pods, publishes per-pod
    step/rate annotations, and posts one GoodputReport per sync —
    the store folds the per-job summary into PODGROUP annotations the
    scheduler's throughput-vector estimator learns from.

    Posting discipline: the report carries the CUMULATIVE per-pod
    ledger; the store folds the diff against this node's previous
    report, so a re-post after a lost ack (server folded, response
    died) is idempotent and a dead server loses nothing — the next
    acked cumulative covers the gap.  Elision: nothing posted while
    no pod has progress state; an unchanged signature is still posted
    once the unreported allocated time passes POST_DEBT_S (stalled
    pods must keep debiting goodput at the store)."""

    name = "goodput"
    events = (EVENT_PODS,)

    POST_DEBT_S = 5.0
    # published rates move only outside a dead-band (same rationale
    # as netaccounting: raw EWMAs jitter; publishing the jitter
    # defeats pod-annotation change-elision)
    PUBLISH_DEADBAND_FRAC = 0.05

    def __init__(self, agent):
        super().__init__(agent)
        self._published = {}           # uid -> published rate
        self._last_report = None       # change-elision signature
        self._posted_alloc = 0.0       # total allocated_s last posted

    def _collector(self):
        col = getattr(self.agent, "goodput_collector", None)
        if col is not None:
            return col
        from volcano_tpu.agent.collect import GoodputCollector
        for c in getattr(self.agent.provider, "collectors", ()):
            if isinstance(c, GoodputCollector):
                return c
        return None

    def _publish_rate(self, uid: str, rate: float) -> float:
        pub = self._published.get(uid)
        if pub is not None and abs(rate - pub) <= \
                max(0.01, self.PUBLISH_DEADBAND_FRAC * pub):
            return pub
        pub = round(rate, 3)
        self._published[uid] = pub
        return pub

    @staticmethod
    def _job_key(pod) -> str:
        from volcano_tpu.api.types import GROUP_NAME_ANNOTATION
        group = pod.annotations.get(GROUP_NAME_ANNOTATION) or pod.owner
        if not group:
            return ""
        return group if "/" in group else f"{pod.namespace}/{group}"

    def handle(self, event: Event) -> None:
        import time as _time

        from volcano_tpu.api.goodput import (
            POD_STEP_ANNOTATION, POD_STEP_RATE_ANNOTATION,
            GoodputReport, PodGoodput, generation_of)
        collector = self._collector()
        if collector is None:
            return                    # goodput not deployed: no-op
        agent = self.agent
        try:
            collector.collect(agent.node_name)
        except Exception as e:  # noqa: BLE001 — degrade, keep sync
            log.warning("goodput sample failed: %s", e)
        rates = collector.rates()
        generation = generation_of(event.node.labels)
        usages = []
        current_uids = set()
        for pod in event.pods:
            st = rates.get(pod.uid)
            if st is None:
                continue              # no progress file for this pod
            current_uids.add(pod.uid)
            rate_pub = self._publish_rate(pod.uid, st.steps_per_s)
            pod.annotations[POD_STEP_ANNOTATION] = str(st.step)
            pod.annotations[POD_STEP_RATE_ANNOTATION] = \
                f"{rate_pub:.3f}"
            usages.append(PodGoodput(
                pod_key=pod.key, uid=pod.uid,
                job=self._job_key(pod), generation=generation,
                epoch=st.epoch or 0, step=st.step,
                steps_per_s=rate_pub,
                examples_per_s=round(st.examples_per_s, 3),
                goodput=round(st.goodput, 4),
                allocated_s=round(st.allocated_s, 3),
                productive_s=round(st.productive_s, 3),
                stalled=st.stalled))
        for uid in set(self._published) - current_uids:
            del self._published[uid]
        if not usages:
            return
        sig = tuple((u.uid, u.step, u.epoch, u.steps_per_s)
                    for u in usages)
        total_alloc = sum(u.allocated_s for u in usages)
        if sig == self._last_report and \
                total_alloc - self._posted_alloc < self.POST_DEBT_S:
            return                    # steady and little unreported
        report = GoodputReport(node=agent.node_name,
                               ts=round(_time.time(), 3),
                               usages=usages)
        try:
            agent.cluster.put_object("goodputreport", report)
        except Exception as e:  # noqa: BLE001 — reporting must never
            log.warning("goodput report post failed: %s", e)  # kill sync
            return
        self._last_report = sig
        self._posted_alloc = total_alloc


@register_handler
class ServingHandler(Handler):
    """Traffic half of the serving plane (api/serving.py): the
    ServingCollector turns per-replica stats files into EWMA QPS and
    latency quantiles; this handler pairs that state with the node's
    pods, publishes per-pod QPS/p99 annotations, and posts one
    ServingReport per sync — the store folds the per-group summary
    into PODGROUP annotations the serving autoscaler
    (controllers/serving.py) scales from.

    Same posting discipline as GoodputHandler: cumulative per-replica
    ledgers on the wire (idempotent store fold), change-elision on a
    (uid, requests, epoch, qps) signature, and a debt re-post once
    POST_DEBT_S of unreported requests accumulate — a group whose
    traffic went flat must still refresh its updated-ts so the
    autoscaler can tell quiet from dead."""

    name = "serving"
    events = (EVENT_PODS,)

    POST_DEBT_S = 5.0
    PUBLISH_DEADBAND_FRAC = 0.05

    def __init__(self, agent):
        super().__init__(agent)
        self._published = {}           # uid -> published qps
        self._last_report = None       # change-elision signature
        self._last_post_ts = 0.0

    def _collector(self):
        col = getattr(self.agent, "serving_collector", None)
        if col is not None:
            return col
        from volcano_tpu.agent.collect import ServingCollector
        for c in getattr(self.agent.provider, "collectors", ()):
            if isinstance(c, ServingCollector):
                return c
        return None

    def _publish_rate(self, uid: str, rate: float) -> float:
        pub = self._published.get(uid)
        if pub is not None and abs(rate - pub) <= \
                max(0.01, self.PUBLISH_DEADBAND_FRAC * pub):
            return pub
        pub = round(rate, 3)
        self._published[uid] = pub
        return pub

    @staticmethod
    def _job_key(pod) -> str:
        from volcano_tpu.api.types import GROUP_NAME_ANNOTATION
        group = pod.annotations.get(GROUP_NAME_ANNOTATION) or pod.owner
        if not group:
            return ""
        return group if "/" in group else f"{pod.namespace}/{group}"

    def handle(self, event: Event) -> None:
        import time as _time

        from volcano_tpu.api.serving import (
            POD_P99_MS_ANNOTATION, POD_QPS_ANNOTATION, ReplicaServing,
            ServingReport)
        collector = self._collector()
        if collector is None:
            return                    # serving not deployed: no-op
        agent = self.agent
        try:
            collector.collect(agent.node_name)
        except Exception as e:  # noqa: BLE001 — degrade, keep sync
            log.warning("serving sample failed: %s", e)
        rates = collector.rates()
        usages = []
        current_uids = set()
        for pod in event.pods:
            st = rates.get(pod.uid)
            if st is None:
                continue              # no stats file for this pod
            current_uids.add(pod.uid)
            qps_pub = self._publish_rate(pod.uid, st.qps)
            pod.annotations[POD_QPS_ANNOTATION] = f"{qps_pub:.3f}"
            pod.annotations[POD_P99_MS_ANNOTATION] = \
                f"{st.p99_ms:.3f}"
            usages.append(ReplicaServing(
                pod_key=pod.key, uid=pod.uid,
                job=self._job_key(pod), epoch=st.epoch or 0,
                qps=qps_pub, p50_ms=round(st.p50_ms, 3),
                p99_ms=round(st.p99_ms, 3),
                requests=st.requests, slo_ok=st.slo_ok))
        for uid in set(self._published) - current_uids:
            del self._published[uid]
        if not usages:
            return
        sig = tuple((u.uid, u.requests, u.epoch, u.qps)
                    for u in usages)
        now = _time.time()
        if sig == self._last_report and \
                now - self._last_post_ts < self.POST_DEBT_S:
            return                    # steady and recently refreshed
        report = ServingReport(node=agent.node_name,
                               ts=round(now, 3), usages=usages)
        try:
            agent.cluster.put_object("servingreport", report)
        except Exception as e:  # noqa: BLE001 — reporting must never
            log.warning("serving report post failed: %s", e)  # kill sync
            return
        self._last_report = sig
        self._last_post_ts = now


@register_handler
class NumaExporterHandler(Handler):
    """Exporter half of the Numatopology contract: republish per-cell
    FREE amounts so the scheduler's single-NUMA gate sees placements
    from earlier cycles."""

    name = "numaexporter"
    events = (EVENT_PODS,)

    def handle(self, event: Event) -> None:
        agent = self.agent
        topo = getattr(agent.cluster, "numatopologies", {}).get(
            agent.node_name)
        if topo is None:
            return
        reqs = []
        for pod in event.pods:
            r = pod.resource_requests()
            reqs.append((r.milli_cpu, r.get(TPU)))
        before = {res: dict(cells)
                  for res, cells in topo.numa_res.items()}
        topo.recompute_free(reqs)
        if topo.numa_res != before:
            agent.cluster.put_object("numatopology", topo)


@register_handler
class EnforcementHandler(Handler):
    """LAST of the EVENT_PODS handlers: apply the decision set the
    QoS handlers built (one apply per pod, all knob families merged)
    and revert enforcement for pods that left the node — decision,
    OS mutation, and revert stay one observable loop."""

    name = "enforcement"
    events = (EVENT_PODS,)

    def handle(self, event: Event) -> None:
        agent = self.agent
        for d in event.decisions.values():
            agent.enforcer.apply_pod_qos(d)
        current_uids = {p.uid for p in event.pods}
        for uid in agent._enforced_uids - current_uids:
            agent.enforcer.remove_pod(uid)
        agent._enforced_uids = current_uids


@register_handler
class EvictionHandler(Handler):
    """Pressure eviction of best-effort pods (reference eviction
    handler)."""

    name = "eviction"
    events = (EVENT_PRESSURE,)

    def handle(self, event: Event) -> None:
        from volcano_tpu.agent.agent import (
            PREEMPTABLE_QOS_ANNOTATION, QOS_BEST_EFFORT)
        for pod in event.pods:
            if pod.annotations.get(PREEMPTABLE_QOS_ANNOTATION) == \
                    QOS_BEST_EFFORT:
                log.info("agent %s: evicting BE pod %s under pressure",
                         self.agent.node_name, pod.key)
                self.agent.cluster.evict_pod(
                    pod.namespace, pod.name, "node resource pressure")
