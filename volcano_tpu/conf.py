"""Scheduler configuration: actions string + plugin tiers.

Reference parity: pkg/scheduler/conf/scheduler_conf.go +
pkg/scheduler/util.go:38-53 (DefaultSchedulerConf, UnmarshalSchedulerConf).

Config sources: a Python dict, or YAML text of the same shape as the
reference's ConfigMap:

    actions: "enqueue, allocate, backfill"
    tiers:
    - plugins:
      - name: priority
      - name: gang
      - name: conformance
    - plugins:
      - name: overcommit
      - name: drf
      - name: predicates
      - name: proportion
      - name: nodeorder
      - name: binpack
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class PluginOption:
    name: str
    # Per-callback enable flags (reference: enableJobOrder etc.); None
    # means plugin default.
    enabled: Dict[str, bool] = field(default_factory=dict)
    arguments: Dict[str, object] = field(default_factory=dict)

    def is_enabled(self, point: str, default: bool = True) -> bool:
        return self.enabled.get(point, default)


@dataclass
class Tier:
    plugins: List[PluginOption] = field(default_factory=list)


@dataclass
class SchedulerConf:
    actions: List[str] = field(default_factory=list)
    tiers: List[Tier] = field(default_factory=list)
    configurations: Dict[str, Dict[str, object]] = field(default_factory=dict)
    # ^ per-action arguments (reference conf.Configuration)

    def plugin_option(self, name: str) -> Optional[PluginOption]:
        for tier in self.tiers:
            for p in tier.plugins:
                if p.name == name:
                    return p
        return None

    def plugin_names(self) -> List[str]:
        return [p.name for t in self.tiers for p in t.plugins]


DEFAULT_SCHEDULER_CONF = {
    # elastic runs after allocate (fixed-size placement first) and
    # before backfill/gangpreempt: a free no-op when no job declares
    # an elastic range (actions/elastic.py)
    "actions": "enqueue, allocate, elastic, backfill",
    "tiers": [
        # failover: quarantined-slice filter + requeued-gang priority —
        # a cheap no-op until the failover controller quarantines a
        # slice (controllers/failover.py); elastic: shrink-before-
        # preempt veto + migration steering (plugins/elastic.py)
        {"plugins": [{"name": "priority"}, {"name": "gang"},
                     {"name": "failover"}, {"name": "elastic"},
                     {"name": "conformance"}]},
        # tier 2 mirrors the reference default's predicates wrap
        # (predicates.go:37 bundles nodeaffinity, podaffinity, taints,
        # ports, volume + spread): here those are separate plugins, so
        # the default enables the full set — each is a cheap no-op for
        # pods that don't use its feature
        {"plugins": [{"name": "overcommit"}, {"name": "drf"},
                     {"name": "predicates"},
                     {"name": "interpodaffinity"},
                     {"name": "pod-topology-spread"},
                     {"name": "volumebinding"},
                     {"name": "deviceshare"},
                     {"name": "proportion"},
                     {"name": "nodeorder"}, {"name": "binpack"}]},
    ],
}


def load_conf(source=None) -> SchedulerConf:
    """Build a SchedulerConf from a dict or YAML text (None => default)."""
    if source is None:
        data = DEFAULT_SCHEDULER_CONF
    elif isinstance(source, str):
        import yaml  # pyyaml ships with the baked-in ML stack
        data = yaml.safe_load(source)
    else:
        data = source

    actions = [a.strip() for a in str(data.get("actions", "")).split(",")
               if a.strip()]
    tiers: List[Tier] = []
    for tier_data in data.get("tiers", []):
        opts = []
        for p in tier_data.get("plugins", []):
            known = {"name", "arguments"}
            # "enableJobOrder: false" -> enabled["jobOrder"] = False,
            # matching the camelCase point names Session dispatches with.
            enabled = {}
            for k, v in p.items():
                if k in known or not isinstance(v, bool):
                    continue
                point = k[len("enable"):] if k.startswith("enable") else k
                enabled[point[0].lower() + point[1:]] = v
            opts.append(PluginOption(name=p["name"],
                                     enabled=enabled,
                                     arguments=dict(p.get("arguments", {}))))
        tiers.append(Tier(plugins=opts))
    configurations = {c["name"]: dict(c.get("arguments", {}))
                      for c in data.get("configurations", [])} \
        if isinstance(data.get("configurations"), list) else \
        dict(data.get("configurations", {}))
    return SchedulerConf(actions=actions, tiers=tiers,
                         configurations=configurations)
