"""Leader election over a state-server lease.

Reference parity: cmd/scheduler/app/server.go:99-128 (client-go
leaderelection).  Renewal runs on a dedicated thread at ttl/3 cadence
— NEVER inline with the scheduling cycle, so a slow cycle (first
session imports, big snapshot) cannot let the lease lapse under the
leader's feet.  A failed or lost renewal clears `is_leader`
immediately; the component checks the flag each cycle and stands by
until re-acquired.
"""

from __future__ import annotations

import logging
import threading

log = logging.getLogger(__name__)


class LeaderElector:
    def __init__(self, cluster, lease_name: str, holder: str,
                 ttl: float = 5.0):
        self.cluster = cluster
        self.lease_name = lease_name
        self.holder = holder
        self.ttl = ttl
        self._leader = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="leader-elector", daemon=True)

    def start(self) -> "LeaderElector":
        self._renew_once()
        self._thread.start()
        return self

    @property
    def is_leader(self) -> bool:
        return self._leader.is_set()

    def _renew_once(self) -> None:
        try:
            # the retry budget must stay inside ONE renewal slot
            # (ttl/3): a wire retrying past the TTL would hold the
            # thread while the lease lapses under it — better to fail
            # this renewal, step down, and re-contend next slot
            res = self.cluster.lease(self.lease_name, self.holder,
                                     ttl=self.ttl,
                                     deadline=self.ttl / 3.0)
            acquired = bool(res.get("acquired"))
        except Exception:  # noqa: BLE001 — server blip: step down
            log.warning("lease renewal failed; standing by",
                        exc_info=True)
            acquired = False
        if acquired != self._leader.is_set():
            log.info("leadership %s (%s)",
                     "acquired" if acquired else "lost", self.holder)
        if acquired:
            self._leader.set()
        else:
            self._leader.clear()

    def _loop(self) -> None:
        # renew at ttl/3 (leader) and retry at ttl/2 (standby) — the
        # standby polls slower than the holder renews, so a healthy
        # leader is never raced at the expiry instant
        while not self._stop.is_set():
            interval = self.ttl / 3.0 if self.is_leader else self.ttl / 2.0
            if self._stop.wait(interval):
                return
            self._renew_once()

    def stop(self) -> None:
        self._stop.set()
        if self._leader.is_set():
            try:
                # shutdown courtesy only (the TTL lapses anyway):
                # never let a dead wire block process exit
                self.cluster.lease(self.lease_name, self.holder,
                                   ttl=self.ttl, release=True,
                                   deadline=1.0)
            except Exception:  # noqa: BLE001
                pass
        self._leader.clear()
